// tbnet — native network plane implementation.  See tbnet.h for the role
// and the reference seams this re-designs (event_dispatcher.cpp,
// input_messenger.cpp:60-129, socket.cpp:1591-1686, baidu_rpc_protocol.cpp).
//
// Threading model: N epoll loop threads own connections (a connection is
// read by exactly its loop thread; LT events, no oneshot re-arm needed).
// Foreign threads (Python handlers answering asynchronously, the client's
// writers) touch a connection only through versioned tokens resolved out
// of a tb_respool — the same Address-after-SetFailed discipline the
// reference builds on Socket's versioned refs (socket.h:619-630).  Writes
// from any thread serialize on the connection's write mutex; the fd is
// closed only after every in-flight token holder drops its ref.

#include "tbnet.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zlib.h>  // crc32: the dispatch key's second polynomial

#if defined(__x86_64__)
#include <x86intrin.h>  // __rdtsc: the telemetry hot path's cheap clock
#endif

namespace {

// wire constants — must match protocol/tbus_std.py and tbutil.cc
constexpr uint32_t kMagic = 0x54505243;  // "TPRC"
constexpr uint32_t kFlagResponse = 1;
constexpr uint32_t kFlagStream = 2;
constexpr uint32_t kFlagHasMeta = 4;
constexpr uint32_t kFlagBodyCrc = 8;
// internal-only callback flag: the frame arrived on a baidu_std (PRPC)
// connection and its meta is raw RpcMeta proto bytes (never on the wire;
// must stay out of the tbus_std wire-flag space above)
constexpr uint32_t kFlagWirePrpc = 0x100;
// internal-only callback flag: the connection's credential was already
// verified on the native plane — the Python route's server_check must
// honor the cached verdict instead of demanding the credential again
constexpr uint32_t kFlagConnAuthed = 0x200;
constexpr size_t kHeader = 32;

// baidu_std: "PRPC" + body_size(u32 BE) + meta_size(u32 BE)
// (protocol/baidu_std.py; reference baidu_rpc_protocol.cpp:53-58)
constexpr uint32_t kMagicPrpc = 0x43505250;  // "PRPC" read as LE u32
constexpr size_t kPrpcHeader = 12;

// connection wire protocol, fixed at sniff time
constexpr int kProtoTbus = 1;
constexpr int kProtoPrpc = 2;

constexpr int kKindEcho = 1;
constexpr int kKindNop = 2;
constexpr int kKindCallback = 3;  // user C fn: tb_server_register_native_fn

uint64_t now_ms() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::milliseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// ---------------------------------------------------------------------------
// minimal JSON scanner for the flat meta object.  The native plane needs
// only the routing fields (service/method/attachment_size); any meta it
// cannot fully vouch for (escapes, compression, stream/trace fields, parse
// trouble) routes to the Python frame callback, which parses properly.
// ---------------------------------------------------------------------------

struct MetaLite {
  bool ok = false;         // meta parsed cleanly
  bool to_python = false;  // fields beyond the native fast path's scope
  std::string service;
  std::string method;
  long attachment = 0;
  long timeout_ms = 0;  // propagated deadline budget (0 = none)
  // Dapper trace context (same keys as protocol/tbus_std.py Meta):
  // decoded natively so OBSERVED tbus traffic keeps the fast path
  uint64_t log_id = 0;
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  uint32_t sampled = 0;  // head-based coherent-sampling bit ("sampled":1)
};

struct Scan {
  const char* p;
  const char* end;
  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool lit(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  // raw string body between quotes; *escaped set if any backslash seen
  bool str(std::string* out, bool* escaped) {
    ws();
    if (p >= end || *p != '"') return false;
    ++p;
    const char* s = p;
    bool esc = false;
    while (p < end) {
      if (*p == '\\') {
        esc = true;
        p += 2;
        continue;
      }
      if (*p == '"') {
        if (out) out->assign(s, p - s);
        if (escaped) *escaped = esc;
        ++p;
        return true;
      }
      ++p;
    }
    return false;
  }
  bool skip_value();
  bool skip_container(char open, char close) {
    int depth = 1;
    ++p;  // past open
    while (p < end && depth > 0) {
      if (*p == '"') {
        if (!str(nullptr, nullptr)) return false;
        continue;
      }
      if (*p == open) ++depth;
      if (*p == close) --depth;
      ++p;
    }
    return depth == 0;
  }
};

bool Scan::skip_value() {
  ws();
  if (p >= end) return false;
  char c = *p;
  if (c == '"') return str(nullptr, nullptr);
  if (c == '{') return skip_container('{', '}');
  if (c == '[') return skip_container('[', ']');
  const char* s = p;  // number / true / false / null
  while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\t' && *p != '\n' && *p != '\r')
    ++p;
  return p > s;
}

MetaLite scan_meta(const char* s, size_t n) {
  MetaLite m;
  if (n == 0) {
    m.ok = true;
    return m;
  }
  Scan sc{s, s + n};
  if (!sc.lit('{')) return m;
  sc.ws();
  if (sc.p < sc.end && *sc.p == '}') {
    m.ok = true;
    return m;
  }
  for (;;) {
    std::string key;
    bool kesc = false;
    if (!sc.str(&key, &kesc) || kesc) return m;
    if (!sc.lit(':')) return m;
    if (key == "service" || key == "method") {
      std::string v;
      bool vesc = false;
      if (!sc.str(&v, &vesc)) return m;
      if (vesc) m.to_python = true;  // escaped name: Python unescapes
      (key == "service" ? m.service : m.method) = std::move(v);
    } else if (key == "attachment_size") {
      sc.ws();
      char* endp = nullptr;
      m.attachment = strtol(sc.p, &endp, 10);
      if (endp == sc.p || m.attachment < 0) return m;
      sc.p = endp;
    } else if (key == "timeout_ms") {
      // the propagated deadline is native-fast-path territory: the
      // cutter sheds expired work itself (run_native), so a deadline-
      // carrying frame must NOT fall off the interpreter-free plane
      sc.ws();
      char* endp = nullptr;
      m.timeout_ms = strtol(sc.p, &endp, 10);
      if (endp == sc.p || m.timeout_ms < 0) return m;
      sc.p = endp;
    } else if (key == "log_id" || key == "trace_id" || key == "span_id" ||
               key == "parent_span_id" || key == "sampled") {
      // trace context is native-fast-path territory too: observed
      // traffic must not pay the interpreter tax (ROADMAP item 1) —
      // the ids ride the telemetry record, the sampled bit is the
      // head-based coherent-sampling election
      sc.ws();
      if (sc.p >= sc.end || *sc.p == '-') {
        m.to_python = true;  // negative/odd ids: Python owns the edge case
        if (!sc.skip_value()) return m;
      } else {
        char* endp = nullptr;
        uint64_t v = strtoull(sc.p, &endp, 10);
        if (endp == sc.p) return m;
        sc.p = endp;
        if (key == "log_id") m.log_id = v;
        else if (key == "trace_id") m.trace_id = v;
        else if (key == "span_id") m.span_id = v;
        else if (key == "parent_span_id") m.parent_span_id = v;
        else m.sampled = v != 0 ? 1u : 0u;
      }
    } else {
      // compress, stream ids, error_text, extra...: semantics the
      // native fast path doesn't implement — Python handles them
      if (!sc.skip_value()) return m;
      m.to_python = true;
    }
    sc.ws();
    if (sc.p < sc.end && *sc.p == ',') {
      ++sc.p;
      continue;
    }
    if (sc.lit('}')) break;
    return m;
  }
  m.ok = true;
  return m;
}

// ---------------------------------------------------------------------------
// baidu_std (PRPC): hand-rolled proto2 wire codec for RpcMeta — varint +
// length-delimited only, the exact field tables of protocol/baidu_std.py
// (policy/baidu_rpc_meta.proto):
//   RpcMeta:        1 request(msg)  2 response(msg)  3 compress_type
//                   4 correlation_id  5 attachment_size
//                   7 authentication_data  8 stream_settings(msg)
//   RpcRequestMeta: 1 service_name  2 method_name  3 log_id  4 trace_id
//                   5 span_id  6 parent_span_id  8 timeout_ms
//                   9 traced_sampled (this stack's extension — the
//                     head-based coherent-sampling bit; docs/PARITY.md)
//   RpcResponseMeta: 1 error_code  2 error_text
// Same routing philosophy as the JSON scanner above: the native fast path
// vouches for service/method/cid/attachment_size, the propagated deadline,
// compression, auth, AND the Dapper trace fields; anything else (streams,
// unknown fields) routes to Python, which implements the full semantics.
// ---------------------------------------------------------------------------

size_t varint_len(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

size_t put_varint(uint8_t* out, uint64_t v) {
  size_t n = 0;
  while (v >= 0x80) {
    out[n++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  out[n++] = static_cast<uint8_t>(v);
  return n;
}

// fixed 10-byte (padded) varint: value-independent length so the pump's
// frame template can patch the correlation id in place.  Decoders accept
// non-minimal varints (protocol/baidu_std.py _read_varint reads through
// shift 63), so the bytes stay wire-legal.
void put_varint_fixed10(uint8_t* out, uint64_t v) {
  for (int i = 0; i < 9; ++i)
    out[i] = static_cast<uint8_t>((v >> (7 * i)) & 0x7F) | 0x80;
  out[9] = static_cast<uint8_t>((v >> 63) & 0x7F);
}

// bounded varint read; false on truncation/overlong
bool read_varint(const uint8_t* p, size_t n, size_t* off, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*off < n && shift <= 63) {
    uint8_t b = p[*off];
    ++*off;
    v |= static_cast<uint64_t>(b & 0x7F) << shift;
    if ((b & 0x80) == 0) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

struct PrpcMeta {
  bool ok = false;         // meta parsed cleanly
  bool to_python = false;  // fields beyond the native fast path's scope
  bool is_response = false;
  const char* svc = nullptr;
  size_t svc_len = 0;
  const char* mth = nullptr;
  size_t mth_len = 0;
  // the RpcRequestMeta submessage slice — the per-connection routing memo
  // key (byte-identical submessage => same method)
  const char* req_sub = nullptr;
  size_t req_sub_len = 0;
  uint64_t cid = 0;
  long attachment = 0;
  long timeout_ms = 0;  // RpcRequestMeta.timeout_ms (field 8); 0 = none
  // Dapper trace context (RpcRequestMeta fields 3-6) + the field-9
  // sampled bit: decoded natively so traced frames keep the fast path;
  // the ids ride the telemetry record, the bit overrides 1/N election
  uint64_t log_id = 0;
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  uint32_t sampled = 0;
  uint32_t error_code = 0;
  // compress_type (field 3): dispatched through the native codec table —
  // out-of-enum values stay here too (run_native answers the clean
  // unknown-codec EREQUEST byte-identically to the Python route)
  uint32_t compress = 0;
  // authentication_data (field 7): verified natively once per connection
  const char* auth = nullptr;
  size_t auth_len = 0;
};

PrpcMeta scan_prpc_meta(const char* s, size_t n) {
  PrpcMeta m;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(s);
  size_t off = 0;
  while (off < n) {
    uint64_t key = 0;
    if (!read_varint(p, n, &off, &key)) return m;
    uint64_t field = key >> 3;
    int wt = static_cast<int>(key & 7);
    if (wt == 0) {
      uint64_t v = 0;
      if (!read_varint(p, n, &off, &v)) return m;
      if (field == 3) {  // compress_type: the native codec table owns it
        if (v > 0xFFFFFFFFull) return m;
        m.compress = static_cast<uint32_t>(v);
      } else if (field == 4) {
        m.cid = v;
      } else if (field == 5) {
        if (v > (1ull << 31)) return m;
        m.attachment = static_cast<long>(v);
      } else {
        m.to_python = true;
      }
    } else if (wt == 2) {
      uint64_t len = 0;
      // subtraction form: `off + len > n` would wrap on an attacker-
      // supplied 64-bit length and defeat the bounds check entirely
      if (!read_varint(p, n, &off, &len) || len > n - off) return m;
      const char* sub = s + off;
      size_t sub_len = static_cast<size_t>(len);
      off += sub_len;
      if (field == 1) {  // RpcRequestMeta
        m.req_sub = sub;
        m.req_sub_len = sub_len;
        const uint8_t* q = reinterpret_cast<const uint8_t*>(sub);
        size_t qoff = 0;
        while (qoff < sub_len) {
          uint64_t k2 = 0;
          if (!read_varint(q, sub_len, &qoff, &k2)) return m;
          uint64_t f2 = k2 >> 3;
          int w2 = static_cast<int>(k2 & 7);
          if (w2 == 2) {
            uint64_t l2 = 0;
            if (!read_varint(q, sub_len, &qoff, &l2) || l2 > sub_len - qoff)
              return m;
            if (f2 == 1) {
              m.svc = sub + qoff;
              m.svc_len = static_cast<size_t>(l2);
            } else if (f2 == 2) {
              m.mth = sub + qoff;
              m.mth_len = static_cast<size_t>(l2);
            } else {
              m.to_python = true;
            }
            qoff += static_cast<size_t>(l2);
          } else if (w2 == 0) {
            uint64_t v2 = 0;
            if (!read_varint(q, sub_len, &qoff, &v2)) return m;
            if (f2 == 8) {
              // timeout_ms: the deadline shed runs natively (run_native)
              if (v2 > (1ull << 31)) return m;
              m.timeout_ms = static_cast<long>(v2);
            } else if (f2 == 3) {  // log_id
              m.log_id = v2;
            } else if (f2 == 4) {  // trace_id: the caller's trace
              m.trace_id = v2;
            } else if (f2 == 5) {  // span_id: the server span's parent
              m.span_id = v2;
            } else if (f2 == 6) {  // parent_span_id
              m.parent_span_id = v2;
            } else if (f2 == 9) {  // head-based sampled bit (extension)
              m.sampled = v2 != 0 ? 1u : 0u;
            } else if (v2 != 0) {
              m.to_python = true;  // unknown request-meta varint
            }
          } else if (w2 == 1 || w2 == 5) {
            size_t skip = w2 == 1 ? 8 : 4;
            if (qoff + skip > sub_len) return m;
            qoff += skip;
            m.to_python = true;
          } else {
            return m;
          }
        }
      } else if (field == 2) {  // RpcResponseMeta
        m.is_response = true;
        const uint8_t* q = reinterpret_cast<const uint8_t*>(sub);
        size_t qoff = 0;
        while (qoff < sub_len) {
          uint64_t k2 = 0;
          if (!read_varint(q, sub_len, &qoff, &k2)) return m;
          uint64_t f2 = k2 >> 3;
          int w2 = static_cast<int>(k2 & 7);
          if (w2 == 0) {
            uint64_t v2 = 0;
            if (!read_varint(q, sub_len, &qoff, &v2)) return m;
            if (f2 == 1) m.error_code = static_cast<uint32_t>(v2);
          } else if (w2 == 2) {
            uint64_t l2 = 0;
            if (!read_varint(q, sub_len, &qoff, &l2) || l2 > sub_len - qoff)
              return m;
            qoff += static_cast<size_t>(l2);  // error_text: Python decodes
          } else if (w2 == 1 || w2 == 5) {
            size_t skip = w2 == 1 ? 8 : 4;
            if (qoff + skip > sub_len) return m;
            qoff += skip;
          } else {
            return m;
          }
        }
      } else if (field == 7) {  // authentication_data: native auth seam
        m.auth = sub;
        m.auth_len = sub_len;
      } else {  // stream settings (8), unknown
        m.to_python = true;
      }
    } else if (wt == 1 || wt == 5) {
      // fixed64/fixed32: RpcMeta never uses them today, but they are
      // legal proto2 — skip and route to Python (which walks them the
      // same way) instead of killing the connection
      size_t skip = wt == 1 ? 8 : 4;
      if (off + skip > n) return m;
      off += skip;
      m.to_python = true;
    } else {
      return m;
    }
  }
  m.ok = true;
  return m;
}

// ---------------------------------------------------------------------------
// codecs — production-shaped PRPC traffic (compress_type field 3) stays on
// the native plane instead of falling off to the ~35 µs Python route.
// Wire ids follow options.proto CompressType as protocol/baidu_std.py maps
// them: 1 = snappy, 2 = gzip, 3 = zlib ("zlib1", level 1).
//
// snappy is the block format hand-rolled here AND mirrored line-for-line
// in protocol/snappy_codec.py: both encoders run the identical greedy
// parse (same hash, same skip schedule, same emit rules), so the two
// planes produce byte-identical compressed output — the PR 2 byte-
// identity discipline extended to codecs.  Any standard snappy decoder
// reads the output; this decoder reads any standard snappy stream.
// gzip/zlib go through zlib (already linked): the gzip container is the
// deterministic header protocol/compress.py emits (mtime=0, XFL=0,
// OS=255, raw deflate level 6) so response recompression byte-matches
// the Python codec there too.
// ---------------------------------------------------------------------------

constexpr uint32_t kCompressSnappy = 1;
constexpr uint32_t kCompressGzip = 2;
constexpr uint32_t kCompressZlib1 = 3;

const char* codec_name(uint32_t id) {
  switch (id) {
    case kCompressSnappy: return "snappy";
    case kCompressGzip: return "gzip";
    case kCompressZlib1: return "zlib1";
  }
  return "?";
}

uint32_t load32le(const uint8_t* p) {
  // explicit little-endian composition: the Python twin reads
  // int.from_bytes(data[i:i+4], "little"), and the hash must match
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

void put_uvarint(std::vector<uint8_t>& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back(static_cast<uint8_t>(v) | 0x80);
    v >>= 7;
  }
  out.push_back(static_cast<uint8_t>(v));
}

// per-reactor snappy hash table: epoch-tagged slots so reuse never pays a
// per-request memset (a stale entry from an earlier compression carries a
// different epoch and reads as empty — invisible to the output bytes,
// which only depend on "present or not")
struct SnappyTable {
  std::vector<uint64_t> slots;  // (epoch << 32) | (pos + 1)  // fabricscan: owner(loop)
  uint32_t epoch = 0;  // fabricscan: owner(loop)
};

// hash-table index mask: the shift (>= 18) already caps every index
// below the table size, so masking is an identity on every input — it
// exists to make the bound explicit (and statically checkable) at the
// subscript itself.  Mask and allocation both derive from the same
// bits constant so they cannot diverge (the parity pass diffs the
// bits against snappy_codec.py's _MAX_TABLE).
constexpr uint32_t kSnappyTableBits = 14;
constexpr uint32_t kSnappyTableMask = (1u << kSnappyTableBits) - 1;

void snappy_emit_literal(std::vector<uint8_t>& out, const uint8_t* s,
                         size_t n) {
  if (n == 0) return;
  size_t n1 = n - 1;
  if (n1 < 60) {
    out.push_back(static_cast<uint8_t>(n1 << 2));
  } else if (n1 < 0x100) {
    out.push_back(60 << 2);
    out.push_back(static_cast<uint8_t>(n1));
  } else if (n1 < 0x10000) {
    out.push_back(61 << 2);
    out.push_back(static_cast<uint8_t>(n1));
    out.push_back(static_cast<uint8_t>(n1 >> 8));
  } else if (n1 < 0x1000000) {
    out.push_back(62 << 2);
    out.push_back(static_cast<uint8_t>(n1));
    out.push_back(static_cast<uint8_t>(n1 >> 8));
    out.push_back(static_cast<uint8_t>(n1 >> 16));
  } else {
    out.push_back(63 << 2);
    out.push_back(static_cast<uint8_t>(n1));
    out.push_back(static_cast<uint8_t>(n1 >> 8));
    out.push_back(static_cast<uint8_t>(n1 >> 16));
    out.push_back(static_cast<uint8_t>(n1 >> 24));
  }
  out.insert(out.end(), s, s + n);
}

void snappy_emit_copy2(std::vector<uint8_t>& out, size_t off, size_t len) {
  out.push_back(static_cast<uint8_t>(((len - 1) << 2) | 2));
  out.push_back(static_cast<uint8_t>(off));
  out.push_back(static_cast<uint8_t>(off >> 8));
}

void snappy_emit_copy(std::vector<uint8_t>& out, size_t off, size_t len) {
  // the standard 60/64 split keeps every tail element >= 4 long
  while (len >= 68) {
    snappy_emit_copy2(out, off, 64);
    len -= 64;
  }
  if (len > 64) {
    snappy_emit_copy2(out, off, 60);
    len -= 60;
  }
  if (len >= 12 || off >= 2048) {
    snappy_emit_copy2(out, off, len);
  } else {
    out.push_back(static_cast<uint8_t>(((off >> 8) << 5) |
                                       ((len - 4) << 2) | 1));
    out.push_back(static_cast<uint8_t>(off));
  }
}

// fabricscan: borrows(SnappyTable)
void snappy_compress_block(const uint8_t* data, size_t n,
                           std::vector<uint8_t>& out, SnappyTable& tbl) {
  out.clear();
  put_uvarint(out, n);
  if (n == 0) return;
  if (n < 4) {
    snappy_emit_literal(out, data, n);
    return;
  }
  size_t ts = 256;
  int shift = 24;  // 32 - log2(ts)
  while (ts < (1u << kSnappyTableBits) && ts < n) {
    ts <<= 1;
    --shift;
  }
  if (tbl.slots.size() < (1u << kSnappyTableBits))
    tbl.slots.assign(1u << kSnappyTableBits, 0);
  const uint64_t epoch = static_cast<uint64_t>(++tbl.epoch);
  size_t i = 0, lit = 0;
  uint32_t skip = 32;
  while (i + 4 <= n) {
    uint32_t h = (load32le(data + i) * 0x1E35A7BDu) >> shift;
    h &= kSnappyTableMask;  // identity: h < table size by construction
    uint64_t e = tbl.slots[h];
    tbl.slots[h] = (epoch << 32) | (i + 1);
    size_t cand = (e >> 32) == epoch ? static_cast<size_t>(
                                           (e & 0xFFFFFFFFu)) - 1
                                     : static_cast<size_t>(-1);
    if (cand != static_cast<size_t>(-1) && i - cand <= 0xFFFF &&
        memcmp(data + cand, data + i, 4) == 0) {
      snappy_emit_literal(out, data + lit, i - lit);
      size_t m = 4;
      while (i + m < n && data[cand + m] == data[i + m]) ++m;
      snappy_emit_copy(out, i - cand, m);
      i += m;
      lit = i;
      skip = 32;
    } else {
      i += skip >> 5;
      ++skip;
    }
  }
  snappy_emit_literal(out, data + lit, n - lit);
}

// 0 ok, -1 corrupt, -2 claimed/produced size beyond max_out
int snappy_decompress_block(const uint8_t* in, size_t n, size_t max_out,
                            std::vector<uint8_t>& out) {
  size_t off = 0;
  uint64_t ulen = 0;
  if (!read_varint(in, n, &off, &ulen)) return -1;
  if (ulen > max_out) return -2;
  out.clear();
  // the reserve is an optimization only: with the ceiling disabled a
  // hostile length claim must not turn into a giant up-front allocation
  // (the per-element bounds checks below still cap actual growth at the
  // input's real expansion)
  out.reserve(static_cast<size_t>(
      ulen < (1u << 20) ? ulen : (1u << 20)));
  while (off < n) {
    uint8_t tag = in[off++];
    if ((tag & 3) == 0) {  // literal
      size_t len = (tag >> 2) + 1;
      if (len > 60) {
        size_t nb = len - 60;  // 1..4 length bytes
        if (off + nb > n) return -1;
        len = 0;
        for (size_t k = 0; k < nb; ++k)
          len |= static_cast<size_t>(in[off + k]) << (8 * k);
        len += 1;
        off += nb;
      }
      if (off + len > n || out.size() + len > ulen) return -1;
      out.insert(out.end(), in + off, in + off + len);
      off += len;
    } else {  // copy
      size_t len, cop;
      if ((tag & 3) == 1) {
        if (off >= n) return -1;
        len = ((tag >> 2) & 7) + 4;
        cop = (static_cast<size_t>(tag >> 5) << 8) | in[off++];
      } else if ((tag & 3) == 2) {
        if (off + 2 > n) return -1;
        len = (tag >> 2) + 1;
        cop = in[off] | (static_cast<size_t>(in[off + 1]) << 8);
        off += 2;
      } else {
        if (off + 4 > n) return -1;
        len = (tag >> 2) + 1;
        cop = in[off] | (static_cast<size_t>(in[off + 1]) << 8) |
              (static_cast<size_t>(in[off + 2]) << 16) |
              (static_cast<size_t>(in[off + 3]) << 24);
        off += 4;
      }
      if (cop == 0 || cop > out.size() || out.size() + len > ulen) return -1;
      size_t start = out.size() - cop;
      for (size_t k = 0; k < len; ++k) out.push_back(out[start + k]);
    }
  }
  return out.size() == ulen ? 0 : -1;
}

// per-reactor codec context: reusable z_streams (deflateReset between
// responses — deflate state is ~256 KB of allocations an inline init per
// response would churn) + snappy table + the three scratch vectors the
// decompress/recompress round reuses.  One per reactor, plus throwaway
// instances on pool workers (off the reactor's hot path by definition).
struct ZCtx {
  SnappyTable snap;
  std::vector<uint8_t> dbuf;  // decompressed request payload  // fabricscan: owner(loop)
  std::vector<uint8_t> cbuf;  // recompressed response payload  // fabricscan: owner(loop)
  std::vector<uint8_t> abuf;  // request attachment staging  // fabricscan: owner(loop)
  std::vector<uint8_t> ibuf;  // contiguous compressed input staging  // fabricscan: owner(loop)
  z_stream defl_raw{};        // gzip body: raw deflate, level 6  // fabricscan: owner(loop)
  z_stream defl_zlib{};       // zlib1: zlib wrapper, level 1  // fabricscan: owner(loop)
  z_stream infl{};            // inflate, wbits swapped per container  // fabricscan: owner(loop)
  bool defl_raw_ok = false, defl_zlib_ok = false, infl_ok = false;  // fabricscan: owner(loop)
  ~ZCtx() {
    if (defl_raw_ok) deflateEnd(&defl_raw);
    if (defl_zlib_ok) deflateEnd(&defl_zlib);
    if (infl_ok) inflateEnd(&infl);
  }
};

// deterministic gzip container: the exact bytes protocol/compress.py's
// gzip codec (gzip.compress(data, 6, mtime=0) on CPython) emits — fixed
// header, raw deflate level 6 / memLevel 8, CRC32 + ISIZE trailer
// fabricscan: borrows(ZCtx)
int gzip_compress(ZCtx& z, const uint8_t* in, size_t n,
                  std::vector<uint8_t>& out) {
  if (!z.defl_raw_ok) {
    if (deflateInit2(&z.defl_raw, 6, Z_DEFLATED, -15, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK)
      return -1;
    z.defl_raw_ok = true;
  } else {
    deflateReset(&z.defl_raw);
  }
  out.clear();
  static const uint8_t hdr[10] = {0x1f, 0x8b, 8, 0, 0, 0, 0, 0, 0, 0xff};
  out.insert(out.end(), hdr, hdr + 10);
  size_t bound = deflateBound(&z.defl_raw, static_cast<uLong>(n));
  size_t base = out.size();
  out.resize(base + bound);
  z.defl_raw.next_in = const_cast<Bytef*>(in);
  z.defl_raw.avail_in = static_cast<uInt>(n);
  z.defl_raw.next_out = out.data() + base;
  z.defl_raw.avail_out = static_cast<uInt>(bound);
  if (deflate(&z.defl_raw, Z_FINISH) != Z_STREAM_END) return -1;
  out.resize(base + (bound - z.defl_raw.avail_out));
  uint32_t crc = static_cast<uint32_t>(
      crc32(0, reinterpret_cast<const Bytef*>(in), static_cast<uInt>(n)));
  for (int k = 0; k < 4; ++k) out.push_back(static_cast<uint8_t>(crc >> (8 * k)));
  uint32_t isize = static_cast<uint32_t>(n);
  for (int k = 0; k < 4; ++k)
    out.push_back(static_cast<uint8_t>(isize >> (8 * k)));
  return 0;
}

// fabricscan: borrows(ZCtx)
int zlib1_compress(ZCtx& z, const uint8_t* in, size_t n,
                   std::vector<uint8_t>& out) {
  if (!z.defl_zlib_ok) {
    if (deflateInit2(&z.defl_zlib, 1, Z_DEFLATED, 15, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK)
      return -1;
    z.defl_zlib_ok = true;
  } else {
    deflateReset(&z.defl_zlib);
  }
  out.clear();
  size_t bound = deflateBound(&z.defl_zlib, static_cast<uLong>(n));
  out.resize(bound);
  z.defl_zlib.next_in = const_cast<Bytef*>(in);
  z.defl_zlib.avail_in = static_cast<uInt>(n);
  z.defl_zlib.next_out = out.data();
  z.defl_zlib.avail_out = static_cast<uInt>(bound);
  if (deflate(&z.defl_zlib, Z_FINISH) != Z_STREAM_END) return -1;
  out.resize(bound - z.defl_zlib.avail_out);
  return 0;
}

// bounded inflate shared by gzip (wbits 31) and zlib1 (wbits 15):
// 0 ok, -1 corrupt/truncated/trailing-garbage, -2 output beyond max_out.
// Mirrors protocol/compress.py's bounded decompressobj discipline —
// including "one member, no trailing bytes" — so the planes agree on
// what parses.
// fabricscan: borrows(ZCtx)
int zlib_decompress(ZCtx& z, int wbits, const uint8_t* in, size_t n,
                    size_t max_out, std::vector<uint8_t>& out) {
  if (!z.infl_ok) {
    if (inflateInit2(&z.infl, wbits) != Z_OK) return -1;
    z.infl_ok = true;
  } else if (inflateReset2(&z.infl, wbits) != Z_OK) {
    return -1;
  }
  out.clear();
  z.infl.next_in = const_cast<Bytef*>(in);
  z.infl.avail_in = static_cast<uInt>(n);
  for (;;) {
    size_t base = out.size();
    if (base > max_out) return -2;
    // chunk = min(want, room + 1), computed without wrapping: with the
    // ceiling disabled max_out is SIZE_MAX and `room + 1` would wrap to
    // 0, starving inflate of output space forever
    size_t want = std::max<size_t>(n * 2 + 64, 16384);
    size_t room = max_out - base;
    size_t chunk = room >= want ? want : room + 1;
    out.resize(base + chunk);
    z.infl.next_out = out.data() + base;
    z.infl.avail_out = static_cast<uInt>(chunk);
    int rc = inflate(&z.infl, Z_NO_FLUSH);
    out.resize(base + (chunk - z.infl.avail_out));
    if (rc == Z_STREAM_END) break;
    if (rc != Z_OK && rc != Z_BUF_ERROR) return -1;
    if (out.size() > max_out) return -2;
    if (z.infl.avail_in == 0 && rc == Z_BUF_ERROR) return -1;  // truncated
    if (z.infl.avail_in == 0 && chunk == z.infl.avail_out) return -1;
  }
  if (out.size() > max_out) return -2;
  if (z.infl.avail_in != 0) return -1;  // trailing garbage
  return 0;
}

// 0 ok, -1 corrupt, -2 beyond max_out, -3 unknown codec id
// fabricscan: borrows(ZCtx)
int codec_decompress(ZCtx& z, uint32_t codec, const uint8_t* in, size_t n,
                     size_t max_out, std::vector<uint8_t>& out) {
  switch (codec) {
    case kCompressSnappy:
      return snappy_decompress_block(in, n, max_out, out);
    case kCompressGzip:
      return zlib_decompress(z, 15 + 16, in, n, max_out, out);
    case kCompressZlib1:
      return zlib_decompress(z, 15, in, n, max_out, out);
  }
  return -3;
}

// 0 ok (out filled), nonzero on codec trouble (caller sends uncompressed)
// fabricscan: borrows(ZCtx)
int codec_compress(ZCtx& z, uint32_t codec, const uint8_t* in, size_t n,
                   std::vector<uint8_t>& out) {
  switch (codec) {
    case kCompressSnappy:
      snappy_compress_block(in, n, out, z.snap);
      return 0;
    case kCompressGzip:
      return gzip_compress(z, in, n, out);
    case kCompressZlib1:
      return zlib1_compress(z, in, n, out);
  }
  return -3;
}

// big-endian u32 (the PRPC header's byte order)
void put_be32(uint8_t* out, uint32_t v) {
  out[0] = static_cast<uint8_t>(v >> 24);
  out[1] = static_cast<uint8_t>(v >> 16);
  out[2] = static_cast<uint8_t>(v >> 8);
  out[3] = static_cast<uint8_t>(v);
}

uint32_t get_be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

// Peek the 12-byte PRPC header off `in` without consuming — the tb_tbus_peek
// analog shared by the server cut loop and both client read paths.
// 0 = sizes filled and sane (magic, meta <= body <= max_body);
// 1 = fewer than 12 bytes buffered; -1 = not a PRPC frame / oversized.
// fabricscan: sanitizes(body_len, meta_len)
int prpc_peek(const tb_iobuf* in, uint32_t* body_len, uint32_t* meta_len,
              size_t max_body) {
  if (tb_iobuf_size(in) < kPrpcHeader) return 1;
  uint8_t hdr[kPrpcHeader];
  tb_iobuf_copy_to(in, hdr, kPrpcHeader, 0);
  uint32_t b = get_be32(hdr + 4), m = get_be32(hdr + 8);
  if (memcmp(hdr, "PRPC", 4) != 0 || m > b || b > max_body) return -1;
  *body_len = b;
  *meta_len = m;
  return 0;
}

// client-side frame size cap (the tbus client paths use the same bound)
constexpr size_t kClientMaxBody = 512u << 20;

// Append "PRPC" header + response RpcMeta, byte-identical to
// protocol/baidu_std.py pack_response: the response submessage is ALWAYS
// emitted (even empty), zero scalar fields are skipped — including
// compress_type (field 3), stamped when the response payload was
// recompressed.  The caller appends payload (+attachment) after.
void append_prpc_resp_header(tb_iobuf* out, uint64_t cid, uint32_t error_code,
                             const char* error_text, size_t text_len,
                             size_t payload_len, size_t att_len,
                             uint32_t compress) {
  uint8_t meta[512];
  // RpcResponseMeta submessage
  uint8_t sub[400];
  size_t sn = 0;
  if (error_code != 0) {
    sub[sn++] = 0x08;  // field 1, varint
    sn += put_varint(sub + sn, error_code);
  }
  if (text_len > sizeof sub - sn - 12) text_len = sizeof sub - sn - 12;
  if (text_len > 0) {
    sub[sn++] = 0x12;  // field 2, len-delimited
    sn += put_varint(sub + sn, text_len);
    memcpy(sub + sn, error_text, text_len);
    sn += text_len;
  }
  size_t mn = 0;
  meta[mn++] = 0x12;  // RpcMeta.response (field 2)
  mn += put_varint(meta + mn, sn);
  memcpy(meta + mn, sub, sn);
  mn += sn;
  if (compress != 0) {
    meta[mn++] = 0x18;  // compress_type (field 3)
    mn += put_varint(meta + mn, compress);
  }
  if (cid != 0) {
    meta[mn++] = 0x20;  // correlation_id (field 4)
    mn += put_varint(meta + mn, cid);
  }
  if (att_len != 0) {
    meta[mn++] = 0x28;  // attachment_size (field 5)
    mn += put_varint(meta + mn, att_len);
  }
  uint8_t hdr[kPrpcHeader];
  hdr[0] = 'P';
  hdr[1] = 'R';
  hdr[2] = 'P';
  hdr[3] = 'C';
  put_be32(hdr + 4, static_cast<uint32_t>(mn + payload_len + att_len));
  put_be32(hdr + 8, static_cast<uint32_t>(mn));
  // header + meta contiguously (one small append)
  uint8_t scratch[sizeof hdr + sizeof meta];
  memcpy(scratch, hdr, sizeof hdr);
  memcpy(scratch + sizeof hdr, meta, mn);
  tb_iobuf_append(out, scratch, sizeof hdr + mn);
}

// Full client-side PRPC request: `sub` is the pre-encoded RpcRequestMeta
// submessage; the wrapper adds compress_type + correlation_id +
// attachment_size + authentication_data in the field order
// protocol/baidu_std.py emits (1, 3, 4, 5, 7), then payload + attachment.
// The payload is compressed by the CALLER (the Python seam shares one
// codec with the server, so the bytes match the wire's compress_type).
void pack_prpc_request(tb_iobuf* out, const void* sub, size_t sub_len,
                       const void* payload, size_t payload_len,
                       const void* att, size_t att_len, uint64_t cid,
                       uint32_t compress, const void* auth,
                       size_t auth_len) {
  std::vector<uint8_t> meta;
  meta.reserve(sub_len + auth_len + 32);
  uint8_t tmp[10];
  meta.push_back(0x0A);  // RpcMeta.request (field 1)
  meta.insert(meta.end(), tmp, tmp + put_varint(tmp, sub_len));
  const uint8_t* sp = static_cast<const uint8_t*>(sub);
  meta.insert(meta.end(), sp, sp + sub_len);
  if (compress != 0) {
    meta.push_back(0x18);  // compress_type (field 3)
    meta.insert(meta.end(), tmp, tmp + put_varint(tmp, compress));
  }
  if (cid != 0) {
    meta.push_back(0x20);
    meta.insert(meta.end(), tmp, tmp + put_varint(tmp, cid));
  }
  if (att_len != 0) {
    meta.push_back(0x28);
    meta.insert(meta.end(), tmp, tmp + put_varint(tmp, att_len));
  }
  if (auth_len != 0) {
    meta.push_back(0x3A);  // authentication_data (field 7)
    meta.insert(meta.end(), tmp, tmp + put_varint(tmp, auth_len));
    const uint8_t* ap = static_cast<const uint8_t*>(auth);
    meta.insert(meta.end(), ap, ap + auth_len);
  }
  uint8_t hdr[kPrpcHeader];
  hdr[0] = 'P';
  hdr[1] = 'R';
  hdr[2] = 'P';
  hdr[3] = 'C';
  put_be32(hdr + 4,
           static_cast<uint32_t>(meta.size() + payload_len + att_len));
  put_be32(hdr + 8, static_cast<uint32_t>(meta.size()));
  tb_iobuf_append(out, hdr, sizeof hdr);
  tb_iobuf_append(out, meta.data(), meta.size());
  if (payload_len) tb_iobuf_append(out, payload, payload_len);
  if (att_len) tb_iobuf_append(out, att, att_len);
}

// ---------------------------------------------------------------------------
// frame pack helpers
// ---------------------------------------------------------------------------

// append the 32-byte header (+ small meta) contiguously
void append_header(tb_iobuf* out, const void* meta, size_t meta_len,
                   size_t body_rest_len, uint32_t crc, uint32_t cid_lo,
                   uint32_t cid_hi, uint32_t flags, uint32_t error_code) {
  uint32_t h[8];
  h[0] = kMagic;
  h[1] = static_cast<uint32_t>(meta_len + body_rest_len);
  h[2] = flags;
  h[3] = cid_lo;
  h[4] = cid_hi;
  h[5] = static_cast<uint32_t>(meta_len);
  h[6] = crc;
  h[7] = error_code;
  if (meta_len > 0 && meta_len <= 4096) {
    char scratch[4096 + sizeof h];
    memcpy(scratch, h, sizeof h);
    memcpy(scratch + sizeof h, meta, meta_len);
    tb_iobuf_append(out, scratch, sizeof h + meta_len);
  } else {
    tb_iobuf_append(out, h, sizeof h);
    if (meta_len) tb_iobuf_append(out, meta, meta_len);
  }
}

// whole frame from contiguous caller memory
void pack_flat(tb_iobuf* out, const void* meta, size_t meta_len,
               const void* payload, size_t payload_len, const void* att,
               size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
               uint32_t flags, uint32_t error_code) {
  if (meta_len) flags |= kFlagHasMeta;
  uint32_t crc = tb_crc32c(0, meta, meta_len);
  if (flags & kFlagBodyCrc) {
    crc = tb_crc32c(crc, payload, payload_len);
    crc = tb_crc32c(crc, att, att_len);
  }
  append_header(out, meta, meta_len, payload_len + att_len, crc, cid_lo,
                cid_hi, flags, error_code);
  if (payload_len) tb_iobuf_append(out, payload, payload_len);
  if (att_len) tb_iobuf_append(out, att, att_len);
}

// ---------------------------------------------------------------------------
// connection registry (token = versioned respool id; global resolve mutex +
// per-conn refcount gate the fd against cross-thread teardown)
// ---------------------------------------------------------------------------

struct NetLoop;

struct PollObj {
  int kind;  // 0 conn, 1 listener, 2 wake  // fabricscan: owner(init)
  explicit PollObj(int k) : kind(k) {}
  virtual ~PollObj() = default;
};

struct NetConn : PollObj {
  NetConn() : PollObj(0) {}
  int fd = -1;  // fabricscan: owner(init)
  uint64_t token = 0;  // fabricscan: owner(init)
  NetLoop* loop = nullptr;  // fabricscan: owner(init)
  tb_server* srv = nullptr;  // fabricscan: owner(init)
  tb_iobuf* rbuf = nullptr;  // fabricscan: owner(loop)
  tb_iobuf* wbuf = nullptr;  // fabricscan: owner(shared)
  std::mutex wmu;
  bool want_out = false;  // fabricscan: owner(shared)
  bool sniffed = false;  // fabricscan: owner(loop)
  int proto = 0;  // kProtoTbus / kProtoPrpc once sniffed  // fabricscan: owner(loop)
  // one-entry meta memo: a client pumping one method sends byte-identical
  // meta every frame — remember the resolved native method for those exact
  // bytes and skip the JSON scan + name join + flatmap probe (the
  // preferred-protocol-memory idea applied to routing).  On PRPC conns the
  // memo key is the RpcRequestMeta SUBMESSAGE (the correlation id lives
  // outside it, so the submessage stays byte-identical across a pump).
  std::string memo_meta;  // fabricscan: owner(loop)
  uint64_t memo_idx = 0;  // fabricscan: owner(loop)
  long memo_attachment = -1;  // -1 = no memo  // fabricscan: owner(loop)
  long memo_timeout = 0;      // timeout_ms of the memoized meta bytes  // fabricscan: owner(loop)
  // name-keyed second memo for TRACED PRPC frames: their submessage
  // bytes change every call (span ids), so the byte-keyed memo above
  // can never hit — this one compares the decoded service/method slices
  // instead, keeping a traced flood at two memcmps per frame instead of
  // a per-request flatmap probe + name join (the prpc_traced_pump_ns
  // gate's margin lives here)
  std::string memo_svc;  // fabricscan: owner(loop)
  std::string memo_mth;  // fabricscan: owner(loop)
  long memo_name_idx = -1;  // -1 = no memo  // fabricscan: owner(loop)
  // stamped once per readable burst (deadline shed baseline + idle reap);
  // written by the loop thread, read by tb_server_close_idle callers
  std::atomic<uint64_t> last_active_ms{0};
  // per-connection auth verdict cache (brpc's first-frame auth): set by
  // the loop thread after a native verify, or from a Python thread via
  // tb_conn_set_authenticated when the Python route verified first
  std::atomic<bool> authenticated{false};
  std::atomic<bool> dead{false};
  std::atomic<int> refs{0};
};

std::mutex g_conn_mu;
tb_respool* g_conn_pool = nullptr;  // slots hold NetConn*  // fabricscan: owner(shared)

// fabricscan: role(init)
uint64_t conn_register(NetConn* c) {
  std::lock_guard<std::mutex> g(g_conn_mu);
  if (g_conn_pool == nullptr) g_conn_pool = tb_respool_create(sizeof(void*));
  uint64_t id = 0;
  void* slot = tb_respool_get(g_conn_pool, &id);
  *static_cast<NetConn**>(slot) = c;
  c->token = id;
  return id;
}

NetConn* conn_resolve(uint64_t token) {
  std::lock_guard<std::mutex> g(g_conn_mu);
  if (g_conn_pool == nullptr) return nullptr;
  void* slot = tb_respool_address(g_conn_pool, token);
  if (slot == nullptr) return nullptr;
  NetConn* c = *static_cast<NetConn**>(slot);
  if (c == nullptr || c->dead.load(std::memory_order_acquire)) return nullptr;
  c->refs.fetch_add(1, std::memory_order_acq_rel);
  return c;
}

void conn_unref(NetConn* c) { c->refs.fetch_sub(1, std::memory_order_acq_rel); }

// retire the token and wait out foreign holders; afterwards the caller owns
// the conn exclusively (the deferred-close discipline of sock.py _io_refs)
void conn_retire(NetConn* c) {
  {
    std::lock_guard<std::mutex> g(g_conn_mu);
    c->dead.store(true, std::memory_order_release);
    tb_respool_return(g_conn_pool, c->token);
  }
  while (c->refs.load(std::memory_order_acquire) > 0) usleep(50);
}

// ---------------------------------------------------------------------------
// server structures
// ---------------------------------------------------------------------------

struct Wake : PollObj {
  Wake() : PollObj(2) {}
  int fd = -1;  // fabricscan: owner(init)
};

struct Listener : PollObj {
  Listener() : PollObj(1) {}
  int fd = -1;  // fabricscan: owner(loop)
};

struct TelemetryRing;
struct WorkDeque;

struct NetLoop {
  int id = 0;  // reactor index (telemetry records carry it)  // fabricscan: owner(init)
  int epfd = -1;  // fabricscan: owner(init)
  Wake wake;
  // per-reactor listener: every reactor binds the same port with
  // SO_REUSEPORT (multi-reactor servers) so accepts run in parallel and
  // lame-duck teardown happens on each owning loop thread; fd -1 when
  // the reactor has no listener (single-reactor, or REUSEPORT fallback)
  Listener listener;
  std::thread th;
  std::atomic<bool> stopping{false};
  std::vector<NetConn*> conns;  // fabricscan: owner(shared)
  std::mutex conns_mu;  // guards conns (loop thread + stop-time sweep)
  // per-reactor data pools: the burst response batch and per-frame body
  // scratch are owned by the reactor and reused across bursts — nothing
  // on the cut/pack path allocates per burst or crosses a lock
  tb_iobuf* batch = nullptr;  // fabricscan: owner(loop)
  tb_iobuf* scratch = nullptr;  // fabricscan: owner(loop)
  // per-reactor codec context: reusable z_streams, snappy table, and the
  // decompress/recompress scratch vectors (zero cross-reactor sharing)
  ZCtx* zctx = nullptr;  // fabricscan: owner(init)
  // per-reactor counters (tb_server_reactor_stats / stats roll-up)
  std::atomic<uint64_t> live_conns{0};
  std::atomic<uint64_t> native_reqs{0};
  // per-reactor completion ring: loop-thread (and pool-worker) producers
  // never contend with another reactor's — set once before listen
  std::atomic<TelemetryRing*> telemetry{nullptr};
  // per-reactor work-stealing deque (dispatch pool enabled only)
  WorkDeque* deque = nullptr;  // fabricscan: owner(init)
  // loop-thread-only: inline user-callback dispatches in the current
  // readable burst (the queue-depth pressure signal for pool deferral)
  int inline_burst = 0;  // fabricscan: owner(loop)
};

struct NativeMethod {
  int kind;  // fabricscan: owner(init)
  uint32_t index = 0;  // position in tb_server::native_methods (telemetry key)  // fabricscan: owner(init)
  // runtime-retunable (tb_server_set_native_max_concurrency stores from
  // the application thread while loop threads load per request)
  std::atomic<uint32_t> max_concurrency{0};
  std::atomic<uint32_t> nprocessing{0};
  std::atomic<uint64_t> nreq{0};
  std::atomic<uint64_t> nerr{0};
  // long-running: with a dispatch pool enabled, requests to this method
  // always defer to the pool (tb_server_set_native_long_running)
  std::atomic<uint32_t> long_running{0};
  std::string full_name;  // fabricscan: owner(init)
  tb_native_fn fn = nullptr;  // kKindCallback  // fabricscan: owner(init)
  void* ud = nullptr;  // fabricscan: owner(init)
};

struct ErrorCodes {
  // mirrors utils/status.py ErrorCode (the cross-plane error constants)
  uint32_t enomethod = 1002;
  uint32_t elimit = 2004;
  uint32_t erequest = 1003;
  uint32_t edeadline = 4004;
  uint32_t erpcauth = 1004;
};

// the EDEADLINE response text — MUST match utils/status.py berror(
// EDEADLINE) byte-for-byte: the acceptance contract is that a shed
// answered natively is indistinguishable from one answered by the
// Python route
constexpr const char kDeadlineShedText[] = "Deadline expired before dispatch";

// same contract for the native auth rejection: berror(ERPCAUTH)
constexpr const char kUnauthorizedText[] = "Unauthorized";

// ---------------------------------------------------------------------------
// telemetry ring: bounded lock-free queue of completion records (Vyukov's
// bounded MPMC shape — per-cell sequence numbers; producers are the loop
// threads, the consumer is the Python drain).  A full ring DROPS the
// record and counts it: the hot path pays one CAS and a few stores, never
// a wait.  This is the seam that keeps natively-dispatched requests
// observable (per-method latency, sampled rpcz spans, limiter feedback)
// without putting the interpreter back on the fast path — the reference
// feeds bvar/rpcz from inside every protocol's ProcessRequest the same
// way (span.cpp, baidu_rpc_protocol.cpp:307-503).
// ---------------------------------------------------------------------------

// Hot-path timestamp: rdtsc where available (~9 ns vs ~22 ns for the
// vDSO clock — two reads per request make the difference measurable on a
// ~1 µs pump).  Records carry raw ticks; the drain converts them to
// CLOCK_MONOTONIC ns with a calibration refined on every drain, so the
// conversion cost lives entirely on the observer's side.
inline uint64_t telemetry_ticks() {
#if defined(__x86_64__)
  return __rdtsc();
#else
  return tb_monotonic_ns();
#endif
}

// The record ABI is checked THREE ways (header struct, ctypes mirror,
// numpy drain dtype) by fabriclint; this sizeof anchor is the fourth,
// diffed against native_plane.py's _TELEMETRY_RECORD_BYTES by
// fabricscan's plane-parity pass so a grown record cannot ship with a
// stale drain overlay.
static_assert(sizeof(tb_telemetry_record) == 64,
              "tb_telemetry_record ABI is 64 bytes (header/ctypes/numpy "
              "move in lockstep)");

// sampled-word bit layout (mirrored in native_plane._consume_records):
// bit 0 = rpcz sample election, bits 1-2 = request codec id, bit 3 =
// the sampled bit arrived ON THE WIRE (head-based coherent sampling)
constexpr uint32_t kTeleSampleBit = 1u;
constexpr uint32_t kTeleCodecShift = 1;
constexpr uint32_t kTeleWireForced = 8u;

struct TelemetryCell {
  std::atomic<uint64_t> seq{0};
  tb_telemetry_record rec;  // fabricscan: owner(shared)
};

struct TelemetryRing {
  TelemetryCell* cells = nullptr;  // fabricscan: owner(init)
  size_t mask = 0;  // fabricscan: owner(init)
  uint32_t sample_every = 0;  // every Nth record carries sampled=1; 0 = never  // fabricscan: owner(init)
  // tick->ns calibration anchor (taken at creation, ratio refined per
  // drain); on non-x86 ticks ARE ns and the identity ratio holds
  uint64_t cal_ticks0 = 0;  // fabricscan: owner(init)
  uint64_t cal_mono0 = 0;  // fabricscan: owner(init)
  std::atomic<double> ns_per_tick{1.0};
  alignas(64) std::atomic<uint64_t> enqueue_pos{0};
  alignas(64) std::atomic<uint64_t> dequeue_pos{0};
  alignas(64) std::atomic<uint64_t> dropped{0};
  ~TelemetryRing() { delete[] cells; }
};

void telemetry_push(TelemetryRing* r, tb_telemetry_record& rec) {
  TelemetryCell* cell;
  uint64_t pos = r->enqueue_pos.load(std::memory_order_relaxed);
  for (;;) {
    cell = &r->cells[pos & r->mask];
    uint64_t seq = cell->seq.load(std::memory_order_acquire);
    int64_t dif = static_cast<int64_t>(seq) - static_cast<int64_t>(pos);
    if (dif == 0) {
      if (r->enqueue_pos.compare_exchange_weak(pos, pos + 1,
                                               std::memory_order_relaxed))
        break;
    } else if (dif < 0) {
      // consumer hasn't freed this slot yet: the ring is full — drop, the
      // overflow counter is the observer's signal to drain faster
      r->dropped.fetch_add(1, std::memory_order_relaxed);
      return;
    } else {
      pos = r->enqueue_pos.load(std::memory_order_relaxed);
    }
  }
  // the claimed position doubles as the sample counter (exact 1/N
  // without a second atomic on the hot path; drops never claim one).
  // Bit 0 only: the producer's codec/forced bits (>> 1) ride through
  // untouched.  A wire-forced record (bit 3: the head-based sampled bit
  // arrived on the wire) OVERRIDES the local election — the edge's
  // decision propagates like the deadline, so a trace sampled there
  // yields spans at every hop instead of an incoherent scatter.
  rec.sampled =
      (rec.sampled & ~kTeleSampleBit) |
      ((rec.sampled & kTeleWireForced) != 0 ||
               (r->sample_every != 0 && pos % r->sample_every == 0)
           ? kTeleSampleBit
           : 0u);
  cell->rec = rec;
  cell->seq.store(pos + 1, std::memory_order_release);
}

long telemetry_pop(TelemetryRing* r, tb_telemetry_record* out, size_t max) {
  size_t n = 0;
  while (n < max) {
    uint64_t pos = r->dequeue_pos.load(std::memory_order_relaxed);
    TelemetryCell* cell = &r->cells[pos & r->mask];
    uint64_t seq = cell->seq.load(std::memory_order_acquire);
    int64_t dif = static_cast<int64_t>(seq) - static_cast<int64_t>(pos + 1);
    if (dif < 0) break;  // empty (or a producer mid-publish: next drain)
    if (dif > 0) continue;  // another drain raced us past this slot
    if (!r->dequeue_pos.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed))
      continue;
    out[n++] = cell->rec;
    cell->seq.store(pos + r->mask + 1, std::memory_order_release);
  }
  return static_cast<long>(n);
}

// per-request routing context shared by the tbus and PRPC dispatch loops
struct ReqCtx {
  int wire;            // kProtoTbus / kProtoPrpc
  uint32_t cid_lo;
  uint32_t cid_hi;
  uint32_t resp_flags; // tbus: response flags to echo (body-crc bit)
  long attachment;     // request attachment size (PRPC echo re-stamps it)
  long timeout_ms;     // propagated deadline budget (0 = none rides this)
  uint32_t compress;   // request compress_type (0 = plain; PRPC only)
  // wire-propagated trace context: the ids land in the telemetry record
  // (the drain parents this hop's span into the caller's trace), the
  // sampled bit forces the record's rpcz election (coherent sampling)
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint32_t traced_sampled = 0;
};

// ---------------------------------------------------------------------------
// work-stealing deque (Chase–Lev) + dispatch pool: the reactor loop thread
// is the single owner (push at the bottom; pop only during stop-time
// drain), pool workers steal the top.  A full deque rejects the push and
// the caller runs the work inline — backpressure, never blocking the
// reactor.  This is the bthread M:N shape specialized to "slow native
// user methods must not stall their reactor's cut/pack work" (reference
// task_group.cc steal loops, SURVEY L3).
// ---------------------------------------------------------------------------

struct WorkDeque {
  explicit WorkDeque(size_t cap) {
    size_t c = 64;
    while (c < cap && c < (1u << 20)) c <<= 1;
    cells = new std::atomic<uint64_t>[c];
    mask = c - 1;
  }
  ~WorkDeque() { delete[] cells; }
  alignas(64) std::atomic<int64_t> top{0};     // thieves CAS this
  alignas(64) std::atomic<int64_t> bottom{0};  // owner only
  std::atomic<uint64_t>* cells = nullptr;
  size_t mask = 0;  // fabricscan: owner(init)

  bool push(uint64_t v) {  // owner only
    int64_t b = bottom.load(std::memory_order_relaxed);
    int64_t t = top.load(std::memory_order_acquire);
    if (b - t > static_cast<int64_t>(mask)) return false;  // full
    cells[b & static_cast<int64_t>(mask)].store(v, std::memory_order_relaxed);
    // release: a thief acquiring `bottom` sees the cell store
    bottom.store(b + 1, std::memory_order_release);
    return true;
  }

  bool pop(uint64_t* out) {  // owner only (stop-time drain)
    int64_t b = bottom.load(std::memory_order_relaxed) - 1;
    bottom.store(b, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t t = top.load(std::memory_order_relaxed);
    if (t > b) {  // empty
      bottom.store(b + 1, std::memory_order_relaxed);
      return false;
    }
    uint64_t v = cells[b & static_cast<int64_t>(mask)].load(
        std::memory_order_relaxed);
    if (t == b) {
      // last element: race the thieves for it via top
      if (!top.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                       std::memory_order_relaxed)) {
        bottom.store(b + 1, std::memory_order_relaxed);
        return false;  // a thief won
      }
      bottom.store(b + 1, std::memory_order_relaxed);
    }
    *out = v;
    return true;
  }

  bool steal(uint64_t* out) {  // any thief
    int64_t t = top.load(std::memory_order_acquire);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t b = bottom.load(std::memory_order_acquire);
    if (t >= b) return false;  // empty
    // safe stale read: push() refuses to reuse a cell until top has
    // advanced past it, so a concurrent overwrite implies our CAS fails
    uint64_t v = cells[t & static_cast<int64_t>(mask)].load(
        std::memory_order_relaxed);
    if (!top.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                     std::memory_order_relaxed))
      return false;  // lost the race (owner pop or another thief)
    *out = v;
    return true;
  }

  long size() const {
    int64_t b = bottom.load(std::memory_order_relaxed);
    int64_t t = top.load(std::memory_order_relaxed);
    return b > t ? static_cast<long>(b - t) : 0;
  }
};

// one deferred native dispatch: everything the worker needs to run the
// method, pack the response in the right wire protocol, and append the
// completion record into the OWNING reactor's telemetry ring
struct WorkTask {
  NativeMethod* nm = nullptr;  // fabricscan: owner(worker)
  tb_server* srv = nullptr;  // fabricscan: owner(worker)
  NetLoop* loop = nullptr;  // owning reactor (ring + reactor_id)  // fabricscan: owner(worker)
  uint64_t conn_token = 0;  // fabricscan: owner(worker)
  ReqCtx rc{};  // fabricscan: owner(worker)
  uint32_t limited = 0;    // nprocessing held across queue + run  // fabricscan: owner(worker)
  uint64_t t_start = 0;    // telemetry ticks at dispatch entry (0 = off)  // fabricscan: owner(worker)
  uint64_t arrival_ms = 0; // frame's burst-arrival stamp (deadline base)  // fabricscan: owner(worker)
  size_t req_len = 0;  // fabricscan: owner(worker)
  char* req = nullptr;     // contiguous request copy (worker frees)  // fabricscan: owner(worker)
};

struct DispatchPool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<uint64_t> pending{0};
  std::atomic<bool> stopping{false};
};

}  // namespace

struct tb_server {
  std::vector<NetLoop*> loops;  // fabricscan: owner(init)
  int port = 0;  // fabricscan: owner(init)
  std::atomic<size_t> next_loop{0};
  tb_frame_fn frame_cb = nullptr;  // fabricscan: owner(init)
  void* frame_ctx = nullptr;  // fabricscan: owner(init)
  tb_handoff_fn handoff_cb = nullptr;  // fabricscan: owner(init)
  void* handoff_ctx = nullptr;  // fabricscan: owner(init)
  tb_closed_fn closed_cb = nullptr;  // fabricscan: owner(init)
  void* closed_ctx = nullptr;  // fabricscan: owner(init)
  size_t max_body = 512u << 20;  // fabricscan: owner(init)
  ErrorCodes errs;  // fabricscan: owner(init)
  tb_flatmap* methods = nullptr;  // key -> index into native_methods  // fabricscan: owner(init)
  std::vector<NativeMethod*> native_methods;  // fabricscan: owner(init)
  std::atomic<uint64_t> accepted{0};
  std::atomic<uint64_t> cb_frames{0};
  std::atomic<uint64_t> handoffs{0};
  // requests answered EDEADLINE because their propagated budget expired
  // before dispatch (the deadline_shed_count feed for native ports)
  std::atomic<uint64_t> deadline_sheds{0};
  // ---- production-shaped traffic knobs (pre-listen configuration) ----
  // response compression floor: decompressed payloads below it answer
  // uncompressed (native_compress_min_bytes; the Python route applies
  // the same floor so the planes stay byte-identical)
  size_t compress_min = 0;  // fabricscan: owner(init)
  // decompressed-size ceiling (max_decompress_bytes): a tiny bomb must
  // not expand unbounded into server memory on either plane
  size_t max_decompress = 256u << 20;  // fabricscan: owner(init)
  // auth seam: a verifier callback (tb_server_set_auth — the arbitrary-
  // Authenticator deferral, one interpreter crossing per CONNECTION) or
  // a constant-time token table (tb_server_set_auth_tokens — the
  // steady-state path never enters the interpreter).  Verified once per
  // connection, verdict cached on the conn (brpc's first-frame auth).
  tb_auth_fn auth_fn = nullptr;  // fabricscan: owner(init)
  void* auth_ud = nullptr;  // fabricscan: owner(init)
  std::vector<std::string> auth_tokens;  // fabricscan: owner(init)
  std::atomic<bool> auth_enabled{false};
  std::atomic<uint64_t> auth_rejects{0};
  // compressed-traffic byte counters (native_compress_bytes_saved feed):
  // request wire/raw and response raw/wire
  std::atomic<uint64_t> c_in_wire{0};
  std::atomic<uint64_t> c_in_raw{0};
  std::atomic<uint64_t> c_out_raw{0};
  std::atomic<uint64_t> c_out_wire{0};
  // lame-duck: stop accepting while existing connections drain; EVERY
  // reactor tears down its own listener on its own loop thread at its
  // next wakeup (per-reactor listeners via SO_REUSEPORT)
  std::atomic<bool> accept_paused{false};
  std::atomic<bool> stopped{false};
  bool listening = false;       // pre-listen-only knobs gate on this  // fabricscan: owner(init)
  bool telemetry_enabled = false;  // per-reactor rings live in the loops  // fabricscan: owner(init)
  // work-stealing dispatch pool (tb_server_set_dispatch_pool): null =
  // every native method runs inline on its reactor
  DispatchPool* pool = nullptr;  // fabricscan: owner(init)
  int pool_workers = 0;  // fabricscan: owner(init)
};

namespace {

uint64_t method_key(const char* name, size_t n) {
  uint64_t lo = tb_crc32c(0, name, n);
  uint64_t hi =
      crc32(0, reinterpret_cast<const Bytef*>(name), static_cast<uInt>(n));
  return lo | (hi << 32);
}

// constant-time credential compare: the loop always walks every token
// byte, and a length mismatch folds into the same accumulator instead of
// short-circuiting — a timing probe learns nothing about how much of a
// token it matched
int ct_token_match(const std::string& tok, const char* a, size_t alen) {
  unsigned diff = static_cast<unsigned>(tok.size() ^ alen);
  for (size_t i = 0; i < tok.size(); ++i) {
    uint8_t b = i < alen ? static_cast<uint8_t>(a[i]) : 0;
    diff |= static_cast<uint8_t>(tok[i]) ^ b;
  }
  return diff == 0 ? 1 : 0;
}

// verify a connection's first-frame credential.  Token table first (pure
// C, constant-time, no interpreter); else the registered verifier (for a
// Python Authenticator this is ONE GIL crossing per connection — the
// verdict caches on the conn).  Auth enabled with neither = fail closed.
bool verify_auth(tb_server* s, NetConn* c, const char* data, size_t len) {
  if (!s->auth_tokens.empty()) {
    int ok = 0;
    for (const std::string& t : s->auth_tokens)
      ok |= ct_token_match(t, data, len);
    return ok != 0;
  }
  if (s->auth_fn != nullptr) {
    char ip[64] = {0};
    int port = 0;
    sockaddr_in addr{};
    socklen_t alen = sizeof addr;
    if (getpeername(c->fd, reinterpret_cast<sockaddr*>(&addr), &alen) == 0 &&
        addr.sin_family == AF_INET) {
      inet_ntop(AF_INET, &addr.sin_addr, ip, sizeof ip);
      port = ntohs(addr.sin_port);
    }
    return s->auth_fn(s->auth_ud, data, len, ip, port) == 0;
  }
  return false;
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

// ---- write path (per-conn mutex; any thread) ----

// under c->wmu: drain wbuf to the fd, arming/disarming EPOLLOUT
// fabricscan: locked
void conn_flush_locked(NetConn* c) {
  while (tb_iobuf_size(c->wbuf) > 0) {
    long rc = tb_iobuf_cut_into_fd(c->wbuf, c->fd, 4u << 20);
    if (rc > 0) continue;
    if (rc == -EINTR) continue;
    if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) {
      if (!c->want_out) {
        c->want_out = true;
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.ptr = static_cast<PollObj*>(c);
        epoll_ctl(c->loop->epfd, EPOLL_CTL_MOD, c->fd, &ev);
      }
      return;
    }
    // hard error: shutdown so the loop thread reaps via EPOLLHUP
    shutdown(c->fd, SHUT_RDWR);
    return;
  }
  if (c->want_out) {
    c->want_out = false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(c);
    epoll_ctl(c->loop->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

void conn_queue_iobuf(NetConn* c, const tb_iobuf* data) {
  std::lock_guard<std::mutex> g(c->wmu);
  tb_iobuf_append_iobuf(c->wbuf, data);
  conn_flush_locked(c);
}

// loop-thread-only teardown; fd closes only after foreign refs drain
void conn_destroy(NetConn* c, bool close_fd) {
  epoll_ctl(c->loop->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  uint64_t token = c->token;
  conn_retire(c);
  if (close_fd && c->fd >= 0) close(c->fd);
  if (c->loop) c->loop->live_conns.fetch_sub(1);
  // close_fd==false means handoff: the connection lives on in Python
  if (close_fd && c->srv && c->srv->closed_cb != nullptr)
    c->srv->closed_cb(c->srv->closed_ctx, token);
  {
    std::lock_guard<std::mutex> g(c->loop->conns_mu);
    auto& v = c->loop->conns;
    for (size_t i = 0; i < v.size(); ++i)
      if (v[i] == c) {
        v[i] = v.back();
        v.pop_back();
        break;
      }
  }
  tb_iobuf_destroy(c->rbuf);
  tb_iobuf_destroy(c->wbuf);
  delete c;
}

// ---- server-side frame dispatch ----

// append an error response frame into `out` (flushed with the batch)
void append_error(tb_iobuf* out, const ReqCtx& rc, uint32_t code,
                  const char* text) {
  if (rc.wire == kProtoPrpc) {
    append_prpc_resp_header(
        out, static_cast<uint64_t>(rc.cid_lo) |
                 (static_cast<uint64_t>(rc.cid_hi) << 32),
        code, text, strlen(text), 0, 0, 0);
    return;
  }
  char meta[256];
  int n = snprintf(meta, sizeof meta, "{\"error_text\":\"%s\"}", text);
  if (n < 0) n = 0;
  pack_flat(out, meta, static_cast<size_t>(n), nullptr, 0, nullptr, 0,
            rc.cid_lo, rc.cid_hi, kFlagResponse, code);
}

// ONE completion-record fill for every dispatch path (inline, pool run,
// pool shed): the 64-byte ABI has a single writer, so a layout change
// cannot silently diverge between the inline and deferred planes.
void push_completion_record(TelemetryRing* tr, NativeMethod* nm,
                            uint32_t err, uint64_t t_start, uint64_t cid64,
                            size_t req_len, size_t resp_len,
                            int reactor_id, const ReqCtx& rc) {
  if (tr == nullptr) return;
  const uint32_t codec = rc.compress;
  tb_telemetry_record rec;
  rec.method_idx = nm->index;
  rec.error_code = err;
  rec.start_ns = t_start;  // raw ticks; the drain converts to ns
  rec.latency_ns = telemetry_ticks() - t_start;
  rec.correlation_id = cid64;
  rec.request_size = static_cast<uint32_t>(
      req_len > 0xFFFFFFFFu ? 0xFFFFFFFFu : req_len);
  rec.response_size = static_cast<uint32_t>(
      resp_len > 0xFFFFFFFFu ? 0xFFFFFFFFu : resp_len);
  // bits 1-2 carry the request's codec id (0 = uncompressed); bit 0 is
  // the sample election telemetry_push stamps from the claimed position
  // (bit 3 — the wire-propagated sampled bit — forces it there).
  // Out-of-enum wire values (rejected EREQUEST upstream) record as 0 —
  // a plain mask would alias compress_type=9 onto "snappy" in /rpcz.
  rec.sampled = ((codec <= 3u ? codec : 0u) << kTeleCodecShift) |
                (rc.traced_sampled != 0 ? kTeleWireForced : 0u);
  rec.reactor_id = static_cast<uint32_t>(reactor_id);
  // wire trace context: the drain parents this hop's server span into
  // the CALLER's trace (fresh ids are minted only when these are 0)
  rec.trace_id = rc.trace_id;
  rec.span_id = rc.span_id;
  telemetry_push(tr, rec);
}

// Pack a user-callback result (or its error) into `out` in the
// request's wire protocol — shared by the inline dispatch and the pool
// worker, so the two planes answer byte-identically by construction.
// `z`/`srv` drive response recompression: a PRPC request that arrived
// compressed gets its response compressed with the same codec when the
// payload clears the floor (the Python _send_response discipline).
// fabricscan: borrows(ZCtx)
void pack_callback_result(tb_iobuf* out, NativeMethod* nm, const ReqCtx& rc,
                          uint64_t cid64, int rc2, const char* resp,
                          size_t resp_len, uint32_t* t_err, size_t* t_resp,
                          tb_server* srv, ZCtx* z) {
  if (rc2 != 0) {
    nm->nerr.fetch_add(1, std::memory_order_relaxed);
    append_error(out, rc, static_cast<uint32_t>(rc2),
                 "native method failed");
    *t_err = static_cast<uint32_t>(rc2);
  } else if (rc.wire == kProtoPrpc) {
    if (rc.compress != 0 && resp_len > 0 && resp_len >= srv->compress_min &&
        codec_compress(*z, rc.compress,
                       reinterpret_cast<const uint8_t*>(resp), resp_len,
                       z->cbuf) == 0) {
      srv->c_out_raw.fetch_add(resp_len, std::memory_order_relaxed);
      srv->c_out_wire.fetch_add(z->cbuf.size(), std::memory_order_relaxed);
      append_prpc_resp_header(out, cid64, 0, nullptr, 0, z->cbuf.size(), 0,
                              rc.compress);
      if (!z->cbuf.empty())
        tb_iobuf_append(out, z->cbuf.data(), z->cbuf.size());
      *t_resp = z->cbuf.size();
    } else {
      append_prpc_resp_header(out, cid64, 0, nullptr, 0, resp_len, 0, 0);
      if (resp_len) tb_iobuf_append(out, resp, resp_len);
      *t_resp = resp_len;
    }
  } else {
    uint32_t flags = kFlagResponse | rc.resp_flags;
    uint32_t crc = tb_crc32c(0, nullptr, 0);
    if (flags & kFlagBodyCrc) crc = tb_crc32c(crc, resp, resp_len);
    append_header(out, nullptr, 0, resp_len, crc, rc.cid_lo, rc.cid_hi,
                  flags, 0);
    if (resp_len) tb_iobuf_append(out, resp, resp_len);
    *t_resp = resp_len;
  }
}

// run one deferred task on a pool worker: user method, response pack in
// the request's wire protocol, completion record into the OWNING
// reactor's ring.  The connection is token-addressed — it may have died
// while the task sat in the deque (the response is then dropped, exactly
// like a death between dispatch and flush).
void run_pool_task(WorkTask* t) {
  NativeMethod* nm = t->nm;
  const uint64_t cid64 = static_cast<uint64_t>(t->rc.cid_lo) |
                         (static_cast<uint64_t>(t->rc.cid_hi) << 32);
  tb_iobuf* out = tb_iobuf_create();
  uint32_t t_err = 0;
  size_t t_resp = 0;
  // the propagated deadline keeps ticking while the task waits in the
  // deque: a budget that expired in the queue is shed EDEADLINE here —
  // running the (slow, that's why it deferred) method for a caller that
  // already gave up would burn worker capacity exactly when overloaded
  if (t->rc.timeout_ms > 0 &&
      now_ms() - t->arrival_ms >= static_cast<uint64_t>(t->rc.timeout_ms)) {
    t->srv->deadline_sheds.fetch_add(1, std::memory_order_relaxed);
    nm->nerr.fetch_add(1, std::memory_order_relaxed);
    append_error(out, t->rc, t->srv->errs.edeadline, kDeadlineShedText);
    t_err = t->srv->errs.edeadline;
  } else {
    char* resp = nullptr;
    size_t resp_len = 0;
    int rc2 = nm->fn(nm->ud, t->req, t->req_len, &resp, &resp_len);
    // worker-local codec context: the reactor's ZCtx belongs to its loop
    // thread, and a deferred (slow) method is off the hot path anyway
    ZCtx z;
    pack_callback_result(out, nm, t->rc, cid64, rc2, resp, resp_len,
                         &t_err, &t_resp, t->srv, &z);
    free(resp);
  }
  NetConn* c = conn_resolve(t->conn_token);
  if (c != nullptr) {
    conn_queue_iobuf(c, out);
    conn_unref(c);
  }
  tb_iobuf_destroy(out);
  if (t->limited) nm->nprocessing.fetch_sub(1);
  if (t->t_start != 0)  // dispatch entry: queue wait is in the latency
    push_completion_record(
        t->loop->telemetry.load(std::memory_order_acquire), nm, t_err,
        t->t_start, cid64, t->req_len, t_resp, t->loop->id, t->rc);
  free(t->req);
  delete t;
}

// fabricscan: role(worker)
void pool_worker(tb_server* s, size_t widx) {
  DispatchPool* p = s->pool;
  const size_t nloops = s->loops.size();
  for (;;) {
    uint64_t v = 0;
    bool got = false;
    // steal from the preferred deque first, then sweep the others — the
    // "steal on empty" half of the Chase–Lev discipline
    for (size_t k = 0; k < nloops && !got; ++k)
      got = s->loops[(widx + k) % nloops]->deque->steal(&v);
    if (got) {
      p->pending.fetch_sub(1, std::memory_order_relaxed);
      run_pool_task(reinterpret_cast<WorkTask*>(v));
      continue;
    }
    std::unique_lock<std::mutex> lk(p->mu);
    if (p->stopping.load(std::memory_order_acquire)) return;
    if (p->pending.load(std::memory_order_acquire) > 0) continue;  // rescan
    p->cv.wait_for(lk, std::chrono::milliseconds(50));
    if (p->stopping.load(std::memory_order_acquire)) return;
  }
}

// budget of inline user-callback dispatches per readable burst: past it,
// further callback-kind frames of the burst defer to the pool even when
// not flagged long-running (queue-depth pressure — a flood of one method
// must not monopolize the reactor's cut/pack slot)
constexpr int kInlineBurstBudget = 32;

// Native method kinds: the response is built and appended into the burst's
// batch without ever leaving C++ — the whole ProcessRpcRequest/user code/
// SendRpcResponse round (baidu_rpc_protocol.cpp:307-503,136) for these
// methods is native.  `out` collects every response of one readable burst;
// the caller queues it once (one writev per burst, not per request).
// `body` stays owned by the caller (the reactor's reusable scratch —
// creating/destroying an iobuf handle per request was measurable on the
// pump's ns/req floor); echo ref-shares its blocks into `out` before the
// caller clears it.
void run_native(NetConn* c, NativeMethod* nm, const ReqCtx& rc,
                tb_iobuf* body, tb_iobuf* out) {
  nm->nreq.fetch_add(1, std::memory_order_relaxed);
  c->loop->native_reqs.fetch_add(1, std::memory_order_relaxed);
  const uint64_t cid64 = static_cast<uint64_t>(rc.cid_lo) |
                         (static_cast<uint64_t>(rc.cid_hi) << 32);
  // telemetry: one record per dispatched request into the reactor's own
  // MPSC ring — the only hot-path cost is clock reads + one CAS
  TelemetryRing* tr = c->loop->telemetry.load(std::memory_order_acquire);
  const uint64_t t_start = tr != nullptr ? telemetry_ticks() : 0;
  const size_t req_len = tr != nullptr ? tb_iobuf_size(body) : 0;
  auto telemetry_done = [&](uint32_t err, size_t resp_len) {
    push_completion_record(tr, nm, err, t_start, cid64, req_len, resp_len,
                           c->loop->id, rc);
  };
  // deadline shed (reference server-side timeout_ms handling): budget
  // expired between the frame's ARRIVAL (burst read stamp) and this
  // dispatch — behind queued frames of the burst or a slow native
  // method — is answered EDEADLINE without running the method.  The
  // response text matches utils/status.py berror(EDEADLINE) so native
  // and Python sheds are byte-identical.
  if (rc.timeout_ms > 0) {
    uint64_t arrived = c->last_active_ms.load(std::memory_order_relaxed);
    if (now_ms() - arrived >= static_cast<uint64_t>(rc.timeout_ms)) {
      c->srv->deadline_sheds.fetch_add(1, std::memory_order_relaxed);
      nm->nerr.fetch_add(1, std::memory_order_relaxed);
      append_error(out, rc, c->srv->errs.edeadline, kDeadlineShedText);
      telemetry_done(c->srv->errs.edeadline, 0);
      return;  // caller owns body
    }
  }
  // snapshot ONCE: a runtime retune between the admission fetch_add and
  // the completion fetch_sub must see a consistent gate, or the counter
  // leaks (limit dropped to 0 mid-request) / underflows (raised from 0)
  const uint32_t limit = nm->max_concurrency.load(std::memory_order_relaxed);
  if (limit && nm->nprocessing.fetch_add(1) >= limit) {
    nm->nprocessing.fetch_sub(1);
    nm->nerr.fetch_add(1, std::memory_order_relaxed);
    append_error(out, rc, c->srv->errs.elimit, "concurrency limit reached");
    telemetry_done(c->srv->errs.elimit, 0);
    return;  // caller owns body
  }
  // native codec round (PRPC): decompress the payload IN PLACE so every
  // downstream consumer — the pool copy, the echo, a user callback —
  // sees raw bytes, exactly like the Python route's pre-handler
  // decompress.  Rejects are answered EREQUEST, with the Python route's
  // deterministic texts (unknown codec, ceiling) byte-for-byte.
  ZCtx& z = *c->loop->zctx;
  if (rc.compress != 0) {
    const size_t wlen = tb_iobuf_size(body);
    const size_t att = static_cast<size_t>(rc.attachment);
    const size_t pay = wlen - att;
    z.ibuf.resize(pay);
    if (pay) tb_iobuf_copy_to(body, z.ibuf.data(), pay, 0);
    int drc = codec_decompress(z, rc.compress, z.ibuf.data(), pay,
                               c->srv->max_decompress, z.dbuf);
    if (drc != 0) {
      char text[160];
      if (drc == -3) {
        snprintf(text, sizeof text,
                 "decompress failed: unknown compression codec 'wire-%u'",
                 rc.compress);
      } else if (drc == -2) {
        snprintf(text, sizeof text,
                 "decompress failed: decompressed size exceeds "
                 "max_decompress_bytes (%zu)",
                 c->srv->max_decompress);
      } else {
        snprintf(text, sizeof text, "decompress failed: corrupt %s body",
                 codec_name(rc.compress));
      }
      nm->nerr.fetch_add(1, std::memory_order_relaxed);
      append_error(out, rc, c->srv->errs.erequest, text);
      if (limit) nm->nprocessing.fetch_sub(1);
      telemetry_done(c->srv->errs.erequest, 0);
      return;  // caller owns body
    }
    c->srv->c_in_wire.fetch_add(pay, std::memory_order_relaxed);
    c->srv->c_in_raw.fetch_add(z.dbuf.size(), std::memory_order_relaxed);
    // rebuild the body: decompressed payload + untouched attachment
    z.abuf.resize(att);
    if (att) tb_iobuf_copy_to(body, z.abuf.data(), att, pay);
    tb_iobuf_clear(body);
    if (!z.dbuf.empty())
      tb_iobuf_append(body, z.dbuf.data(), z.dbuf.size());
    if (att) tb_iobuf_append(body, z.abuf.data(), att);
  }
  // work-stealing deferral: user methods flagged long-running — or
  // arriving behind a queue-depth-pressured burst — hand off to the
  // dispatch pool so one slow handler can't stall this reactor's
  // cut/pack work.  Admission (nprocessing above) spans queue + run; the
  // worker appends the telemetry record at completion.  A full deque
  // falls through and runs inline: backpressure, never blocking.
  DispatchPool* pool = c->srv->pool;
  if (pool != nullptr && nm->kind == kKindCallback &&
      (nm->long_running.load(std::memory_order_relaxed) != 0 ||
       c->loop->inline_burst >= kInlineBurstBudget)) {
    size_t blen = tb_iobuf_size(body);
    char* req = static_cast<char*>(malloc(blen ? blen : 1));
    if (req != nullptr) {
      if (blen) tb_iobuf_copy_to(body, req, blen, 0);
      WorkTask* t = new WorkTask();
      t->nm = nm;
      t->srv = c->srv;
      t->loop = c->loop;
      t->conn_token = c->token;
      t->rc = rc;
      t->limited = limit ? 1u : 0u;
      t->t_start = tr != nullptr ? t_start : 0;
      t->arrival_ms = c->last_active_ms.load(std::memory_order_relaxed);
      t->req_len = blen;
      t->req = req;
      if (c->loop->deque->push(reinterpret_cast<uint64_t>(t))) {
        pool->pending.fetch_add(1, std::memory_order_release);
        {
          // empty critical section pairs with the worker's wait: a
          // sleeper that checked pending before our fetch_add cannot
          // miss the notify (this path is already off the 544 ns lane)
          std::lock_guard<std::mutex> g(pool->mu);
        }
        pool->cv.notify_one();
        return;  // caller owns body; worker answers
      }
      delete t;
      free(req);
    }
  }
  if (nm->kind == kKindCallback) ++c->loop->inline_burst;
  uint32_t flags = kFlagResponse | rc.resp_flags;
  char meta[64];
  size_t meta_len = 0;
  uint32_t t_err = 0;  // what telemetry records for this request
  size_t t_resp = 0;
  if (nm->kind == kKindEcho) {
    size_t blen = tb_iobuf_size(body);
    if (rc.wire == kProtoPrpc && rc.compress != 0) {
      // recompress the echoed payload with the request's codec, floor
      // honored (tiny payloads answer uncompressed — the reference's
      // response_compress_type discipline); the attachment travels
      // uncompressed like the Python route.  dbuf still holds the
      // decompressed payload from the codec round above.
      const size_t att = static_cast<size_t>(rc.attachment);
      const size_t raw_len = blen - att;
      uint32_t out_codec =
          raw_len > 0 && raw_len >= c->srv->compress_min &&
                  codec_compress(z, rc.compress, z.dbuf.data(), raw_len,
                                 z.cbuf) == 0
              ? rc.compress
              : 0;
      if (out_codec != 0) {
        c->srv->c_out_raw.fetch_add(raw_len, std::memory_order_relaxed);
        c->srv->c_out_wire.fetch_add(z.cbuf.size(),
                                     std::memory_order_relaxed);
        append_prpc_resp_header(out, cid64, 0, nullptr, 0, z.cbuf.size(),
                                att, out_codec);
        if (!z.cbuf.empty())
          tb_iobuf_append(out, z.cbuf.data(), z.cbuf.size());
        if (att) tb_iobuf_append(out, z.abuf.data(), att);
        t_resp = z.cbuf.size() + att;
      } else {
        append_prpc_resp_header(out, cid64, 0, nullptr, 0, raw_len, att, 0);
        tb_iobuf_append_iobuf(out, body);  // decompressed payload + att
        t_resp = blen;
      }
      if (limit) nm->nprocessing.fetch_sub(1);
      telemetry_done(0, t_resp);
      return;  // caller owns body
    }
    if (rc.wire == kProtoPrpc) {
      append_prpc_resp_header(out, cid64, 0, nullptr, 0,
                              blen - static_cast<size_t>(rc.attachment),
                              static_cast<size_t>(rc.attachment), 0);
    } else {
      if (rc.attachment > 0) {
        int n = snprintf(meta, sizeof meta, "{\"attachment_size\":%ld}",
                         rc.attachment);
        meta_len = n > 0 ? static_cast<size_t>(n) : 0;
      }
      if (meta_len) flags |= kFlagHasMeta;
      uint32_t crc = tb_crc32c(0, meta, meta_len);
      if (flags & kFlagBodyCrc) crc = tb_iobuf_crc32c(body, crc, 0, blen);
      append_header(out, meta, meta_len, blen, crc, rc.cid_lo, rc.cid_hi,
                    flags, 0);
    }
    tb_iobuf_append_iobuf(out, body);  // zero-copy: request refs shared
    t_resp = blen;
  } else if (nm->kind == kKindCallback) {
    // contiguous request for the C ABI (stack buffer for small bodies)
    size_t blen = tb_iobuf_size(body);
    char stackbuf[4096];
    char* req = blen <= sizeof stackbuf ? stackbuf
                                        : static_cast<char*>(malloc(blen));
    if (req == nullptr) {  // OOM on a huge body: an error response, not a crash
      nm->nerr.fetch_add(1, std::memory_order_relaxed);
      append_error(out, rc, c->srv->errs.erequest,
                   "request too large to stage");
      if (limit) nm->nprocessing.fetch_sub(1);
      telemetry_done(c->srv->errs.erequest, 0);
      return;  // caller owns body
    }
    if (blen) tb_iobuf_copy_to(body, req, blen, 0);
    char* resp = nullptr;
    size_t resp_len = 0;
    int rc2 = nm->fn(nm->ud, req, blen, &resp, &resp_len);
    if (req != stackbuf) free(req);
    pack_callback_result(out, nm, rc, cid64, rc2, resp, resp_len, &t_err,
                         &t_resp, c->srv, &z);
    free(resp);
  } else {  // nop
    if (rc.wire == kProtoPrpc) {
      append_prpc_resp_header(out, cid64, 0, nullptr, 0, 0, 0, 0);
    } else {
      append_header(out, nullptr, 0, 0, tb_crc32c(0, nullptr, 0), rc.cid_lo,
                    rc.cid_hi, flags, 0);
    }
  }
  // body is the caller's reusable scratch: NOT destroyed here (the echo
  // kind ref-shared its blocks into `out`; clear just drops this handle)
  if (limit) nm->nprocessing.fetch_sub(1);
  telemetry_done(t_err, t_resp);
}

enum class FrameStatus { kOk, kHandoff, kKilled };

void do_handoff(NetConn* c) {
  tb_server* s = c->srv;
  s->handoffs.fetch_add(1, std::memory_order_relaxed);
  size_t n = tb_iobuf_size(c->rbuf);
  char* buffered = static_cast<char*>(malloc(n ? n : 1));
  if (n) tb_iobuf_copy_to(c->rbuf, buffered, n, 0);
  int fd = c->fd;
  tb_handoff_fn cb = s->handoff_cb;
  void* ctx = s->handoff_ctx;
  conn_destroy(c, /*close_fd=*/false);
  if (cb != nullptr) {
    cb(ctx, fd, buffered, n);  // callee owns fd from here
  } else {
    close(fd);
  }
  free(buffered);
}

FrameStatus process_frames_tbus(NetConn* c);
FrameStatus process_frames_prpc(NetConn* c);

FrameStatus process_frames(NetConn* c) {
  if (!c->sniffed) {
    if (tb_iobuf_size(c->rbuf) < 4) return FrameStatus::kOk;
    uint32_t magic = 0;
    tb_iobuf_copy_to(c->rbuf, &magic, 4, 0);
    if (magic == kMagic) {
      c->proto = kProtoTbus;
    } else if (magic == kMagicPrpc) {
      // baidu_std spoken natively: no interpreter, no fd handoff (the
      // handoff fallback still owns every OTHER protocol)
      c->proto = kProtoPrpc;
    } else {
      do_handoff(c);
      return FrameStatus::kHandoff;
    }
    c->sniffed = true;
  }
  return c->proto == kProtoPrpc ? process_frames_prpc(c)
                                : process_frames_tbus(c);
}

FrameStatus process_frames_tbus(NetConn* c) {
  tb_server* s = c->srv;
  // One response batch per readable burst: native responses append here
  // and flush with ONE conn_queue_iobuf (one writev) at every exit —
  // the per-request syscall was the dominant cost of the old shape.
  // Both buffers are the REACTOR's data pool (created once per loop,
  // cleared per burst): the hot path allocates nothing and never shares
  // them with another reactor.
  tb_iobuf* batch = c->loop->batch;
  tb_iobuf* scratch = c->loop->scratch;  // per-frame body, cleared and reused
  auto flush = [&](FrameStatus st) {
    // every exit flushes: even a killed connection sends the responses of
    // the frames that parsed cleanly before the bad one
    if (tb_iobuf_size(batch) > 0) conn_queue_iobuf(c, batch);
    tb_iobuf_clear(batch);
    tb_iobuf_clear(scratch);
    return st;
  };
  for (;;) {
    tb_tbus_hdr hdr;
    int rc = tb_tbus_peek(c->rbuf, &hdr);
    if (rc == 1) return flush(FrameStatus::kOk);
    if (rc == -1 || hdr.meta_len > hdr.body_len || hdr.body_len > s->max_body) {
      flush(FrameStatus::kKilled);  // earlier valid responses go out
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    if (tb_iobuf_size(c->rbuf) < kHeader + hdr.body_len)
      return flush(FrameStatus::kOk);
    char mstack[4096];
    std::string mheap;
    char* mptr = nullptr;
    if (hdr.meta_len > 0) {
      if (hdr.meta_len <= sizeof mstack) {
        mptr = mstack;
      } else {
        mheap.resize(hdr.meta_len);
        mptr = &mheap[0];
      }
    }
    rc = tb_tbus_cut(c->rbuf, &hdr, mptr, scratch);
    if (rc != 0) {  // crc mismatch / malformed: the stream can't re-sync
      flush(FrameStatus::kKilled);
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    const char* cb_meta = mptr != nullptr ? mptr : mstack;  // never null
    // native fast path: plain request frame whose meta is fully
    // understood, on a connection whose auth (if the server wants any)
    // already settled — tbus credentials ride the JSON meta's extra
    // object, which the Python route owns, so an unproven connection's
    // frames route there until server_check marks it (the mark flows
    // back via tb_conn_set_authenticated)
    if ((hdr.flags & (kFlagResponse | kFlagStream)) == 0 &&
        (!s->auth_enabled.load(std::memory_order_relaxed) ||
         c->authenticated.load(std::memory_order_relaxed))) {
      if (c->memo_attachment >= 0 && hdr.meta_len == c->memo_meta.size() &&
          memcmp(cb_meta, c->memo_meta.data(), hdr.meta_len) == 0 &&
          c->memo_attachment <= static_cast<long>(tb_iobuf_size(scratch))) {
        ReqCtx rc2{kProtoTbus, hdr.cid_lo, hdr.cid_hi,
                   hdr.flags & kFlagBodyCrc, c->memo_attachment,
                   c->memo_timeout, 0};
        run_native(c, s->native_methods[c->memo_idx], rc2, scratch, batch);
        tb_iobuf_clear(scratch);
        continue;
      }
      MetaLite ml = scan_meta(cb_meta, hdr.meta_len);
      if (ml.ok && !ml.to_python &&
          ml.attachment <= static_cast<long>(tb_iobuf_size(scratch))) {
        char full[256];
        size_t sl = ml.service.size(), mn = ml.method.size();
        if (sl + 1 + mn < sizeof full) {
          memcpy(full, ml.service.data(), sl);
          full[sl] = '.';
          memcpy(full + sl + 1, ml.method.data(), mn);
          size_t fn = sl + 1 + mn;
          full[fn] = '\0';
          uint64_t idx = 0;
          if (s->methods != nullptr &&
              tb_flatmap_get(s->methods, method_key(full, fn), &idx) == 1 &&
              s->native_methods[idx]->full_name == full) {
            // traced metas never seed the memo (see the PRPC loop: the
            // ids change per call and the memo'd ReqCtx carries none)
            if (ml.trace_id == 0 && ml.span_id == 0 && ml.log_id == 0 &&
                ml.parent_span_id == 0 && ml.sampled == 0) {
              c->memo_meta.assign(cb_meta, hdr.meta_len);
              c->memo_idx = idx;
              c->memo_attachment = ml.attachment;
              c->memo_timeout = ml.timeout_ms;
            }
            ReqCtx rc2{kProtoTbus, hdr.cid_lo, hdr.cid_hi,
                       hdr.flags & kFlagBodyCrc, ml.attachment,
                       ml.timeout_ms, 0,
                       ml.trace_id, ml.span_id, ml.sampled};
            run_native(c, s->native_methods[idx], rc2, scratch, batch);
            tb_iobuf_clear(scratch);
            continue;
          }
        }
      }
    }
    // python route (responses, streams, compressed, unknown methods —
    // admission/stats/errors stay consistent with the Python server path)
    s->cb_frames.fetch_add(1, std::memory_order_relaxed);
    if (s->frame_cb == nullptr) {
      if ((hdr.flags & kFlagResponse) == 0) {
        ReqCtx rc2{kProtoTbus, hdr.cid_lo, hdr.cid_hi, 0, 0, 0, 0};
        append_error(batch, rc2, s->errs.enomethod, "no such method");
      }
      tb_iobuf_clear(scratch);
      continue;
    }
    // the Python callee owns its body: hand it a fresh handle that
    // ref-shares the scratch's blocks (no byte copy), then reuse scratch
    tb_iobuf* body = tb_iobuf_create();
    tb_iobuf_append_iobuf(body, scratch);
    tb_iobuf_clear(scratch);
    s->frame_cb(s->frame_ctx, c->token, hdr.cid_lo, hdr.cid_hi,
                hdr.flags |
                    (c->authenticated.load(std::memory_order_relaxed)
                         ? kFlagConnAuthed
                         : 0),
                hdr.error_code, cb_meta, hdr.meta_len, body);
  }
}

// baidu_std cut + dispatch loop: the PRPC counterpart of the tbus loop
// above (reference ParseRpcMessage + ProcessRpcRequest,
// baidu_rpc_protocol.cpp:92-503), same batching/scratch discipline — one
// writev per readable burst, native methods answered without the
// interpreter, everything else one frame callback into Python.
FrameStatus process_frames_prpc(NetConn* c) {
  tb_server* s = c->srv;
  tb_iobuf* batch = c->loop->batch;      // reactor data pool (see tbus loop)
  tb_iobuf* scratch = c->loop->scratch;
  auto flush = [&](FrameStatus st) {
    if (tb_iobuf_size(batch) > 0) conn_queue_iobuf(c, batch);
    tb_iobuf_clear(batch);
    tb_iobuf_clear(scratch);
    return st;
  };
  for (;;) {
    uint32_t body_len = 0, meta_len = 0;
    int prc = prpc_peek(c->rbuf, &body_len, &meta_len, s->max_body);
    if (prc == 1) return flush(FrameStatus::kOk);
    if (prc != 0) {
      flush(FrameStatus::kKilled);  // earlier valid responses go out
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    if (tb_iobuf_size(c->rbuf) < kPrpcHeader + body_len)
      return flush(FrameStatus::kOk);
    char mstack[4096];
    std::string mheap;
    char* mptr = mstack;
    if (meta_len > sizeof mstack) {
      mheap.resize(meta_len);
      mptr = &mheap[0];
    }
    if (meta_len) tb_iobuf_copy_to(c->rbuf, mptr, meta_len, kPrpcHeader);
    tb_iobuf_popn(c->rbuf, kPrpcHeader + meta_len);
    tb_iobuf_cutn(c->rbuf, scratch, body_len - meta_len);
    PrpcMeta pm = scan_prpc_meta(mptr, meta_len);
    if (!pm.ok) {
      // meta that doesn't parse as proto2 at all: the stream is hopeless
      // (the Python plane's FatalParseError path)
      flush(FrameStatus::kKilled);
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    const long blen = static_cast<long>(tb_iobuf_size(scratch));
    if (!pm.is_response && !pm.to_python && pm.attachment <= blen) {
      // auth gate (reference: VerifyRpcRequest before ProcessRpcRequest,
      // baidu_rpc_protocol.cpp): verified ONCE per connection, verdict
      // cached on the conn; rejects answer the berror(ERPCAUTH) frame
      // byte-identically to the Python route and keep the conn open
      if (s->auth_enabled.load(std::memory_order_relaxed) &&
          !c->authenticated.load(std::memory_order_relaxed)) {
        if (verify_auth(s, c, pm.auth, pm.auth_len)) {
          c->authenticated.store(true, std::memory_order_relaxed);
        } else {
          s->auth_rejects.fetch_add(1, std::memory_order_relaxed);
          ReqCtx rc{kProtoPrpc, static_cast<uint32_t>(pm.cid),
                    static_cast<uint32_t>(pm.cid >> 32), 0, 0, 0, 0};
          append_error(batch, rc, s->errs.erpcauth, kUnauthorizedText);
          tb_iobuf_clear(scratch);
          continue;
        }
      }
      ReqCtx rc{kProtoPrpc, static_cast<uint32_t>(pm.cid),
                static_cast<uint32_t>(pm.cid >> 32), 0, pm.attachment,
                pm.timeout_ms, pm.compress,
                pm.trace_id, pm.span_id, pm.sampled};
      const bool traced = pm.trace_id != 0 || pm.span_id != 0 ||
                          pm.log_id != 0 || pm.parent_span_id != 0 ||
                          pm.sampled != 0;
      // memo keyed on the request submessage (cid lives outside it).
      // Traced submessages never enter the memo: the ids change per
      // call, and a byte-identical traced repeat hitting a memo seeded
      // by an UNTRACED frame would drop its trace context — so traced
      // frames always take the full lookup (they still stay native).
      if (c->memo_attachment >= 0 &&
          pm.req_sub_len == c->memo_meta.size() && pm.req_sub_len > 0 &&
          memcmp(pm.req_sub, c->memo_meta.data(), pm.req_sub_len) == 0) {
        run_native(c, s->native_methods[c->memo_idx], rc, scratch, batch);
        tb_iobuf_clear(scratch);
        continue;
      }
      // traced frames: the per-call span ids defeat the byte memo, so
      // route through the NAME-keyed memo (two memcmps) before paying
      // the full name join + flatmap probe
      if (traced && c->memo_name_idx >= 0 &&
          pm.svc_len == c->memo_svc.size() &&
          pm.mth_len == c->memo_mth.size() && pm.svc != nullptr &&
          pm.mth != nullptr &&
          memcmp(pm.svc, c->memo_svc.data(), pm.svc_len) == 0 &&
          memcmp(pm.mth, c->memo_mth.data(), pm.mth_len) == 0) {
        run_native(c, s->native_methods[c->memo_name_idx], rc, scratch,
                   batch);
        tb_iobuf_clear(scratch);
        continue;
      }
      char full[256];
      size_t sl = pm.svc_len, mn = pm.mth_len;
      if (pm.svc != nullptr && pm.mth != nullptr && sl + 1 + mn < sizeof full) {
        memcpy(full, pm.svc, sl);
        full[sl] = '.';
        memcpy(full + sl + 1, pm.mth, mn);
        size_t fn = sl + 1 + mn;
        full[fn] = '\0';
        uint64_t idx = 0;
        if (s->methods != nullptr &&
            tb_flatmap_get(s->methods, method_key(full, fn), &idx) == 1 &&
            s->native_methods[idx]->full_name == full) {
          if (!traced) {
            c->memo_meta.assign(pm.req_sub, pm.req_sub_len);
            c->memo_idx = idx;
            c->memo_attachment = 0;  // >=0 marks the memo live (PRPC mode)
          } else {
            c->memo_svc.assign(pm.svc, pm.svc_len);
            c->memo_mth.assign(pm.mth, pm.mth_len);
            c->memo_name_idx = static_cast<long>(idx);
          }
          run_native(c, s->native_methods[idx], rc, scratch, batch);
          tb_iobuf_clear(scratch);
          continue;
        }
      }
    }
    // python route: responses, compressed, traced, auth'd, streamed or
    // unknown-method frames — flag 0x100 tells the callee the meta is
    // RpcMeta proto bytes and the connection answers in PRPC
    s->cb_frames.fetch_add(1, std::memory_order_relaxed);
    uint32_t cb_flags = kFlagWirePrpc | (pm.is_response ? kFlagResponse : 0) |
                        (c->authenticated.load(std::memory_order_relaxed)
                             ? kFlagConnAuthed
                             : 0);
    if (s->frame_cb == nullptr) {
      if (!pm.is_response) {
        ReqCtx rc{kProtoPrpc, static_cast<uint32_t>(pm.cid),
                  static_cast<uint32_t>(pm.cid >> 32), 0, 0, 0, 0};
        append_error(batch, rc, s->errs.enomethod, "no such method");
      }
      tb_iobuf_clear(scratch);
      continue;
    }
    tb_iobuf* body = tb_iobuf_create();
    tb_iobuf_append_iobuf(body, scratch);
    tb_iobuf_clear(scratch);
    s->frame_cb(s->frame_ctx, c->token, static_cast<uint32_t>(pm.cid),
                static_cast<uint32_t>(pm.cid >> 32), cb_flags, pm.error_code,
                mptr, meta_len, body);
  }
}

void conn_readable(NetConn* c) {
  // one clock read per readable burst: the arrival baseline for the
  // deadline shed in run_native AND the idle-reap activity stamp
  c->last_active_ms.store(now_ms(), std::memory_order_relaxed);
  c->loop->inline_burst = 0;  // fresh pressure budget per readable burst
  size_t burst = tb_iobuf_read_burst();
  bool eof = false;
  for (;;) {
    long rc = tb_iobuf_append_from_fd(c->rbuf, c->fd, burst);
    if (rc > 0) {
      if (static_cast<size_t>(rc) < burst) break;
      continue;
    }
    if (rc == -EAGAIN || rc == -EWOULDBLOCK) break;
    if (rc == -EINTR) continue;
    eof = true;  // 0 = EOF; other negatives = read error
    break;
  }
  if (tb_iobuf_size(c->rbuf) > 0) {
    FrameStatus st = process_frames(c);
    if (st != FrameStatus::kOk) return;  // conn already gone
  }
  if (eof) conn_destroy(c, true);
}

void accept_ready(tb_server* s, Listener* lst) {
  for (;;) {
    if (s->accept_paused.load(std::memory_order_acquire)) return;
    int fd = accept4(lst->fd, nullptr, nullptr,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) return;  // EAGAIN / EMFILE / EINTR: next event retries
    set_nodelay(fd);
    s->accepted.fetch_add(1, std::memory_order_relaxed);
    NetConn* c = new NetConn();
    c->last_active_ms.store(now_ms(), std::memory_order_relaxed);
    c->fd = fd;
    c->srv = s;
    // sharded at accept time, never migrates: round-robin assignment
    // keeps the distribution even regardless of which reactor's
    // SO_REUSEPORT listener the kernel handed the connection to
    c->loop = s->loops[s->next_loop.fetch_add(1) % s->loops.size()];
    c->loop->live_conns.fetch_add(1, std::memory_order_relaxed);
    c->rbuf = tb_iobuf_create();
    c->wbuf = tb_iobuf_create();
    conn_register(c);
    {
      std::lock_guard<std::mutex> g(c->loop->conns_mu);
      c->loop->conns.push_back(c);
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(c);
    if (epoll_ctl(c->loop->epfd, EPOLL_CTL_ADD, fd, &ev) != 0)
      conn_destroy(c, true);
  }
}

// fabricscan: role(loop)
void loop_run(tb_server* s, NetLoop* l) {
  epoll_event evs[128];
  while (!l->stopping.load(std::memory_order_acquire)) {
    int n = epoll_wait(l->epfd, evs, 128, 500);
    // lame-duck: every reactor owns its own listener's epoll
    // registration, so the actual teardown runs HERE on the owning loop
    // thread (no cross-thread epoll_ctl/close race with a concurrent
    // accept_ready)
    if (s->accept_paused.load(std::memory_order_acquire) &&
        l->listener.fd >= 0) {
      epoll_ctl(l->epfd, EPOLL_CTL_DEL, l->listener.fd, nullptr);
      close(l->listener.fd);
      l->listener.fd = -1;
    }
    for (int i = 0; i < n; ++i) {
      PollObj* o = static_cast<PollObj*>(evs[i].data.ptr);
      if (o == nullptr) continue;
      if (o->kind == 2) {  // wake
        uint64_t v;
        ssize_t r = read(static_cast<Wake*>(o)->fd, &v, sizeof v);
        (void)r;
        continue;
      }
      if (o->kind == 1) {  // listener (this reactor's own)
        accept_ready(s, static_cast<Listener*>(o));
        continue;
      }
      NetConn* c = static_cast<NetConn*>(o);
      uint32_t e = evs[i].events;
      if (e & (EPOLLERR | EPOLLHUP)) {
        conn_destroy(c, true);
        continue;
      }
      if (e & EPOLLOUT) {
        std::lock_guard<std::mutex> g(c->wmu);
        conn_flush_locked(c);
      }
      if (e & EPOLLIN) conn_readable(c);
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// server C API
// ---------------------------------------------------------------------------

// fabricscan: role(init)
tb_server* tb_server_create(int nloops) {
  if (nloops < 1) nloops = 1;
  tb_server* s = new tb_server();
  s->methods = tb_flatmap_create(64);
  for (int i = 0; i < nloops; ++i) {
    NetLoop* l = new NetLoop();
    l->id = i;
    l->epfd = epoll_create1(EPOLL_CLOEXEC);
    l->wake.fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    // reactor-owned data pools, reused across every burst the loop cuts
    l->batch = tb_iobuf_create();
    l->scratch = tb_iobuf_create();
    l->zctx = new ZCtx();
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(&l->wake);
    epoll_ctl(l->epfd, EPOLL_CTL_ADD, l->wake.fd, &ev);
    s->loops.push_back(l);
  }
  return s;
}

int tb_server_num_reactors(const tb_server* s) {
  return static_cast<int>(s->loops.size());
}

// fabricscan: role(init)
int tb_server_set_dispatch_pool(tb_server* s, int nworkers) {
  // pre-listen only: loop threads read s->pool / deques without fences
  if (s->listening) return -1;
  s->pool_workers = nworkers > 0 ? nworkers : 0;
  return 0;
}

int tb_server_set_native_long_running(tb_server* s, const char* full_name,
                                      int on) {
  for (NativeMethod* nm : s->native_methods) {
    if (nm->full_name == full_name) {
      nm->long_running.store(on ? 1u : 0u, std::memory_order_relaxed);
      return 0;
    }
  }
  return -1;
}

// fabricscan: role(init)
void tb_server_set_frame_cb(tb_server* s, tb_frame_fn cb, void* ctx) {
  s->frame_cb = cb;
  s->frame_ctx = ctx;
}

// fabricscan: role(init)
void tb_server_set_handoff_cb(tb_server* s, tb_handoff_fn cb, void* ctx) {
  s->handoff_cb = cb;
  s->handoff_ctx = ctx;
}

// fabricscan: role(init)
void tb_server_set_closed_cb(tb_server* s, tb_closed_fn cb, void* ctx) {
  s->closed_cb = cb;
  s->closed_ctx = ctx;
}

// fabricscan: role(init)
void tb_server_set_max_body(tb_server* s, size_t bytes) { s->max_body = bytes; }

// fabricscan: role(init)
void tb_server_set_compress_min_bytes(tb_server* s, size_t bytes) {
  s->compress_min = bytes;
}

// fabricscan: role(init)
void tb_server_set_max_decompress(tb_server* s, size_t bytes) {
  s->max_decompress = bytes != 0 ? bytes : static_cast<size_t>(-1);
}

// fabricscan: role(init)
int tb_server_set_auth(tb_server* s, tb_auth_fn fn, void* ud) {
  // pre-listen only: loop threads read auth_fn/auth_tokens without fences
  if (s->listening) return -1;
  s->auth_fn = fn;
  s->auth_ud = ud;
  s->auth_enabled.store(fn != nullptr || !s->auth_tokens.empty(),
                        std::memory_order_relaxed);
  return 0;
}

// fabricscan: role(init)
int tb_server_set_auth_tokens(tb_server* s, const char* blob,
                              size_t blob_len) {
  // blob = repeated [u32 LE length][bytes]; replaces the table wholesale.
  // Pre-listen only, like tb_server_set_auth.
  if (s->listening) return -1;
  std::vector<std::string> tokens;
  size_t off = 0;
  while (off < blob_len) {
    if (off + 4 > blob_len) return -1;
    uint32_t n = static_cast<uint8_t>(blob[off]) |
                 (static_cast<uint32_t>(static_cast<uint8_t>(blob[off + 1]))
                  << 8) |
                 (static_cast<uint32_t>(static_cast<uint8_t>(blob[off + 2]))
                  << 16) |
                 (static_cast<uint32_t>(static_cast<uint8_t>(blob[off + 3]))
                  << 24);
    off += 4;
    if (n > blob_len - off) return -1;
    tokens.emplace_back(blob + off, n);
    off += n;
  }
  s->auth_tokens = std::move(tokens);
  s->auth_enabled.store(s->auth_fn != nullptr || !s->auth_tokens.empty(),
                        std::memory_order_relaxed);
  return 0;
}

uint64_t tb_server_auth_rejects(const tb_server* s) {
  return s->auth_rejects.load(std::memory_order_relaxed);
}

void tb_server_compress_stats(const tb_server* s, uint64_t* in_wire,
                              uint64_t* in_raw, uint64_t* out_raw,
                              uint64_t* out_wire) {
  if (in_wire) *in_wire = s->c_in_wire.load(std::memory_order_relaxed);
  if (in_raw) *in_raw = s->c_in_raw.load(std::memory_order_relaxed);
  if (out_raw) *out_raw = s->c_out_raw.load(std::memory_order_relaxed);
  if (out_wire) *out_wire = s->c_out_wire.load(std::memory_order_relaxed);
}

namespace {

TelemetryRing* make_telemetry_ring(uint32_t capacity, uint32_t sample_every) {
  size_t cap = 64;
  while (cap < capacity && cap < (1u << 24)) cap <<= 1;
  TelemetryRing* r = new TelemetryRing();
  r->cells = new TelemetryCell[cap];
  for (size_t i = 0; i < cap; ++i)
    r->cells[i].seq.store(i, std::memory_order_relaxed);
  r->mask = cap - 1;
  r->sample_every = sample_every;
#if defined(__x86_64__)
  // tick->ns calibration: anchor now, short-baseline initial ratio (the
  // first drain refines it over its much longer window); server creation
  // is a once-per-port event, the 200 µs sleep is invisible there
  r->cal_ticks0 = telemetry_ticks();
  r->cal_mono0 = tb_monotonic_ns();
  usleep(200);
  uint64_t dt = telemetry_ticks() - r->cal_ticks0;
  uint64_t dm = tb_monotonic_ns() - r->cal_mono0;
  if (dt > 0 && dm > 0)
    r->ns_per_tick.store(static_cast<double>(dm) / static_cast<double>(dt),
                         std::memory_order_relaxed);
#else
  r->cal_ticks0 = r->cal_mono0 = tb_monotonic_ns();  // ticks ARE ns
#endif
  return r;
}

long ring_drain(TelemetryRing* r, tb_telemetry_record* out,
                size_t max_records) {
#if defined(__x86_64__)
  // refine the tick->ns ratio over the ever-growing anchor baseline,
  // then convert the popped records in place: start_ns becomes
  // CLOCK_MONOTONIC ns, latency_ns real ns — callers never see ticks
  uint64_t dt = telemetry_ticks() - r->cal_ticks0;
  uint64_t dm = tb_monotonic_ns() - r->cal_mono0;
  if (dt > 1000000 && dm > 0)
    r->ns_per_tick.store(static_cast<double>(dm) / static_cast<double>(dt),
                         std::memory_order_relaxed);
  const double npt = r->ns_per_tick.load(std::memory_order_relaxed);
  long kept = 0;
  long n;
  // re-pop while everything popped was discarded: a return of 0 must
  // mean "nothing left", or the caller's drain-until-0 loop strands the
  // valid records queued behind a fully clock-invalid batch
  do {
    n = telemetry_pop(r, out, max_records);
    for (long i = 0; i < n; ++i) {
      tb_telemetry_record rec = out[i];
      double lat = rec.latency_ns * npt;
      // a TSC hiccup (thread migrated onto an unsynced core mid-request)
      // shows as a wrapped/huge delta: DROP the record — a fabricated
      // 0-latency "success" would drag the min-latency EMA (and with it
      // the adaptive limit) toward zero on a healthy server.  Counted as
      // dropped so produced == drained + dropped accounting holds.
      if (!(lat >= 0 && lat < 60e9)) {
        r->dropped.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      rec.latency_ns = static_cast<uint64_t>(lat);
      rec.start_ns =
          rec.start_ns >= r->cal_ticks0
              ? r->cal_mono0 + static_cast<uint64_t>(
                                   (rec.start_ns - r->cal_ticks0) * npt)
              : r->cal_mono0;
      out[kept++] = rec;
    }
  } while (n > 0 && kept == 0);
  return kept;
#else
  return telemetry_pop(r, out, max_records);
#endif
}

}  // namespace

// fabricscan: role(init)
void tb_server_set_telemetry(tb_server* s, uint32_t capacity,
                             uint32_t sample_every) {
  // pre-listen only: the per-reactor ring pointers are published once,
  // so the loop threads never see a ring torn down under them
  if (capacity == 0 || s->telemetry_enabled) return;
  s->telemetry_enabled = true;
  for (NetLoop* l : s->loops)
    l->telemetry.store(make_telemetry_ring(capacity, sample_every),
                       std::memory_order_release);
}

long tb_server_drain_telemetry(tb_server* s, tb_telemetry_record* out,
                               size_t max_records) {
  if (out == nullptr || max_records == 0) return 0;
  long total = 0;
  for (NetLoop* l : s->loops) {
    TelemetryRing* r = l->telemetry.load(std::memory_order_acquire);
    if (r == nullptr) continue;
    total += ring_drain(r, out + total, max_records - total);
    if (static_cast<size_t>(total) >= max_records) break;
  }
  return total;
}

long tb_server_drain_telemetry_ring(tb_server* s, int reactor,
                                    tb_telemetry_record* out,
                                    size_t max_records) {
  if (reactor < 0 || static_cast<size_t>(reactor) >= s->loops.size())
    return -1;
  if (out == nullptr || max_records == 0) return 0;
  TelemetryRing* r =
      s->loops[reactor]->telemetry.load(std::memory_order_acquire);
  return r == nullptr ? 0 : ring_drain(r, out, max_records);
}

uint64_t tb_server_telemetry_dropped(const tb_server* s) {
  uint64_t total = 0;
  for (NetLoop* l : s->loops) {
    TelemetryRing* r = l->telemetry.load(std::memory_order_acquire);
    if (r != nullptr) total += r->dropped.load(std::memory_order_relaxed);
  }
  return total;
}

int tb_server_reactor_stats(const tb_server* s, int reactor,
                            uint64_t* live_conns, uint64_t* native_reqs,
                            uint64_t* telemetry_dropped) {
  if (reactor < 0 || static_cast<size_t>(reactor) >= s->loops.size())
    return -1;
  NetLoop* l = s->loops[reactor];
  if (live_conns) *live_conns = l->live_conns.load(std::memory_order_relaxed);
  if (native_reqs)
    *native_reqs = l->native_reqs.load(std::memory_order_relaxed);
  if (telemetry_dropped) {
    TelemetryRing* r = l->telemetry.load(std::memory_order_acquire);
    *telemetry_dropped =
        r == nullptr ? 0 : r->dropped.load(std::memory_order_relaxed);
  }
  return 0;
}

namespace {

int register_native_common(tb_server* s, const char* full_name, int kind,
                           tb_native_fn fn, void* ud,
                           uint32_t max_concurrency) {
  uint64_t key = method_key(full_name, strlen(full_name));
  uint64_t existing = 0;
  if (tb_flatmap_get(s->methods, key, &existing) == 1)
    return -1;  // double registration / key collision: keep the Python route
  NativeMethod* nm = new NativeMethod();
  nm->kind = kind;
  nm->fn = fn;
  nm->ud = ud;
  nm->max_concurrency.store(max_concurrency, std::memory_order_relaxed);
  nm->full_name = full_name;
  nm->index = static_cast<uint32_t>(s->native_methods.size());
  s->native_methods.push_back(nm);
  tb_flatmap_insert(s->methods, key, s->native_methods.size() - 1);
  return 0;
}

}  // namespace

int tb_server_set_native_max_concurrency(tb_server* s, const char* full_name,
                                         uint32_t max_concurrency) {
  // runtime retune of a natively-dispatched method's admission limit
  // (the Python plane's MaxConcurrencyOf setter must reach methods that
  // never touch the interpreter); nm->max_concurrency is read per
  // request, so the store takes effect on the next admission check
  for (NativeMethod* nm : s->native_methods) {
    if (nm->full_name == full_name) {
      nm->max_concurrency.store(max_concurrency, std::memory_order_relaxed);
      return 0;
    }
  }
  return -1;
}

long tb_server_get_native_max_concurrency(tb_server* s,
                                          const char* full_name) {
  for (NativeMethod* nm : s->native_methods) {
    if (nm->full_name == full_name)
      return static_cast<long>(
          nm->max_concurrency.load(std::memory_order_relaxed));
  }
  return -1;  // not natively registered
}

// fabricscan: role(init)
int tb_server_register_native(tb_server* s, const char* full_name, int kind,
                              uint32_t max_concurrency) {
  if (kind != kKindEcho && kind != kKindNop) return -1;
  return register_native_common(s, full_name, kind, nullptr, nullptr,
                                max_concurrency);
}

// fabricscan: role(init)
int tb_server_register_native_fn(tb_server* s, const char* full_name,
                                 tb_native_fn fn, void* ud,
                                 uint32_t max_concurrency) {
  if (fn == nullptr) return -1;
  return register_native_common(s, full_name, kKindCallback, fn, ud,
                                max_concurrency);
}

// fabricscan: role(init)
int tb_server_listen(tb_server* s, const char* ip, int port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) return -EINVAL;
  const bool reuseport = s->loops.size() > 1;
  // SO_REUSEPORT would also let an UNRELATED server (same uid) bind the
  // same explicit port — the kernel would then split connections between
  // the two with no error anywhere.  Keep the EADDRINUSE contract: probe
  // the requested port with a plain exclusive bind first (the tiny
  // close-to-rebind window can only turn into a clean bind failure
  // below, never into silent sharing with a server that was already
  // there).  Ephemeral binds (port 0) pick a free port by construction.
  if (reuseport && port != 0) {
    int probe = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (probe < 0) return -errno;
    int one = 1;
    setsockopt(probe, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(probe, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
      int e = errno;
      close(probe);
      return -e;
    }
    close(probe);
  }
  int bound_port = port;
  for (size_t i = 0; i < s->loops.size(); ++i) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) {
      if (i == 0) return -errno;
      break;  // reactors without a listener still get conns round-robin
    }
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    // per-reactor listeners on ONE port: the SO_REUSEPORT analog of the
    // reference's per-core EventDispatcher accept sharding.  Single-
    // reactor servers keep the plain bind (and its EADDRINUSE contract).
    if (reuseport) setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof one);
    addr.sin_port = htons(static_cast<uint16_t>(bound_port));
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0 ||
        listen(fd, 1024) != 0) {
      int e = errno;
      close(fd);
      if (i == 0) return -e;
      break;  // REUSEPORT unsupported: earlier listeners carry the load
    }
    if (i == 0) {
      socklen_t alen = sizeof addr;
      getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
      bound_port = ntohs(addr.sin_port);
    }
    s->loops[i]->listener.fd = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(&s->loops[i]->listener);
    epoll_ctl(s->loops[i]->epfd, EPOLL_CTL_ADD, fd, &ev);
  }
  s->port = bound_port;
  s->listening = true;
  // dispatch pool: per-reactor deques + worker threads, started before
  // the loops so no push can beat the workers into existence
  if (s->pool_workers > 0) {
    for (NetLoop* l : s->loops) l->deque = new WorkDeque(8192);
    s->pool = new DispatchPool();
    for (int w = 0; w < s->pool_workers; ++w)
      s->pool->workers.emplace_back(pool_worker, s, static_cast<size_t>(w));
  }
  for (NetLoop* l : s->loops) l->th = std::thread(loop_run, s, l);
  return s->port;
}

int tb_server_port(const tb_server* s) { return s->port; }

// fabricscan: role(stop)
void tb_server_stop(tb_server* s) {
  if (s->stopped.exchange(true)) return;
  for (NetLoop* l : s->loops) {
    l->stopping.store(true, std::memory_order_release);
    uint64_t one = 1;
    ssize_t r = write(l->wake.fd, &one, sizeof one);
    (void)r;
  }
  for (NetLoop* l : s->loops)
    if (l->th.joinable()) l->th.join();
  for (NetLoop* l : s->loops) {
    if (l->listener.fd >= 0) {
      close(l->listener.fd);
      l->listener.fd = -1;
    }
  }
  // dispatch pool: stop workers, then run the stranded tasks on THIS
  // thread (loops are joined, so nobody else pushes; connections are
  // still alive, so the answers flush before the sweep below)
  if (s->pool != nullptr) {
    {
      std::lock_guard<std::mutex> g(s->pool->mu);
      s->pool->stopping.store(true, std::memory_order_release);
    }
    s->pool->cv.notify_all();
    for (std::thread& t : s->pool->workers)
      if (t.joinable()) t.join();
    for (NetLoop* l : s->loops) {
      uint64_t v = 0;
      while (l->deque->pop(&v))
        run_pool_task(reinterpret_cast<WorkTask*>(v));
    }
  }
  // loops are quiescent: sweep remaining conns single-threaded
  for (NetLoop* l : s->loops) {
    std::vector<NetConn*> left;
    {
      std::lock_guard<std::mutex> g(l->conns_mu);
      left = l->conns;
    }
    for (NetConn* c : left) conn_destroy(c, true);
  }
}

// fabricscan: role(stop)
void tb_server_destroy(tb_server* s) {
  tb_server_stop(s);
  for (NetLoop* l : s->loops) {
    close(l->wake.fd);
    close(l->epfd);
    tb_iobuf_destroy(l->batch);
    tb_iobuf_destroy(l->scratch);
    delete l->zctx;
    delete l->telemetry.load(std::memory_order_relaxed);
    delete l->deque;
    delete l;
  }
  for (NativeMethod* nm : s->native_methods) delete nm;
  tb_flatmap_destroy(s->methods);
  delete s->pool;
  delete s;
}

void tb_server_stats(const tb_server* s, uint64_t* accepted,
                     uint64_t* native_reqs, uint64_t* cb_frames,
                     uint64_t* handoffs, uint64_t* live_conns) {
  if (accepted) *accepted = s->accepted.load();
  if (native_reqs) {
    uint64_t total = 0;
    for (NetLoop* l : s->loops)
      total += l->native_reqs.load(std::memory_order_relaxed);
    *native_reqs = total;
  }
  if (cb_frames) *cb_frames = s->cb_frames.load();
  if (handoffs) *handoffs = s->handoffs.load();
  if (live_conns) {
    uint64_t total = 0;
    for (NetLoop* l : s->loops)
      total += l->live_conns.load(std::memory_order_relaxed);
    *live_conns = total;
  }
}

uint64_t tb_server_deadline_sheds(const tb_server* s) {
  return s->deadline_sheds.load(std::memory_order_relaxed);
}

void tb_server_pause_accept(tb_server* s) {
  if (s->accept_paused.exchange(true)) return;
  // wake EVERY loop: each reactor tears down its own listener on its own
  // thread at the next wakeup (the PR 8 single-loop assumption, retired)
  for (NetLoop* l : s->loops) {
    uint64_t one = 1;
    ssize_t r = write(l->wake.fd, &one, sizeof one);
    (void)r;
  }
}

long tb_server_close_idle(tb_server* s, uint64_t idle_ms) {
  // idle reap for native ports (reference Acceptor::CloseIdleConnections,
  // acceptor.cpp:111): shutdown() is the thread-safe kill — the owning
  // loop thread reaps the connection via EPOLLHUP, exactly the
  // tb_conn_close discipline.  Returns the number of connections culled.
  if (s->stopped.load(std::memory_order_acquire)) return 0;
  uint64_t cutoff = now_ms();
  long culled = 0;
  for (NetLoop* l : s->loops) {
    std::lock_guard<std::mutex> g(l->conns_mu);
    for (NetConn* c : l->conns) {
      if (c->dead.load(std::memory_order_acquire)) continue;
      uint64_t last = c->last_active_ms.load(std::memory_order_relaxed);
      if (last != 0 && cutoff > last && cutoff - last >= idle_ms) {
        shutdown(c->fd, SHUT_RDWR);
        ++culled;
      }
    }
  }
  return culled;
}

// ---------------------------------------------------------------------------
// per-connection API (token-addressed; any thread)
// ---------------------------------------------------------------------------

int tb_conn_respond(uint64_t token, const void* meta, size_t meta_len,
                    const void* payload, size_t payload_len, const void* att,
                    size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
                    uint32_t flags, uint32_t error_code) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  tb_iobuf* out = tb_iobuf_create();
  pack_flat(out, meta, meta_len, payload, payload_len, att, att_len, cid_lo,
            cid_hi, flags | kFlagResponse, error_code);
  conn_queue_iobuf(c, out);
  tb_iobuf_destroy(out);
  conn_unref(c);
  return 0;
}

int tb_conn_write(uint64_t token, const tb_iobuf* data) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  conn_queue_iobuf(c, data);
  conn_unref(c);
  return 0;
}

int tb_conn_peer(uint64_t token, char* ip_out, size_t ip_cap) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  sockaddr_in addr{};
  socklen_t alen = sizeof addr;
  int port = -1;
  if (getpeername(c->fd, reinterpret_cast<sockaddr*>(&addr), &alen) == 0 &&
      addr.sin_family == AF_INET) {
    if (ip_out && ip_cap > 0) inet_ntop(AF_INET, &addr.sin_addr, ip_out, ip_cap);
    port = ntohs(addr.sin_port);
  }
  conn_unref(c);
  return port;
}

int tb_conn_close(uint64_t token) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  shutdown(c->fd, SHUT_RDWR);  // the loop thread reaps via EPOLLHUP
  conn_unref(c);
  return 0;
}

int tb_conn_set_authenticated(uint64_t token) {
  // the Python route verified this connection's credential (server_check)
  // — cache the verdict natively so the conn's later frames ride the
  // fast path without re-fighting auth
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  c->authenticated.store(true, std::memory_order_relaxed);
  conn_unref(c);
  return 0;
}

// ---------------------------------------------------------------------------
// client channel
// ---------------------------------------------------------------------------

namespace {

struct Pending {
  bool targeted;  // fabricscan: owner(shared)
  bool done = false;  // fabricscan: owner(shared)
  uint32_t err_code = 0;  // fabricscan: owner(shared)
  int fail = 0;   // -errno when the channel died under us  // fabricscan: owner(shared)
  std::string meta;  // fabricscan: owner(shared)
  tb_iobuf* body;  // targeted: caller's out buffer; any-mode: owned temp  // fabricscan: owner(shared)
};

}  // namespace

struct tb_channel {
  int fd = -1;  // fabricscan: owner(init)
  int proto = 0;  // 0 = tbus_std, 1 = baidu_std (PRPC)  // fabricscan: owner(init)
  // client reactor shard, pinned at connect: the top 8 bits of every cid
  // this channel mints carry it, so completions route to the owning
  // channel's pending table without any cross-channel map and a frame
  // carrying another shard's tag is detectably misrouted
  uint32_t shard = 0;  // fabricscan: owner(init)
  std::atomic<uint64_t> cid_misroutes{0};
  std::mutex wmu;  // writers (pack + writev serialize)
  std::mutex rmu;  // reader election
  std::mutex pmu;  // pending table + done queue + cv
  std::condition_variable pcv;
  std::unordered_map<uint64_t, Pending*> pending;  // fabricscan: owner(shared)
  std::deque<std::pair<uint64_t, Pending*>> doneq;  // any-mode completions  // fabricscan: owner(shared)
  std::atomic<uint64_t> next_cid{1};
  tb_iobuf* rbuf = nullptr;  // fabricscan: owner(shared)
  tb_iobuf* pump_body = nullptr;  // reused per-response cut target (pump)  // fabricscan: owner(shared)
  std::atomic<int> err{0};  // sticky -errno
  // counter-scheduled fault injection (tb_channel_set_fault): the native
  // analog of the Python Socket.write seam — every fail_every'th call
  // answers fault_err_code without touching the wire, every
  // close_every'th kills the connection mid-run, every delay_every'th
  // sleeps delay_ms first.  All zero = disabled (the steady-state cost
  // is one load).
  std::atomic<uint64_t> fault_counter{0};
  uint32_t fault_fail_every = 0;  // fabricscan: owner(init)
  uint32_t fault_close_every = 0;  // fabricscan: owner(init)
  uint32_t fault_delay_every = 0;  // fabricscan: owner(init)
  uint32_t fault_delay_ms = 0;  // fabricscan: owner(init)
  uint32_t fault_err_code = 0;  // fabricscan: owner(init)
  // production-shaped request stamping (baidu_std only; set before
  // concurrent use, like the fault schedule): a channel-default
  // compress_type spliced into RpcMeta field 3 (per-call override rides
  // flags_extra), and the credential for field 7 — stamped until the
  // first successful response proves the connection (the reference's
  // first-request auth fight), then omitted.
  uint32_t req_compress = 0;  // fabricscan: owner(init)
  std::string auth_data;  // fabricscan: owner(init)
  std::atomic<bool> auth_proven{false};
  // ambient trace context for the pipelined pump (tb_channel_set_trace):
  // every trace_every'th pump frame carries the Dapper fields in its
  // RpcRequestMeta, span_id incremented per traced frame — counter-
  // scheduled exact-rate like the fault seam.  Set before concurrent use.
  uint64_t tr_log_id = 0;  // fabricscan: owner(init)
  uint64_t tr_trace_id = 0;  // fabricscan: owner(init)
  uint64_t tr_span_id = 0;  // fabricscan: owner(init)
  uint64_t tr_parent_span_id = 0;  // fabricscan: owner(init)
  int tr_sampled = 0;  // fabricscan: owner(init)
  uint32_t trace_every = 0;  // 0 = untraced pump  // fabricscan: owner(init)
};

namespace {

// cid space partition: top 8 bits = client reactor shard, low 56 bits =
// the channel's sequence.  56 bits of sequence cannot wrap in practice.
constexpr int kCidShardShift = 56;
constexpr uint64_t kCidSeqMask = (1ull << kCidShardShift) - 1;
std::atomic<uint32_t> g_next_client_shard{0};

uint64_t channel_next_cid(tb_channel* ch) {
  return (static_cast<uint64_t>(ch->shard) << kCidShardShift) |
         (ch->next_cid.fetch_add(1, std::memory_order_relaxed) & kCidSeqMask);
}

// Validate an inbound cid's shard tag.  Returns the cid to complete
// (re-tagged to the local shard on mismatch) and sets *misroute — the
// caller fails the re-tagged pending with -EBADMSG instead of letting a
// corrupted tag strand its caller until timeout.
uint64_t channel_check_cid(tb_channel* ch, uint64_t cid, bool* misroute) {
  if ((cid >> kCidShardShift) == ch->shard) {
    *misroute = false;
    return cid;
  }
  *misroute = true;
  ch->cid_misroutes.fetch_add(1, std::memory_order_relaxed);
  return (static_cast<uint64_t>(ch->shard) << kCidShardShift) |
         (cid & kCidSeqMask);
}

void channel_fail(tb_channel* ch, int err) {
  ch->err.store(err, std::memory_order_release);
  std::lock_guard<std::mutex> g(ch->pmu);
  for (auto& kv : ch->pending) {
    if (!kv.second->done) {
      kv.second->done = true;
      kv.second->fail = err;
      if (!kv.second->targeted) ch->doneq.emplace_back(kv.first, kv.second);
    }
  }
  ch->pcv.notify_all();
}

// Cut one complete PRPC response off ch->rbuf.  Returns 1 when a frame
// was consumed (fills cid/meta/err_code and cuts payload+attachment into
// the pending's dst under pmu — same locking contract as the tbus path),
// 0 when incomplete, -EPROTO on garbage.  Caller holds rmu.
// fabricscan: locked
int prpc_complete_one(tb_channel* ch) {
  uint32_t body_len = 0, meta_len = 0;
  int prc = prpc_peek(ch->rbuf, &body_len, &meta_len, kClientMaxBody);
  if (prc == 1) return 0;
  if (prc != 0) return -EPROTO;
  if (tb_iobuf_size(ch->rbuf) < kPrpcHeader + body_len) return 0;
  std::string meta(meta_len, '\0');
  if (meta_len) tb_iobuf_copy_to(ch->rbuf, &meta[0], meta_len, kPrpcHeader);
  PrpcMeta pm = scan_prpc_meta(meta.data(), meta_len);
  if (!pm.ok) return -EPROTO;
  size_t rest = body_len - meta_len;
  bool mis = false;
  uint64_t cid = channel_check_cid(ch, pm.cid, &mis);
  {
    // completion runs under pmu so a timed-out caller can't free its
    // Pending (or its body iobuf) while the cut writes into it
    std::unique_lock<std::mutex> pl(ch->pmu);
    auto it = ch->pending.find(cid);
    Pending* p = it == ch->pending.end() ? nullptr : it->second;
    // a wrong-shard frame's payload never reaches the caller's buffer:
    // the pending (located by re-tagged sequence) fails with -EBADMSG
    tb_iobuf* dst =
        (p != nullptr && p->targeted && !mis) ? p->body : tb_iobuf_create();
    tb_iobuf_popn(ch->rbuf, kPrpcHeader + meta_len);
    if (rest) tb_iobuf_cutn(ch->rbuf, dst, rest);
    if (p == nullptr) {
      tb_iobuf_destroy(dst);  // timed-out caller already left: drop
    } else if (mis) {
      tb_iobuf_destroy(dst);
      p->fail = -EBADMSG;  // surfaced as EREQUEST by the Python plane
      if (!p->targeted) ch->doneq.emplace_back(cid, p);
      p->done = true;
      ch->pcv.notify_all();
    } else {
      p->meta = std::move(meta);
      p->err_code = pm.error_code;
      if (!p->targeted) {
        p->body = dst;
        ch->doneq.emplace_back(cid, p);
      }
      p->done = true;
      ch->pcv.notify_all();
    }
  }
  return 1;
}

// read whatever arrives within `slice_ms`, completing pendings.  Caller
// holds rmu.  Returns false when the channel failed.
// fabricscan: locked
bool pump_once(tb_channel* ch, int slice_ms) {
  pollfd pf{ch->fd, POLLIN, 0};
  int rc = poll(&pf, 1, slice_ms);
  if (rc < 0) {
    if (errno == EINTR) return true;
    channel_fail(ch, -errno);
    return false;
  }
  if (rc == 0) return true;
  size_t burst = tb_iobuf_read_burst();
  for (;;) {
    long n = tb_iobuf_append_from_fd(ch->rbuf, ch->fd, burst);
    if (n > 0) {
      if (static_cast<size_t>(n) < burst) break;
      continue;
    }
    if (n == -EAGAIN || n == -EWOULDBLOCK) break;
    if (n == -EINTR) continue;
    channel_fail(ch, n == 0 ? -EPIPE : static_cast<int>(n));
    return false;
  }
  if (ch->proto == 1) {
    for (;;) {
      int rc2 = prpc_complete_one(ch);
      if (rc2 == 0) break;
      if (rc2 < 0) {
        channel_fail(ch, rc2);
        return false;
      }
    }
    return true;
  }
  for (;;) {
    tb_tbus_hdr hdr;
    int prc = tb_tbus_peek(ch->rbuf, &hdr);
    if (prc == 1) break;
    if (prc == -1 || hdr.meta_len > hdr.body_len ||
        hdr.body_len > kClientMaxBody) {
      channel_fail(ch, -EPROTO);
      return false;
    }
    if (tb_iobuf_size(ch->rbuf) < kHeader + hdr.body_len) break;
    uint64_t wire_cid = static_cast<uint64_t>(hdr.cid_lo) |
                        (static_cast<uint64_t>(hdr.cid_hi) << 32);
    bool mis = false;
    uint64_t cid = channel_check_cid(ch, wire_cid, &mis);
    std::string meta(hdr.meta_len, '\0');
    bool proto_err = false;
    {
      // completion runs under pmu so a timed-out caller can't free its
      // Pending (or its body iobuf) while the cut writes into it
      std::unique_lock<std::mutex> pl(ch->pmu);
      auto it = ch->pending.find(cid);
      Pending* p = it == ch->pending.end() ? nullptr : it->second;
      tb_iobuf* dst =
          (p != nullptr && p->targeted && !mis) ? p->body : tb_iobuf_create();
      int crc =
          tb_tbus_cut(ch->rbuf, &hdr, meta.empty() ? nullptr : &meta[0], dst);
      if (crc != 0) {
        if (p == nullptr || !p->targeted || mis) tb_iobuf_destroy(dst);
        proto_err = true;
      } else if (p == nullptr) {
        tb_iobuf_destroy(dst);  // timed-out caller already left: drop
      } else if (mis) {
        // wrong-shard tag: the re-tagged pending fails -EBADMSG (the
        // Python plane answers EREQUEST); the channel itself survives
        tb_iobuf_destroy(dst);
        p->fail = -EBADMSG;
        if (!p->targeted) ch->doneq.emplace_back(cid, p);
        p->done = true;
        ch->pcv.notify_all();
      } else {
        p->meta = std::move(meta);
        p->err_code = hdr.error_code;
        if (!p->targeted) {
          p->body = dst;
          ch->doneq.emplace_back(cid, p);
        }
        p->done = true;
        ch->pcv.notify_all();
      }
    }
    if (proto_err) {
      channel_fail(ch, -EPROTO);
      return false;
    }
  }
  return true;
}

// blocking full write of `frame` under wmu with a deadline
int write_frame(tb_channel* ch, tb_iobuf* frame, uint64_t deadline) {
  std::lock_guard<std::mutex> g(ch->wmu);
  while (tb_iobuf_size(frame) > 0) {
    long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
    if (rc > 0) continue;
    if (rc == -EINTR) continue;
    if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) {
      uint64_t now = now_ms();
      if (now >= deadline) return -ETIMEDOUT;
      pollfd pf{ch->fd, POLLOUT, 0};
      poll(&pf, 1, static_cast<int>(deadline - now));
      continue;
    }
    return static_cast<int>(rc);
  }
  return 0;
}

// pack with an explicit cid and write fully; 0 ok, -errno otherwise
int channel_send_cid(tb_channel* ch, uint64_t cid, const void* meta,
                     size_t meta_len, const void* payload, size_t payload_len,
                     const void* att, size_t att_len, uint32_t flags_extra,
                     uint64_t deadline) {
  tb_iobuf* frame = tb_iobuf_create();
  if (ch->proto == 1) {
    // meta = RpcRequestMeta submessage.  In PRPC mode flags_extra's low
    // bits carry a per-call compress_type (0 = the channel default) —
    // the tbus flag space is meaningless here, so the argument is free
    // for race-free per-call codec selection.  The credential stamps
    // until the connection is proven.
    uint32_t compress =
        (flags_extra & 0xFu) != 0 ? (flags_extra & 0xFu) : ch->req_compress;
    const bool stamp_auth =
        !ch->auth_data.empty() &&
        !ch->auth_proven.load(std::memory_order_relaxed);
    pack_prpc_request(frame, meta, meta_len, payload, payload_len, att,
                      att_len, cid, compress,
                      stamp_auth ? ch->auth_data.data() : nullptr,
                      stamp_auth ? ch->auth_data.size() : 0);
  } else
    pack_flat(frame, meta, meta_len, payload, payload_len, att, att_len,
              static_cast<uint32_t>(cid), static_cast<uint32_t>(cid >> 32),
              flags_extra, 0);
  int rc = write_frame(ch, frame, deadline);
  tb_iobuf_destroy(frame);
  if (rc != 0 && rc != -ETIMEDOUT) channel_fail(ch, rc);
  return rc;
}

// shared wait-or-pump loop: wait until pred() under pmu, electing a reader
// to pump completions when nobody else is.  Returns false on deadline.
template <typename Pred>
bool wait_or_pump(tb_channel* ch, std::unique_lock<std::mutex>& pl,
                  uint64_t deadline, Pred pred) {
  while (!pred()) {
    if (ch->err.load(std::memory_order_acquire) != 0) return true;
    uint64_t now = now_ms();
    if (now >= deadline) return false;
    if (ch->rmu.try_lock()) {
      pl.unlock();
      int slice = static_cast<int>(std::min<uint64_t>(deadline - now, 50));
      pump_once(ch, slice);
      ch->rmu.unlock();
      pl.lock();
      ch->pcv.notify_all();
    } else {
      ch->pcv.wait_for(pl, std::chrono::milliseconds(10));
    }
  }
  return true;
}

}  // namespace

// fabricscan: role(init)
tb_channel* tb_channel_connect(const char* ip, int port, int timeout_ms,
                               int* err_out) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    if (err_out) *err_out = errno;
    return nullptr;
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    close(fd);
    if (err_out) *err_out = EINVAL;
    return nullptr;
  }
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr);
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pf{fd, POLLOUT, 0};
    rc = poll(&pf, 1, timeout_ms > 0 ? timeout_ms : 5000);
    if (rc == 1) {
      int soerr = 0;
      socklen_t slen = sizeof soerr;
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
      rc = soerr == 0 ? 0 : -1;
      errno = soerr;
    } else {
      rc = -1;
      errno = ETIMEDOUT;
    }
  }
  if (rc != 0) {
    if (err_out) *err_out = errno;
    close(fd);
    return nullptr;
  }
  set_nodelay(fd);
  set_nonblock(fd);
  tb_channel* ch = new tb_channel();
  ch->fd = fd;
  // pin to a client reactor shard (round-robin over the process): the
  // shard tag partitions the cid space so completions route without any
  // cross-channel shared map
  ch->shard = g_next_client_shard.fetch_add(1, std::memory_order_relaxed) &
              0xFFu;
  ch->rbuf = tb_iobuf_create();
  return ch;
}

int tb_channel_reactor(const tb_channel* ch) {
  return static_cast<int>(ch->shard);
}

uint64_t tb_channel_cid_misroutes(const tb_channel* ch) {
  return ch->cid_misroutes.load(std::memory_order_relaxed);
}

// fabricscan: role(init)
int tb_channel_set_protocol(tb_channel* ch, int proto) {
  if (proto != 0 && proto != 1) return -1;
  ch->proto = proto;
  return 0;
}

// fabricscan: role(init)
int tb_channel_set_compress(tb_channel* ch, int compress_type) {
  // channel-default request compress_type (baidu_std RpcMeta field 3);
  // the CALLER compresses payloads with the matching codec — this only
  // stamps the wire field.  Set before concurrent use.
  if (compress_type < 0 || compress_type > 3) return -1;
  ch->req_compress = static_cast<uint32_t>(compress_type);
  return 0;
}

// fabricscan: role(init)
int tb_channel_set_auth(tb_channel* ch, const void* data, size_t len) {
  // credential for RpcMeta field 7, stamped on requests until the first
  // successful response proves the connection.  Set before concurrent
  // use (a redial mints a fresh channel and re-arms it).
  if (data == nullptr || len == 0) {
    ch->auth_data.clear();
  } else {
    ch->auth_data.assign(static_cast<const char*>(data), len);
    ch->auth_proven.store(false, std::memory_order_relaxed);
  }
  return 0;
}

// fabricscan: role(init)
int tb_channel_set_fault(tb_channel* ch, uint32_t fail_every,
                         uint32_t close_every, uint32_t delay_every,
                         uint32_t delay_ms, uint32_t err_code) {
  // set BEFORE concurrent calls (rpc_press arms at channel creation);
  // the schedule fields are plain stores read by callers afterwards
  ch->fault_fail_every = fail_every;
  ch->fault_close_every = close_every;
  ch->fault_delay_every = delay_every;
  ch->fault_delay_ms = delay_ms;
  ch->fault_err_code = err_code != 0 ? err_code : 2001;  // EINTERNAL
  return 0;
}

// fabricscan: role(init)
int tb_channel_set_trace(tb_channel* ch, uint64_t log_id, uint64_t trace_id,
                         uint64_t span_id, uint64_t parent_span_id,
                         int sampled, uint32_t every) {
  // trace fields ride the PRPC RpcRequestMeta; the tbus pump's meta is
  // caller-built JSON, so a traced tbus pump has no seam here
  if (every != 0 && ch->proto != 1) return -1;
  ch->tr_log_id = log_id;
  ch->tr_trace_id = trace_id;
  ch->tr_span_id = span_id;
  ch->tr_parent_span_id = parent_span_id;
  ch->tr_sampled = sampled != 0 ? 1 : 0;
  ch->trace_every = every;
  return 0;
}

long tb_channel_call(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len, const void* att,
                     size_t att_len, uint32_t flags_extra, tb_iobuf* body_out,
                     void* meta_out, size_t meta_cap, uint32_t* meta_len_out,
                     uint32_t* err_code_out, int timeout_ms) {
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) return sticky;
  if (ch->fault_fail_every || ch->fault_close_every || ch->fault_delay_every) {
    // deterministic injection (counter schedule, not RNG — the same call
    // sequence injects the same faults, the FaultInjector discipline)
    uint64_t n = ch->fault_counter.fetch_add(1, std::memory_order_relaxed) + 1;
    if (ch->fault_close_every && n % ch->fault_close_every == 0) {
      // kill the connection mid-run: the write below fails and the
      // caller's redial machinery owns recovery (the socket-seam
      // ACTION_CLOSE analog)
      shutdown(ch->fd, SHUT_RDWR);
    } else if (ch->fault_fail_every && n % ch->fault_fail_every == 0) {
      // a completed-but-failed RPC, channel intact: the server "browned
      // out" this one call
      if (err_code_out) *err_code_out = ch->fault_err_code;
      if (meta_len_out) *meta_len_out = 0;
      return 0;
    }
    if (ch->fault_delay_every && n % ch->fault_delay_every == 0 &&
        ch->fault_delay_ms > 0) {
      usleep(static_cast<useconds_t>(ch->fault_delay_ms) * 1000);
    }
  }
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  uint64_t cid = channel_next_cid(ch);
  Pending p;
  p.targeted = true;
  p.body = body_out;
  {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.emplace(cid, &p);
  }
  int rc = channel_send_cid(ch, cid, meta, meta_len, payload, payload_len, att,
                            att_len, flags_extra, deadline);
  if (rc != 0) {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.erase(cid);
    return rc;
  }
  std::unique_lock<std::mutex> pl(ch->pmu);
  bool in_time = wait_or_pump(ch, pl, deadline, [&] { return p.done; });
  ch->pending.erase(cid);
  if (!in_time) return -ETIMEDOUT;
  if (!p.done) {  // channel failed before completion
    int e = ch->err.load(std::memory_order_acquire);
    return e != 0 ? e : -EPIPE;
  }
  int fail = p.fail;
  std::string meta_resp = std::move(p.meta);
  uint32_t ec = p.err_code;
  pl.unlock();
  if (fail != 0) return fail;
  // an accepted response proves the connection: later requests stop
  // stamping the credential (an ERPCAUTH reject must NOT prove it — the
  // next attempt still needs the credential on the wire)
  if (ec == 0) ch->auth_proven.store(true, std::memory_order_relaxed);
  if (meta_len_out)
    *meta_len_out = static_cast<uint32_t>(std::min(meta_resp.size(), meta_cap));
  if (meta_out && meta_cap > 0 && !meta_resp.empty())
    memcpy(meta_out, meta_resp.data(), std::min(meta_resp.size(), meta_cap));
  if (err_code_out) *err_code_out = ec;
  return static_cast<long>(tb_iobuf_size(body_out));
}

uint64_t tb_channel_send(tb_channel* ch, const void* meta, size_t meta_len,
                         const void* payload, size_t payload_len,
                         const void* att, size_t att_len, uint32_t flags_extra,
                         int* err_out) {
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) {
    if (err_out) *err_out = -sticky;
    return 0;
  }
  uint64_t cid = channel_next_cid(ch);
  Pending* p = new Pending();
  p->targeted = false;
  p->body = nullptr;
  {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.emplace(cid, p);
  }
  int rc = channel_send_cid(ch, cid, meta, meta_len, payload, payload_len, att,
                            att_len, flags_extra, now_ms() + 60000);
  if (rc != 0) {
    std::lock_guard<std::mutex> g(ch->pmu);
    auto it = ch->pending.find(cid);
    if (it != ch->pending.end() && it->second == p && !p->done) {
      ch->pending.erase(it);
      delete p;
    }  // else channel_fail moved it to doneq: recv() frees it
    if (err_out) *err_out = -rc;
    return 0;
  }
  return cid;
}

long tb_channel_recv(tb_channel* ch, uint64_t* cid_out, tb_iobuf* body_out,
                     void* meta_out, size_t meta_cap, uint32_t* meta_len_out,
                     uint32_t* err_code_out, int timeout_ms) {
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  std::unique_lock<std::mutex> pl(ch->pmu);
  for (;;) {
    if (!ch->doneq.empty()) {
      auto [cid, p] = ch->doneq.front();
      ch->doneq.pop_front();
      ch->pending.erase(cid);
      pl.unlock();
      long n;
      if (p->fail != 0) {
        n = p->fail;
      } else {
        if (cid_out) *cid_out = cid;
        if (meta_len_out)
          *meta_len_out =
              static_cast<uint32_t>(std::min(p->meta.size(), meta_cap));
        if (meta_out && meta_cap > 0 && !p->meta.empty())
          memcpy(meta_out, p->meta.data(), std::min(p->meta.size(), meta_cap));
        if (err_code_out) *err_code_out = p->err_code;
        n = 0;
        if (p->body != nullptr) {
          n = static_cast<long>(tb_iobuf_size(p->body));
          tb_iobuf_append_iobuf(body_out, p->body);
        }
      }
      if (p->body != nullptr) tb_iobuf_destroy(p->body);
      delete p;
      return n;
    }
    int sticky = ch->err.load(std::memory_order_acquire);
    if (sticky != 0) {
      pl.unlock();
      return sticky;
    }
    if (!wait_or_pump(ch, pl, deadline, [&] { return !ch->doneq.empty(); })) {
      pl.unlock();
      return -ETIMEDOUT;
    }
  }
}

int tb_channel_error(const tb_channel* ch) {
  return ch->err.load(std::memory_order_acquire);
}

long tb_channel_pump(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len, int n,
                     int inflight, int timeout_ms) {
  if (n <= 0) return -EINVAL;
  if (inflight < 1) inflight = 1;
  std::lock_guard<std::mutex> rg(ch->rmu);
  std::lock_guard<std::mutex> wg(ch->wmu);
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) return sticky;
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  size_t burst = tb_iobuf_read_burst();
  tb_iobuf* frame = tb_iobuf_create();
  int sent = 0, done = 0, outstanding = 0;
  long result = 0;
  // every frame of the pump is identical except the correlation id: build
  // the wire bytes ONCE (header + meta + payload, meta crc precomputed)
  // and per request patch the cid bytes + one append — no per-request
  // crc, header build, or multi-append.  PRPC carries the cid as a meta
  // varint, so the template encodes it as a padded 10-byte varint (fixed
  // width => patchable in place; decoders accept non-minimal varints).
  std::vector<char> tmpl;
  size_t cid_off = 12;  // tbus: header words 3-4
  if (ch->proto == 1) {
    // channel-default compress_type and (until proven) the credential
    // ride every frame of the pump — the template is fixed, and a
    // pipelined first burst legitimately carries the credential on each
    // frame (the reference's FightAuthentication lets first-writers race)
    const uint32_t compress = ch->req_compress;
    const bool stamp_auth =
        !ch->auth_data.empty() &&
        !ch->auth_proven.load(std::memory_order_relaxed);
    const size_t auth_len = stamp_auth ? ch->auth_data.size() : 0;
    size_t meta_total = 1 + varint_len(meta_len) + meta_len +
                        (compress ? 1 + varint_len(compress) : 0) + 1 + 10 +
                        (auth_len ? 1 + varint_len(auth_len) + auth_len : 0);
    tmpl.resize(kPrpcHeader + meta_total + payload_len);
    uint8_t* t = reinterpret_cast<uint8_t*>(tmpl.data());
    memcpy(t, "PRPC", 4);
    put_be32(t + 4, static_cast<uint32_t>(meta_total + payload_len));
    put_be32(t + 8, static_cast<uint32_t>(meta_total));
    size_t o = kPrpcHeader;
    t[o++] = 0x0A;  // RpcMeta.request wrapping the caller's submessage
    o += put_varint(t + o, meta_len);
    if (meta_len) memcpy(t + o, meta, meta_len);
    o += meta_len;
    if (compress) {
      t[o++] = 0x18;  // compress_type (field 3)
      o += put_varint(t + o, compress);
    }
    t[o++] = 0x20;  // correlation_id
    cid_off = o;
    o += 10;  // patched per request
    if (auth_len) {
      t[o++] = 0x3A;  // authentication_data (field 7)
      o += put_varint(t + o, auth_len);
      memcpy(t + o, ch->auth_data.data(), auth_len);
      o += auth_len;
    }
    if (payload_len) memcpy(t + o, payload, payload_len);
  } else {
    // (tbus template below; the traced PRPC template is built after it)
    tmpl.resize(32 + meta_len + payload_len);
    uint32_t h[8];
    h[0] = kMagic;
    h[1] = static_cast<uint32_t>(meta_len + payload_len);
    h[2] = meta_len ? kFlagHasMeta : 0;
    h[3] = 0;
    h[4] = 0;
    h[5] = static_cast<uint32_t>(meta_len);
    h[6] = tb_crc32c(0, meta, meta_len);
    h[7] = 0;
    memcpy(tmpl.data(), h, sizeof h);
    if (meta_len) memcpy(tmpl.data() + 32, meta, meta_len);
    if (payload_len) memcpy(tmpl.data() + 32 + meta_len, payload, payload_len);
  }
  // traced-frame template (tb_channel_set_trace): the caller's
  // RpcRequestMeta submessage grown with the Dapper fields — trace_id /
  // parent_span_id / log_id / sampled are run-constant minimal varints,
  // span_id is a padded 10-byte varint patched per traced frame
  // (span = base + sequence, so every traced request is its own span).
  // Built ONCE like the plain template; every trace_every'th frame uses
  // it, the rest the plain one — counter-scheduled exact rate with zero
  // per-frame re-encoding, which is what keeps a traced flood within a
  // hair of the bare pump (the prpc_traced_pump_ns bench gate).
  std::vector<char> ttmpl;
  size_t tcid_off = 0, tspan_off = 0;
  const uint32_t trace_every = ch->proto == 1 ? ch->trace_every : 0;
  if (trace_every != 0) {
    const uint32_t compress = ch->req_compress;
    const bool stamp_auth =
        !ch->auth_data.empty() &&
        !ch->auth_proven.load(std::memory_order_relaxed);
    const size_t auth_len = stamp_auth ? ch->auth_data.size() : 0;
    size_t sub_total =
        meta_len + (ch->tr_log_id ? 1 + varint_len(ch->tr_log_id) : 0) +
        (ch->tr_trace_id ? 1 + varint_len(ch->tr_trace_id) : 0) + 1 + 10 +
        (ch->tr_parent_span_id ? 1 + varint_len(ch->tr_parent_span_id)
                               : 0) +
        (ch->tr_sampled ? 2 : 0);
    size_t meta_total = 1 + varint_len(sub_total) + sub_total +
                        (compress ? 1 + varint_len(compress) : 0) + 1 + 10 +
                        (auth_len ? 1 + varint_len(auth_len) + auth_len : 0);
    ttmpl.resize(kPrpcHeader + meta_total + payload_len);
    uint8_t* t = reinterpret_cast<uint8_t*>(ttmpl.data());
    memcpy(t, "PRPC", 4);
    put_be32(t + 4, static_cast<uint32_t>(meta_total + payload_len));
    put_be32(t + 8, static_cast<uint32_t>(meta_total));
    size_t o = kPrpcHeader;
    t[o++] = 0x0A;  // RpcMeta.request wrapping the grown submessage
    o += put_varint(t + o, sub_total);
    if (meta_len) memcpy(t + o, meta, meta_len);
    o += meta_len;
    if (ch->tr_log_id) {
      t[o++] = 0x18;  // RpcRequestMeta.log_id (field 3)
      o += put_varint(t + o, ch->tr_log_id);
    }
    if (ch->tr_trace_id) {
      t[o++] = 0x20;  // RpcRequestMeta.trace_id (field 4)
      o += put_varint(t + o, ch->tr_trace_id);
    }
    t[o++] = 0x28;  // RpcRequestMeta.span_id (field 5)
    tspan_off = o;
    o += 10;  // patched per traced frame
    if (ch->tr_parent_span_id) {
      t[o++] = 0x30;  // RpcRequestMeta.parent_span_id (field 6)
      o += put_varint(t + o, ch->tr_parent_span_id);
    }
    if (ch->tr_sampled) {
      t[o++] = 0x48;  // RpcRequestMeta.traced_sampled (field 9, extension)
      t[o++] = 1;
    }
    if (compress) {
      t[o++] = 0x18;  // RpcMeta.compress_type (field 3)
      o += put_varint(t + o, compress);
    }
    t[o++] = 0x20;  // RpcMeta.correlation_id (field 4)
    tcid_off = o;
    o += 10;  // patched per request
    if (auth_len) {
      t[o++] = 0x3A;  // authentication_data (field 7)
      o += put_varint(t + o, auth_len);
      memcpy(t + o, ch->auth_data.data(), auth_len);
      o += auth_len;
    }
    if (payload_len) memcpy(t + o, payload, payload_len);
  }
  auto t0 = std::chrono::steady_clock::now();
  uint64_t trace_seq = 0;  // counter schedule: frame 0 is traced
  while (done < n && result == 0) {
    // fill the window: pack EVERY frame the window allows, then flush the
    // whole batch with as few writev calls as the kernel accepts (one
    // syscall per window refill, not per request)
    while (outstanding < inflight && sent < n) {
      uint64_t cid = channel_next_cid(ch);
      if (ch->proto == 1) {
        if (trace_every != 0 && trace_seq++ % trace_every == 0) {
          uint8_t* t = reinterpret_cast<uint8_t*>(ttmpl.data());
          put_varint_fixed10(t + tspan_off, ch->tr_span_id + trace_seq);
          put_varint_fixed10(t + tcid_off, cid);
          tb_iobuf_append(frame, ttmpl.data(), ttmpl.size());
          ++sent;
          ++outstanding;
          continue;
        }
        put_varint_fixed10(
            reinterpret_cast<uint8_t*>(tmpl.data()) + cid_off, cid);
      } else {
        uint32_t cid32[2] = {static_cast<uint32_t>(cid),
                             static_cast<uint32_t>(cid >> 32)};
        memcpy(tmpl.data() + cid_off, cid32, sizeof cid32);
      }
      tb_iobuf_append(frame, tmpl.data(), tmpl.size());
      ++sent;
      ++outstanding;
    }
    while (tb_iobuf_size(frame) > 0) {
      long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
      if (rc > 0) continue;
      if (rc == -EINTR) continue;
      if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) break;  // kernel full
      result = rc;  // hard write error
      break;
    }
    if (result != 0) break;
    // drain completions (and finish any partial write while waiting)
    pollfd pf{ch->fd, static_cast<short>(
                          POLLIN | (tb_iobuf_size(frame) > 0 ? POLLOUT : 0)),
              0};
    uint64_t now = now_ms();
    if (now >= deadline) {
      result = -ETIMEDOUT;
      break;
    }
    int prc = poll(&pf, 1, static_cast<int>(std::min<uint64_t>(deadline - now, 100)));
    if (prc < 0 && errno != EINTR) {
      result = -errno;
      break;
    }
    if (pf.revents & POLLOUT) {
      while (tb_iobuf_size(frame) > 0) {
        long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
        if (rc > 0) continue;
        if (rc == -EINTR) continue;
        if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) break;
        result = rc;
        break;
      }
    }
    if (pf.revents & POLLIN) {
      for (;;) {
        long rd = tb_iobuf_append_from_fd(ch->rbuf, ch->fd, burst);
        if (rd > 0) {
          if (static_cast<size_t>(rd) < burst) break;
          continue;
        }
        if (rd == -EAGAIN || rd == -EWOULDBLOCK) break;
        if (rd == -EINTR) continue;
        result = rd == 0 ? -EPIPE : rd;
        break;
      }
      while (result == 0) {
        if (ch->proto == 1) {
          uint32_t body_len = 0, pmeta_len = 0;
          int prc3 = prpc_peek(ch->rbuf, &body_len, &pmeta_len,
                               kClientMaxBody);
          if (prc3 == 1) break;
          char mscratch[4096];
          if (prc3 != 0 || pmeta_len > sizeof mscratch) {
            result = -EPROTO;
            break;
          }
          if (tb_iobuf_size(ch->rbuf) < kPrpcHeader + body_len) break;
          if (pmeta_len)
            tb_iobuf_copy_to(ch->rbuf, mscratch, pmeta_len, kPrpcHeader);
          tb_iobuf_popn(ch->rbuf, kPrpcHeader + body_len);
          PrpcMeta pm = scan_prpc_meta(mscratch, pmeta_len);
          if (!pm.ok) {
            result = -EPROTO;
          } else {
            bool mis = false;  // count wrong-shard tags; the pump's
            channel_check_cid(ch, pm.cid, &mis);  // completion count stands
            if (pm.error_code != 0) result = -EREMOTEIO;
            ++done;
            --outstanding;
          }
          continue;
        }
        tb_tbus_hdr hdr;
        int prc2 = tb_tbus_peek(ch->rbuf, &hdr);
        if (prc2 == 1) break;
        // the frame cap was missing here (fabricscan wire-bounds catch):
        // without it a hostile server claiming a ~4 GiB body_len makes
        // the "wait for the full frame" test below grow rbuf without
        // bound — the exact DoS pump_once's cap already closed
        if (prc2 == -1 || hdr.meta_len > hdr.body_len ||
            hdr.body_len > kClientMaxBody) {
          result = -EPROTO;
          break;
        }
        if (tb_iobuf_size(ch->rbuf) < kHeader + hdr.body_len) break;
        char mscratch[4096];
        if (hdr.meta_len > sizeof mscratch) {
          result = -EPROTO;
          break;
        }
        // one reusable body handle for the whole pump (clear per frame):
        // a create/destroy pair per response is pure overhead here
        if (ch->pump_body == nullptr) ch->pump_body = tb_iobuf_create();
        if (tb_tbus_cut(ch->rbuf, &hdr, hdr.meta_len ? mscratch : nullptr,
                        ch->pump_body) != 0)
          result = -EPROTO;
        tb_iobuf_clear(ch->pump_body);
        if (result == 0) {
          bool mis = false;
          channel_check_cid(
              ch,
              static_cast<uint64_t>(hdr.cid_lo) |
                  (static_cast<uint64_t>(hdr.cid_hi) << 32),
              &mis);
          if (hdr.error_code != 0) result = -EREMOTEIO;
          ++done;
          --outstanding;
        }
      }
    }
  }
  tb_iobuf_destroy(frame);
  if (result != 0) return result;
  ch->auth_proven.store(true, std::memory_order_relaxed);
  auto dt = std::chrono::duration_cast<std::chrono::nanoseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  return static_cast<long>(dt / n);
}

// fabricscan: role(stop)
void tb_channel_destroy(tb_channel* ch) {
  channel_fail(ch, -ECANCELED);
  if (ch->fd >= 0) close(ch->fd);
  std::unique_lock<std::mutex> pl(ch->pmu);
  for (auto& kv : ch->pending) {
    Pending* p = kv.second;
    if (!p->targeted) {
      if (p->body != nullptr) tb_iobuf_destroy(p->body);
      delete p;
    }
  }
  ch->pending.clear();
  ch->doneq.clear();
  pl.unlock();
  tb_iobuf_destroy(ch->rbuf);
  if (ch->pump_body != nullptr) tb_iobuf_destroy(ch->pump_body);
  delete ch;
}

// ---------------------------------------------------------------------------
// codec C surface (tb_codec_*): the server's codec table exported so the
// Python seam (protocol/compress.py) runs the SAME implementation — the
// client-side compress before a native call, and the Python route's
// decompress, stop paying interpreter-speed codec loops while staying
// byte-identical to the plane by construction.
// ---------------------------------------------------------------------------

long tb_codec_compress(int codec, const void* in, size_t in_len,
                       tb_iobuf* out) {
  static thread_local ZCtx ctx;  // callers are arbitrary Python threads
  int rc = codec_compress(ctx, static_cast<uint32_t>(codec),
                          static_cast<const uint8_t*>(in), in_len, ctx.cbuf);
  if (rc != 0) return rc == -3 ? -3 : -1;
  if (!ctx.cbuf.empty()) tb_iobuf_append(out, ctx.cbuf.data(),
                                         ctx.cbuf.size());
  return static_cast<long>(ctx.cbuf.size());
}

long tb_codec_decompress(int codec, const void* in, size_t in_len,
                         size_t max_out, tb_iobuf* out) {
  static thread_local ZCtx ctx;
  size_t ceil = max_out != 0 ? max_out : static_cast<size_t>(-1);
  int rc = codec_decompress(ctx, static_cast<uint32_t>(codec),
                            static_cast<const uint8_t*>(in), in_len, ceil,
                            ctx.dbuf);
  if (rc != 0) return rc;
  if (!ctx.dbuf.empty()) tb_iobuf_append(out, ctx.dbuf.data(),
                                         ctx.dbuf.size());
  return static_cast<long>(ctx.dbuf.size());
}

// ---------------------------------------------------------------------------
// RpcMeta scanner C surface (tb_scan_prpc_meta): the scanner the server
// cut path and the client pump run, exported so the differential
// wire-decoder fuzz (tests/test_wire_differential.py) can feed identical
// meta bytes to this and to protocol/baidu_std.py's decoder and assert
// the twins agree on accept/reject and on every decoded field.
// ---------------------------------------------------------------------------

long tb_scan_prpc_meta(const void* meta, size_t meta_len,
                       uint64_t* cid_out, long* attachment_out,
                       long* timeout_ms_out, uint32_t* compress_out,
                       uint32_t* error_code_out,
                       char* svc_out, size_t svc_cap, size_t* svc_len_out,
                       char* mth_out, size_t mth_cap, size_t* mth_len_out,
                       uint64_t* log_id_out, uint64_t* trace_id_out,
                       uint64_t* span_id_out, uint64_t* parent_span_id_out,
                       uint32_t* sampled_out) {
  PrpcMeta pm = scan_prpc_meta(static_cast<const char*>(meta), meta_len);
  if (!pm.ok) return -1;  // the connection-kill reject verdict
  if (pm.svc_len > svc_cap || pm.mth_len > mth_cap) return -2;
  *cid_out = pm.cid;
  *attachment_out = pm.attachment;
  *timeout_ms_out = pm.timeout_ms;
  *compress_out = pm.compress;
  *error_code_out = pm.error_code;
  if (pm.svc_len != 0) memcpy(svc_out, pm.svc, pm.svc_len);
  *svc_len_out = pm.svc_len;
  if (pm.mth_len != 0) memcpy(mth_out, pm.mth, pm.mth_len);
  *mth_len_out = pm.mth_len;
  *log_id_out = pm.log_id;
  *trace_id_out = pm.trace_id;
  *span_id_out = pm.span_id;
  *parent_span_id_out = pm.parent_span_id;
  *sampled_out = pm.sampled;
  return (pm.to_python ? 1 : 0) | (pm.is_response ? 2 : 0);
}

// ---------------------------------------------------------------------------
// work-stealing deque C surface (tb_wsq_*): the dispatch pool's Chase–Lev
// deque exported standalone — the TSAN steal-storm stress drives it from
// Python, and future native schedulers can reuse it.
// ---------------------------------------------------------------------------

struct tb_wsq {
  explicit tb_wsq(size_t cap) : d(cap) {}
  WorkDeque d;
};

tb_wsq* tb_wsq_create(size_t capacity) { return new tb_wsq(capacity); }

void tb_wsq_destroy(tb_wsq* q) { delete q; }

int tb_wsq_push(tb_wsq* q, uint64_t value) {
  return q->d.push(value) ? 0 : -1;
}

int tb_wsq_pop(tb_wsq* q, uint64_t* out) { return q->d.pop(out) ? 1 : 0; }

int tb_wsq_steal(tb_wsq* q, uint64_t* out) {
  return q->d.steal(out) ? 1 : 0;
}

long tb_wsq_size(const tb_wsq* q) { return q->d.size(); }
