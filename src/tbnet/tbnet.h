// tbnet — native L2/L3 network plane: epoll reactor, tbus_std AND
// baidu_std (PRPC) frame cut, method dispatch, and a client channel, all
// in C++.
//
// Re-designed counterpart of the reference's I/O core
// (/root/reference/src/brpc/event_dispatcher.cpp epoll loops,
//  input_messenger.cpp:60-129 cut loop, socket.cpp:1591-1686 write path,
//  baidu_rpc_protocol.cpp:92-668 parse/pack+dispatch).  NOT a port: one
// C-ABI surface over the tbutil IOBuf/pool primitives, driven from Python
// via ctypes.  The per-request path — readv, frame cut, CRC verify, method
// lookup, response pack, writev — never touches the Python interpreter for
// natively-registered methods; everything else routes to ONE Python
// callback per frame (the "process_request" boundary), and connections
// that speak a different protocol (HTTP portal, nshead...) are handed off
// to the Python plane wholesale after the first bytes are sniffed (the
// reference's server tries every registered protocol on a new connection
// the same way, input_messenger.cpp:60-129).
//
// Wire protocols spoken natively per connection (sniffed on the first 4
// bytes, fixed for the connection's lifetime):
//   * tbus_std — "TPRC" 32-byte header (protocol/tbus_std.py)
//   * baidu_std — "PRPC" 12-byte header + proto2 RpcMeta, the reference's
//     canonical protocol (baidu_rpc_protocol.cpp:53-58); the RpcMeta
//     varint/length-delimited codec is hand-rolled here, byte-compatible
//     with protocol/baidu_std.py.  Production-shaped frames stay native:
//     compress_type (snappy/gzip/zlib1 via the built-in codec table,
//     decompress on cut + floor-honoring recompress on pack) and
//     authentication_data (verified once per connection — constant-time
//     token table or registered verifier — rejects answered ERPCAUTH)
//     are handled here, byte-identical to the Python codecs, and so is
//     trace context: RpcRequestMeta fields 3/4/5/6 (log_id/trace_id/
//     span_id/parent_span_id, the reference's Dapper fields) plus the
//     head-based sampled bit (field 9, this stack's extension — see
//     docs/PARITY.md) decode on the cut path and ride the telemetry
//     record, so OBSERVED traffic stays on the fast path.  Frames
//     whose meta carries semantics the fast path doesn't implement
//     (stream settings, responses) route per-frame to
//     Python with flag bit 8 (0x100) set in the callback's `flags` so
//     the Python side decodes the meta as RpcMeta instead of JSON (bit
//     9, 0x200, marks a connection whose credential already verified
//     natively).
#ifndef TBNET_H
#define TBNET_H

#include <stddef.h>
#include <stdint.h>

#include "../tbutil/tbutil.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tb_server tb_server;
typedef struct tb_channel tb_channel;

// Per-frame Python route: meta/body of one request frame whose method is
// not natively registered (or that carries stream/response flags,
// compression, or JSON escapes).  Ownership of `body` (payload+attachment,
// meta already stripped) transfers to the callee — it must eventually
// tb_iobuf_destroy it.  Runs on a loop thread; must not block for long.
// `flags` bit 8 (0x100) marks a frame that arrived on a baidu_std (PRPC)
// connection: `meta` is then raw RpcMeta proto bytes, not JSON, and the
// callee answers with PRPC bytes via tb_conn_write.
typedef void (*tb_frame_fn)(void* ctx, uint64_t conn_token, uint32_t cid_lo,
                            uint32_t cid_hi, uint32_t flags,
                            uint32_t error_code, const char* meta,
                            size_t meta_len, tb_iobuf* body);

// Protocol-sniff handoff: the first bytes of a new connection are not
// tbus_std.  The callee takes ownership of `fd` and receives whatever was
// already buffered (copied; free'd by tbnet after the call returns).
typedef void (*tb_handoff_fn)(void* ctx, int fd, const void* buffered,
                              size_t len);

// A connection died (EOF, error, server stop).  The token is already stale
// when this fires; Python uses it to drop per-connection state (streams'
// on_failed hooks).  Not fired for handed-off connections.
typedef void (*tb_closed_fn)(void* ctx, uint64_t conn_token);

// Credential verifier (tb_server_set_auth): called ONCE per connection
// with the first frame's authentication_data (may be NULL/empty when the
// frame carried none) and the peer address.  Return 0 to accept; any
// other value rejects the request with ERPCAUTH (the connection stays
// open and may retry with a fresh credential).  Runs on a loop thread —
// a Python trampoline here costs one GIL crossing per CONNECTION, not
// per request (the verdict caches on the conn).
typedef int (*tb_auth_fn)(void* ud, const char* auth_data, size_t auth_len,
                          const char* peer_ip, int peer_port);

// One completion record per natively-dispatched request (the telemetry
// ring's element; see tb_server_set_telemetry).  Field layout is ABI —
// 64 bytes, checked THREE ways (this header, the ctypes.Structure in
// transport/native_plane.py, and the numpy drain dtype) by fabriclint's
// ffi-struct pass.
typedef struct {
  uint32_t method_idx;      // index into the server's native method table
  uint32_t error_code;      // 0 = success (ELIMIT for admission refusals)
  uint64_t start_ns;        // CLOCK_MONOTONIC at dispatch entry
  uint64_t latency_ns;      // dispatch entry -> response queued
  uint64_t correlation_id;
  uint32_t request_size;    // payload + attachment bytes
  uint32_t response_size;   // payload + attachment bytes (0 on error)
  // bit 0: rpcz sample election (counter-based 1/N, OR wire-forced);
  // bits 1-2: request codec id; bit 3: the sampled bit arrived ON THE
  // WIRE (head-based coherent sampling — the edge's decision, which
  // overrides the local 1/N election)
  uint32_t sampled;
  uint32_t reactor_id;      // reactor that cut/dispatched the request
  // wire-propagated trace context (RpcRequestMeta fields 4/5; 0 = the
  // request carried none): the drain parents this hop's server span
  // into the CALLER's trace instead of minting a fresh one
  uint64_t trace_id;
  uint64_t span_id;
} tb_telemetry_record;

// ---- server ----
// `nloops` is the reactor count: each reactor owns its own epoll fd, loop
// thread, listener (SO_REUSEPORT when nloops > 1), telemetry ring, and
// reusable cut/pack buffers.  Accepted connections are sharded round-robin
// across reactors at accept time and never migrate — the frame-cutter →
// decode → dispatch → pack hot path crosses zero cross-reactor locks.
tb_server* tb_server_create(int nloops);
// Reactor count this server was created with (>= 1).
int tb_server_num_reactors(const tb_server* s);
// Enable the per-port completion-record ring: every natively dispatched
// request appends ONE tb_telemetry_record into a lock-free MPSC ring of
// `capacity` slots (rounded up to a power of two); when the ring is full
// the record is dropped and a counter incremented — the hot path never
// blocks on the observer.  Every sample_every'th record (counter-based,
// 0 = never) carries sampled=1, the rpcz span election.  Call BEFORE
// tb_server_listen; later calls are ignored.  capacity 0 = disabled.
void tb_server_set_telemetry(tb_server* s, uint32_t capacity,
                             uint32_t sample_every);
// Pop up to max_records completed records into `out`; returns the count
// RETURNED, which can be less than what was popped (clock-invalid
// records are discarded and counted as dropped) — callers must drain
// until 0, not until a short batch.  Safe against concurrent loop-thread
// producers; drains race each other safely but the Python side still
// serializes them (single consumer).  Walks every reactor's ring; use
// tb_server_drain_telemetry_ring to drain one reactor's ring in
// per-reactor batches (the vectorized drain's shape).
long tb_server_drain_telemetry(tb_server* s, tb_telemetry_record* out,
                               size_t max_records);
// Drain ONE reactor's completion ring (reactor in [0, num_reactors)).
// Same return/drain-until-0 contract as tb_server_drain_telemetry;
// -1 for an out-of-range reactor.
long tb_server_drain_telemetry_ring(tb_server* s, int reactor,
                                    tb_telemetry_record* out,
                                    size_t max_records);
// Records lost: ring overflow + clock-invalid discards at drain
// (0 when telemetry is disabled).  Summed across every reactor's ring.
uint64_t tb_server_telemetry_dropped(const tb_server* s);
// Per-reactor counters (reactor in [0, num_reactors)): live connections
// owned by the reactor, requests it dispatched natively, and its
// telemetry ring's drop count.  0 ok, -1 out of range.  Any thread.
int tb_server_reactor_stats(const tb_server* s, int reactor,
                            uint64_t* live_conns, uint64_t* native_reqs,
                            uint64_t* telemetry_dropped);
void tb_server_set_frame_cb(tb_server* s, tb_frame_fn cb, void* ctx);
void tb_server_set_handoff_cb(tb_server* s, tb_handoff_fn cb, void* ctx);
void tb_server_set_closed_cb(tb_server* s, tb_closed_fn cb, void* ctx);
void tb_server_set_max_body(tb_server* s, size_t bytes);
// Response-compression floor (native_compress_min_bytes): a PRPC request
// that arrived compressed gets its response recompressed with the same
// codec ONLY when the payload has at least this many bytes — tiny
// payloads answer uncompressed, matching the Python route's floor so the
// planes stay byte-identical.  0 = always recompress.
void tb_server_set_compress_min_bytes(tb_server* s, size_t bytes);
// Decompressed-size ceiling (max_decompress_bytes): a compressed request
// whose payload would expand past this is rejected EREQUEST instead of
// expanding into server memory (0 = unlimited; default 256 MiB).
void tb_server_set_max_decompress(tb_server* s, size_t bytes);
// Install a credential verifier: PRPC frames carrying
// authentication_data are verified natively (once per connection,
// verdict cached) and rejects answered ERPCAUTH byte-identically to the
// Python route.  Pre-listen only (0 ok, -1 after listen).
int tb_server_set_auth(tb_server* s, tb_auth_fn fn, void* ud);
// Constant-time token table (the default trampoline): blob is repeated
// [u32 LE length][bytes] records; a credential equal to ANY token
// verifies — entirely in C, so authenticated steady-state traffic never
// enters the interpreter.  Replaces the previous table.  Pre-listen only
// (0 ok, -1 after listen or on a malformed blob).
int tb_server_set_auth_tokens(tb_server* s, const char* blob,
                              size_t blob_len);
// Requests rejected ERPCAUTH by the native auth seam (the
// native_auth_rejects bvar feed).
uint64_t tb_server_auth_rejects(const tb_server* s);
// Compressed-traffic byte counters: request wire (compressed) and raw
// (decompressed) bytes in, response raw and wire bytes out — the
// native_compress_bytes_saved feed.  Any thread.
void tb_server_compress_stats(const tb_server* s, uint64_t* in_wire,
                              uint64_t* in_raw, uint64_t* out_raw,
                              uint64_t* out_wire);
// kind: 1 = echo (respond with the request body), 2 = nop (empty response).
// max_concurrency 0 = unlimited; exceeding it answers ELIMIT natively.
// runtime retune of a native method's admission limit (0 = unlimited)
int tb_server_set_native_max_concurrency(tb_server* s, const char* full_name,
                                         uint32_t max_concurrency);
long tb_server_get_native_max_concurrency(tb_server* s,
                                          const char* full_name);
int tb_server_register_native(tb_server* s, const char* full_name, int kind,
                              uint32_t max_concurrency);
// User native method: bytes-in/bytes-out C callback, run entirely on the
// loop thread — the request never crosses into Python (the reference's
// whole ProcessRpcRequest/user-code/SendRpcResponse round is native,
// baidu_rpc_protocol.cpp:307-503; this is that generality for tbnet).
// Contract: `req` is the contiguous request payload (attachment included,
// valid only during the call); on success (return 0) the callee mallocs
// *resp (may be NULL when *resp_len==0) and tbnet free()s it after the
// response is queued.  A nonzero return becomes the response error_code.
// Must not block — it runs on the connection's event loop — and MUST be
// thread-safe: connections are round-robined across loops, so the same
// callback runs concurrently on multiple loop threads.
typedef int (*tb_native_fn)(void* ud, const char* req, size_t req_len,
                            char** resp, size_t* resp_len);
int tb_server_register_native_fn(tb_server* s, const char* full_name,
                                 tb_native_fn fn, void* ud,
                                 uint32_t max_concurrency);
// Work-stealing dispatch pool: `nworkers` threads, each reactor owning a
// Chase–Lev deque the workers steal from when their preferred deque runs
// empty.  User methods (tb_server_register_native_fn kinds) flagged
// long-running — or arriving behind a queue-depth-pressured burst —
// defer to the pool so one slow handler can't stall its reactor's
// cut/pack work; fast methods stay inline on the loop thread.  Call
// BEFORE tb_server_listen (0 disables; returns -1 after listen).
int tb_server_set_dispatch_pool(tb_server* s, int nworkers);
// Mark a registered user method long-running: with a dispatch pool
// enabled its requests always defer to the pool.  0 ok, -1 unknown
// method.  Runtime-safe (loop threads read the flag per request).
int tb_server_set_native_long_running(tb_server* s, const char* full_name,
                                      int on);
// listen on ip:port (port 0 = ephemeral); returns the bound port or -errno.
int tb_server_listen(tb_server* s, const char* ip, int port);
int tb_server_port(const tb_server* s);
// stop accepting, fail every connection, join the loop threads.
void tb_server_stop(tb_server* s);
void tb_server_destroy(tb_server* s);
void tb_server_stats(const tb_server* s, uint64_t* accepted,
                     uint64_t* native_reqs, uint64_t* cb_frames,
                     uint64_t* handoffs, uint64_t* live_conns);
// Requests answered EDEADLINE because their propagated deadline (RpcMeta
// timeout_ms / JSON meta timeout_ms) expired before dispatch — the
// native plane's feed for the deadline_shed_count bvar.
uint64_t tb_server_deadline_sheds(const tb_server* s);
// Lame-duck: stop accepting NEW connections while existing ones keep
// being served.  Asynchronous and reactor-aware — EVERY reactor tears
// down its own listener on its own loop thread at its next wakeup
// (sub-ms).  Irreversible for this server; tb_server_stop still performs
// the full teardown.
void tb_server_pause_accept(tb_server* s);
// Close every connection idle (no readable burst) for >= idle_ms,
// across every reactor's connection list.  Thread-safe (shutdown(); the
// owning reactor reaps via EPOLLHUP — the tb_conn_close discipline).
// Returns the number of connections culled.
long tb_server_close_idle(tb_server* s, uint64_t idle_ms);

// ---- per-connection surface (used by the Python frame route) ----
// Queue a tbus_std response frame on the connection (tbus_std conns only;
// the Python route answers baidu_std conns with pre-packed PRPC bytes
// through tb_conn_write). 0 ok, -1 stale token.
int tb_conn_respond(uint64_t token, const void* meta, size_t meta_len,
                    const void* payload, size_t payload_len,
                    const void* att, size_t att_len, uint32_t cid_lo,
                    uint32_t cid_hi, uint32_t flags, uint32_t error_code);
// Queue arbitrary pre-framed bytes (stream frames, feedback). Consumes
// nothing from `data` (refs are shared). 0 ok, -1 stale token.
int tb_conn_write(uint64_t token, const tb_iobuf* data);
// Peer address. Returns port (>=0) and fills ip (textual), or -1.
int tb_conn_peer(uint64_t token, char* ip_out, size_t ip_cap);
// Fail + close the connection (0 ok, -1 stale).
int tb_conn_close(uint64_t token);
// Cache a Python-route auth verdict on the connection: its later frames
// ride the native fast path without re-fighting the credential (0 ok,
// -1 stale token).
int tb_conn_set_authenticated(uint64_t token);

// ---- client channel ----
// Blocking connect with timeout; NULL on failure (*err_out = errno).
// Every channel pins to a client reactor shard at connect (round-robin
// over a process-global counter): the correlation-id space is
// partitioned per shard — the top 8 bits of every cid the channel mints
// carry its shard id, so completions route back to the owning channel's
// pending table with NO shared cross-channel map, and a response whose
// cid names a different shard is detectably misrouted (see
// tb_channel_cid_misroutes) instead of silently corrupting a wait.
tb_channel* tb_channel_connect(const char* ip, int port, int timeout_ms,
                               int* err_out);
// The client reactor shard this channel pinned at connect (>= 0).
int tb_channel_reactor(const tb_channel* ch);
// Responses observed with a WRONG shard tag in their correlation id.
// Each one is counted, re-tagged to the local shard, and — when a
// pending call with the same sequence exists — completes that call with
// -EBADMSG (the Python plane surfaces it as EREQUEST); the channel
// itself survives.
uint64_t tb_channel_cid_misroutes(const tb_channel* ch);
// Select the channel's wire protocol BEFORE the first send: 0 = tbus_std
// (default), 1 = baidu_std (PRPC).  In baidu_std mode the `meta` argument
// of call/send/pump is the pre-encoded RpcRequestMeta SUBMESSAGE
// (service_name/method_name/...); the channel wraps it into a full
// RpcMeta, splicing in its own correlation_id and attachment_size, so the
// emitted frames are byte-identical to protocol/baidu_std.py's
// pack_request.  meta_out of call/recv receives the raw response RpcMeta
// proto bytes (decode on the Python side); err_code_out carries the
// RpcResponseMeta error_code.  Returns 0, or -1 for an unknown protocol.
int tb_channel_set_protocol(tb_channel* ch, int proto);
// Channel-default request compress_type (baidu_std RpcMeta field 3,
// values 0-3 per options.proto).  The CALLER compresses payloads with
// the matching codec before call/send/pump — this stamps the wire field
// only.  In baidu_std mode the low 4 bits of call/send's flags_extra
// override it per call.  Set before concurrent use.  0 ok, -1 bad value.
int tb_channel_set_compress(tb_channel* ch, int compress_type);
// Credential for RpcMeta field 7 (authentication_data), stamped on every
// request until the first successful response proves the connection —
// the reference's first-request auth fight.  NULL/0 clears.  Set before
// concurrent use.  Returns 0.
int tb_channel_set_auth(tb_channel* ch, const void* data, size_t len);
// Ambient trace context for the pipelined pump (tb_channel_pump):
// every `every`'th frame of a pump carries the trace fields in its
// RpcRequestMeta (3 log_id / 4 trace_id / 5 span_id / 6 parent_span_id
// / 9 sampled) — counter-scheduled exact-rate like the fault seam, so a
// traced flood is one call.  Per traced frame the span_id is
// `span_id + sequence` (patched in the pump's fixed-width template), so
// every traced request is its own child span of `parent_span_id`.
// `every` 0 disables; 1 = every frame.  baidu_std channels only (the
// tbus pump meta is caller-built); set before concurrent use.
// Returns 0, or -1 on a tbus_std channel with every != 0.
int tb_channel_set_trace(tb_channel* ch, uint64_t log_id, uint64_t trace_id,
                         uint64_t span_id, uint64_t parent_span_id,
                         int sampled, uint32_t every);
// Counter-scheduled client-side fault injection (the native analog of
// the Python Socket.write seam, rpc/fault_injector.py): every
// fail_every'th tb_channel_call answers err_code (0 -> EINTERNAL)
// without touching the wire, every close_every'th kills the connection
// mid-run, every delay_every'th sleeps delay_ms first.  0 disables a
// schedule; set before issuing concurrent calls.  Returns 0.
int tb_channel_set_fault(tb_channel* ch, uint32_t fail_every,
                         uint32_t close_every, uint32_t delay_every,
                         uint32_t delay_ms, uint32_t err_code);
// Synchronous call over the shared connection.  Thread-safe: concurrent
// callers elect one reader which pumps completions for everyone (the
// single-connection multi-caller shape of the reference's client,
// socket.cpp write queue + cid wakeups).  Returns body length (>=0) or
// -errno (-ETIMEDOUT, -EPIPE, -EPROTO...).  body_out receives
// payload+attachment; resp meta (JSON) is copied into meta_out.
long tb_channel_call(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len,
                     const void* att, size_t att_len, uint32_t flags_extra,
                     tb_iobuf* body_out, void* meta_out, size_t meta_cap,
                     uint32_t* meta_len_out, uint32_t* err_code_out,
                     int timeout_ms);
// Pipelined surface: send returns the frame's cid (>0) or 0 on error
// (*err_out = errno); recv returns the body length of ONE completed
// send()-originated frame (filling cid_out/meta/err_code) or -errno.
uint64_t tb_channel_send(tb_channel* ch, const void* meta, size_t meta_len,
                         const void* payload, size_t payload_len,
                         const void* att, size_t att_len,
                         uint32_t flags_extra, int* err_out);
long tb_channel_recv(tb_channel* ch, uint64_t* cid_out, tb_iobuf* body_out,
                     void* meta_out, size_t meta_cap, uint32_t* meta_len_out,
                     uint32_t* err_code_out, int timeout_ms);
// Sticky failure code (0 = healthy).
int tb_channel_error(const tb_channel* ch);
void tb_channel_destroy(tb_channel* ch);

// Native perf harness (the example/rdma_performance client analog; the
// Python rpc_press tool drives the same shape from the interpreter):
// issue `n` requests keeping `inflight` outstanding on this connection,
// entirely in C++.  Requires exclusive use of the channel for the call's
// duration (takes both the writer and reader roles).  Returns ns/request,
// or -errno.
long tb_channel_pump(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len, int n,
                     int inflight, int timeout_ms);

// ---- codec table (the native compress/auth seam's codecs, exported) ----
// codec: 1 = snappy (block format), 2 = gzip (deterministic container,
// mtime=0), 3 = zlib level 1.  Appends the result to `out` and returns
// the byte count, or negative: -1 corrupt input, -2 output beyond
// max_out (decompress only; 0 = unlimited), -3 unknown codec.  Any
// thread (per-thread codec state).  protocol/compress.py prefers these
// over its pure-Python twins so BOTH planes run the identical codec.
long tb_codec_compress(int codec, const void* in, size_t in_len,
                       tb_iobuf* out);
long tb_codec_decompress(int codec, const void* in, size_t in_len,
                         size_t max_out, tb_iobuf* out);

// ---- RpcMeta scanner (differential-testing surface) ----
// Runs the SAME proto2 scanner the server cut path and the client pump
// run over one RpcMeta blob, so tests can feed identical bytes to this
// and to protocol/baidu_std.py's decoder and diff the verdicts.
// Returns -1 when the scanner rejects (the connection-kill path), -2
// when a decoded service/method name exceeds its caller cap, else a
// flags bitmask: bit 0 = fields beyond the native fast path's scope
// (the frame would route to Python), bit 1 = response meta.  On accept
// every out-param is filled (names copied raw — they may contain NULs;
// read *svc_len_out/*mth_len_out, not strlen).  The trace out-params
// carry RpcRequestMeta fields 3/4/5/6 (+ the field-9 sampled bit) so
// the wire-differential fuzz diffs the trace decode too.  Diagnostic
// surface, not a hot path.
long tb_scan_prpc_meta(const void* meta, size_t meta_len,
                       uint64_t* cid_out, long* attachment_out,
                       long* timeout_ms_out, uint32_t* compress_out,
                       uint32_t* error_code_out,
                       char* svc_out, size_t svc_cap, size_t* svc_len_out,
                       char* mth_out, size_t mth_cap, size_t* mth_len_out,
                       uint64_t* log_id_out, uint64_t* trace_id_out,
                       uint64_t* span_id_out, uint64_t* parent_span_id_out,
                       uint32_t* sampled_out);

// ---- work-stealing deque (Chase–Lev) ----
// The dispatch pool's per-reactor queue, exported standalone so the
// TSAN stress (and any future native scheduler) can drive it directly:
// ONE owner thread pushes/pops the bottom, any number of thieves steal
// the top.  Values are opaque u64 (the server stores task pointers).
typedef struct tb_wsq tb_wsq;
// capacity is rounded up to a power of two (min 64).
tb_wsq* tb_wsq_create(size_t capacity);
void tb_wsq_destroy(tb_wsq* q);
// Owner-only: 0 ok, -1 full (caller runs the work inline — backpressure,
// never blocking).
int tb_wsq_push(tb_wsq* q, uint64_t value);
// Owner-only: 1 = popped into *out, 0 = empty.
int tb_wsq_pop(tb_wsq* q, uint64_t* out);
// Any thread: 1 = stolen into *out, 0 = empty or lost the race (retry).
int tb_wsq_steal(tb_wsq* q, uint64_t* out);
// Approximate outstanding count (owner's view; racy by design).
long tb_wsq_size(const tb_wsq* q);

#ifdef __cplusplus
}
#endif
#endif  // TBNET_H
