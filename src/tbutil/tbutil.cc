// tbutil implementation — see tbutil.h for the design contract and the
// reference counterparts each piece mirrors.
#include "tbutil.h"

#include <errno.h>
#include <string.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>
#include <zlib.h>

#include <atomic>
#include <cstdlib>
#include <deque>
#include <list>
#include <mutex>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Blocks
// ---------------------------------------------------------------------------

enum BlockSource : uint8_t {
  SRC_POOL = 0,      // header+data in one allocation, cached in the pool
  SRC_MALLOC = 1,    // same layout but non-default cap: freed, not cached
  SRC_EXTERNAL = 2,  // data owned by caller; release_cb on last unref
  SRC_REGION = 3,    // data carved from a registered region slab
};

struct Block {
  std::atomic<uint32_t> nshared;
  std::atomic<uint32_t> size;  // high-water write offset into data
  uint32_t cap;
  uint8_t source;
  int region_id;
  char* data;
  tb_release_fn release_cb;
  void* release_ctx;
  Block* next;  // freelist link
};

std::atomic<size_t> g_default_block_size{8192};
std::atomic<size_t> g_blocks_live{0};

// Global overflow cache behind the TLS caches.
struct GlobalBlockCache {
  std::mutex mu;
  Block* head = nullptr;
  size_t count = 0;
  static constexpr size_t kMax = 1024;
};
GlobalBlockCache g_block_cache;

// Per-thread cache (reference keeps <=8 blocks/thread, iobuf.cpp:355-430).
struct TlsBlockCache {
  // sized to one full readv burst so the read loop recycles blocks
  // through the TLS cache instead of malloc (reference keeps 8/thread;
  // our reader frees a whole burst at once after the cut)
  static constexpr size_t kMax = 64;
  Block* head = nullptr;
  size_t count = 0;
  ~TlsBlockCache();
};

void free_block_memory(Block* b) {
  g_blocks_live.fetch_sub(1, std::memory_order_relaxed);
  ::free(b);
}

TlsBlockCache::~TlsBlockCache() {
  // Thread exit: hand cached blocks to the global cache (or free).
  std::lock_guard<std::mutex> lk(g_block_cache.mu);
  while (head) {
    Block* b = head;
    head = b->next;
    if (g_block_cache.count < GlobalBlockCache::kMax) {
      b->next = g_block_cache.head;
      g_block_cache.head = b;
      ++g_block_cache.count;
    } else {
      free_block_memory(b);
    }
  }
  count = 0;
}

thread_local TlsBlockCache tls_block_cache;

Block* alloc_block_raw(size_t cap) {
  Block* b = static_cast<Block*>(::malloc(sizeof(Block) + cap));
  if (!b) return nullptr;
  g_blocks_live.fetch_add(1, std::memory_order_relaxed);
  b->nshared.store(1, std::memory_order_relaxed);
  b->size.store(0, std::memory_order_relaxed);
  b->cap = static_cast<uint32_t>(cap);
  b->source = cap == g_default_block_size.load(std::memory_order_relaxed)
                  ? SRC_POOL
                  : SRC_MALLOC;
  b->region_id = -1;
  b->data = reinterpret_cast<char*>(b + 1);
  b->release_cb = nullptr;
  b->release_ctx = nullptr;
  b->next = nullptr;
  return b;
}

Block* get_block() {
  const size_t def = g_default_block_size.load(std::memory_order_relaxed);
  TlsBlockCache& tls = tls_block_cache;
  while (tls.head) {
    Block* b = tls.head;
    tls.head = b->next;
    --tls.count;
    if (b->cap == def) {
      b->nshared.store(1, std::memory_order_relaxed);
      b->size.store(0, std::memory_order_relaxed);
      b->next = nullptr;
      return b;
    }
    free_block_memory(b);  // stale size after tb_set_block_size
  }
  {
    std::lock_guard<std::mutex> lk(g_block_cache.mu);
    while (g_block_cache.head) {
      Block* b = g_block_cache.head;
      g_block_cache.head = b->next;
      --g_block_cache.count;
      if (b->cap == def) {
        b->nshared.store(1, std::memory_order_relaxed);
        b->size.store(0, std::memory_order_relaxed);
        b->next = nullptr;
        return b;
      }
      free_block_memory(b);
    }
  }
  return alloc_block_raw(def);
}

// ---- regions ----

struct Region {
  char* base = nullptr;
  size_t block_bytes = 0;
  std::mutex mu;
  std::vector<char*> freelist;
};
std::mutex g_regions_mu;
std::deque<Region>* g_regions = nullptr;  // leaked on purpose (never-free)

void region_return(int rid, char* data) {
  std::lock_guard<std::mutex> lk(g_regions_mu);
  Region& r = (*g_regions)[static_cast<size_t>(rid)];
  std::lock_guard<std::mutex> lk2(r.mu);
  r.freelist.push_back(data);
}

void dec_ref(Block* b) {
  if (b->nshared.fetch_sub(1, std::memory_order_acq_rel) != 1) return;
  switch (b->source) {
    case SRC_EXTERNAL: {
      // Last ref dropped: fire the owner's release callback on this thread.
      // Contract (reference iobuf.cpp:258-306): cb must be cheap/non-
      // blocking — it may run on a transport completion path.
      if (b->release_cb) b->release_cb(b->data, b->release_ctx);
      g_blocks_live.fetch_sub(1, std::memory_order_relaxed);
      ::free(b);
      return;
    }
    case SRC_REGION: {
      region_return(b->region_id, b->data);
      g_blocks_live.fetch_sub(1, std::memory_order_relaxed);
      ::free(b);
      return;
    }
    case SRC_MALLOC:
      free_block_memory(b);
      return;
    case SRC_POOL:
    default: {
      TlsBlockCache& tls = tls_block_cache;
      if (tls.count < TlsBlockCache::kMax) {
        b->next = tls.head;
        tls.head = b;
        ++tls.count;
        return;
      }
      std::lock_guard<std::mutex> lk(g_block_cache.mu);
      if (g_block_cache.count < GlobalBlockCache::kMax) {
        b->next = g_block_cache.head;
        g_block_cache.head = b;
        ++g_block_cache.count;
        return;
      }
      free_block_memory(b);
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// IOBuf
// ---------------------------------------------------------------------------

struct BlockRef {
  Block* block;
  uint32_t offset;
  uint32_t length;
};

}  // namespace

struct tb_iobuf {
  std::deque<BlockRef> refs;
  size_t nbytes = 0;
};

namespace {

// Try to extend the tail ref in place. Safe under sharing: extension is a
// CAS claiming [expected, expected+m) of the block, so two IOBufs sharing
// the tail block can never hand out the same bytes twice.
size_t append_into_tail(tb_iobuf* b, const char* data, size_t n) {
  if (b->refs.empty()) return 0;
  BlockRef& r = b->refs.back();
  Block* blk = r.block;
  if (blk->source == SRC_EXTERNAL) return 0;
  uint32_t expected = r.offset + r.length;
  if (expected >= blk->cap) return 0;
  uint32_t m = static_cast<uint32_t>(
      n < static_cast<size_t>(blk->cap - expected) ? n : blk->cap - expected);
  uint32_t cur = expected;
  if (!blk->size.compare_exchange_strong(cur, expected + m,
                                         std::memory_order_acq_rel)) {
    return 0;  // someone else extended past our view; take a fresh block
  }
  memcpy(blk->data + expected, data, m);
  r.length += m;
  b->nbytes += m;
  return m;
}

void push_ref_shared(tb_iobuf* b, const BlockRef& r) {
  r.block->nshared.fetch_add(1, std::memory_order_relaxed);
  b->refs.push_back(r);
  b->nbytes += r.length;
}

}  // namespace

extern "C" {

void tb_set_block_size(size_t bytes) {
  if (bytes < 64) bytes = 64;
  g_default_block_size.store(bytes, std::memory_order_relaxed);
}

size_t tb_block_size(void) {
  return g_default_block_size.load(std::memory_order_relaxed);
}

void tb_block_pool_stats(size_t* live, size_t* cached) {
  if (live) *live = g_blocks_live.load(std::memory_order_relaxed);
  if (cached) {
    size_t c = tls_block_cache.count;
    std::lock_guard<std::mutex> lk(g_block_cache.mu);
    *cached = c + g_block_cache.count;
  }
}

// IOBuf handles churn once per frame on the hot path: they come from the
// never-freeing ObjectPool (placement-new over pooled slots) instead of
// malloc/free — the reference backs its hottest fixed-size objects with
// the same pool (object_pool.h; butex objects, TaskMeta).
static tb_objpool* iobuf_handle_pool() {
  static tb_objpool* pool = tb_objpool_create(sizeof(tb_iobuf));
  return pool;
}

tb_iobuf* tb_iobuf_create(void) {
  void* mem = tb_objpool_get(iobuf_handle_pool());
  if (!mem) return nullptr;
  return new (mem) tb_iobuf();
}

void tb_iobuf_clear(tb_iobuf* b) {
  for (BlockRef& r : b->refs) dec_ref(r.block);
  b->refs.clear();
  b->nbytes = 0;
}

void tb_iobuf_destroy(tb_iobuf* b) {
  if (!b) return;
  tb_iobuf_clear(b);
  b->~tb_iobuf();
  tb_objpool_return(iobuf_handle_pool(), b);
}

void tb_iobuf_handle_pool_stats(size_t* live, size_t* free_count) {
  if (live) *live = tb_objpool_live(iobuf_handle_pool());
  if (free_count) *free_count = tb_objpool_free_count(iobuf_handle_pool());
}

size_t tb_iobuf_size(const tb_iobuf* b) { return b->nbytes; }

size_t tb_iobuf_block_count(const tb_iobuf* b) { return b->refs.size(); }

void tb_iobuf_append(tb_iobuf* b, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  size_t done = append_into_tail(b, p, n);
  p += done;
  n -= done;
  while (n > 0) {
    Block* blk = get_block();
    uint32_t m = static_cast<uint32_t>(n < blk->cap ? n : blk->cap);
    memcpy(blk->data, p, m);
    blk->size.store(m, std::memory_order_release);
    b->refs.push_back(BlockRef{blk, 0, m});
    b->nbytes += m;
    p += m;
    n -= m;
  }
}

namespace {

// Shared-release shim for external buffers that exceed one Block's 32-bit
// length field: each chunk-block decrements; the last one fires the user
// callback exactly once.
struct SharedExternal {
  std::atomic<uint32_t> pending;
  char* base;
  tb_release_fn cb;
  void* ctx;
};

void shared_external_release(void* data, void* shim_ptr) {
  (void)data;
  SharedExternal* s = static_cast<SharedExternal*>(shim_ptr);
  if (s->pending.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (s->cb) s->cb(s->base, s->ctx);
    delete s;
  }
}

}  // namespace

void tb_iobuf_append_external(tb_iobuf* b, void* data, size_t n,
                              tb_release_fn cb, void* ctx) {
  // BlockRef lengths are 32-bit; chunk huge buffers across several
  // external blocks sharing one release shim so the callback still fires
  // exactly once, after the last chunk's last ref drops.
  constexpr size_t kMaxChunk = 0xC0000000u;  // 3 GiB, well under UINT32_MAX
  const size_t nchunks = n == 0 ? 1 : (n + kMaxChunk - 1) / kMaxChunk;
  SharedExternal* shim = nullptr;
  if (nchunks > 1) {
    shim = new SharedExternal{
        {static_cast<uint32_t>(nchunks)}, static_cast<char*>(data), cb, ctx};
  }
  char* p = static_cast<char*>(data);
  size_t left = n;
  for (size_t i = 0; i < nchunks; ++i) {
    const size_t m = left < kMaxChunk ? left : kMaxChunk;
    Block* blk = static_cast<Block*>(::malloc(sizeof(Block)));
    g_blocks_live.fetch_add(1, std::memory_order_relaxed);
    blk->nshared.store(1, std::memory_order_relaxed);
    blk->size.store(static_cast<uint32_t>(m), std::memory_order_relaxed);
    blk->cap = static_cast<uint32_t>(m);
    blk->source = SRC_EXTERNAL;
    blk->region_id = -1;
    blk->data = p;
    if (shim) {
      blk->release_cb = shared_external_release;
      blk->release_ctx = shim;
    } else {
      blk->release_cb = cb;
      blk->release_ctx = ctx;
    }
    blk->next = nullptr;
    b->refs.push_back(BlockRef{blk, 0, static_cast<uint32_t>(m)});
    b->nbytes += m;
    p += m;
    left -= m;
  }
}

void tb_iobuf_append_iobuf(tb_iobuf* to, const tb_iobuf* from) {
  for (const BlockRef& r : from->refs) push_ref_shared(to, r);
}

size_t tb_iobuf_cutn(tb_iobuf* from, tb_iobuf* to, size_t n) {
  size_t moved = 0;
  while (n > 0 && !from->refs.empty()) {
    BlockRef& r = from->refs.front();
    if (r.length <= n) {
      to->refs.push_back(r);  // ref moves wholesale; refcount unchanged
      to->nbytes += r.length;
      from->nbytes -= r.length;
      n -= r.length;
      moved += r.length;
      from->refs.pop_front();
    } else {
      BlockRef part{r.block, r.offset, static_cast<uint32_t>(n)};
      push_ref_shared(to, part);
      r.offset += static_cast<uint32_t>(n);
      r.length -= static_cast<uint32_t>(n);
      from->nbytes -= n;
      moved += n;
      n = 0;
    }
  }
  return moved;
}

size_t tb_iobuf_popn(tb_iobuf* from, size_t n) {
  size_t popped = 0;
  while (n > 0 && !from->refs.empty()) {
    BlockRef& r = from->refs.front();
    if (r.length <= n) {
      n -= r.length;
      popped += r.length;
      from->nbytes -= r.length;
      dec_ref(r.block);
      from->refs.pop_front();
    } else {
      r.offset += static_cast<uint32_t>(n);
      r.length -= static_cast<uint32_t>(n);
      from->nbytes -= n;
      popped += n;
      n = 0;
    }
  }
  return popped;
}

size_t tb_iobuf_copy_to(const tb_iobuf* b, void* out, size_t n, size_t pos) {
  char* dst = static_cast<char*>(out);
  size_t copied = 0;
  for (const BlockRef& r : b->refs) {
    if (n == 0) break;
    if (pos >= r.length) {
      pos -= r.length;
      continue;
    }
    size_t avail = r.length - pos;
    size_t m = n < avail ? n : avail;
    memcpy(dst + copied, r.block->data + r.offset + pos, m);
    copied += m;
    n -= m;
    pos = 0;
  }
  return copied;
}

int tb_iobuf_refs(const tb_iobuf* b, tb_ref_view* out, int max) {
  int i = 0;
  for (const BlockRef& r : b->refs) {
    if (i >= max) break;
    out[i].data = r.block->data + r.offset;
    out[i].length = r.length;
    ++i;
  }
  return i;
}

int tb_iobuf_block_shared_count(const tb_iobuf* b, size_t i) {
  if (i >= b->refs.size()) return -1;
  return static_cast<int>(
      b->refs[i].block->nshared.load(std::memory_order_relaxed));
}

long tb_iobuf_cut_into_fd(tb_iobuf* b, int fd, size_t max_bytes) {
  // Continuation loop over the 256-iovec writev ceiling: a multi-MB
  // backlog of small blocks (256 × 8 KB = 2 MB per writev) keeps writing
  // until max_bytes, a short write (kernel buffer full), or an error —
  // callers see ONE call drain what the kernel will take instead of
  // bouncing through the ctypes boundary once per 2 MB.
  constexpr int kMaxIov = 256;
  struct iovec iov[kMaxIov];
  long written_total = 0;
  while (static_cast<size_t>(written_total) < max_bytes) {
    int niov = 0;
    size_t total = 0;
    size_t budget = max_bytes - static_cast<size_t>(written_total);
    for (const BlockRef& r : b->refs) {
      if (niov >= kMaxIov || total >= budget) break;
      size_t len = r.length;
      if (total + len > budget) len = budget - total;
      iov[niov].iov_base = r.block->data + r.offset;
      iov[niov].iov_len = len;
      total += len;
      ++niov;
    }
    if (niov == 0) break;
    ssize_t nw = ::writev(fd, iov, niov);
    if (nw < 0) {
      if (errno == EINTR) continue;
      return written_total > 0 ? written_total : -errno;
    }
    tb_iobuf_popn(b, static_cast<size_t>(nw));
    written_total += nw;
    if (static_cast<size_t>(nw) < total) break;  // short write: kernel full
  }
  return written_total;
}

// iovec budget per readv: 64 default blocks = 512KB/burst — the bytes-
// per-event ceiling of the reader loop (the reference's IOPortal reads
// with a comparable budget; 8 iovecs capped loopback at ~64KB/event)
constexpr int kReadIovBudget = 64;

size_t tb_iobuf_read_burst(void) {
  return kReadIovBudget * g_default_block_size.load(std::memory_order_relaxed);
}

long tb_iobuf_append_from_fd(tb_iobuf* b, int fd, size_t max_bytes) {
  constexpr int kMaxIov = kReadIovBudget;
  Block* blocks[kMaxIov];
  struct iovec iov[kMaxIov];
  int niov = 0;
  size_t total = 0;
  while (niov < kMaxIov && total < max_bytes) {
    Block* blk = get_block();
    blocks[niov] = blk;
    size_t want = max_bytes - total;
    size_t len = want < blk->cap ? want : blk->cap;
    iov[niov].iov_base = blk->data;
    iov[niov].iov_len = len;
    total += len;
    ++niov;
  }
  ssize_t nr = ::readv(fd, iov, niov);
  if (nr < 0) {
    int err = errno;
    for (int i = 0; i < niov; ++i) dec_ref(blocks[i]);
    return -err;
  }
  size_t left = static_cast<size_t>(nr);
  for (int i = 0; i < niov; ++i) {
    if (left == 0) {
      dec_ref(blocks[i]);
      continue;
    }
    uint32_t used = static_cast<uint32_t>(
        left < iov[i].iov_len ? left : iov[i].iov_len);
    blocks[i]->size.store(used, std::memory_order_release);
    b->refs.push_back(BlockRef{blocks[i], 0, used});
    b->nbytes += used;
    left -= used;
  }
  return nr;
}

// Bulk variant for long streaming drains: same readv shape, but blocks of
// ``block_bytes`` (SRC_MALLOC — freed, not pooled) instead of the pooled
// default. 64 x 8 KB pooled blocks cap a burst at 512 KB and cost a
// refcount+freelist round trip per 8 KB; a saturated byte stream reads
// multi-MB bursts into a handful of big blocks instead (the reference's
// IOPortal grows its read budget the same way when a socket keeps
// delivering full reads, input_messenger read loop).
long tb_iobuf_append_from_fd_bulk(tb_iobuf* b, int fd, size_t max_bytes,
                                  size_t block_bytes) {
  const size_t def = g_default_block_size.load(std::memory_order_relaxed);
  if (block_bytes <= def) return tb_iobuf_append_from_fd(b, fd, max_bytes);
  constexpr int kMaxIov = 32;
  Block* blocks[kMaxIov];
  struct iovec iov[kMaxIov];
  int niov = 0;
  size_t total = 0;
  while (niov < kMaxIov && total < max_bytes) {
    size_t want = max_bytes - total;
    size_t cap = want < block_bytes ? want : block_bytes;
    Block* blk = alloc_block_raw(cap);
    if (blk == nullptr) break;
    blocks[niov] = blk;
    iov[niov].iov_base = blk->data;
    iov[niov].iov_len = cap;
    total += cap;
    ++niov;
  }
  if (niov == 0) return -ENOMEM;
  ssize_t nr = ::readv(fd, iov, niov);
  if (nr < 0) {
    int err = errno;
    for (int i = 0; i < niov; ++i) dec_ref(blocks[i]);
    return -err;
  }
  size_t left = static_cast<size_t>(nr);
  for (int i = 0; i < niov; ++i) {
    if (left == 0) {
      dec_ref(blocks[i]);
      continue;
    }
    uint32_t used = static_cast<uint32_t>(
        left < iov[i].iov_len ? left : iov[i].iov_len);
    blocks[i]->size.store(used, std::memory_order_release);
    b->refs.push_back(BlockRef{blocks[i], 0, used});
    b->nbytes += used;
    left -= used;
  }
  return nr;
}

// ---- regions ----

int tb_region_register(void* base, size_t total, size_t block_bytes) {
  if (!base || block_bytes == 0 || total < block_bytes) return -1;
  std::lock_guard<std::mutex> lk(g_regions_mu);
  if (!g_regions) g_regions = new std::deque<Region>();
  g_regions->emplace_back();
  Region& r = g_regions->back();
  r.base = static_cast<char*>(base);
  r.block_bytes = block_bytes;
  for (size_t off = 0; off + block_bytes <= total; off += block_bytes) {
    r.freelist.push_back(r.base + off);
  }
  return static_cast<int>(g_regions->size() - 1);
}

int tb_iobuf_append_from_region(tb_iobuf* b, int rid, const void* data,
                                size_t n) {
  Region* reg;
  {
    std::lock_guard<std::mutex> lk(g_regions_mu);
    if (!g_regions || rid < 0 ||
        static_cast<size_t>(rid) >= g_regions->size()) {
      return -1;
    }
    reg = &(*g_regions)[static_cast<size_t>(rid)];
  }
  // Reserve every slab up front so exhaustion mid-copy cannot leave the
  // IOBuf half-mutated (failure must not consume blocks or append bytes).
  const size_t nblocks =
      n == 0 ? 0 : (n + reg->block_bytes - 1) / reg->block_bytes;
  std::vector<char*> slabs;
  {
    std::lock_guard<std::mutex> lk(reg->mu);
    if (reg->freelist.size() < nblocks) return -1;
    slabs.assign(reg->freelist.end() - nblocks, reg->freelist.end());
    reg->freelist.resize(reg->freelist.size() - nblocks);
  }
  const char* p = static_cast<const char*>(data);
  for (char* slab : slabs) {
    Block* blk = static_cast<Block*>(::malloc(sizeof(Block)));
    g_blocks_live.fetch_add(1, std::memory_order_relaxed);
    uint32_t m = static_cast<uint32_t>(
        n < reg->block_bytes ? n : reg->block_bytes);
    blk->nshared.store(1, std::memory_order_relaxed);
    blk->size.store(m, std::memory_order_relaxed);
    blk->cap = static_cast<uint32_t>(reg->block_bytes);
    blk->source = SRC_REGION;
    blk->region_id = rid;
    blk->data = slab;
    blk->release_cb = nullptr;
    blk->release_ctx = nullptr;
    blk->next = nullptr;
    memcpy(slab, p, m);
    b->refs.push_back(BlockRef{blk, 0, m});
    b->nbytes += m;
    p += m;
    n -= m;
  }
  return 0;
}

size_t tb_region_free_blocks(int rid) {
  std::lock_guard<std::mutex> lk(g_regions_mu);
  if (!g_regions || rid < 0 || static_cast<size_t>(rid) >= g_regions->size()) {
    return 0;
  }
  Region& r = (*g_regions)[static_cast<size_t>(rid)];
  std::lock_guard<std::mutex> lk2(r.mu);
  return r.freelist.size();
}

// ---- wire fast path ----

}  // extern "C"

namespace {

// CRC32C (Castagnoli, reflected poly 0x82F63B78). zlib-style chaining:
// internal state is ~crc so seed 0 composes across calls.
uint32_t g_crc32c_table[8][256];

void crc32c_init_table() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    g_crc32c_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = g_crc32c_table[0][i];
    for (int s = 1; s < 8; ++s) {
      c = g_crc32c_table[0][c & 0xFF] ^ (c >> 8);
      g_crc32c_table[s][i] = c;
    }
  }
}

uint32_t crc32c_sw(uint32_t crc, const unsigned char* p, size_t n) {
  // slice-by-8
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= crc;
    crc = g_crc32c_table[7][w & 0xFF] ^ g_crc32c_table[6][(w >> 8) & 0xFF] ^
          g_crc32c_table[5][(w >> 16) & 0xFF] ^
          g_crc32c_table[4][(w >> 24) & 0xFF] ^
          g_crc32c_table[3][(w >> 32) & 0xFF] ^
          g_crc32c_table[2][(w >> 40) & 0xFF] ^
          g_crc32c_table[1][(w >> 48) & 0xFF] ^ g_crc32c_table[0][w >> 56];
    p += 8;
    n -= 8;
  }
  while (n--) crc = g_crc32c_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(uint32_t crc,
                                                     const unsigned char* p,
                                                     size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    c = __builtin_ia32_crc32di(c, w);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (n--) c32 = __builtin_ia32_crc32qi(c32, *p++);
  return c32;
}
#endif

uint32_t (*pick_crc32c_impl())(uint32_t, const unsigned char*, size_t) {
  crc32c_init_table();
#if defined(__x86_64__)
  if (__builtin_cpu_supports("sse4.2")) return crc32c_hw;
#endif
  return crc32c_sw;
}

// resolved once at load time (before any Python thread exists)
uint32_t (*const g_crc32c_impl)(uint32_t, const unsigned char*, size_t) =
    pick_crc32c_impl();

inline uint32_t crc32c_update(uint32_t state, const void* data, size_t n) {
  return g_crc32c_impl(state, static_cast<const unsigned char*>(data), n);
}

constexpr uint32_t kTbusMagic = 0x54505243u;  // "TPRC" little-endian

}  // namespace

extern "C" {

uint32_t tb_crc32c(uint32_t seed, const void* data, size_t n) {
  return ~crc32c_update(~seed, data, n);
}

uint32_t tb_iobuf_crc32c(const tb_iobuf* b, uint32_t seed, size_t pos,
                         size_t n) {
  uint32_t state = ~seed;
  for (const BlockRef& r : b->refs) {
    if (n == 0) break;
    if (pos >= r.length) {
      pos -= r.length;
      continue;
    }
    size_t avail = r.length - pos;
    size_t m = n < avail ? n : avail;
    state = crc32c_update(state, r.block->data + r.offset + pos, m);
    n -= m;
    pos = 0;
  }
  return ~state;
}

int tb_tbus_peek(const tb_iobuf* in, tb_tbus_hdr* out) {
  // Reject a foreign magic as soon as 4 bytes exist — a short frame of
  // another protocol must yield "not mine" (so the messenger tries other
  // parsers), never "incomplete" (which would wait forever).
  if (in->nbytes >= 4) {
    uint32_t magic;
    tb_iobuf_copy_to(in, &magic, 4, 0);
    if (magic != kTbusMagic) return -1;
  }
  if (in->nbytes < 32) return 1;
  uint32_t w[8];
  tb_iobuf_copy_to(in, w, 32, 0);
  if (w[0] != kTbusMagic) return -1;
  out->body_len = w[1];
  out->flags = w[2];
  out->cid_lo = w[3];
  out->cid_hi = w[4];
  out->meta_len = w[5];
  out->crc = w[6];
  out->error_code = w[7];
  return 0;
}

// flag bit 3: the frame's crc covers the whole body (meta+payload+
// attachment). Default frames cover META ONLY — the reference's baidu_std
// carries no body checksum at all (TCP already checksums segments;
// baidu_rpc_protocol.cpp:53-58's header is just sizes), so routing info is
// protected here and bulk bytes ride the transport's own integrity.
constexpr uint32_t kFlagBodyCrc = 8;

// Callers bound the header's claimed sizes BEFORE cutting: peek fills
// them raw off the wire, and this function trusts them to size the meta
// copy-out and the body cut.
// fabricscan: requires-bounded(arg2.body_len, arg2.meta_len)
int tb_tbus_cut(tb_iobuf* in, const tb_tbus_hdr* hdr, void* meta_out,
                tb_iobuf* body_out) {
  if (hdr->meta_len > hdr->body_len) return -3;
  const size_t total = 32 + static_cast<size_t>(hdr->body_len);
  if (in->nbytes < total) return 1;
  const size_t span =
      (hdr->flags & kFlagBodyCrc) ? hdr->body_len : hdr->meta_len;
  if (tb_iobuf_crc32c(in, 0, 32, span) != hdr->crc) return -2;
  tb_iobuf_popn(in, 32);
  if (hdr->meta_len) {
    tb_iobuf_copy_to(in, meta_out, hdr->meta_len, 0);
    tb_iobuf_popn(in, hdr->meta_len);
  }
  tb_iobuf_cutn(in, body_out, hdr->body_len - hdr->meta_len);
  return 0;
}

void tb_tbus_pack(tb_iobuf* out, const void* meta, size_t meta_len,
                  const void* payload, size_t payload_len, const void* att,
                  size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
                  uint32_t flags, uint32_t error_code, int copy_body) {
  uint32_t state = ~0u;
  if (meta_len) state = crc32c_update(state, meta, meta_len);
  if (flags & kFlagBodyCrc) {
    if (payload_len) state = crc32c_update(state, payload, payload_len);
    if (att_len) state = crc32c_update(state, att, att_len);
  }
  uint32_t hdr[8] = {kTbusMagic,
                     static_cast<uint32_t>(meta_len + payload_len + att_len),
                     flags,
                     cid_lo,
                     cid_hi,
                     static_cast<uint32_t>(meta_len),
                     ~state,
                     error_code};
  tb_iobuf_append(out, hdr, sizeof(hdr));
  if (meta_len) tb_iobuf_append(out, meta, meta_len);
  if (copy_body) {
    if (payload_len) tb_iobuf_append(out, payload, payload_len);
    if (att_len) tb_iobuf_append(out, att, att_len);
  }
}

// ---- misc ----

uint32_t tb_crc32(uint32_t seed, const void* data, size_t n) {
  return static_cast<uint32_t>(
      ::crc32(seed, static_cast<const Bytef*>(data),
              static_cast<uInt>(n)));
}

uint64_t tb_fast_rand(void) {
  // xorshift128+ per thread (reference fast_rand.cpp uses the same family).
  thread_local uint64_t s0 = 0, s1 = 0;
  if (s0 == 0 && s1 == 0) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    s0 = static_cast<uint64_t>(ts.tv_nsec) ^
         (reinterpret_cast<uintptr_t>(&s0) << 16);
    s1 = static_cast<uint64_t>(ts.tv_sec) * 1000000007ULL ^ 0x9E3779B97F4A7C15ULL;
    if (s0 == 0 && s1 == 0) s1 = 1;
  }
  uint64_t x = s0;
  const uint64_t y = s1;
  s0 = y;
  x ^= x << 23;
  s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
  return s1 + y;
}

uint64_t tb_fast_rand_less_than(uint64_t bound) {
  if (bound == 0) return 0;
  return tb_fast_rand() % bound;
}

uint64_t tb_monotonic_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ULL +
         static_cast<uint64_t>(ts.tv_nsec);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// ResourcePool — versioned-id slab, never frees memory (ABA-safe).
// Versions are odd while live, even while free; id = version<<32 | slot.
// ---------------------------------------------------------------------------

struct tb_respool {
  size_t item_size;
  std::mutex mu;
  std::vector<char*> chunks;          // each chunk holds kChunkItems items
  std::vector<uint32_t> versions;     // per slot
  std::vector<uint32_t> free_slots;
  size_t nslots = 0;
  size_t live = 0;
  static constexpr size_t kChunkItems = 256;
};

extern "C" {

tb_respool* tb_respool_create(size_t item_size) {
  tb_respool* p = new tb_respool();
  p->item_size = item_size ? item_size : 1;
  return p;
}

void tb_respool_destroy(tb_respool* p) {
  if (!p) return;
  for (char* c : p->chunks) ::free(c);
  delete p;
}

static void* respool_slot_ptr(tb_respool* p, uint32_t slot) {
  return p->chunks[slot / tb_respool::kChunkItems] +
         (slot % tb_respool::kChunkItems) * p->item_size;
}

void* tb_respool_get(tb_respool* p, uint64_t* out_id) {
  std::lock_guard<std::mutex> lk(p->mu);
  uint32_t slot;
  if (!p->free_slots.empty()) {
    slot = p->free_slots.back();
    p->free_slots.pop_back();
    p->versions[slot] += 1;  // even -> odd: live again, old ids stale
  } else {
    if (p->nslots % tb_respool::kChunkItems == 0) {
      char* chunk = static_cast<char*>(
          ::calloc(tb_respool::kChunkItems, p->item_size));
      if (!chunk) return nullptr;
      p->chunks.push_back(chunk);
    }
    slot = static_cast<uint32_t>(p->nslots++);
    p->versions.push_back(1);
  }
  ++p->live;
  if (out_id) {
    *out_id = (static_cast<uint64_t>(p->versions[slot]) << 32) | slot;
  }
  return respool_slot_ptr(p, slot);
}

void* tb_respool_address(tb_respool* p, uint64_t id) {
  const uint32_t slot = static_cast<uint32_t>(id & 0xFFFFFFFFu);
  const uint32_t version = static_cast<uint32_t>(id >> 32);
  std::lock_guard<std::mutex> lk(p->mu);
  if (slot >= p->nslots) return nullptr;
  if (p->versions[slot] != version || (version & 1) == 0) return nullptr;
  return respool_slot_ptr(p, slot);
}

int tb_respool_return(tb_respool* p, uint64_t id) {
  const uint32_t slot = static_cast<uint32_t>(id & 0xFFFFFFFFu);
  const uint32_t version = static_cast<uint32_t>(id >> 32);
  std::lock_guard<std::mutex> lk(p->mu);
  if (slot >= p->nslots) return -1;
  if (p->versions[slot] != version || (version & 1) == 0) return -1;
  p->versions[slot] += 1;  // odd -> even: dead
  p->free_slots.push_back(slot);
  --p->live;
  return 0;
}

size_t tb_respool_live(const tb_respool* p) {
  tb_respool* q = const_cast<tb_respool*>(p);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->live;
}

// ---------------------------------------------------------------------------
// ObjectPool (reference src/butil/object_pool.h: pointer-addressed slab,
// free list, memory never returned to the OS so a stale pointer is at worst
// a recycled object, never a wild read)
// ---------------------------------------------------------------------------

struct tb_objpool {
  static constexpr size_t kChunkItems = 256;
  std::mutex mu;
  size_t item_size = 0;
  std::vector<char*> chunks;
  std::vector<void*> free_list;
  size_t nitems = 0;  // slots ever carved
  size_t live = 0;
};

tb_objpool* tb_objpool_create(size_t item_size) {
  tb_objpool* p = new tb_objpool();
  p->item_size = item_size < 8 ? 8 : item_size;
  return p;
}

void tb_objpool_destroy(tb_objpool* p) {
  if (!p) return;
  for (char* c : p->chunks) ::free(c);
  delete p;
}

void* tb_objpool_get(tb_objpool* p) {
  std::lock_guard<std::mutex> lk(p->mu);
  void* item;
  if (!p->free_list.empty()) {
    item = p->free_list.back();
    p->free_list.pop_back();
  } else {
    if (p->nitems % tb_objpool::kChunkItems == 0) {
      char* chunk =
          static_cast<char*>(::calloc(tb_objpool::kChunkItems, p->item_size));
      if (!chunk) return nullptr;
      p->chunks.push_back(chunk);
    }
    item = p->chunks.back() +
           (p->nitems % tb_objpool::kChunkItems) * p->item_size;
    ++p->nitems;
  }
  ++p->live;
  return item;
}

void tb_objpool_return(tb_objpool* p, void* item) {
  if (!item) return;
  std::lock_guard<std::mutex> lk(p->mu);
  p->free_list.push_back(item);
  --p->live;
}

size_t tb_objpool_live(const tb_objpool* p) {
  tb_objpool* q = const_cast<tb_objpool*>(p);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->live;
}

size_t tb_objpool_free_count(const tb_objpool* p) {
  tb_objpool* q = const_cast<tb_objpool*>(p);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->free_list.size();
}

// ---------------------------------------------------------------------------
// FlatMap (reference src/butil/containers/flat_map.h re-expressed as the
// typed u64->u64 open-addressing table hot paths need; linear probing,
// tombstones, grow at 70% occupancy)
// ---------------------------------------------------------------------------

struct tb_flatmap {
  enum : uint8_t { EMPTY = 0, FULL = 1, TOMB = 2 };
  // internally locked: ctypes drops the GIL per call, so Python threads
  // hit this concurrently (ObjectPool/ResourcePool get the same treatment)
  mutable std::mutex mu;
  std::vector<uint64_t> keys;
  std::vector<uint64_t> vals;
  std::vector<uint8_t> states;
  size_t nfull = 0;
  size_t noccupied = 0;  // FULL + TOMB (drives rehash)
};

static inline uint64_t fm_hash(uint64_t x) {
  // splitmix64 finalizer — cheap and well distributed
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

static size_t fm_round_up_pow2(size_t n) {
  // clamp: anything past 2^32 entries is a caller bug, and an unbounded
  // shift would overflow to 0 and spin forever
  const size_t kMaxCap = size_t(1) << 32;
  if (n > kMaxCap) n = kMaxCap;
  size_t c = 16;
  while (c < n) c <<= 1;
  return c;
}

static void fm_rehash(tb_flatmap* m, size_t new_cap);

static void fm_insert_nogrow(tb_flatmap* m, uint64_t key, uint64_t value) {
  const size_t mask = m->keys.size() - 1;
  size_t i = fm_hash(key) & mask;
  while (m->states[i] == tb_flatmap::FULL) i = (i + 1) & mask;
  if (m->states[i] == tb_flatmap::EMPTY) ++m->noccupied;
  m->states[i] = tb_flatmap::FULL;
  m->keys[i] = key;
  m->vals[i] = value;
  ++m->nfull;
}

static void fm_rehash(tb_flatmap* m, size_t new_cap) {
  std::vector<uint64_t> keys(new_cap), vals(new_cap);
  std::vector<uint8_t> states(new_cap, tb_flatmap::EMPTY);
  keys.swap(m->keys);
  vals.swap(m->vals);
  states.swap(m->states);
  const size_t old_full = m->nfull;
  m->nfull = 0;
  m->noccupied = 0;
  for (size_t i = 0; i < keys.size(); ++i) {
    if (states[i] == tb_flatmap::FULL) fm_insert_nogrow(m, keys[i], vals[i]);
  }
  (void)old_full;
}

tb_flatmap* tb_flatmap_create(size_t initial_capacity) {
  tb_flatmap* m = nullptr;
  try {
    m = new tb_flatmap();
    const size_t cap =
        fm_round_up_pow2(initial_capacity ? initial_capacity : 16);
    m->keys.assign(cap, 0);
    m->vals.assign(cap, 0);
    m->states.assign(cap, tb_flatmap::EMPTY);
    return m;
  } catch (const std::bad_alloc&) {
    delete m;
    return nullptr;  // never let the throw cross the C ABI into ctypes
  }
}

void tb_flatmap_destroy(tb_flatmap* m) { delete m; }

int tb_flatmap_insert(tb_flatmap* m, uint64_t key, uint64_t value) {
  std::lock_guard<std::mutex> lk(m->mu);
  const size_t mask = m->keys.size() - 1;
  size_t i = fm_hash(key) & mask;
  long first_tomb = -1;
  while (m->states[i] != tb_flatmap::EMPTY) {
    if (m->states[i] == tb_flatmap::FULL && m->keys[i] == key) {
      m->vals[i] = value;
      return 1;
    }
    if (m->states[i] == tb_flatmap::TOMB && first_tomb < 0) {
      first_tomb = static_cast<long>(i);
    }
    i = (i + 1) & mask;
  }
  // the scan already found the landing slot: reuse the first tombstone on
  // the chain, else the terminating EMPTY — no second probe
  if (first_tomb >= 0) {
    i = static_cast<size_t>(first_tomb);
  } else {
    ++m->noccupied;
  }
  m->states[i] = tb_flatmap::FULL;
  m->keys[i] = key;
  m->vals[i] = value;
  ++m->nfull;
  if (m->noccupied * 10 >= m->keys.size() * 7) {
    // size from live entries, not old capacity: tombstone churn rehashes
    // in place (clearing tombs) instead of growing without bound
    size_t want = fm_round_up_pow2(m->nfull * 4 < 16 ? 16 : m->nfull * 4);
    try {
      fm_rehash(m, want);
    } catch (const std::bad_alloc&) {
      return -1;  // documented OOM contract; never let the throw cross ctypes
    }
  }
  return 0;
}

int tb_flatmap_get(const tb_flatmap* m, uint64_t key, uint64_t* out) {
  std::lock_guard<std::mutex> lk(m->mu);
  const size_t mask = m->keys.size() - 1;
  size_t i = fm_hash(key) & mask;
  while (m->states[i] != tb_flatmap::EMPTY) {
    if (m->states[i] == tb_flatmap::FULL && m->keys[i] == key) {
      if (out) *out = m->vals[i];
      return 1;
    }
    i = (i + 1) & mask;
  }
  return 0;
}

int tb_flatmap_erase(tb_flatmap* m, uint64_t key) {
  std::lock_guard<std::mutex> lk(m->mu);
  const size_t mask = m->keys.size() - 1;
  size_t i = fm_hash(key) & mask;
  while (m->states[i] != tb_flatmap::EMPTY) {
    if (m->states[i] == tb_flatmap::FULL && m->keys[i] == key) {
      m->states[i] = tb_flatmap::TOMB;
      --m->nfull;
      return 1;
    }
    i = (i + 1) & mask;
  }
  return 0;
}

size_t tb_flatmap_size(const tb_flatmap* m) {
  std::lock_guard<std::mutex> lk(m->mu);
  return m->nfull;
}
size_t tb_flatmap_capacity(const tb_flatmap* m) {
  std::lock_guard<std::mutex> lk(m->mu);
  return m->keys.size();
}

// ---------------------------------------------------------------------------
// tb_cimap — case-ignored string map (reference CaseIgnoredFlatMap,
// containers/case_ignored_flat_map.h).  Open addressing, case-folded FNV
// hash, case-insensitive equality; stored keys keep original spelling.
// ---------------------------------------------------------------------------

namespace {

inline char ci_lower(char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<char>(c + 32) : c;
}

inline uint64_t ci_hash(const char* s, size_t n) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a over folded bytes
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<uint8_t>(ci_lower(s[i]));
    h *= 1099511628211ull;
  }
  return h;
}

inline bool ci_equal(const std::string& a, const char* b, size_t n) {
  if (a.size() != n) return false;
  for (size_t i = 0; i < n; ++i)
    if (ci_lower(a[i]) != ci_lower(b[i])) return false;
  return true;
}

}  // namespace

struct tb_cimap {
  enum : uint8_t { EMPTY = 0, FULL = 1, TOMB = 2 };
  mutable std::mutex mu;
  std::vector<std::string> keys;
  std::vector<std::string> vals;
  std::vector<uint8_t> states;
  size_t nfull = 0;
  size_t noccupied = 0;

  void rehash(size_t newcap) {
    std::vector<std::string> ok = std::move(keys), ov = std::move(vals);
    std::vector<uint8_t> os = std::move(states);
    keys.assign(newcap, {});
    vals.assign(newcap, {});
    states.assign(newcap, EMPTY);
    nfull = noccupied = 0;
    for (size_t i = 0; i < os.size(); ++i) {
      if (os[i] != FULL) continue;
      size_t mask = keys.size() - 1;
      size_t j = ci_hash(ok[i].data(), ok[i].size()) & mask;
      while (states[j] == FULL) j = (j + 1) & mask;
      keys[j] = std::move(ok[i]);
      vals[j] = std::move(ov[i]);
      states[j] = FULL;
      ++nfull;
      ++noccupied;
    }
  }

  // slot of the key (FULL) or of the first insertable slot; found tells
  long probe(const char* key, size_t n, bool* found) const {
    size_t mask = keys.size() - 1;
    size_t j = ci_hash(key, n) & mask;
    long first_free = -1;
    for (size_t step = 0; step < keys.size(); ++step, j = (j + 1) & mask) {
      if (states[j] == EMPTY) {
        *found = false;
        return first_free >= 0 ? first_free : static_cast<long>(j);
      }
      if (states[j] == TOMB) {
        if (first_free < 0) first_free = static_cast<long>(j);
        continue;
      }
      if (ci_equal(keys[j], key, n)) {
        *found = true;
        return static_cast<long>(j);
      }
    }
    *found = false;
    return first_free;
  }
};

tb_cimap* tb_cimap_create(size_t initial_capacity) {
  size_t cap = 16;
  while (cap < initial_capacity) cap <<= 1;
  tb_cimap* m = new (std::nothrow) tb_cimap();
  if (m == nullptr) return nullptr;
  m->keys.assign(cap, {});
  m->vals.assign(cap, {});
  m->states.assign(cap, tb_cimap::EMPTY);
  return m;
}

void tb_cimap_destroy(tb_cimap* m) { delete m; }

int tb_cimap_set(tb_cimap* m, const char* key, size_t klen, const char* val,
                 size_t vlen) {
  std::lock_guard<std::mutex> lk(m->mu);
  if ((m->noccupied + 1) * 4 >= m->keys.size() * 3) {
    // grow only when LIVE entries justify it; a tombstone-dominated table
    // rehashes in place (same capacity), so insert/erase churn with a
    // small live set cannot grow memory without bound
    size_t newcap = m->keys.size();
    if ((m->nfull + 1) * 4 >= newcap * 3) newcap *= 2;
    m->rehash(newcap);
  }
  bool found = false;
  long j = m->probe(key, klen, &found);
  if (j < 0) return -1;
  if (found) {
    m->vals[j].assign(val, vlen);
    return 1;
  }
  if (m->states[j] == tb_cimap::EMPTY) ++m->noccupied;
  m->keys[j].assign(key, klen);
  m->vals[j].assign(val, vlen);
  m->states[j] = tb_cimap::FULL;
  ++m->nfull;
  return 0;
}

long tb_cimap_get(const tb_cimap* m, const char* key, size_t klen, char* out,
                  size_t cap) {
  std::lock_guard<std::mutex> lk(m->mu);
  bool found = false;
  long j = m->probe(key, klen, &found);
  if (!found || j < 0) return -1;
  const std::string& v = m->vals[j];
  size_t n = v.size() < cap ? v.size() : cap;
  if (out != nullptr && n > 0) memcpy(out, v.data(), n);
  return static_cast<long>(v.size());
}

int tb_cimap_erase(tb_cimap* m, const char* key, size_t klen) {
  std::lock_guard<std::mutex> lk(m->mu);
  bool found = false;
  long j = m->probe(key, klen, &found);
  if (!found || j < 0) return 0;
  m->keys[j].clear();
  m->vals[j].clear();
  m->states[j] = tb_cimap::TOMB;
  --m->nfull;
  return 1;
}

size_t tb_cimap_size(const tb_cimap* m) {
  std::lock_guard<std::mutex> lk(m->mu);
  return m->nfull;
}

long tb_cimap_key_at(const tb_cimap* m, size_t i, char* out, size_t cap) {
  std::lock_guard<std::mutex> lk(m->mu);
  size_t seen = 0;
  for (size_t j = 0; j < m->keys.size(); ++j) {
    if (m->states[j] != tb_cimap::FULL) continue;
    if (seen++ == i) {
      const std::string& k = m->keys[j];
      size_t n = k.size() < cap ? k.size() : cap;
      if (out != nullptr && n > 0) memcpy(out, k.data(), n);
      return static_cast<long>(k.size());
    }
  }
  return -1;
}

// ---------------------------------------------------------------------------
// tb_mru — MRU cache (reference MRUCache, containers/mru_cache.h): a
// doubly-linked recency list over a hash index; puts past capacity evict
// the least-recently-used entry.
// ---------------------------------------------------------------------------

struct tb_mru {
  mutable std::mutex mu;
  size_t capacity;
  std::list<std::pair<uint64_t, uint64_t>> order;  // front = most recent
  std::unordered_map<uint64_t,
                     std::list<std::pair<uint64_t, uint64_t>>::iterator>
      index;
};

tb_mru* tb_mru_create(size_t capacity) {
  tb_mru* c = new (std::nothrow) tb_mru();
  if (c == nullptr) return nullptr;
  c->capacity = capacity < 1 ? 1 : capacity;
  return c;
}

void tb_mru_destroy(tb_mru* c) { delete c; }

int tb_mru_put(tb_mru* c, uint64_t key, uint64_t value) {
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->index.find(key);
  if (it != c->index.end()) {
    it->second->second = value;
    c->order.splice(c->order.begin(), c->order, it->second);
    return 1;
  }
  c->order.emplace_front(key, value);
  c->index[key] = c->order.begin();
  if (c->order.size() > c->capacity) {
    c->index.erase(c->order.back().first);
    c->order.pop_back();
  }
  return 0;
}

int tb_mru_get(tb_mru* c, uint64_t key, uint64_t* out) {
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->index.find(key);
  if (it == c->index.end()) return 0;
  if (out != nullptr) *out = it->second->second;
  c->order.splice(c->order.begin(), c->order, it->second);
  return 1;
}

size_t tb_mru_size(const tb_mru* c) {
  std::lock_guard<std::mutex> lk(c->mu);
  return c->order.size();
}

}  // extern "C"
