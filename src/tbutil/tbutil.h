// tbutil — native L1 base for the TPU-native brpc-class framework.
//
// Re-designed counterpart of the reference's butil core
// (/root/reference/src/butil/iobuf.h:52, iobuf.cpp:221-306,
//  resource_pool.h:24-83, rdma/block_pool.h:20-66).  NOT a port: the
// reference interleaves a Chromium base fork; this is a from-scratch,
// minimal, C-ABI surface designed to be driven from Python via ctypes and
// from future native transports directly.
//
// Key properties kept from the reference design:
//   * IOBuf = queue of refcounted BlockRef{block, offset, length}; O(1)
//     cut/append/share; no data copies between IOBufs.
//   * Blocks come from a TLS-cached pool; refcounts are atomic; an IOBuf
//     itself is externally synchronized (one owner thread at a time).
//   * External blocks wrap caller-owned memory (the HBM/registered-memory
//     hook) and fire a release callback when the last ref drops — the
//     IOBUF_HUGE_BLOCK / Block::release_cb design (iobuf.cpp:258-306).
//   * Region allocator: carve fixed blocks out of one registered slab
//     (modeled on rdma/block_pool) so payloads can live in pinned/device
//     memory end to end.
//   * ResourcePool: never-freeing slab of fixed-size items addressed by
//     versioned 64-bit ids (ABA-safe) — backs socket/stream id tables.
#ifndef TBUTIL_H
#define TBUTIL_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tb_iobuf tb_iobuf;
typedef void (*tb_release_fn)(void* data, void* ctx);

typedef struct tb_ref_view {
  const void* data;
  size_t length;
} tb_ref_view;

// ---- block pool ----
// Default block payload size (bytes). Changing it only affects new blocks.
void tb_set_block_size(size_t bytes);
size_t tb_block_size(void);
// blocks currently live (allocated - freed), blocks parked in caches.
void tb_block_pool_stats(size_t* live, size_t* cached);
// bytes one tb_iobuf_append_from_fd readv can deliver (iovec budget x
// current block size) — read loops size their asks and short-read tests
// from this so the contract lives in ONE place.
size_t tb_iobuf_read_burst(void);

// ---- IOBuf ----
// Handles are placement-new'd over ObjectPool slots (never freed to the
// OS); stats expose the pool's live/free counts for tests and /ids.
tb_iobuf* tb_iobuf_create(void);
void tb_iobuf_handle_pool_stats(size_t* live, size_t* free_count);
void tb_iobuf_destroy(tb_iobuf* b);
void tb_iobuf_clear(tb_iobuf* b);
size_t tb_iobuf_size(const tb_iobuf* b);
size_t tb_iobuf_block_count(const tb_iobuf* b);
// copy n bytes in (fills the tail block first — the portal-append path).
void tb_iobuf_append(tb_iobuf* b, const void* data, size_t n);
// zero-copy wrap of caller-owned memory; cb(data, ctx) fires when the last
// ref drops, on whichever thread drops it (keep cb cheap; see
// reference iobuf.cpp:258-306 on why release must not block).
void tb_iobuf_append_external(tb_iobuf* b, void* data, size_t n,
                              tb_release_fn cb, void* ctx);
// share `from`'s refs into `to` (refcount bump, no copy).
void tb_iobuf_append_iobuf(tb_iobuf* to, const tb_iobuf* from);
// move up to n bytes from the front of `from` to the back of `to`; O(blocks).
size_t tb_iobuf_cutn(tb_iobuf* from, tb_iobuf* to, size_t n);
// drop up to n front bytes.
size_t tb_iobuf_popn(tb_iobuf* from, size_t n);
// copy out [pos, pos+n) without consuming; returns bytes copied.
size_t tb_iobuf_copy_to(const tb_iobuf* b, void* out, size_t n, size_t pos);
// expose up to max {ptr,len} views of the refs (zero-copy read from Python).
int tb_iobuf_refs(const tb_iobuf* b, tb_ref_view* out, int max);
// white-box: refcount of the i-th ref's block (tests; reference
// iobuf.cpp:329 block_shared_count).
int tb_iobuf_block_shared_count(const tb_iobuf* b, size_t i);

// ---- fd IO (vectored, zero-copy w.r.t. Python) ----
// writev the first <=max_bytes; pops what was written. Returns bytes
// written, or -errno.
long tb_iobuf_cut_into_fd(tb_iobuf* b, int fd, size_t max_bytes);
// readv up to max_bytes into fresh pool blocks appended to b. Returns bytes
// read (0 on EOF), or -errno.
long tb_iobuf_append_from_fd(tb_iobuf* b, int fd, size_t max_bytes);
// bulk streaming drains: big SRC_MALLOC blocks instead of the pooled default
long tb_iobuf_append_from_fd_bulk(tb_iobuf* b, int fd, size_t max_bytes,
                                  size_t block_bytes);

// ---- region allocator (registered-slab blocks; rdma/block_pool analog) ----
// Carve `total` into fixed `block_bytes` blocks over caller memory `base`
// (caller keeps ownership of the slab; must outlive the region's blocks).
// Returns region id >=0, or -1.
int tb_region_register(void* base, size_t total, size_t block_bytes);
// Append n bytes into `b` copied into blocks drawn from region `rid`.
// Returns 0, or -1 if the region is exhausted.
int tb_iobuf_append_from_region(tb_iobuf* b, int rid, const void* data,
                                size_t n);
// free blocks available in region.
size_t tb_region_free_blocks(int rid);

// ---- wire fast path (tbus_std framing; reference splits this between
// policy/baidu_rpc_protocol.cpp pack/parse and input_messenger.cpp's cut
// loop — here the whole per-frame byte path is native so Python never
// copies or checksums payload bytes) ----

// CRC32C (Castagnoli) with zlib-style chaining: seed 0 to start, feed the
// previous return value to continue. Uses SSE4.2 hardware CRC when the CPU
// has it (one u64 step per cycle), a slice-table otherwise.
uint32_t tb_crc32c(uint32_t seed, const void* data, size_t n);
// CRC32C over [pos, pos+n) of the chain without copying.
uint32_t tb_iobuf_crc32c(const tb_iobuf* b, uint32_t seed, size_t pos,
                         size_t n);

typedef struct tb_tbus_hdr {
  uint32_t body_len;
  uint32_t flags;
  uint32_t cid_lo;
  uint32_t cid_hi;
  uint32_t meta_len;
  uint32_t crc;
  uint32_t error_code;
} tb_tbus_hdr;

// Peek the fixed 32-byte header off the front of `in` without consuming.
// 0 = filled `out`; 1 = fewer than 32 bytes buffered; -1 = bad magic.
int tb_tbus_peek(const tb_iobuf* in, tb_tbus_hdr* out);
// Consume one complete frame: verify CRC32C (over the meta, or the whole
// body when header flag bit 3 is set) by walking the block refs (no copy),
// pop the header, copy the (small) meta into `meta_out` (capacity >=
// hdr->meta_len), and CUT payload+attachment into `body_out` zero-copy
// (refs move, bytes don't).
// 0 = ok; 1 = frame incomplete; -2 = crc mismatch (nothing consumed);
// -3 = malformed (meta_len > body_len).
int tb_tbus_cut(tb_iobuf* in, const tb_tbus_hdr* hdr, void* meta_out,
                tb_iobuf* body_out);
// Append header + meta to `out`, computing the CRC32C over meta (and over
// payload+attachment too when flags bit 3 is set) in one native pass.
// copy_body != 0: payload+attachment are appended (copied) too — the whole
// frame in ONE call, right for small frames. copy_body == 0: the caller
// appends them after (zero-copy via append_external if large).
void tb_tbus_pack(tb_iobuf* out, const void* meta, size_t meta_len,
                  const void* payload, size_t payload_len, const void* att,
                  size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
                  uint32_t flags, uint32_t error_code, int copy_body);

// ---- misc ----
uint32_t tb_crc32(uint32_t seed, const void* data, size_t n);
uint64_t tb_fast_rand(void);
uint64_t tb_fast_rand_less_than(uint64_t bound);
// monotonic ns (CLOCK_MONOTONIC; the cpuwide_time analog).
uint64_t tb_monotonic_ns(void);

// ---- ResourcePool: versioned-id slab, never frees (ABA-safe ids) ----
typedef struct tb_respool tb_respool;
tb_respool* tb_respool_create(size_t item_size);
void tb_respool_destroy(tb_respool* p);
// allocate a slot; *out_id = (version<<32)|slot; returns item ptr.
void* tb_respool_get(tb_respool* p, uint64_t* out_id);
// resolve id; NULL if the slot's version moved on (the Address-after-
// SetFailed contract of socket versioned refs).
void* tb_respool_address(tb_respool* p, uint64_t id);
// bump version and recycle slot; returns 0 or -1 if id stale.
int tb_respool_return(tb_respool* p, uint64_t id);
size_t tb_respool_live(const tb_respool* p);

// ---- ObjectPool: fixed-size objects addressed by pointer, free-listed,
// never returned to the OS (reference src/butil/object_pool.h) ----
typedef struct tb_objpool tb_objpool;
tb_objpool* tb_objpool_create(size_t item_size);
void tb_objpool_destroy(tb_objpool* p);
void* tb_objpool_get(tb_objpool* p);
// return an item obtained from this pool; it becomes reusable immediately.
void tb_objpool_return(tb_objpool* p, void* item);
size_t tb_objpool_live(const tb_objpool* p);
size_t tb_objpool_free_count(const tb_objpool* p);

// ---- FlatMap: open-addressing u64->u64 hash map for hot-path id lookups
// (reference src/butil/containers/flat_map.h; this is the narrow typed
// variant native transports need — socket ids, stream ids, cids) ----
typedef struct tb_flatmap tb_flatmap;
tb_flatmap* tb_flatmap_create(size_t initial_capacity);
void tb_flatmap_destroy(tb_flatmap* m);
// 0 = inserted new, 1 = replaced existing, -1 = OOM on grow.
int tb_flatmap_insert(tb_flatmap* m, uint64_t key, uint64_t value);
// 1 = found (*out filled), 0 = absent.
int tb_flatmap_get(const tb_flatmap* m, uint64_t key, uint64_t* out);
// 1 = erased, 0 = absent.
int tb_flatmap_erase(tb_flatmap* m, uint64_t key);
size_t tb_flatmap_size(const tb_flatmap* m);
size_t tb_flatmap_capacity(const tb_flatmap* m);

// Case-ignored string map (reference CaseIgnoredFlatMap,
// containers/case_ignored_flat_map.h — HTTP header tables): open
// addressing keyed by case-folded hash with case-insensitive equality;
// stored keys keep their original spelling.
typedef struct tb_cimap tb_cimap;
tb_cimap* tb_cimap_create(size_t initial_capacity);
void tb_cimap_destroy(tb_cimap* m);
// 0 = inserted new, 1 = replaced existing value, -1 = OOM.
int tb_cimap_set(tb_cimap* m, const char* key, size_t klen, const char* val,
                 size_t vlen);
// value length (>=0, copied into out up to cap) or -1 when absent.  A
// value longer than cap is truncated to cap; the true length returns.
long tb_cimap_get(const tb_cimap* m, const char* key, size_t klen, char* out,
                  size_t cap);
// 1 = erased, 0 = absent.
int tb_cimap_erase(tb_cimap* m, const char* key, size_t klen);
size_t tb_cimap_size(const tb_cimap* m);
// iterate: copies the i-th live entry's key into out (original spelling);
// returns key length or -1 past the end.  Order is unspecified but stable
// between mutations.
long tb_cimap_key_at(const tb_cimap* m, size_t i, char* out, size_t cap);

// MRU cache (reference MRUCache, containers/mru_cache.h): u64→u64 with a
// capacity bound; get/put move the entry to the front, inserts past
// capacity evict the least-recently-used entry.
typedef struct tb_mru tb_mru;
tb_mru* tb_mru_create(size_t capacity);
void tb_mru_destroy(tb_mru* c);
// 0 = inserted, 1 = replaced; evicts LRU when over capacity.
int tb_mru_put(tb_mru* c, uint64_t key, uint64_t value);
// 1 = hit (*out filled, entry freshened), 0 = miss.
int tb_mru_get(tb_mru* c, uint64_t key, uint64_t* out);
size_t tb_mru_size(const tb_mru* c);

#ifdef __cplusplus
}
#endif
#endif  // TBUTIL_H
