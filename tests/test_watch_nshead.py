"""watch:// long-poll naming + nshead legacy protocol tests (reference
policy/consul_naming_service.cpp blocking queries; nshead.h +
policy/nshead_protocol.cpp multiplexed on the shared port)."""

import socket as pysocket
import struct
import threading
import time

import pytest

from incubator_brpc_tpu.naming.watch import (
    WatchRegistry,
    install_watch_endpoint,
)
from incubator_brpc_tpu.protocol import nshead
from incubator_brpc_tpu.rpc import Channel, Server, ServerOptions


@pytest.fixture
def watch_server():
    """A framework Server hosting the watch endpoint (the test stand-in
    for consul, same shape as the reference's consul unittest mock)."""
    registry = WatchRegistry()
    srv = Server()
    install_watch_endpoint(srv, registry)
    assert srv.start(0)
    yield srv, registry
    srv.stop()
    srv.join(timeout=5)


class TestWatchNaming:
    def test_blocking_query_returns_on_update(self, watch_server):
        srv, registry = watch_server
        registry.update("db", ["127.0.0.1:7001"])
        from incubator_brpc_tpu.protocol.http import http_call

        status, _, body = http_call(
            "127.0.0.1", srv.port, "/naming/db?index=0&wait=5"
        )
        assert status == 200
        import json

        obj = json.loads(body)
        assert obj["index"] == 1
        assert obj["servers"] == ["127.0.0.1:7001"]

        # index=current parks until the NEXT update, then returns fast
        got = {}

        def poll():
            s, _, b = http_call(
                "127.0.0.1", srv.port, "/naming/db?index=1&wait=10", timeout=15
            )
            got["resp"] = json.loads(b)

        t = threading.Thread(target=poll)
        t0 = time.monotonic()
        t.start()
        time.sleep(0.2)
        registry.update("db", ["127.0.0.1:7001", "127.0.0.1:7002"])
        t.join(timeout=10)
        dt = time.monotonic() - t0
        assert got["resp"]["index"] == 2
        assert len(got["resp"]["servers"]) == 2
        assert dt < 5, f"watch did not wake on update ({dt:.1f}s)"

    def test_mid_traffic_server_set_change_propagates_fast(self, watch_server):
        # the Done criterion: a server-set change reaches a live channel's
        # LB through the watch, without polling lag
        watch_srv, registry = watch_server

        backends = []
        for _ in range(2):
            b = Server()
            port_holder = {}

            def who(cntl, req, holder=port_holder):
                return str(holder["port"]).encode()

            b.add_service("w", {"who": who})
            assert b.start(0)
            port_holder["port"] = b.port
            backends.append(b)

        try:
            registry.update("pool", [f"127.0.0.1:{backends[0].port}"])
            ch = Channel()
            assert ch.init(
                f"watch://127.0.0.1:{watch_srv.port}/pool", "rr"
            )
            seen = set()
            for _ in range(4):
                c = ch.call_method("w", "who", b"")
                assert c.ok(), c.error_text
                seen.add(c.response_payload)
            assert seen == {str(backends[0].port).encode()}

            # add the second backend mid-traffic: the blocking query should
            # push it within ~an RTT (assert well under any poll interval)
            registry.update(
                "pool",
                [f"127.0.0.1:{b.port}" for b in backends],
            )
            deadline = time.monotonic() + 5
            seen2 = set()
            while time.monotonic() < deadline and len(seen2) < 2:
                c = ch.call_method("w", "who", b"")
                if c.ok():
                    seen2.add(c.response_payload)
                time.sleep(0.05)
            assert seen2 == {str(b.port).encode() for b in backends}, (
                "watch update did not propagate"
            )
        finally:
            for b in backends:
                b.stop()
                b.join(timeout=5)


class TestNshead:
    def test_frame_roundtrip_and_header_layout(self):
        wire = nshead.pack_frame(b"body!", id=3, version=1, log_id=77)
        assert len(wire) == 36 + 5
        # magic at byte 24 (2+2+4+16 preceding bytes), little-endian
        assert struct.unpack_from("<I", wire, 24)[0] == 0xFB709394
        frame, consumed = nshead.try_parse_frame(wire)
        assert consumed == len(wire)
        assert frame.head["id"] == 3
        assert frame.head["log_id"] == 77
        assert frame.payload == b"body!"

    def test_incomplete_and_foreign(self):
        wire = nshead.pack_frame(b"x" * 10)
        for cut in (0, 20, 35, 40):
            assert nshead.try_parse_frame(wire[:cut]) == (None, 0)
        from incubator_brpc_tpu.protocol.tbus_std import ParseError

        with pytest.raises(ParseError):
            nshead.try_parse_frame(b"Z" * 40)

    def test_nshead_multiplexes_on_tbus_port(self):
        # one server, one port: tbus_std echo AND nshead frames both served
        def ns_handler(cntl, head, body):
            return b"ns:" + body + b":" + str(head["log_id"]).encode()

        srv = Server(ServerOptions(nshead_service=ns_handler))
        srv.add_service("t", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            # binary tbus call
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            assert ch.call_method("t", "echo", b"tbus-ok").ok()
            # raw nshead call on the same port
            c = pysocket.create_connection(("127.0.0.1", srv.port))
            c.settimeout(5)
            c.sendall(nshead.pack_frame(b"legacy", id=9, log_id=42))
            buf = b""
            while True:
                buf += c.recv(65536)
                frame, consumed = nshead.try_parse_frame(buf)
                if frame is not None:
                    break
            assert frame.payload == b"ns:legacy:42"
            assert frame.head["id"] == 9
            c.close()
            # and tbus still works afterwards
            assert ch.call_method("t", "echo", b"still-ok").ok()
        finally:
            srv.stop()
            srv.join(timeout=5)


class TestRemoteFileNaming:
    def test_remotefile_serves_and_refreshes(self):
        # host the list on a framework Server's http handler
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        listing = {"body": b""}
        srv = Server()
        srv.add_http_handler(
            "/servers.lst", lambda frame: (200, "text/plain", listing["body"])
        )
        assert srv.start(0)

        backend = Server()
        backend.add_service("rf", {"echo": lambda cntl, req: req})
        assert backend.start(0)
        listing["body"] = f"127.0.0.1:{backend.port}\n".encode()

        old = None
        try:
            from incubator_brpc_tpu.utils.flags import flag_registry

            old = flag_registry.get("ns_refresh_interval_s")
            set_flag_unchecked("ns_refresh_interval_s", 0.1)
            ch = Channel()
            assert ch.init(
                f"remotefile://127.0.0.1:{srv.port}/servers.lst", "rr"
            )
            c = ch.call_method("rf", "echo", b"via-remotefile")
            assert c.ok(), c.error_text
            assert c.response_payload == b"via-remotefile"
        finally:
            if old is not None:
                set_flag_unchecked("ns_refresh_interval_s", old)
            backend.stop()
            backend.join(timeout=5)
            srv.stop()
            srv.join(timeout=5)
