"""mcpack codec + schema bridge (protocol/mcpack.py — the mcpack2pb
analog; byte layouts per the reference's field_type.h:28-77 and the packed
head structs in serializer.cpp:25-80).

Fixtures are hand-assembled from the format description — the same way
the reference's mcpack tests hand-build frames — so the codec is pinned
to the WIRE, not to itself.
"""

from __future__ import annotations

import struct

import pytest

from incubator_brpc_tpu.protocol import mcpack
from incubator_brpc_tpu.protocol.json2pb import Message, field
from incubator_brpc_tpu.protocol.tbus_std import ParseError


def obj_frame(*items: bytes, name: bytes = b"") -> bytes:
    """Hand-build | FieldLongHead | name | ItemsHead | items |."""
    body = struct.pack("<I", len(items)) + b"".join(items)
    return struct.pack("<BBI", 0x10, len(name), len(body)) + name + body


class TestWireFixtures:
    def test_int32_field_bytes(self):
        # {"a": 1}: OBJECT long head, then INT32 fixed head:
        # | 0x14 | name_size=2 | "a\0" | 01 00 00 00 |
        item = bytes([0x14, 2]) + b"a\x00" + struct.pack("<i", 1)
        frame = obj_frame(item)
        assert mcpack.loads(frame) == {"a": 1}
        assert mcpack.dumps({"a": 1}) == frame  # byte-exact emit

    def test_short_string_field_bytes(self):
        # "s": "hi" → short head: | 0x50|0x80 | name=2 | value=3 | s\0 hi\0
        item = bytes([0xD0, 2, 3]) + b"s\x00" + b"hi\x00"
        frame = obj_frame(item)
        assert mcpack.loads(frame) == {"s": "hi"}
        assert mcpack.dumps({"s": "hi"}) == frame

    def test_long_string_uses_long_head(self):
        s = "x" * 300  # 301 incl NUL > 255 → FieldLongHead
        data = mcpack.dumps({"s": s})
        # top head(6) + items(4) + field head: type without short mask
        assert data[10] == 0x50
        assert mcpack.loads(data) == {"s": s}

    def test_binary_field_bytes(self):
        item = bytes([0xE0, 2, 4]) + b"b\x00" + b"\x00\x01\x02\xff"
        frame = obj_frame(item)
        assert mcpack.loads(frame) == {"b": b"\x00\x01\x02\xff"}
        assert mcpack.dumps({"b": b"\x00\x01\x02\xff"}) == frame

    def test_bool_null_double(self):
        items = [
            bytes([0x31, 2]) + b"t\x00" + b"\x01",
            bytes([0x61, 2]) + b"n\x00" + b"\x00",
            bytes([0x48, 2]) + b"d\x00" + struct.pack("<d", 2.5),
        ]
        frame = obj_frame(*items)
        assert mcpack.loads(frame) == {"t": True, "n": None, "d": 2.5}

    def test_nested_object_and_array(self):
        value = {"outer": {"inner": [1, "two", None]}}
        assert mcpack.loads(mcpack.dumps(value)) == value

    def test_isoarray_parses(self):
        # iso array of int32 [1,2,3]: long head ISOARRAY, value =
        # | item_type=0x14 | 3 packed int32 |
        body = bytes([0x14]) + struct.pack("<iii", 1, 2, 3)
        item = struct.pack("<BBI", 0x30, 2, len(body)) + b"v\x00" + body
        frame = obj_frame(item)
        assert mcpack.loads(frame) == {"v": [1, 2, 3]}

    def test_deleted_field_skipped(self):
        # type 0x0F: & 0x70 == 0 → deleted; value_size = low nibble (15)
        deleted = bytes([0x0F, 2]) + b"x\x00" + b"\xaa" * 15
        keep = bytes([0x14, 2]) + b"k\x00" + struct.pack("<i", 7)
        frame = obj_frame(deleted, keep)
        assert mcpack.loads(frame) == {"k": 7}

    def test_int_width_selection(self):
        small = mcpack.dumps({"v": 1})
        big = mcpack.dumps({"v": 1 << 40})
        huge = mcpack.dumps({"v": (1 << 63) + 1})
        assert small[10] == 0x14  # INT32
        assert big[10] == 0x18  # INT64
        assert huge[10] == 0x28  # UINT64
        for frame, expect in ((small, 1), (big, 1 << 40), (huge, (1 << 63) + 1)):
            assert mcpack.loads(frame) == {"v": expect}


class TestRobustness:
    def test_truncated_raises(self):
        data = mcpack.dumps({"a": 1, "s": "hello"})
        for cut in (1, 5, len(data) - 1):
            with pytest.raises(ParseError):
                mcpack.loads(data[:cut])

    def test_bad_string_termination(self):
        item = bytes([0xD0, 2, 2]) + b"s\x00" + b"hi"  # no NUL
        with pytest.raises(ParseError):
            mcpack.loads(obj_frame(item))

    def test_name_missing_nul_rejected(self):
        # name_size counts the NUL (field_type.h note); 'a' without it must
        # raise, not silently become the empty name
        item = bytes([0x14, 1]) + b"a" + struct.pack("<i", 1)
        with pytest.raises(ParseError):
            mcpack.loads(obj_frame(item))

    def test_non_utf8_name_and_string_raise_parse_error(self):
        item = bytes([0x14, 3]) + b"\xff\xfe\x00" + struct.pack("<i", 1)
        with pytest.raises(ParseError):
            mcpack.loads(obj_frame(item))
        sval = bytes([0xD0, 2, 3]) + b"s\x00" + b"\xff\xfe\x00"
        with pytest.raises(ParseError):
            mcpack.loads(obj_frame(sval))

    def test_depth_bomb_rejected(self):
        v = {}
        for _ in range(200):
            v = {"d": v}
        with pytest.raises(ValueError):
            mcpack.dumps(v)

    def test_isoarray_ragged_rejected(self):
        body = bytes([0x14]) + b"\x01\x02\x03"  # 3 bytes, not /4
        item = struct.pack("<BBI", 0x30, 0, len(body)) + body
        with pytest.raises(ParseError):
            mcpack.loads(obj_frame(item))

    def test_value_roundtrip_all_kinds(self):
        value = {
            "i": -5,
            "big": 1 << 50,
            "f": 3.25,
            "t": True,
            "s": "héllo",
            "b": b"\x00raw",
            "n": None,
            "arr": [1, [2, 3], {"k": "v"}],
            "obj": {"nested": {"deep": 1}},
        }
        assert mcpack.loads(mcpack.dumps(value)) == value


class Inner(Message):
    tag = field(1, str)


class Req(Message):
    name = field(1, str)
    count = field(2, int)
    ratio = field(3, float)
    blob = field(4, bytes)
    inner = field(5, Inner)
    values = field(6, int, repeated=True)


class TestSchemaBridge:
    def test_message_roundtrip(self):
        msg = Req(
            name="n",
            count=12,
            ratio=0.5,
            blob=b"bb",
            inner=Inner(tag="t"),
            values=[1, 2, 3],
        )
        data = mcpack.message_to_mcpack(msg)
        back = mcpack.message_from_mcpack(Req, data)
        assert back == msg

    def test_same_schema_serves_proto2_and_mcpack(self):
        """The mcpack2pb promise: ONE typed message, two wire formats."""
        msg = Req(name="dual", count=3)
        pb = Req.from_binary(msg.to_binary())
        mc = mcpack.message_from_mcpack(Req, mcpack.message_to_mcpack(msg))
        assert pb == mc == msg

    def test_int_coerces_to_float_field(self):
        data = mcpack.dumps({"ratio": 2})
        msg = mcpack.message_from_mcpack(Req, data)
        assert msg.ratio == 2.0

    def test_type_mismatch_raises(self):
        data = mcpack.dumps({"count": "not-an-int"})
        with pytest.raises(ParseError):
            mcpack.message_from_mcpack(Req, data)


class TestNsheadMcpackService:
    def test_end_to_end_over_nshead(self):
        """nshead+mcpack loopback: the reference's NsheadMcpackAdaptor
        shape — typed dict in, typed dict out, nshead framing outside."""
        from incubator_brpc_tpu.protocol import nshead
        from incubator_brpc_tpu.rpc import Channel, Server, ServerOptions

        def handler(cntl, req: dict) -> dict:
            return {"echo": req.get("msg", ""), "n": req.get("n", 0) + 1}

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                nshead_service=mcpack.make_mcpack_service(handler),
            )
        )
        assert srv.start(0)
        try:
            import socket as pysock

            body = mcpack.dumps({"msg": "hi", "n": 41})
            conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
            conn.sendall(nshead.pack_frame(body, log_id=7))
            resp = b""
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                resp += chunk
                total = nshead.parse_header(resp[: nshead.HEADER_BYTES])
                if total is not None and len(resp) >= total:
                    break
            frame, _ = nshead.try_parse_frame(resp)
            assert frame is not None
            assert mcpack.loads(frame.payload) == {"echo": "hi", "n": 42}
            conn.close()
        finally:
            srv.stop()


class TestAotGenerator:
    """tools/mcpack_gen.py — the mcpack2pb protoc-plugin analog
    (generator.cpp emits C++ parse/serialize; ours emits unrolled Python
    codecs). Contract: generated bytes are IDENTICAL to the runtime
    bridge's, so generated and reflective peers interoperate."""

    SCHEMA = '''
from incubator_brpc_tpu.protocol.json2pb import Message, field


class Inner(Message):
    tag = field(1, str)
    weight = field(2, float)


class Outer(Message):
    name = field(1, str)
    count = field(2, int)
    ratio = field(3, float)
    ok = field(4, bool)
    blob = field(5, bytes)
    inner = field(6, Inner)
    labels = field(7, str, repeated=True)
    inners = field(8, Inner, repeated=True)
'''

    def _build(self, tmp_path):
        import importlib.util
        import sys as _sys

        schema_path = tmp_path / "gen_schema.py"
        schema_path.write_text(self.SCHEMA)
        spec = importlib.util.spec_from_file_location("gen_schema", schema_path)
        module = importlib.util.module_from_spec(spec)
        _sys.modules["gen_schema"] = module
        spec.loader.exec_module(module)
        from tools.mcpack_gen import generate

        src = generate(module, src_name="gen_schema.py")
        ns = {}
        exec(compile(src, "<generated>", "exec"), ns)
        return module, ns

    def _samples(self, module):
        Inner, Outer = module.Inner, module.Outer
        yield Outer()
        yield Outer(name="x")
        yield Outer(
            name="full", count=42, ratio=2.5, ok=True, blob=b"\x00\xff",
            inner=Inner(tag="t", weight=0.25),
            labels=["a", "b", ""],
            inners=[Inner(tag="i1"), Inner(weight=9.0)],
        )
        yield Outer(count=-(2**40), ok=False)  # int64 path
        yield Outer(count=2**63 + 5)  # uint64 path
        yield Outer(name="s" * 300, blob=b"B" * 300)  # long heads

    def test_generated_bytes_match_runtime_bridge(self, tmp_path):
        from incubator_brpc_tpu.protocol.mcpack import message_to_mcpack

        module, ns = self._build(tmp_path)
        for msg in self._samples(module):
            assert ns["pack_Outer"](msg) == message_to_mcpack(msg)

    def test_generated_roundtrip_and_cross_decode(self, tmp_path):
        from incubator_brpc_tpu.protocol.mcpack import (
            message_from_mcpack,
            message_to_mcpack,
        )

        module, ns = self._build(tmp_path)
        for msg in self._samples(module):
            wire = ns["pack_Outer"](msg)
            # generated unpack of generated bytes
            back = ns["unpack_Outer"](wire)
            # runtime unpack of generated bytes (interop both ways)
            back2 = message_from_mcpack(module.Outer, wire)
            back3 = ns["unpack_Outer"](message_to_mcpack(msg))
            for m in (back, back2, back3):
                for spec in module.Outer._specs.values():
                    got, want = getattr(m, spec.name), getattr(msg, spec.name)
                    if isinstance(want, list) and want and hasattr(want[0], "_specs"):
                        assert [i.tag for i in got] == [i.tag for i in want]
                    elif hasattr(want, "_specs"):
                        assert got.tag == want.tag and got.weight == want.weight
                    else:
                        assert got == want, spec.name

    def test_present_null_rejected_like_runtime(self, tmp_path):
        import pytest

        from incubator_brpc_tpu.protocol.mcpack import (
            ParseError,
            dumps,
            message_from_mcpack,
        )

        module, ns = self._build(tmp_path)
        wire = dumps({"count": None})  # present NULL field
        with pytest.raises(ParseError):
            message_from_mcpack(module.Outer, wire)
        with pytest.raises(ParseError):  # generated must agree
            ns["unpack_Outer"](wire)

    def test_out_of_range_int_raises_valueerror(self, tmp_path):
        import pytest

        module, ns = self._build(tmp_path)
        with pytest.raises(ValueError):
            ns["pack_Outer"](module.Outer(count=-(2**63) - 1))
        with pytest.raises(ValueError):
            ns["pack_Outer"](module.Outer(count=2**64))

    def test_generated_unpack_rejects_bad_types(self, tmp_path):
        import pytest

        from incubator_brpc_tpu.protocol.mcpack import ParseError, dumps

        module, ns = self._build(tmp_path)
        with pytest.raises(ParseError):
            ns["unpack_Outer"](dumps({"count": "not-an-int"}))
        with pytest.raises(ParseError):
            ns["unpack_Outer"](dumps({"inner": "not-an-object"}))
        with pytest.raises(ParseError):
            ns["unpack_Outer"](dumps({"labels": "not-an-array"}))
