"""baidu_std wire-format conformance + end-to-end selection.

The byte-exact fixtures are hand-assembled from the reference's format
notes (baidu_rpc_protocol.cpp:53-58: 12-byte "PRPC" header, network-order
sizes, protobuf RpcMeta per baidu_rpc_meta.proto) — the interop oracle
SURVEY §7 step 4 calls for."""

import struct

import pytest

from incubator_brpc_tpu.protocol import baidu_std
from incubator_brpc_tpu.protocol.baidu_std import RpcMeta
from incubator_brpc_tpu.protocol.tbus_std import Meta, ParseError
from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server
from incubator_brpc_tpu.utils.status import ErrorCode


class TestWireFormat:
    def test_request_frame_byte_exact(self):
        # RpcRequestMeta{service_name:"Echo", method_name:"E"} +
        # correlation_id=5 — protobuf bytes computed by hand:
        #   request (field 1, LEN): 0a 09 ( 0a 04 "Echo" 12 01 "E" )
        #   correlation_id (field 4, VARINT): 20 05
        expected_meta = bytes.fromhex("0a090a044563686f12014520" "05")
        payload = b"hello"
        expected = (
            b"PRPC"
            + struct.pack(">II", len(expected_meta) + len(payload), len(expected_meta))
            + expected_meta
            + payload
        )
        got = baidu_std.pack_request(
            Meta(service="Echo", method="E"), payload, correlation_id=5
        )
        assert got == expected

    def test_response_frame_byte_exact(self):
        # RpcResponseMeta{error_code:1001, error_text:"no"} + cid=7:
        #   response (field 2, LEN): 12 07 ( 08 e9 07 12 02 "no" )
        #   correlation_id: 20 07
        expected_meta = bytes.fromhex("120708e90712026e6f2007")
        expected = (
            b"PRPC"
            + struct.pack(">II", len(expected_meta) + 2, len(expected_meta))
            + expected_meta
            + b"ok"
        )
        got = baidu_std.pack_response(
            Meta(error_text="no"), b"ok", correlation_id=7, error_code=1001
        )
        assert got == expected

    def test_attachment_sets_meta_field(self):
        wire = baidu_std.pack_request(
            Meta(service="S", method="m"), b"pp", correlation_id=1,
            attachment=b"attach",
        )
        frame, consumed = baidu_std.try_parse_frame(wire)
        assert consumed == len(wire)
        assert frame.payload == b"pp"
        assert frame.attachment == b"attach"
        assert frame.meta.attachment_size == 6

    def test_roundtrip_all_fields(self):
        meta = Meta(
            service="svc", method="mth", log_id=9, trace_id=11, span_id=13,
            compress="gzip",
        )
        meta.extra["auth"] = "cred"
        wire = baidu_std.pack_request(meta, b"xyz", correlation_id=(3 << 32) | 4)
        frame, _ = baidu_std.try_parse_frame(wire)
        m = frame.meta
        assert (m.service, m.method) == ("svc", "mth")
        assert (m.log_id, m.trace_id, m.span_id) == (9, 11, 13)
        assert m.compress == "gzip"  # CompressType GZIP=2 mapped back
        assert m.extra["auth"] == "cred"
        assert frame.correlation_id == (3 << 32) | 4
        assert not frame.is_response

    def test_parse_header_sizes_the_cut(self):
        wire = baidu_std.pack_request(Meta(service="S", method="m"), b"12345", 1)
        assert baidu_std.parse_header(wire[:12]) == len(wire)
        assert baidu_std.parse_header(wire[:8]) is None
        with pytest.raises(ParseError):
            baidu_std.parse_header(b"TPRCxxxxxxxx")  # other protocol's magic

    def test_resumable_and_meta_size_guard(self):
        wire = baidu_std.pack_request(Meta(service="S", method="m"), b"body", 2)
        for cut in (0, 3, 11, len(wire) - 1):
            assert baidu_std.try_parse_frame(wire[:cut]) == (None, 0)
        bad = bytearray(wire)
        struct.pack_into(">I", bad, 8, 1 << 20)  # meta_size > body_size
        with pytest.raises(ParseError):
            baidu_std.try_parse_frame(bytes(bad))

    def test_rpc_meta_decode_skips_unknown_fields(self):
        # forward compat: an unknown varint field (99) must not break decode
        # (field 99's tag encodes as a two-byte varint)
        blob = RpcMeta(service_name="a", method_name="b").encode()
        tag = (99 << 3) | 0
        blob += bytes([tag & 0x7F | 0x80, tag >> 7]) + b"\x2a"
        m = RpcMeta.decode(blob)
        assert m.service_name == "a" and m.unknown.get(99) == 42


class TestEndToEnd:
    @pytest.fixture
    def server(self):
        srv = Server()

        def echo(cntl, req):
            cntl.response_attachment = cntl.request_attachment
            return req

        def boom(cntl, req):
            cntl.set_failed(ErrorCode.EINTERNAL, "kaboom")
            return b""

        srv.add_service("EchoService", {"Echo": echo, "Boom": boom})
        assert srv.start(0)
        yield srv
        srv.stop()
        srv.join(timeout=5)

    def _channel(self, srv) -> Channel:
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}", options=ChannelOptions(protocol="baidu_std")
        )
        return ch

    def test_echo_over_baidu_std(self, server):
        ch = self._channel(server)
        cntl = ch.call_method("EchoService", "Echo", b"ping", attachment=b"att")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"ping"
        assert cntl.response_attachment == b"att"

    def test_error_propagates_with_text(self, server):
        ch = self._channel(server)
        cntl = ch.call_method("EchoService", "Boom", b"")
        assert cntl.failed()
        assert cntl.error_code == ErrorCode.EINTERNAL
        assert "kaboom" in cntl.error_text

    def test_same_port_serves_both_protocols(self, server):
        b = self._channel(server)
        t = Channel()
        assert t.init(f"127.0.0.1:{server.port}")  # default tbus_std
        for i in range(5):
            cb = b.call_method("EchoService", "Echo", f"b{i}".encode())
            ct = t.call_method("EchoService", "Echo", f"t{i}".encode())
            assert cb.ok() and cb.response_payload == f"b{i}".encode()
            assert ct.ok() and ct.response_payload == f"t{i}".encode()

    def test_concurrent_baidu_calls(self, server):
        import threading

        ch = self._channel(server)
        errs = []

        def worker(i):
            for j in range(20):
                c = ch.call_method("EchoService", "Echo", f"{i}-{j}".encode())
                if c.failed() or c.response_payload != f"{i}-{j}".encode():
                    errs.append((i, j, c.error_code))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        assert not errs, errs[:3]
