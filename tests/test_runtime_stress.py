"""Scheduler contention stress — worker_pool + timer_thread under a
schedule/unschedule storm racing stop (slow; also run under the TSAN
interpreter by ``make san``, probe-gated like the telemetry-ring
stress).

The fabricverify lock-order pass proves the *static* acquisition graph
is acyclic; this stress drives the dynamic side: N producer threads
hammer one TimerThread (schedule / racing unschedule / timer-fired
callbacks spawning pool fibers) while the pool's workers steal across
queues, then stop() lands mid-storm.  Assertions are conservation laws,
so a lost wake, a dropped tombstone, or a fiber stranded by the
stop/steal race fails loudly instead of hanging:

- every schedule() attempt is accounted: fired + prevented-by-
  unschedule + refused-after-stop == attempts;
- every spawned fiber completes its join() contract — normally, or
  with the pool-stopped error for orphans;
- stop_and_join() returns (bounded) with all workers joined.

Sized by ``SCHED_STRESS_THREADS`` / ``SCHED_STRESS_N`` so the TSAN run
(~20x slower) can turn the burn down, exactly like TBNET_STRESS_*.
"""

from __future__ import annotations

import os
import threading

import pytest

from incubator_brpc_tpu.runtime.timer_thread import TimerThread
from incubator_brpc_tpu.runtime.worker_pool import WorkerPool

THREADS = int(os.environ.get("SCHED_STRESS_THREADS", "8"))
N = int(os.environ.get("SCHED_STRESS_N", "600"))


@pytest.mark.slow
class TestSchedulerContentionStress:
    def test_schedule_unschedule_storm_against_stop(self):
        timer = TimerThread(name="stress-timer")
        pool = WorkerPool(concurrency=4, name="sched_stress")
        fired = []
        fired_lock = threading.Lock()
        stats = [dict(attempts=0, prevented=0, refused=0)
                 for _ in range(THREADS)]
        fibers = []
        fibers_lock = threading.Lock()
        start_gate = threading.Event()

        def cb(tag):
            with fired_lock:
                fired.append(tag)

        def producer(idx):
            start_gate.wait(5.0)  # release all producers together
            st = stats[idx]
            for i in range(N):
                st["attempts"] += 1
                tag = (idx, i)
                try:
                    tid = timer.schedule(
                        lambda _t=tag: cb(_t),
                        # half due ~instantly (fire during the storm),
                        # half far out (must be unscheduled or die at stop)
                        delay=0.0005 if i % 2 == 0 else 30.0,
                    )
                except RuntimeError:
                    st["refused"] += 1  # stopped mid-storm: accounted
                    continue
                if i % 3 == 0:
                    if timer.unschedule(tid):
                        st["prevented"] += 1
                if i % 5 == 0:
                    try:
                        f = pool.spawn(lambda: None)
                        with fibers_lock:
                            fibers.append(f)
                    except RuntimeError:
                        pass  # pool stopped mid-storm

        threads = [
            threading.Thread(target=producer, args=(i,))
            for i in range(THREADS)
        ]
        for t in threads:
            t.start()
        start_gate.set()
        # land stop mid-storm: producers keep scheduling into a stopping
        # timer and spawning into a stopping pool — the race under test
        threading.Event().wait(0.05)
        timer.stop_and_join()
        pool.stop_and_join()
        for t in threads:
            t.join(30)
            assert not t.is_alive(), "producer wedged against stop"

        attempts = sum(s["attempts"] for s in stats)
        prevented = sum(s["prevented"] for s in stats)
        refused = sum(s["refused"] for s in stats)
        assert attempts == THREADS * N
        with fired_lock:
            nfired = len(fired)
        # conservation: a scheduled timer either fired, was provably
        # prevented by unschedule, was refused after stop, or was still
        # parked when the thread stopped (pending are dropped at stop —
        # counted from the timer's own stats)
        pending = timer.stats()["pending"]
        assert nfired + prevented + refused + pending == attempts, (
            f"lost timers: fired={nfired} prevented={prevented} "
            f"refused={refused} pending={pending} attempts={attempts}"
        )
        # no double-fire: every fired tag unique
        with fired_lock:
            assert len(set(fired)) == nfired
        # every fiber completes its join contract — normally or with the
        # orphan error from stop_and_join
        with fibers_lock:
            snapshot = list(fibers)
        for f in snapshot:
            assert f.join(timeout=10), "fiber join hung after pool stop"
