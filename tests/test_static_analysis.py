"""fabriclint — FFI-boundary & hot-path static analysis (tools/fabriclint).

Two kinds of tests:

1. **The repo is clean**: every checker runs over the live tree inside
   tier-1 and must report zero unannotated violations.  These tests ARE
   the lint gate — a PR that drifts a ctypes signature, adds a dead
   flag, or puts a per-record loop on a hotpath function fails here.
2. **The checkers work**: seeded mutations (a width change in one
   tbnet.h signature, a dropped argument, a struct field resize...)
   must flip the FFI checker red; synthetic sources prove each hotpath/
   keepalive/errcheck rule fires and each annotation form is enforced.

The sanitizer harness (`make san`) is exercised by slow, probe-gated
tests at the bottom: where the toolchain supports ASAN/TSAN they run
the real thing; elsewhere they skip cleanly.
"""

from __future__ import annotations

import os

import pytest

from tools.fabriclint import (
    RULES,
    cdecl,
    errcheck,
    ffi_check,
    hotpath,
    lifetime,
    registry_lint,
    run_all,
    scan_annotations,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt(violations):
    return "\n".join(str(v) for v in violations)


# ---------------------------------------------------------------------------
# 1. the live tree is clean (the lint gate)
# ---------------------------------------------------------------------------


class TestRepoIsClean:
    def test_ffi_signatures_match_headers(self):
        vs = ffi_check.check()
        assert not vs, _fmt(vs)

    def test_hotpath_functions_are_pure(self):
        vs = hotpath.check()
        assert not vs, _fmt(vs)

    def test_flag_and_bvar_registries(self):
        vs = registry_lint.check()
        assert not vs, _fmt(vs)

    def test_ffi_callbacks_have_keepalives(self):
        vs = lifetime.check()
        assert not vs, _fmt(vs)

    def test_tb_error_codes_checked_or_voided(self):
        vs = errcheck.check()
        assert not vs, _fmt(vs)

    def test_run_all_aggregate(self):
        vs = run_all()
        assert not vs, _fmt(vs)


# ---------------------------------------------------------------------------
# 2a. the header parser models the real headers completely
# ---------------------------------------------------------------------------


class TestHeaderParser:
    @pytest.fixture(scope="class")
    def merged(self):
        return ffi_check.parse_repo_headers()

    def test_every_declaration_parsed(self, merged):
        assert merged.unparsed == []

    def test_function_count_matches_sigs(self, merged):
        from incubator_brpc_tpu import native

        assert set(merged.funcs) == set(native.SIGNATURES)

    def test_telemetry_record_is_48_bytes(self, merged):
        assert merged.structs["tb_telemetry_record"].size_bits == 48 * 8

    def test_callback_typedefs_present(self, merged):
        assert {
            "tb_frame_fn",
            "tb_handoff_fn",
            "tb_closed_fn",
            "tb_native_fn",
            "tb_release_fn",
        } <= set(merged.funcptrs)

    def test_one_line_extern_c_declaration_still_parses(self):
        # the one-line form must not vanish: it either parses (and then
        # trips ffi-unbound) or lands in unparsed — never silently gone
        src = (
            'extern "C" int tb_one_liner(int x);\n'
            'extern "C" {\n'
            "int tb_block_form(int y);\n"
            "}\n"
        )
        h = cdecl.parse_header("/synthetic.h", text=src)
        assert set(h.funcs) == {"tb_one_liner", "tb_block_form"}
        assert h.unparsed == []

    def test_scalar_canonicalization(self, merged):
        h = merged
        t = cdecl.parse_type("const char*", h)
        assert t.kind == "ptr" and t.pointee == "char"
        t = cdecl.parse_type("uint64_t", h)
        assert (t.bits, t.signed_) == (64, False)
        t = cdecl.parse_type("long", h)
        assert (t.bits, t.signed_) == (64, True)
        assert cdecl.parse_type("tb_iobuf*", h).pointee == "opaque:tb_iobuf"


# ---------------------------------------------------------------------------
# 2b. seeded mutations flip the FFI checker red (the meta-tests)
# ---------------------------------------------------------------------------


class TestFfiCheckerCatchesDrift:
    @pytest.fixture(scope="class")
    def tbnet_text(self):
        with open(os.path.join(REPO, "src", "tbnet", "tbnet.h")) as fh:
            return fh.read()

    def _mutate(self, text, old, new):
        assert old in text, f"mutation anchor missing: {old!r}"
        return text.replace(old, new)

    def test_width_change_in_one_signature(self, tbnet_text):
        # the acceptance-criterion mutation: int -> long on
        # tb_server_listen's port parameter (32 -> 64 bits)
        mut = self._mutate(tbnet_text, "const char* ip, int port)",
                           "const char* ip, long port)")
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type" and "tb_server_listen" in v.message
            for v in vs
        ), _fmt(vs)

    def test_signedness_change(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "uint64_t tb_server_telemetry_dropped",
            "int64_t tb_server_telemetry_dropped",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type"
            and "tb_server_telemetry_dropped" in v.message
            and "signedness" in v.message
            for v in vs
        ), _fmt(vs)

    def test_dropped_argument(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "void tb_server_set_telemetry(tb_server* s, uint32_t capacity,\n"
            "                             uint32_t sample_every);",
            "void tb_server_set_telemetry(tb_server* s, uint32_t capacity);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(v.rule == "ffi-arity" for v in vs), _fmt(vs)

    def test_callback_layout_change(self, tbnet_text):
        mut = self._mutate(tbnet_text, "uint32_t cid_lo,\n                            uint32_t cid_hi, uint32_t flags",
                           "uint64_t cid_lo,\n                            uint32_t cid_hi, uint32_t flags")
        vs = ffi_check.check(tbnet_text=mut)
        assert any(v.rule == "ffi-callback" for v in vs), _fmt(vs)

    def test_struct_field_resize(self, tbnet_text):
        mut = self._mutate(
            tbnet_text, "uint32_t request_size;", "uint64_t request_size;"
        )
        vs = ffi_check.check(tbnet_text=mut)
        struct_vs = [v for v in vs if v.rule == "ffi-struct"]
        # the 48-byte ABI is mirrored twice: ctypes Structure AND the
        # numpy drain dtype — both must scream
        assert any("TelemetryRecord" in v.message or "ctypes" in v.message
                   or "offset" in v.message for v in struct_vs), _fmt(vs)
        assert any("numpy" in v.message for v in struct_vs), _fmt(vs)

    def test_removed_declaration_is_ffi_missing(self, tbnet_text):
        mut = self._mutate(
            tbnet_text, "int tb_server_port(const tb_server* s);", ""
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-missing" and "tb_server_port" in v.message
            for v in vs
        ), _fmt(vs)

    def test_new_unbound_export_is_flagged(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "int tb_server_port(const tb_server* s);",
            "int tb_server_port(const tb_server* s);\n"
            "int tb_server_shiny_new_api(tb_server* s);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-unbound" and "tb_server_shiny_new_api" in v.message
            for v in vs
        ), _fmt(vs)


# ---------------------------------------------------------------------------
# 2c. annotation grammar is enforced
# ---------------------------------------------------------------------------


class TestAnnotations:
    def test_allow_reason_must_be_nonempty(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("# fabriclint: allow(hotpath-loop)\nx = 1\n")
        ann = scan_annotations(str(p))
        assert len(ann.bad) == 1 and ann.bad[0].rule == "bad-allow"
        assert "no reason" in ann.bad[0].message

    def test_allow_unknown_rule_is_flagged(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("# fabriclint: allow(no-such-rule) because\n")
        ann = scan_annotations(str(p))
        assert len(ann.bad) == 1 and "unknown rule" in ann.bad[0].message

    def test_allow_inside_string_literal_is_ignored(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text('s = "# fabriclint: allow(hotpath-loop)"\n')
        ann = scan_annotations(str(p))
        assert not ann.bad and not ann.allows

    def test_rules_list_is_closed(self):
        assert "hotpath-lock" in RULES and "ffi-unchecked" in RULES


# ---------------------------------------------------------------------------
# 2d. hotpath purity rules fire (synthetic sources)
# ---------------------------------------------------------------------------

_HOTPATH_BAD = '''
import threading, logging, time
logger = logging.getLogger(__name__)

# fabriclint: hotpath
def drain(self, records):
    with self._lock:
        pass
    self._lock.acquire()
    logger.info("tick")
    print("tick")
    time.sleep(0.1)
    for r in records:
        pass
    squares = [r * r for r in records]
    while records:
        records.pop()
'''

_HOTPATH_OK = '''
import logging
logger = logging.getLogger(__name__)

# fabriclint: hotpath
def drain(self, arr):
    total = arr.sum()
    # fabriclint: allow(hotpath-loop) bounded by distinct methods, not records
    for m in set(arr.tolist()):
        total += m
    try:
        total /= len(arr)
    except ZeroDivisionError:
        logger.exception("error paths may log")
    return total

def unmarked(records):
    for r in records:  # no marker: not on the hot path
        pass
'''


class TestHotpathRules:
    def test_all_rules_fire(self):
        vs = hotpath.check_source("/synthetic/bad.py", _HOTPATH_BAD)
        rules = sorted({v.rule for v in vs})
        assert rules == [
            "hotpath-io", "hotpath-lock", "hotpath-log", "hotpath-loop",
        ], _fmt(vs)
        loops = [v for v in vs if v.rule == "hotpath-loop"]
        assert len(loops) == 3  # for + comprehension + while

    def test_allows_and_handlers_and_unmarked(self):
        vs = hotpath.check_source("/synthetic/ok.py", _HOTPATH_OK)
        assert not vs, _fmt(vs)

    def test_detached_marker_is_flagged(self):
        src = "# fabriclint: hotpath\n\n\nx = 1\n"
        vs = hotpath.check_source("/synthetic/detached.py", src)
        assert len(vs) == 1 and "not attached" in vs[0].message


# ---------------------------------------------------------------------------
# 2e. keepalive + errcheck rules fire (synthetic sources)
# ---------------------------------------------------------------------------

_KEEPALIVE_BAD = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

def start(srv, handler):
    LIB.tb_server_set_frame_cb(srv, FRAME_FN(handler), None)
'''

_KEEPALIVE_LOCAL = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

def start(srv, handler):
    cb = FRAME_FN(handler)  # dies with this frame
    LIB.tb_server_set_frame_cb(srv, cb, None)
'''

_KEEPALIVE_OK = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

class Plane:
    def __init__(self, srv, handler):
        self._cb = FRAME_FN(handler)
        LIB.tb_server_set_frame_cb(srv, self._cb, None)
'''

_ERRCHECK_SRC = '''
from incubator_brpc_tpu.native import LIB

def f(token, srv):
    LIB.tb_conn_close(token)                      # discarded: violation
    rc = LIB.tb_server_listen(srv, b"0.0.0.0", 0)  # checked: fine
    LIB.tb_server_stop(srv)                        # void restype: fine
    # fabriclint: allow(ffi-unchecked) teardown path, stale token expected
    LIB.tb_conn_close(token)
    return rc
'''


class TestLifetimeAndErrcheck:
    def test_inline_callback_is_flagged(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_BAD)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_frame_local_callback_is_flagged(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_LOCAL)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_self_attribute_keepalive_passes(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_OK)
        assert not vs, _fmt(vs)

    def test_frame_local_holder_attribute_is_flagged(self):
        # holder dies with the frame even though the access spells like
        # an attribute — only module-level receivers are retained
        src = (
            "from incubator_brpc_tpu.native import FRAME_FN, LIB\n"
            "def start(srv, make_holder, h):\n"
            "    holder = make_holder(h)\n"
            "    LIB.tb_server_set_frame_cb(srv, holder.cb, None)\n"
        )
        vs = lifetime.check_source("/synthetic/ka.py", src)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_discarded_return_flagged_checked_and_voided_pass(self):
        vs = errcheck.check_source("/synthetic/ec.py", _ERRCHECK_SRC)
        assert len(vs) == 1 and vs[0].rule == "ffi-unchecked", _fmt(vs)
        assert vs[0].line == 5


# ---------------------------------------------------------------------------
# 2f. registry rules fire (synthetic package trees)
# ---------------------------------------------------------------------------


class TestRegistryRules:
    def _pkg_file(self, tmp_path, name, source):
        d = tmp_path / "incubator_brpc_tpu"
        d.mkdir(exist_ok=True)
        p = d / name
        p.write_text(source)
        return str(p)

    def test_dead_flag_flagged_read_flag_passes(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag, get_flag\n'
            'define_flag("zombie_knob", 1, "never read")\n'
            'define_flag("live_knob", 2, "read below")\n'
            'def f():\n    return get_flag("live_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-dead", _fmt(vs)
        assert "zombie_knob" in vs[0].message

    def test_dict_get_does_not_mask_dead_flag(self, tmp_path):
        # a plain dict .get("name") sharing the flag's name is NOT a
        # flag read — only get_flag aliases / flag_registry.get count
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag\n'
            'from incubator_brpc_tpu.utils.flags import flag_registry\n'
            'define_flag("shadow_knob", 1, "read only as a dict key")\n'
            'define_flag("registry_knob", 2, "read via the registry")\n'
            'def f(ctx):\n'
            '    _ = ctx.get("shadow_knob")\n'
            '    return flag_registry.get("registry_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-dead", _fmt(vs)
        assert "shadow_knob" in vs[0].message

    def test_flag_without_help_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag, get_flag\n'
            'define_flag("mute_knob", 1)\n'
            'def f():\n    return get_flag("mute_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-undocumented", _fmt(vs)

    def test_invalid_bvar_name_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'bad = Adder(name="native plane calls")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert any(v.rule == "bvar-name" for v in vs), _fmt(vs)

    def test_undocumented_native_bvar_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'x = Adder(name="native_totally_new_counter")\n'
            'y = Adder(name="unprefixed_counter_is_fine")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert len(vs) == 1 and vs[0].rule == "bvar-undocumented", _fmt(vs)
        assert "native_totally_new_counter" in vs[0].message

    def test_documented_native_bvar_passes(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'x = Adder(name="native_client_calls")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert not vs, _fmt(vs)


# ---------------------------------------------------------------------------
# 3. sanitizer harness (slow; probe-gated like the multiprocess tiers)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSanitizers:
    def test_asan_ubsan_native_subset(self):
        from tools.fabriclint import san

        ok, detail = san.probe("asan")
        if not ok:
            pytest.skip(f"asan unsupported here: {detail}")
        assert san.run_asan() == 0

    def test_tsan_ring_stress(self):
        from tools.fabriclint import san

        ok, detail = san.probe("tsan")
        if not ok:
            pytest.skip(f"tsan unsupported here: {detail}")
        assert san.run_tsan() == 0
