"""fabriclint — FFI-boundary & hot-path static analysis (tools/fabriclint).

Two kinds of tests:

1. **The repo is clean**: every checker runs over the live tree inside
   tier-1 and must report zero unannotated violations.  These tests ARE
   the lint gate — a PR that drifts a ctypes signature, adds a dead
   flag, or puts a per-record loop on a hotpath function fails here.
2. **The checkers work**: seeded mutations (a width change in one
   tbnet.h signature, a dropped argument, a struct field resize...)
   must flip the FFI checker red; synthetic sources prove each hotpath/
   keepalive/errcheck rule fires and each annotation form is enforced.

The sanitizer harness (`make san`) is exercised by slow, probe-gated
tests at the bottom: where the toolchain supports ASAN/TSAN they run
the real thing; elsewhere they skip cleanly.
"""

from __future__ import annotations

import os

import pytest

from tools.fabriclint import (
    RULES,
    cdecl,
    errcheck,
    ffi_check,
    hotpath,
    lifetime,
    registry_lint,
    run_all,
    scan_annotations,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt(violations):
    return "\n".join(str(v) for v in violations)


# ---------------------------------------------------------------------------
# 1. the live tree is clean (the lint gate)
# ---------------------------------------------------------------------------


class TestRepoIsClean:
    def test_ffi_signatures_match_headers(self):
        vs = ffi_check.check()
        assert not vs, _fmt(vs)

    def test_hotpath_functions_are_pure(self):
        vs = hotpath.check()
        assert not vs, _fmt(vs)

    def test_flag_and_bvar_registries(self):
        vs = registry_lint.check()
        assert not vs, _fmt(vs)

    def test_ffi_callbacks_have_keepalives(self):
        vs = lifetime.check()
        assert not vs, _fmt(vs)

    def test_tb_error_codes_checked_or_voided(self):
        vs = errcheck.check()
        assert not vs, _fmt(vs)

    def test_run_all_aggregate(self):
        vs = run_all()
        assert not vs, _fmt(vs)


# ---------------------------------------------------------------------------
# 2a. the header parser models the real headers completely
# ---------------------------------------------------------------------------


class TestHeaderParser:
    @pytest.fixture(scope="class")
    def merged(self):
        return ffi_check.parse_repo_headers()

    def test_every_declaration_parsed(self, merged):
        assert merged.unparsed == []

    def test_function_count_matches_sigs(self, merged):
        from incubator_brpc_tpu import native

        assert set(merged.funcs) == set(native.SIGNATURES)

    def test_telemetry_record_is_64_bytes(self, merged):
        # grown 48 -> 64 in ISSUE 15 (wire trace_id + span_id ride it)
        assert merged.structs["tb_telemetry_record"].size_bits == 64 * 8

    def test_callback_typedefs_present(self, merged):
        assert {
            "tb_frame_fn",
            "tb_handoff_fn",
            "tb_closed_fn",
            "tb_native_fn",
            "tb_release_fn",
        } <= set(merged.funcptrs)

    def test_one_line_extern_c_declaration_still_parses(self):
        # the one-line form must not vanish: it either parses (and then
        # trips ffi-unbound) or lands in unparsed — never silently gone
        src = (
            'extern "C" int tb_one_liner(int x);\n'
            'extern "C" {\n'
            "int tb_block_form(int y);\n"
            "}\n"
        )
        h = cdecl.parse_header("/synthetic.h", text=src)
        assert set(h.funcs) == {"tb_one_liner", "tb_block_form"}
        assert h.unparsed == []

    def test_scalar_canonicalization(self, merged):
        h = merged
        t = cdecl.parse_type("const char*", h)
        assert t.kind == "ptr" and t.pointee == "char"
        t = cdecl.parse_type("uint64_t", h)
        assert (t.bits, t.signed_) == (64, False)
        t = cdecl.parse_type("long", h)
        assert (t.bits, t.signed_) == (64, True)
        assert cdecl.parse_type("tb_iobuf*", h).pointee == "opaque:tb_iobuf"


# ---------------------------------------------------------------------------
# 2b. seeded mutations flip the FFI checker red (the meta-tests)
# ---------------------------------------------------------------------------


class TestFfiCheckerCatchesDrift:
    @pytest.fixture(scope="class")
    def tbnet_text(self):
        with open(os.path.join(REPO, "src", "tbnet", "tbnet.h")) as fh:
            return fh.read()

    def _mutate(self, text, old, new):
        assert old in text, f"mutation anchor missing: {old!r}"
        return text.replace(old, new)

    def test_width_change_in_one_signature(self, tbnet_text):
        # the acceptance-criterion mutation: int -> long on
        # tb_server_listen's port parameter (32 -> 64 bits)
        mut = self._mutate(tbnet_text, "const char* ip, int port)",
                           "const char* ip, long port)")
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type" and "tb_server_listen" in v.message
            for v in vs
        ), _fmt(vs)

    def test_width_change_in_new_reactor_export(self, tbnet_text):
        # ISSUE 9 acceptance: a seeded width flip in one of the NEW
        # multi-reactor exports still flips the checker red — the FFI
        # gate covers the grown surface, not just the seed's
        mut = self._mutate(
            tbnet_text,
            "int tb_server_reactor_stats(const tb_server* s, int reactor,",
            "int tb_server_reactor_stats(const tb_server* s, long reactor,",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type" and "tb_server_reactor_stats" in v.message
            for v in vs
        ), _fmt(vs)

    def test_width_change_in_auth_export(self, tbnet_text):
        # ISSUE 11 acceptance: the FFI gate covers the new compress/auth
        # surface too — a width flip in tb_server_set_auth_tokens' blob
        # length flips the checker red
        mut = self._mutate(
            tbnet_text,
            "int tb_server_set_auth_tokens(tb_server* s, const char* blob,\n"
            "                              size_t blob_len);",
            "int tb_server_set_auth_tokens(tb_server* s, const char* blob,\n"
            "                              int blob_len);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type" and "tb_server_set_auth_tokens" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_telemetry_record_layout(self, tbnet_text):
        # ISSUE 15 acceptance: the record grew 48 -> 64 bytes (trace_id
        # + span_id); a skewed field width in the header flips the
        # 3-way struct check red — the ctypes mirror AND the numpy
        # drain dtype both disagree with the mutated C layout
        mut = self._mutate(
            tbnet_text,
            "  uint64_t trace_id;\n  uint64_t span_id;\n} tb_telemetry_record;",
            "  uint32_t trace_id;\n  uint32_t span_id;\n} tb_telemetry_record;",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-struct" and "tb_telemetry_record" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_scan_trace_out_param_flips_red(self, tbnet_text):
        # the grown tb_scan_prpc_meta trace out-params are covered by
        # the signature gate too: narrowing trace_id_out flips red
        mut = self._mutate(
            tbnet_text,
            "uint64_t* log_id_out, uint64_t* trace_id_out,",
            "uint64_t* log_id_out, uint32_t* trace_id_out,",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type" and "tb_scan_prpc_meta" in v.message
            for v in vs
        ), _fmt(vs)

    def test_auth_callback_layout_drift_flips_red(self, tbnet_text):
        # the tb_auth_fn <-> AUTH_FN layout is checked field-for-field:
        # dropping the peer-port argument is an ffi-callback violation
        mut = self._mutate(
            tbnet_text,
            "typedef int (*tb_auth_fn)(void* ud, const char* auth_data, "
            "size_t auth_len,\n"
            "                          const char* peer_ip, int peer_port);",
            "typedef int (*tb_auth_fn)(void* ud, const char* auth_data, "
            "size_t auth_len,\n"
            "                          const char* peer_ip);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(v.rule == "ffi-callback" for v in vs), _fmt(vs)

    def test_signedness_change(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "uint64_t tb_server_telemetry_dropped",
            "int64_t tb_server_telemetry_dropped",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-type"
            and "tb_server_telemetry_dropped" in v.message
            and "signedness" in v.message
            for v in vs
        ), _fmt(vs)

    def test_dropped_argument(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "void tb_server_set_telemetry(tb_server* s, uint32_t capacity,\n"
            "                             uint32_t sample_every);",
            "void tb_server_set_telemetry(tb_server* s, uint32_t capacity);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(v.rule == "ffi-arity" for v in vs), _fmt(vs)

    def test_callback_layout_change(self, tbnet_text):
        mut = self._mutate(tbnet_text, "uint32_t cid_lo,\n                            uint32_t cid_hi, uint32_t flags",
                           "uint64_t cid_lo,\n                            uint32_t cid_hi, uint32_t flags")
        vs = ffi_check.check(tbnet_text=mut)
        assert any(v.rule == "ffi-callback" for v in vs), _fmt(vs)

    def test_struct_field_resize(self, tbnet_text):
        mut = self._mutate(
            tbnet_text, "uint32_t request_size;", "uint64_t request_size;"
        )
        vs = ffi_check.check(tbnet_text=mut)
        struct_vs = [v for v in vs if v.rule == "ffi-struct"]
        # the 48-byte ABI is mirrored twice: ctypes Structure AND the
        # numpy drain dtype — both must scream
        assert any("TelemetryRecord" in v.message or "ctypes" in v.message
                   or "offset" in v.message for v in struct_vs), _fmt(vs)
        assert any("numpy" in v.message for v in struct_vs), _fmt(vs)

    def test_removed_declaration_is_ffi_missing(self, tbnet_text):
        mut = self._mutate(
            tbnet_text, "int tb_server_port(const tb_server* s);", ""
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-missing" and "tb_server_port" in v.message
            for v in vs
        ), _fmt(vs)

    def test_new_unbound_export_is_flagged(self, tbnet_text):
        mut = self._mutate(
            tbnet_text,
            "int tb_server_port(const tb_server* s);",
            "int tb_server_port(const tb_server* s);\n"
            "int tb_server_shiny_new_api(tb_server* s);",
        )
        vs = ffi_check.check(tbnet_text=mut)
        assert any(
            v.rule == "ffi-unbound" and "tb_server_shiny_new_api" in v.message
            for v in vs
        ), _fmt(vs)


# ---------------------------------------------------------------------------
# 2c. annotation grammar is enforced
# ---------------------------------------------------------------------------


class TestAnnotations:
    def test_allow_reason_must_be_nonempty(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("# fabriclint: allow(hotpath-loop)\nx = 1\n")
        ann = scan_annotations(str(p))
        assert len(ann.bad) == 1 and ann.bad[0].rule == "bad-allow"
        assert "no reason" in ann.bad[0].message

    def test_allow_unknown_rule_is_flagged(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("# fabriclint: allow(no-such-rule) because\n")
        ann = scan_annotations(str(p))
        assert len(ann.bad) == 1 and "unknown rule" in ann.bad[0].message

    def test_allow_inside_string_literal_is_ignored(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text('s = "# fabriclint: allow(hotpath-loop)"\n')
        ann = scan_annotations(str(p))
        assert not ann.bad and not ann.allows

    def test_rules_list_is_closed(self):
        assert "hotpath-lock" in RULES and "ffi-unchecked" in RULES


# ---------------------------------------------------------------------------
# 2d. hotpath purity rules fire (synthetic sources)
# ---------------------------------------------------------------------------

_HOTPATH_BAD = '''
import threading, logging, time
logger = logging.getLogger(__name__)

# fabriclint: hotpath
def drain(self, records):
    with self._lock:
        pass
    self._lock.acquire()
    logger.info("tick")
    print("tick")
    time.sleep(0.1)
    for r in records:
        pass
    squares = [r * r for r in records]
    while records:
        records.pop()
'''

_HOTPATH_OK = '''
import logging
logger = logging.getLogger(__name__)

# fabriclint: hotpath
def drain(self, arr):
    total = arr.sum()
    # fabriclint: allow(hotpath-loop) bounded by distinct methods, not records
    for m in set(arr.tolist()):
        total += m
    try:
        total /= len(arr)
    except ZeroDivisionError:
        logger.exception("error paths may log")
    return total

def unmarked(records):
    for r in records:  # no marker: not on the hot path
        pass
'''


class TestHotpathRules:
    def test_all_rules_fire(self):
        vs = hotpath.check_source("/synthetic/bad.py", _HOTPATH_BAD)
        rules = sorted({v.rule for v in vs})
        assert rules == [
            "hotpath-io", "hotpath-lock", "hotpath-log", "hotpath-loop",
        ], _fmt(vs)
        loops = [v for v in vs if v.rule == "hotpath-loop"]
        assert len(loops) == 3  # for + comprehension + while

    def test_allows_and_handlers_and_unmarked(self):
        vs = hotpath.check_source("/synthetic/ok.py", _HOTPATH_OK)
        assert not vs, _fmt(vs)

    def test_detached_marker_is_flagged(self):
        src = "# fabriclint: hotpath\n\n\nx = 1\n"
        vs = hotpath.check_source("/synthetic/detached.py", src)
        assert len(vs) == 1 and "not attached" in vs[0].message


# ---------------------------------------------------------------------------
# 2e. keepalive + errcheck rules fire (synthetic sources)
# ---------------------------------------------------------------------------

_KEEPALIVE_BAD = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

def start(srv, handler):
    LIB.tb_server_set_frame_cb(srv, FRAME_FN(handler), None)
'''

_KEEPALIVE_LOCAL = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

def start(srv, handler):
    cb = FRAME_FN(handler)  # dies with this frame
    LIB.tb_server_set_frame_cb(srv, cb, None)
'''

_KEEPALIVE_OK = '''
from incubator_brpc_tpu.native import FRAME_FN, LIB

class Plane:
    def __init__(self, srv, handler):
        self._cb = FRAME_FN(handler)
        LIB.tb_server_set_frame_cb(srv, self._cb, None)
'''

_ERRCHECK_SRC = '''
from incubator_brpc_tpu.native import LIB

def f(token, srv):
    LIB.tb_conn_close(token)                      # discarded: violation
    rc = LIB.tb_server_listen(srv, b"0.0.0.0", 0)  # checked: fine
    LIB.tb_server_stop(srv)                        # void restype: fine
    # fabriclint: allow(ffi-unchecked) teardown path, stale token expected
    LIB.tb_conn_close(token)
    return rc
'''


class TestLifetimeAndErrcheck:
    def test_inline_callback_is_flagged(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_BAD)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_frame_local_callback_is_flagged(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_LOCAL)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_self_attribute_keepalive_passes(self):
        vs = lifetime.check_source("/synthetic/ka.py", _KEEPALIVE_OK)
        assert not vs, _fmt(vs)

    def test_frame_local_holder_attribute_is_flagged(self):
        # holder dies with the frame even though the access spells like
        # an attribute — only module-level receivers are retained
        src = (
            "from incubator_brpc_tpu.native import FRAME_FN, LIB\n"
            "def start(srv, make_holder, h):\n"
            "    holder = make_holder(h)\n"
            "    LIB.tb_server_set_frame_cb(srv, holder.cb, None)\n"
        )
        vs = lifetime.check_source("/synthetic/ka.py", src)
        assert len(vs) == 1 and vs[0].rule == "ffi-keepalive", _fmt(vs)

    def test_discarded_return_flagged_checked_and_voided_pass(self):
        vs = errcheck.check_source("/synthetic/ec.py", _ERRCHECK_SRC)
        assert len(vs) == 1 and vs[0].rule == "ffi-unchecked", _fmt(vs)
        assert vs[0].line == 5


# ---------------------------------------------------------------------------
# 2f. registry rules fire (synthetic package trees)
# ---------------------------------------------------------------------------


class TestRegistryRules:
    def _pkg_file(self, tmp_path, name, source):
        d = tmp_path / "incubator_brpc_tpu"
        d.mkdir(exist_ok=True)
        p = d / name
        p.write_text(source)
        return str(p)

    def test_dead_flag_flagged_read_flag_passes(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag, get_flag\n'
            'define_flag("zombie_knob", 1, "never read")\n'
            'define_flag("live_knob", 2, "read below")\n'
            'def f():\n    return get_flag("live_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-dead", _fmt(vs)
        assert "zombie_knob" in vs[0].message

    def test_dict_get_does_not_mask_dead_flag(self, tmp_path):
        # a plain dict .get("name") sharing the flag's name is NOT a
        # flag read — only get_flag aliases / flag_registry.get count
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag\n'
            'from incubator_brpc_tpu.utils.flags import flag_registry\n'
            'define_flag("shadow_knob", 1, "read only as a dict key")\n'
            'define_flag("registry_knob", 2, "read via the registry")\n'
            'def f(ctx):\n'
            '    _ = ctx.get("shadow_knob")\n'
            '    return flag_registry.get("registry_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-dead", _fmt(vs)
        assert "shadow_knob" in vs[0].message

    def test_flag_without_help_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "flags_mod.py",
            'from incubator_brpc_tpu.utils.flags import define_flag, get_flag\n'
            'define_flag("mute_knob", 1)\n'
            'def f():\n    return get_flag("mute_knob")\n',
        )
        vs = registry_lint.check_flags([p])
        assert len(vs) == 1 and vs[0].rule == "flag-undocumented", _fmt(vs)

    def test_invalid_bvar_name_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'bad = Adder(name="native plane calls")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert any(v.rule == "bvar-name" for v in vs), _fmt(vs)

    def test_undocumented_native_bvar_flagged(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'x = Adder(name="native_totally_new_counter")\n'
            'y = Adder(name="unprefixed_counter_is_fine")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert len(vs) == 1 and vs[0].rule == "bvar-undocumented", _fmt(vs)
        assert "native_totally_new_counter" in vs[0].message

    def test_documented_native_bvar_passes(self, tmp_path):
        p = self._pkg_file(
            tmp_path, "bvars_mod.py",
            'from incubator_brpc_tpu.bvar import Adder\n'
            'x = Adder(name="native_client_calls")\n',
        )
        vs = registry_lint.check_bvars([p])
        assert not vs, _fmt(vs)


# ---------------------------------------------------------------------------
# 3. sanitizer harness (slow; probe-gated like the multiprocess tiers)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSanitizers:
    def test_asan_ubsan_native_subset(self):
        from tools.fabriclint import san

        ok, detail = san.probe("asan")
        if not ok:
            pytest.skip(f"asan unsupported here: {detail}")
        assert san.run_asan() == 0

    def test_tsan_ring_stress(self):
        from tools.fabriclint import san

        ok, detail = san.probe("tsan")
        if not ok:
            pytest.skip(f"tsan unsupported here: {detail}")
        assert san.run_tsan() == 0


# ===========================================================================
# fabricverify — lock-order, lifecycle, and state-machine verification
# (tools/fabricverify; sibling of fabriclint, same annotation grammar)
# ===========================================================================

import ast
import json

from tools.fabriclint import to_records
from tools.fabricverify import run_all as verify_run_all
from tools.fabricverify import lifecycle, lockorder, modelcheck
from tools.fabricverify.models import BreakerModel, SessionModel


class TestFabricverifyClean:
    """The live tree is clean — these tests ARE the concurrency lint gate."""

    def test_lock_order_graph_is_acyclic(self):
        vs = lockorder.check()
        assert not vs, _fmt(vs)

    def test_lifecycle_balance(self):
        vs = lifecycle.check()
        assert not vs, _fmt(vs)

    def test_protocol_models_hold(self):
        vs = modelcheck.check()
        assert not vs, _fmt(vs)

    def test_run_all_aggregate(self):
        vs = verify_run_all()
        assert not vs, _fmt(vs)


class TestLockCoverage:
    """The acceptance contract: every threading.Lock/RLock/Condition
    construction site in incubator_brpc_tpu/ is modeled, allowlist-free."""

    @pytest.fixture(scope="class")
    def analysis(self):
        return lockorder.analyze()

    def test_every_lock_site_modeled(self, analysis):
        # independent count: a plain AST scan with none of the analyzer's
        # binding machinery — the two must agree exactly
        expected = 0
        for path in lockorder.iter_pkg_files():
            with open(path) as fh:
                try:
                    tree = ast.parse(fh.read())
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "threading"
                    and fn.attr in ("Lock", "RLock", "Condition",
                                    "Semaphore", "BoundedSemaphore")
                ):
                    expected += 1
        modeled = sum(len(m.sites) for m in analysis.modules.values())
        unmodeled = sum(len(m.unmodeled) for m in analysis.modules.values())
        assert unmodeled == 0, "unbound lock construction sites exist"
        assert modeled == expected and expected > 80, (
            f"analyzer modeled {modeled} of {expected} lock sites"
        )
        # allowlist-free: no lock-unmodeled exemptions anywhere in the pkg
        for path in lockorder.iter_pkg_files():
            with open(path) as fh:
                src = fh.read()
            ann = scan_annotations(path, src)
            for allows in ann.allows.values():
                assert not any(r == "lock-unmodeled" for r, _ in allows), (
                    f"{path}: lock-unmodeled allowlisted"
                )

    def test_condition_wraps_lock_as_alias(self, analysis):
        # Server._quiescent = Condition(Server._lock): one entity, so a
        # Condition wait is correctly modeled as holding the lock
        e = analysis.entities.get("rpc/server.Server._quiescent")
        assert e is not None and e.alias_of == "rpc/server.Server._lock"

    def test_known_nesting_edges_found(self, analysis):
        # ground truth spot checks: nesting that exists in the code today
        keys = set(analysis.edges)
        assert (
            "rpc/server.Server._session_lock",
            "rpc/data_pool.SimpleDataPool._lock",
        ) in keys  # session_local_data borrows under the session lock
        assert (
            "lb/__init__.LoadBalancerWithNaming._cb_lock",
            "rpc/circuit_breaker._BreakerRegistry._lock",
        ) in keys  # _breaker registers inside the cb lock

    def test_hierarchy_doc_in_sync(self, analysis):
        generated = lockorder.render_hierarchy(analysis).strip()
        documented = lockorder.documented_hierarchy()
        assert generated == documented, (
            "docs/ANALYSIS.md lock hierarchy is stale — run "
            "`python -m tools.fabricverify --write-docs`"
        )


_CYCLE_SRC = '''
import threading

class A:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()

    def ab(self):
        with self._alpha_lock:
            with self._beta_lock:
                pass

    def ba(self):
        with self._beta_lock:
            with self._alpha_lock:
                pass
'''

_CALL_CYCLE_SRC = '''
import threading

class B:
    def __init__(self):
        self._front_lock = threading.Lock()
        self._back_lock = threading.Lock()

    def _touch_back(self):
        with self._back_lock:
            pass

    def front_then_back(self):
        with self._front_lock:
            self._touch_back()       # front -> back, through the call graph

    def _touch_front(self):
        with self._front_lock:
            pass

    def back_then_front(self):
        with self._back_lock:
            self._touch_front()      # back -> front: the cycle
'''

_SELF_REACQUIRE_SRC = '''
import threading

class C:
    def __init__(self):
        self._mono_lock = threading.Lock()

    def _inner(self):
        with self._mono_lock:
            pass

    def outer(self):
        with self._mono_lock:
            self._inner()            # non-reentrant Lock re-acquired: deadlock
'''


class TestLockOrderMeta:
    """Seeded violations flip the pass red (the meta-tests)."""

    def _check(self, tmp_path, src):
        p = tmp_path / "m.py"
        p.write_text(src)
        return lockorder.check([str(p)])

    def test_opposite_order_cycle_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _CYCLE_SRC)
        assert any(v.rule == "lock-cycle" for v in vs), _fmt(vs)
        msg = next(v.message for v in vs if v.rule == "lock-cycle")
        assert "_alpha_lock" in msg and "_beta_lock" in msg

    def test_cycle_through_call_graph_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _CALL_CYCLE_SRC)
        assert any(v.rule == "lock-cycle" for v in vs), _fmt(vs)

    def test_self_reacquisition_through_call_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _SELF_REACQUIRE_SRC)
        assert any(
            v.rule == "lock-cycle" and "_mono_lock" in v.message for v in vs
        ), _fmt(vs)

    def test_allow_breaks_the_edge(self, tmp_path):
        src = _CYCLE_SRC.replace(
            "        with self._beta_lock:\n            with self._alpha_lock:",
            "        with self._beta_lock:\n"
            "            # fabriclint: allow(lock-cycle) proven safe: ba() "
            "only runs single-threaded at init\n"
            "            with self._alpha_lock:",
        )
        vs = self._check(tmp_path, src)
        assert not [v for v in vs if v.rule == "lock-cycle"], _fmt(vs)

    def test_unbindable_ctor_is_unmodeled(self, tmp_path):
        vs = self._check(
            tmp_path,
            "import threading\ndef f(q):\n    q.put(threading.Lock())\n",
        )
        assert any(v.rule == "lock-unmodeled" for v in vs), _fmt(vs)


_BORROW_LEAK_SRC = '''
class H:
    def grab(self):
        obj = self._pool.borrow()
        return obj.size          # never given back, never stored
'''

_BORROW_OK_LOCAL_SRC = '''
class H:
    def use(self):
        obj = self._pool.borrow()
        try:
            return obj.size
        finally:
            self._pool.give_back(obj)
'''

_BORROW_OK_STORED_SRC = '''
class H:
    def attach(self, ctx):
        obj = self._pool.borrow()
        ctx["_data"] = obj

    def detach(self, ctx):
        data = ctx.pop("_data", None)
        if data is not None:
            self._pool.give_back(data)
'''

_TIMER_DISCARD_SRC = '''
class H:
    def arm(self, timer):
        timer.schedule(self._tick, delay=1.0)
'''

_TIMER_OK_SRC = '''
class H:
    def arm(self, timer):
        self._tid = timer.schedule(self._tick, delay=1.0)

    def stop(self, timer):
        timer.unschedule(self._tid)
'''

_HOOK_LEAK_SRC = '''
class H:
    def watch(self, sock):
        sock.on_failed.append(self._on_fail)
'''

_HOOK_OK_SRC = '''
class H:
    def watch(self, sock):
        sock.on_failed.append(self._on_fail)

    def unwatch(self, sock):
        sock.on_failed.remove(self._on_fail)
'''


class TestLifecycleMeta:
    def _check(self, tmp_path, src):
        p = tmp_path / "m.py"
        p.write_text(src)
        return lifecycle.check([str(p)])

    def test_missing_give_back_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _BORROW_LEAK_SRC)
        assert [v.rule for v in vs] == ["lifecycle-borrow"], _fmt(vs)

    def test_local_give_back_passes(self, tmp_path):
        assert not self._check(tmp_path, _BORROW_OK_LOCAL_SRC)

    def test_stored_borrow_with_teardown_passes(self, tmp_path):
        assert not self._check(tmp_path, _BORROW_OK_STORED_SRC)

    def test_ownership_transfer_annotation(self, tmp_path):
        src = _BORROW_LEAK_SRC.replace(
            "        obj = self._pool.borrow()",
            "        # fabriclint: allow(lifecycle-borrow) caller owns it; "
            "died-connection teardown gives it back\n"
            "        obj = self._pool.borrow()",
        )
        assert not self._check(tmp_path, src)

    def test_missing_unschedule_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _TIMER_DISCARD_SRC)
        assert [v.rule for v in vs] == ["lifecycle-timer"], _fmt(vs)

    def test_stored_id_with_unschedule_passes(self, tmp_path):
        assert not self._check(tmp_path, _TIMER_OK_SRC)

    def test_stored_id_without_unschedule_flips_red(self, tmp_path):
        src = _TIMER_OK_SRC.replace(
            "    def stop(self, timer):\n"
            "        timer.unschedule(self._tid)\n",
            "",
        )
        vs = self._check(tmp_path, src)
        assert [v.rule for v in vs] == ["lifecycle-timer"], _fmt(vs)

    def test_hook_without_removal_flips_red(self, tmp_path):
        vs = self._check(tmp_path, _HOOK_LEAK_SRC)
        assert [v.rule for v in vs] == ["lifecycle-callback"], _fmt(vs)

    def test_hook_with_removal_passes(self, tmp_path):
        assert not self._check(tmp_path, _HOOK_OK_SRC)

    def test_observer_without_removal_flips_red(self, tmp_path):
        vs = self._check(
            tmp_path,
            "class H:\n"
            "    def start(self, ns):\n"
            "        ns.add_observer(self)\n",
        )
        assert [v.rule for v in vs] == ["lifecycle-callback"], _fmt(vs)

    def test_observer_with_removal_passes(self, tmp_path):
        assert not self._check(
            tmp_path,
            "class H:\n"
            "    def start(self, ns):\n"
            "        ns.add_observer(self)\n"
            "    def stop(self, ns):\n"
            "        ns.remove_observer(self)\n",
        )


class TestModelChecker:
    def test_session_space_is_exhaustive(self):
        # the acceptance scope: 3 parties, 2 steps, reorder + 1 drop +
        # 1 duplicate — a real state space, not a toy walk
        res = modelcheck.explore(SessionModel(n_parties=3, steps=2,
                                              floors=(0, 1, 3)))
        assert not res.violations, _fmt(res.violations)
        assert res.states > 1000 and res.transitions > res.states

    def test_breaker_machine_fully_covered(self):
        from tools.fabricverify.models import (
            B_CLOSED, B_HALF_OPEN, B_ISOLATED,
        )

        res = modelcheck.explore(BreakerModel())
        assert not res.violations, _fmt(res.violations)
        modes = {s[0] for s in res.parent}
        levels = {s[1] for s in res.parent}
        assert modes == {B_CLOSED, B_ISOLATED, B_HALF_OPEN}
        assert levels == {1, 2, 4, 8}  # every doubling level reached

    # -- the seeded protocol mutations (acceptance criteria) --------------

    def test_dropped_close_echo_flips_red(self):
        res = modelcheck.explore(SessionModel(drop_close_echo=True))
        assert any(v.rule == "model-stuck" for v in res.violations), (
            _fmt(res.violations)
        )

    def test_non_monotone_join_flips_red(self):
        res = modelcheck.explore(SessionModel(min_join=True))
        assert any(v.rule == "model-unsafe" for v in res.violations), (
            _fmt(res.violations)
        )

    def test_silent_floor_violation_flips_red(self):
        res = modelcheck.explore(
            SessionModel(min_join=True, no_floor_reject=True)
        )
        assert any(
            v.rule == "model-unsafe" and "floor" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    # -- the fault plane (party death + abort convergence) ----------------

    def test_party_death_scope_holds(self):
        """The shipped fault scope: one party may die at any instant —
        every reachable terminal state leaves no LIVING party stuck in
        the lockstep barrier (the abort broadcast + detection converge)."""
        res = modelcheck.explore(
            SessionModel(
                n_parties=3, steps=2, floors=(0, 1, 3), max_deaths=1
            )
        )
        assert not res.violations, _fmt(res.violations)
        assert res.states > 10_000  # a real fault space, not a toy walk

    def test_dropped_abort_broadcast_flips_red(self):
        """The acceptance meta-test: a proposer that aborts without
        broadcasting leaves survivors wedged in the barrier — the
        abort-convergence check names the stuck party."""
        res = modelcheck.explore(
            SessionModel(max_deaths=1, drop_abort=True)
        )
        assert any(
            v.rule == "model-unsafe"
            and "stuck in the lockstep barrier" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    def test_default_models_cover_party_death(self):
        """make verify-models runs the extended scope by default."""
        names = [m.name for m in modelcheck.default_models()]
        assert "mc_dispatch_session_party_death" in names

    def test_unrevivable_breaker_flips_red(self):
        res = modelcheck.explore(BreakerModel(reset_keeps_broken=True))
        assert any(
            v.rule == "model-unrevivable" for v in res.violations
        ), _fmt(res.violations)

    def test_missing_revive_timer_deadlocks(self):
        res = modelcheck.explore(BreakerModel(no_revive_timer=True))
        assert any(v.rule == "model-stuck" for v in res.violations), (
            _fmt(res.violations)
        )

    def test_unreset_duration_flips_red(self):
        res = modelcheck.explore(BreakerModel(no_duration_reset=True))
        assert any(v.rule == "model-unsafe" for v in res.violations), (
            _fmt(res.violations)
        )

    # -- the resume scope (elastic sessions: checkpoint/resume/replace) ----

    def test_resume_scope_explores_exhaustively(self):
        """The acceptance scope: step-granular progress, nondeterministic
        per-party checkpointing, ≤1 death + ≤1 drop, the resume barrier
        and the replacement join — exhaustively clean and well past 10k
        states."""
        from tools.fabricverify.models import ResumeSessionModel

        res = modelcheck.explore(ResumeSessionModel(n_parties=3, steps=2))
        assert not res.violations, _fmt(res.violations)
        assert res.states > 10_000, res.states

    def test_default_models_cover_resume_scope(self):
        """make verify-models runs (and prints the state count of) the
        resume scope by default."""
        names = [m.name for m in modelcheck.default_models()]
        assert "mc_dispatch_session_resume" in names

    def test_max_resume_join_flips_red(self):
        """Folding survivor watermarks with max instead of min elects a
        resume point some survivor never checkpointed."""
        from tools.fabricverify.models import ResumeSessionModel

        res = modelcheck.explore(ResumeSessionModel(max_resume_join=True))
        assert any(
            v.rule == "model-unsafe" and "min-join" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    def test_skip_replacement_flips_red(self):
        """Resuming without filling the dead slot re-runs steps with a
        divergent party set — silently different math for axis-reducing
        kernels."""
        from tools.fabricverify.models import ResumeSessionModel

        res = modelcheck.explore(ResumeSessionModel(skip_replacement=True))
        assert any(
            v.rule == "model-unsafe" and "divergent party set" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    def test_no_resume_timeout_deadlocks(self):
        """A resume barrier without a drop backstop wedges the proposer
        forever on one lost query/ack."""
        from tools.fabricverify.models import ResumeSessionModel

        res = modelcheck.explore(ResumeSessionModel(no_resume_timeout=True))
        assert any(v.rule == "model-stuck" for v in res.violations), (
            _fmt(res.violations)
        )

    # -- the overlap scope (chunked double-buffered sessions, T3) ---------

    def test_overlap_scope_explores_exhaustively(self):
        """The shipped scope: chunk-granular dispatch/ack pipelines per
        party, two-slot double buffer, per-chunk collective rendezvous,
        ≤1 death + ≤1 drop (including mid-step with half a step's chunks
        acked) — exhaustively clean and well past 10k states."""
        from tools.fabricverify.models import OverlapSessionModel

        res = modelcheck.explore(
            OverlapSessionModel(n_parties=3, steps=3, chunks=3)
        )
        assert not res.violations, _fmt(res.violations)
        assert res.states > 10_000, res.states

    def test_default_models_cover_overlap_scope(self):
        """make verify-models runs (and prints the state count of) the
        overlap scope by default."""
        names = [m.name for m in modelcheck.default_models()]
        assert "mc_dispatch_session_overlap" in names

    def test_ack_before_chunk_complete_flips_red(self):
        """A chunk acked at dispatch time (before its sub-collective
        completed) witnesses nothing — the ack discipline is violated."""
        from tools.fabricverify.models import OverlapSessionModel

        res = modelcheck.explore(
            OverlapSessionModel(ack_before_complete=True)
        )
        assert any(
            v.rule == "model-unsafe"
            and "before the sub-collective completed" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    def test_dispatch_before_predecessor_ack_flips_red(self):
        """Dispatching step k+1's slice j before step k's chunk j was
        acked puts more than two step slots in flight on one slice —
        the double-buffer window invariant."""
        from tools.fabricverify.models import OverlapSessionModel

        res = modelcheck.explore(OverlapSessionModel(no_ack_gate=True))
        assert any(
            v.rule == "model-unsafe"
            and "more than two step slots in flight" in v.message
            for v in res.violations
        ), _fmt(res.violations)

    def test_overlap_death_mid_step_converges(self):
        """Death during a half-acked step: every terminal state of the
        fault scope leaves no living party wedged in its chunk pipeline
        (the abort reaches everyone) — asserted by the clean explore,
        and the death branch is genuinely exercised."""
        from tools.fabricverify.models import OverlapSessionModel

        res = modelcheck.explore(
            OverlapSessionModel(n_parties=2, steps=2, chunks=2)
        )
        assert not res.violations, _fmt(res.violations)
        died = [
            lbl for _s, (_p, lbl) in res.parent.items()
            if lbl.startswith("die")
        ]
        assert died, "the death environment action was never explored"

    def test_counterexample_traces_attached(self):
        res = modelcheck.explore(SessionModel(drop_close_echo=True))
        v = next(v for v in res.violations if v.rule == "model-stuck")
        assert "trace:" in v.message and "deliver" in v.message

    def test_standalone_cli_reports_state_counts(self, capsys):
        assert modelcheck.main([]) == 0
        out = capsys.readouterr().out
        assert "mc_dispatch_session" in out and "states" in out
        assert "circuit_breaker" in out


class TestJsonReports:
    """--json: {rule, file, line, reason} records, diffable across commits."""

    def test_record_schema(self):
        from tools.fabriclint import Violation

        recs = to_records(
            [Violation("lock-cycle", os.path.join(REPO, "x/y.py"), 7, "boom")]
        )
        assert recs == [
            {"rule": "lock-cycle", "file": "x/y.py", "line": 7,
             "reason": "boom"}
        ]

    def test_fabriclint_json_clean(self, capsys):
        from tools.fabriclint.__main__ import main as lint_main

        assert lint_main(["--json"]) == 0
        assert json.loads(capsys.readouterr().out) == []

    def test_fabricverify_json_clean(self, capsys):
        from tools.fabricverify.__main__ import main as verify_main

        assert verify_main(["--json"]) == 0
        assert json.loads(capsys.readouterr().out) == []

    def test_verify_rules_registered_in_shared_grammar(self):
        # one scanner validates every allow(): fabricverify's ids must be
        # in fabriclint.RULES or its exemptions would be bad-allow
        from tools.fabricverify import RULES as VRULES

        assert set(VRULES) <= set(RULES)


# ---------------------------------------------------------------------------
# fabricscan — C++-plane static analysis (tools/fabricscan; third sibling,
# same annotation grammar: wire-bounds taint dataflow, reactor-ownership
# checking, cross-plane parity lint)
# ---------------------------------------------------------------------------

from tools.fabricscan import cmodel as scan_cmodel
from tools.fabricscan import ownership as scan_ownership
from tools.fabricscan import parity as scan_parity
from tools.fabricscan import wirebounds as scan_wirebounds
from tools.fabricscan import run_all as scan_run_all


@pytest.fixture(scope="module")
def tbnet_cc_text():
    with open(os.path.join(REPO, "src", "tbnet", "tbnet.cc")) as fh:
        return fh.read()


def _mutate_cc(text, old, new):
    assert old in text, f"mutation anchor missing: {old!r}"
    mutated = text.replace(old, new)
    assert mutated != text
    return mutated


class TestScanRepoIsClean:
    """The live C++ tree passes all three passes — this IS the lint gate
    for src/tbnet + src/tbutil (the same run as `make lint`)."""

    def test_wire_bounds_clean(self):
        vs = scan_wirebounds.check()
        assert not vs, _fmt(vs)

    def test_ownership_clean(self):
        vs = scan_ownership.check()
        assert not vs, _fmt(vs)

    def test_plane_parity_clean(self):
        vs = scan_parity.check()
        assert not vs, _fmt(vs)

    def test_run_all_aggregate(self):
        vs = scan_run_all()
        assert not vs, _fmt(vs)

    def test_fabricscan_json_clean(self, capsys):
        from tools.fabricscan.__main__ import main as scan_main

        assert scan_main(["--json"]) == 0
        assert json.loads(capsys.readouterr().out) == []

    def test_scan_rules_registered_in_shared_grammar(self):
        # one scanner validates every allow(): fabricscan's ids must be
        # in fabriclint.RULES or its exemptions would be bad-allow
        from tools.fabricscan import RULES as SRULES

        assert set(SRULES) <= set(RULES)


class TestScanCoverageIsAllowlistFree:
    """ISSUE 12 acceptance: the analysis covers what it claims to cover,
    with NO allow() escape hatches on the checked surfaces."""

    @pytest.fixture(scope="class")
    def model(self):
        return scan_cmodel.parse_native_plane()

    def test_cpp_model_parses_everything(self, model):
        # the cdecl discipline lifted to bodies: an unparsed definition
        # is an unchecked definition
        assert model.unparsed == []

    def test_cutter_call_graph_is_visited(self, model):
        # every wire-bounds root resolves, and the closure reaches the
        # functions the frame path actually rides — scanner, codec
        # table, tbus header pair, varint reader
        for root in scan_wirebounds.ROOTS:
            assert root in model.funcs, f"root {root} vanished"
        reach = scan_wirebounds.checked_functions(model)
        for expected in (
            "process_frames", "scan_prpc_meta", "prpc_peek", "read_varint",
            "codec_decompress", "snappy_decompress_block", "zlib_decompress",
            "tb_tbus_peek", "tb_tbus_cut", "run_native",
            "tb_channel_pump", "pump_once", "prpc_complete_one",
            "tb_scan_prpc_meta",
        ):
            assert expected in reach, f"{expected} fell out of the checked"\
                " call graph"

    def test_netloop_netconn_fields_all_owned(self, model):
        # every mutable NetLoop/NetConn field carries a declared owner —
        # the multi-reactor structures are fully covered, not sampled
        for sname in ("NetLoop", "NetConn"):
            owned = scan_ownership.owned_fields(model, sname)
            assert owned, f"{sname} lost its fields"
            missing = [f for f, o in owned.items() if o is None]
            assert not missing, f"{sname} fields without owners: {missing}"

    def test_checked_structs_all_owned(self, model):
        # the wider claim: every mutable field on every checked struct
        missing = []
        for sname in scan_ownership.CHECKED_STRUCTS:
            for f, o in scan_ownership.owned_fields(model, sname).items():
                if o is None:
                    missing.append(f"{sname}.{f}")
        assert not missing, missing

    def test_no_scan_rule_allowlisted_in_cpp(self):
        # allowlist-free: fixes, not exemptions (the PR 6/7 discipline) —
        # no allow() for any fabricscan rule anywhere in the C++ plane
        from tools.fabricscan import RULES as SRULES

        for path in (scan_cmodel.TBNET_CC, scan_cmodel.TBUTIL_CC):
            anns = scan_annotations(path)
            allowed_scan = [
                (line, rule)
                for line, items in anns.allows.items()
                for rule, _reason in items
                if rule in SRULES
            ]
            assert not allowed_scan, (
                f"{path}: fabricscan violations must be fixed, not "
                f"allowlisted: {allowed_scan}"
            )


class TestWireBoundsCatchesMutations:
    """Seeded mutations flip wire-bounds red (≥2 per ISSUE 12)."""

    def test_dropped_pump_frame_cap(self, tbnet_cc_text):
        # the guard the pass found missing at introduction: without the
        # client-side cap a hostile tbus body_len grows rbuf unbounded
        mut = _mutate_cc(
            tbnet_cc_text,
            " ||\n            hdr.body_len > kClientMaxBody",
            "",
        )
        vs = scan_wirebounds.check(tbnet_text=mut)
        assert any(
            v.rule == "wire-bounds" and "tb_channel_pump" in v.message
            and "hdr.body_len" in v.message
            for v in vs
        ), _fmt(vs)

    def test_dropped_submessage_length_guard(self, tbnet_cc_text):
        # the scanner's `len > n - off` subtraction idiom removed: the
        # tainted submessage length reaches read_varint's bound unguarded
        mut = _mutate_cc(
            tbnet_cc_text,
            "if (!read_varint(p, n, &off, &len) || len > n - off) return m;",
            "if (!read_varint(p, n, &off, &len)) return m;",
        )
        vs = scan_wirebounds.check(tbnet_text=mut)
        assert any(
            v.rule == "wire-bounds" and "scan_prpc_meta" in v.message
            and "sub_len" in v.message
            for v in vs
        ), _fmt(vs)

    def test_dropped_snappy_table_mask(self, tbnet_cc_text):
        # the hash-table subscript loses its explicit cap: the value
        # loaded out of the input buffer indexes slots unguarded
        mut = _mutate_cc(
            tbnet_cc_text,
            "    h &= kSnappyTableMask;",
            "",
        )
        vs = scan_wirebounds.check(tbnet_text=mut)
        assert any(
            v.rule == "wire-bounds" and "snappy_compress_block" in v.message
            for v in vs
        ), _fmt(vs)


class TestOwnershipCatchesMutations:
    """Seeded mutations flip ownership/owner-missing red (≥2)."""

    def test_stripped_owner_annotation(self, tbnet_cc_text):
        # unannotated mutable shared state is itself a violation
        mut = _mutate_cc(
            tbnet_cc_text,
            "int inline_burst = 0;  // fabricscan: owner(loop)",
            "int inline_burst = 0;",
        )
        vs = scan_ownership.check(tbnet_text=mut)
        assert any(
            v.rule == "owner-missing" and "inline_burst" in v.message
            for v in vs
        ), _fmt(vs)

    def test_loop_owned_field_written_from_python_role(self, tbnet_cc_text):
        # a loop-owned field touched from a Python-caller export without
        # an atomic/ring/lock — PR 9's invariant, checked
        mut = _mutate_cc(
            tbnet_cc_text,
            "int tb_server_num_reactors(const tb_server* s) {\n"
            "  return static_cast<int>(s->loops.size());",
            "int tb_server_num_reactors(const tb_server* s) {\n"
            "  s->loops[0]->inline_burst = 0;\n"
            "  return static_cast<int>(s->loops.size());",
        )
        vs = scan_ownership.check(tbnet_text=mut)
        assert any(
            v.rule == "ownership" and "inline_burst" in v.message
            and "tb_server_num_reactors" in v.message
            for v in vs
        ), _fmt(vs)

    def test_setter_losing_init_seed_flips_red(self, tbnet_cc_text):
        # init-owned = write-once setup: a pre-listen setter that loses
        # its role(init) seed becomes an arbitrary-Python-thread export
        # writing an init-owned field — flagged
        mut = _mutate_cc(
            tbnet_cc_text,
            "// fabricscan: role(init)\n"
            "void tb_server_set_max_body",
            "void tb_server_set_max_body",
        )
        vs = scan_ownership.check(tbnet_text=mut)
        assert any(
            v.rule == "ownership" and "tb_server.max_body" in v.message
            and "tb_server_set_max_body" in v.message
            for v in vs
        ), _fmt(vs)


class TestPlaneParityCatchesMutations:
    """Seeded constant drift between the twins flips plane-parity red
    (≥2): wire numbers, enum ids, error texts, codec constants."""

    def test_skewed_rpc_meta_field_number(self, tbnet_cc_text):
        mut = _mutate_cc(
            tbnet_cc_text,
            "} else if (field == 4) {\n        m.cid = v;",
            "} else if (field == 6) {\n        m.cid = v;",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "correlation_id" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_codec_enum_id(self, tbnet_cc_text):
        mut = _mutate_cc(
            tbnet_cc_text,
            "constexpr uint32_t kCompressGzip = 2;",
            "constexpr uint32_t kCompressGzip = 4;",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "gzip" in v.message for v in vs
        ), _fmt(vs)

    def test_skewed_berror_text(self, tbnet_cc_text):
        mut = _mutate_cc(
            tbnet_cc_text,
            'kDeadlineShedText[] = "',
            'kDeadlineShedText[] = "x',
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "EDEADLINE" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_snappy_hash_multiplier(self, tbnet_cc_text):
        mut = _mutate_cc(tbnet_cc_text, "0x1E35A7BDu", "0x1E35A7BFu")
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "hash multiplier" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_trace_decode_field_number(self, tbnet_cc_text):
        # ISSUE 15: the cutter decoding trace_id from the wrong
        # RpcRequestMeta field would silently break every distributed
        # trace — the decode-side anchor flips red
        mut = _mutate_cc(
            tbnet_cc_text,
            "} else if (f2 == 4) {  // trace_id: the caller's trace",
            "} else if (f2 == 14) {  // trace_id: the caller's trace",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "trace_id" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_sampled_bit_field_number(self, tbnet_cc_text):
        mut = _mutate_cc(
            tbnet_cc_text,
            "} else if (f2 == 9) {  // head-based sampled bit (extension)",
            "} else if (f2 == 7) {  // head-based sampled bit (extension)",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity" and "traced_sampled" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_traced_pump_pack_tag(self, tbnet_cc_text):
        # pack side: the traced pump template stamping log_id under the
        # wrong tag byte (field 7 instead of 3) must flip red against
        # encode_request_submeta's field table
        mut = _mutate_cc(
            tbnet_cc_text,
            "t[o++] = 0x18;  // RpcRequestMeta.log_id (field 3)",
            "t[o++] = 0x38;  // RpcRequestMeta.log_id (field 3)",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity"
            and "traced pump-template field number of log_id" in v.message
            for v in vs
        ), _fmt(vs)

    def test_skewed_telemetry_record_size_anchor(self, tbnet_cc_text):
        # the 48 -> 64 byte record growth, pinned: one side's size
        # constant left behind flips the parity anchor red
        mut = _mutate_cc(
            tbnet_cc_text,
            "static_assert(sizeof(tb_telemetry_record) == 64,",
            "static_assert(sizeof(tb_telemetry_record) == 48,",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "plane-parity"
            and "telemetry record ABI bytes" in v.message
            for v in vs
        ), _fmt(vs)

    def test_refactored_anchor_screams_not_silently_passes(self,
                                                           tbnet_cc_text):
        # extraction anchors are load-bearing: refactoring a constant out
        # from under its regex must fail loudly (scan-parse), never
        # silently compare nothing
        mut = _mutate_cc(
            tbnet_cc_text,
            "constexpr uint32_t kMagicPrpc = ",
            "constexpr uint32_t kMagicPrpcRenamed = ",
        )
        vs = scan_parity.check(tbnet_text=mut)
        assert any(
            v.rule == "scan-parse" and "PRPC magic" in v.message
            for v in vs
        ), _fmt(vs)


class TestFfiCountIsGenerated:
    """ISSUE 12 satellite: the FFI surface size quoted in the docs is
    generated from native.SIGNATURES, not hand-kept prose — the number
    in PARITY row 53 can't rot."""

    def test_parity_row_53_count_matches_signatures(self):
        from incubator_brpc_tpu import native

        n = len(native.SIGNATURES)
        with open(os.path.join(REPO, "docs", "PARITY.md")) as fh:
            parity_text = fh.read()
        assert f"{n} functions" in parity_text, (
            f"docs/PARITY.md row 53 must quote the generated count "
            f"({n} functions == len(native.SIGNATURES))"
        )
        # and no stale hand-kept count survives
        import re as _re

        for m in _re.finditer(r"(?<![~\d])(\d+) functions", parity_text):
            assert int(m.group(1)) == n, (
                f"stale FFI count {m.group(0)!r} in docs/PARITY.md "
                f"(SIGNATURES has {n})"
            )

    def test_analysis_md_count_matches_signatures(self):
        from incubator_brpc_tpu import native

        n = len(native.SIGNATURES)
        with open(os.path.join(REPO, "docs", "ANALYSIS.md")) as fh:
            text = fh.read()
        import re as _re

        for m in _re.finditer(r"(?<![~\d])(\d+) functions", text):
            assert int(m.group(1)) == n, (
                f"stale FFI count {m.group(0)!r} in docs/ANALYSIS.md "
                f"(SIGNATURES has {n})"
            )
