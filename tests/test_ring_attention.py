"""Ring attention tests: exactness vs single-device full attention on the
virtual sp mesh (the long-context sequence-parallel slot)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from incubator_brpc_tpu.models.ring_attention import (
    full_attention,
    make_ring_attention_step,
    ring_attention,
)


def sp_mesh(n):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, axis_names=("sp",))


def rand_qkv(key, b=2, t=32, h=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, sp, causal):
        mesh = sp_mesh(sp)
        q, k, v = rand_qkv(jax.random.key(0), t=32)
        step, place = make_ring_attention_step(mesh, causal=causal)
        out = step(place(q), place(k), place(v))
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_single_rank_degenerates_to_full(self):
        mesh = sp_mesh(1)
        q, k, v = rand_qkv(jax.random.key(1), t=16)
        step, place = make_ring_attention_step(mesh, causal=True)
        out = step(place(q), place(k), place(v))
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_bfloat16_stays_bfloat16(self):
        mesh = sp_mesh(4)
        q, k, v = rand_qkv(jax.random.key(2), t=32, dtype=jnp.bfloat16)
        step, place = make_ring_attention_step(mesh, causal=True)
        out = step(place(q), place(k), place(v))
        assert out.dtype == jnp.bfloat16
        want = full_attention(q, k, v, causal=True)
        # accumulation is f32 internally; compare loosely at bf16 precision
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(want, np.float32),
            rtol=0.05, atol=0.05,
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_prefetch_is_bit_identical(self, causal):
        """The rotate-while-computing emission (prefetch=True, the T3
        overlap shape): each hop's ppermute fires before the held
        block's fold — same dataflow, so the output must match the
        serialized emission BITWISE, not just approximately."""
        mesh = sp_mesh(4)
        q, k, v = rand_qkv(jax.random.key(3), t=32)
        plain, place = make_ring_attention_step(mesh, causal=causal)
        pref, _ = make_ring_attention_step(
            mesh, causal=causal, prefetch=True
        )
        a = np.asarray(plain(place(q), place(k), place(v)))
        b = np.asarray(pref(place(q), place(k), place(v)))
        assert a.tobytes() == b.tobytes()

    def test_grads_flow(self):
        """Differentiability through the scan + ppermute (training usage)."""
        mesh = sp_mesh(4)
        q, k, v = rand_qkv(jax.random.key(3), t=16)
        step, place = make_ring_attention_step(mesh, causal=True)

        def loss(q, k, v):
            return jnp.mean(jnp.square(step(q, k, v)))

        g = jax.grad(loss)(place(q), place(k), place(v))
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0

    def test_long_sequence_memory_shape(self):
        """The point of the ring: per-rank score blocks are (T/sp, T/sp),
        never (T, T). Indirect check: a sequence long enough that a full
        (T, T) f32 score tensor per head would be large still runs
        sharded, and matches the reference computed blockwise."""
        mesh = sp_mesh(8)
        q, k, v = rand_qkv(jax.random.key(4), b=1, t=512, h=2, d=8)
        step, place = make_ring_attention_step(mesh, causal=True)
        out = step(place(q), place(k), place(v))
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=5e-5, atol=5e-5
        )
