"""Quantized + topology-aware collectives (parallel/quantized.py + the
mc_dispatch scheduler extensions).

Three tiers:
- pure-numpy quantizer units (round-trip exactness, error bounds,
  chunk-split identity, fingerprint stability) — no devices needed;
- in-process sessions on the virtual 8-device mesh: the quantize= knob
  end to end (accept validation, wire accounting, bvars, overlap
  composition), against the exact session and the numpy model;
- topology-aware scheduling units (synthetic skewed link telemetry) and
  the DeviceLinkMap.link_profile() accessor over a real loopback link.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from incubator_brpc_tpu.parallel import quantized as Q

WIDTH = 256  # 64 floats = 2 default blocks — small enough to jit fast


@pytest.fixture(scope="module")
def shard_map_capable():
    import jax

    from incubator_brpc_tpu.parallel.compat import resolve_shard_map

    try:
        resolve_shard_map()
    except ImportError:
        pytest.skip("no shard_map in this jax build")
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4+ device mesh")
    return True


def _rows(n, nfloats, seed=5, scale=3.0):
    rng = np.random.default_rng(seed)
    return [
        (rng.standard_normal(nfloats) * scale * (i + 1)).astype(np.float32)
        for i in range(n)
    ]


class TestQuantizerMath:
    """The numpy twin: the arithmetic contract everything else rides."""

    @pytest.mark.parametrize("mode", ["int8", "int4"])
    @pytest.mark.parametrize("width,block", [(128, 32), (512, 32), (512, 64), (4096, 32), (256, 8)])
    def test_round_trip_error_inside_bound(self, mode, width, block):
        (x,) = _rows(1, width // 4)
        q, e = Q.np_quantize(x, mode, block)
        v = Q.np_dequantize(q, e, mode, block)
        bound = Q.pmean_error_bound([x], 1, mode, block)
        assert float(np.abs(v - x).max()) <= bound

    @pytest.mark.parametrize("mode", ["int8", "int4"])
    def test_round_trip_is_idempotent(self, mode):
        """dequantize∘quantize is a projection: applying it twice yields
        the identical BYTES — the property quantized checkpoint rings
        need for byte-identical resume (power-of-two scales make the
        scaling arithmetic exact)."""
        (x,) = _rows(1, WIDTH // 4, seed=9, scale=40.0)
        v1 = Q.np_dequantize(*Q.np_quantize(x, mode), mode)
        v2 = Q.np_dequantize(*Q.np_quantize(v1, mode), mode)
        assert v1.tobytes() == v2.tobytes()

    @pytest.mark.parametrize("mode", ["int8", "int4"])
    def test_zero_and_uniform_blocks(self, mode):
        x = np.zeros(64, np.float32)
        v = Q.np_dequantize(*Q.np_quantize(x, mode), mode)
        assert v.tobytes() == x.tobytes()
        x = np.full(64, 7.5, np.float32)
        v = Q.np_dequantize(*Q.np_quantize(x, mode), mode)
        assert float(np.abs(v - x).max()) <= Q.pmean_error_bound([x], 1, mode)

    @pytest.mark.parametrize("mode", ["int8", "int4"])
    def test_chunk_split_identity(self, mode):
        """Block-aligned chunking is exact: quantizing each chunk
        separately produces the same dequantized bytes as slicing the
        full-width quantization — the chunk-safety declaration the
        overlap scheduler relies on."""
        (x,) = _rows(1, 128, seed=3)
        block = 32
        full = Q.np_dequantize(*Q.np_quantize(x, mode, block), mode, block)
        for chunks in (2, 4):
            cw = 128 // chunks
            assert cw % block == 0
            parts = [
                Q.np_dequantize(
                    *Q.np_quantize(x[j * cw:(j + 1) * cw], mode, block),
                    mode, block,
                )
                for j in range(chunks)
            ]
            assert np.concatenate(parts).tobytes() == full.tobytes()

    def test_wire_bytes_and_support(self):
        assert Q.wire_bytes(512, "none") == 512
        assert Q.wire_bytes(512, "int8") == 128 + 4  # values + exponents
        assert Q.wire_bytes(512, "int4") == 64 + 4
        assert Q.wire_bytes(512, "int8") / 512 < 0.3
        assert Q.wire_bytes(512, "int4") / 512 < 0.15
        assert not Q.supports(100, "int8")   # 25 floats: no whole block
        assert not Q.supports(514, "int8")   # not float32-aligned
        assert not Q.supports(512, "int4", block=31)  # odd int4 block
        with pytest.raises(ValueError):
            Q.wire_bytes(100, "int8")

    def test_quantized_pmean_model_error_bound(self):
        rows = _rows(3, 64, seed=12)
        for mode in ("int8", "int4"):
            exact = np.mean(np.stack(rows), axis=0, dtype=np.float32)
            for steps in (1, 4):
                got = Q.np_quantized_pmean(rows, steps, mode)
                bound = Q.pmean_error_bound(rows, steps, mode)
                assert float(np.abs(got - exact).max()) <= bound


class TestVariantRegistry:
    """DeviceMethod variants: fingerprints, geometry, the quantized()
    resolution the session knob rides."""

    def test_fingerprint_stability_and_distinctness(self):
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod

        # two INDEPENDENT mints of the same parametrization (bypassing
        # the cache — what two separate processes do) agree
        a = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 32), width=WIDTH
        )
        b = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 32), width=WIDTH
        )
        assert a.fingerprint() == b.fingerprint()
        # mode, block and width all enter the identity
        c = DeviceMethod(
            Q._make_quantized_pmean_kernel("int4", 32), width=WIDTH
        )
        d = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 16), width=WIDTH
        )
        e = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 32), width=2 * WIDTH
        )
        fps = {x.fingerprint() for x in (a, c, d, e)}
        assert len(fps) == 4

    def test_quantized_resolution(self):
        from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm

        dm = _pmean_dm(WIDTH)
        assert dm.quantized("none") is dm
        assert dm.quantized("") is dm
        v8 = dm.quantized("int8")
        assert v8 is not None and v8.quant_mode == "int8"
        assert v8.chunkable and v8.chunk_align == 4 * Q.DEFAULT_BLOCK
        assert v8.wire_bytes() == Q.wire_bytes(WIDTH, "int8")
        assert v8.quantized("int8") is v8  # a variant resolves itself
        # unaligned width: no variant minted — the knob rejects cleanly
        odd = _pmean_dm(68)  # 17 floats: no whole default block
        assert odd.quantized("int8") is None

    def test_variant_cache_is_shared(self):
        assert Q.quantized_pmean_dm(WIDTH, "int8") is Q.quantized_pmean_dm(
            WIDTH, "int8"
        )


class TestQuantizedSessions:
    """The quantize= knob end to end on the virtual mesh."""

    @pytest.fixture
    def pmean_registered(self, shard_map_capable):
        from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
        from incubator_brpc_tpu.rpc.device_method import (
            lookup_device_method,
            register_device_method,
            unregister_device_method,
        )

        dm = _pmean_dm(WIDTH)
        prev = lookup_device_method("_collective", "pmean")
        register_device_method("_collective", "pmean", dm)
        yield dm
        # restore EXACTLY: a leaked registration would shadow the
        # width-minting pmean resolver for every other suite
        if prev is not None:
            register_device_method("_collective", "pmean", prev)
        else:
            unregister_device_method("_collective", "pmean")

    def _run(self, dm, rows, steps, **kw):
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import (
            run_dispatch_session,
        )

        party_ids = [d.id for d in jax.devices()[:3]]
        ops = [r.tobytes() for r in rows]
        row, n, _ = run_dispatch_session(
            party_ids, 0, dm, ops, steps,
            service="_collective", method="pmean", **kw,
        )
        return np.frombuffer(
            bytes(np.asarray(row[:n], np.uint8)), np.float32
        )

    @pytest.mark.parametrize("mode", ["int8", "int4"])
    def test_session_matches_model_and_bound(self, pmean_registered, mode):
        rows = _rows(3, WIDTH // 4, seed=7)
        steps = 2
        exact = self._run(pmean_registered, rows, steps)
        got = self._run(pmean_registered, rows, steps, quantize=mode)
        bound = Q.pmean_error_bound(rows, steps, mode)
        assert float(np.abs(got - exact).max()) <= bound
        model = Q.np_quantized_pmean(rows, steps, mode)
        # XLA may re-associate the party sum: tolerance, not bytes
        assert np.allclose(got, model, atol=1e-5)

    def test_determinism_across_repeat_runs(self, pmean_registered):
        """The quantized chain is bit-deterministic run to run — the
        property resume byte-identity (and every party computing the
        identical mean) rides on."""
        rows = _rows(3, WIDTH // 4, seed=8)
        a = self._run(pmean_registered, rows, 2, quantize="int8")
        b = self._run(pmean_registered, rows, 2, quantize="int8")
        assert a.tobytes() == b.tobytes()

    def test_all_parties_converge_to_identical_bytes(self, pmean_registered):
        """Determinism ACROSS PARTIES: after step 1 of a quantized pmean
        every party holds the same mean, and because the quantized
        arithmetic is deterministic (round-half-even, power-of-two
        scales, one shared jitted program) their final rows are
        byte-identical — the property the lockstep contract needs."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
        from incubator_brpc_tpu.rpc import Channel, Server, ServerOptions

        servers = []
        for i in range(2):
            s = Server(
                ServerOptions(
                    device_index=i + 1,
                    usercode_inline=True,
                    enable_collective_service=True,
                    collective_max_concurrency=0,
                )
            )
            assert s.start(0)
            servers.append(s)
        try:
            chans = []
            for s in servers:
                ch = Channel()
                assert ch.init(f"127.0.0.1:{s.port}")
                chans.append(ch)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            rows = _rows(2, WIDTH // 4, seed=17)
            out = propose_dispatch(
                chans, party_ids, "_collective", "pmean",
                [r.tobytes() for r in rows],
                steps=2, proposer_index=None, timeout_ms=60000,
                quantize="int8",
            )
            assert out["results"][0] == out["results"][1]
            assert out["quantize"] == "int8"
            assert out["wire_bytes"] == Q.wire_bytes(WIDTH, "int8") * 2 * 2
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_overlap_composes_byte_identically(self, pmean_registered):
        """chunks>1 + double_buffer + quantize: the overlap schedule and
        any chunk_order permutation leave the bytes unchanged."""
        rows = _rows(3, WIDTH // 4, seed=10)
        base = self._run(pmean_registered, rows, 2, quantize="int8")
        chunked = self._run(
            pmean_registered, rows, 2, quantize="int8",
            chunks=2, double_buffer=True,
        )
        assert chunked.tobytes() == base.tobytes()
        routed = self._run(
            pmean_registered, rows, 2, quantize="int8",
            chunks=2, double_buffer=True, chunk_order=[1, 0],
        )
        assert routed.tobytes() == base.tobytes()

    def test_misaligned_chunks_reject_pre_lockstep(self, pmean_registered):
        """A chunk split that would cut a scale block in half is refused
        at admission (chunk_align), before any dispatch."""
        from incubator_brpc_tpu.parallel.mc_dispatch import _validate_chunks

        v8 = pmean_registered.quantized("int8")
        # WIDTH=256 -> 2 blocks of 32 floats; chunks=4 would cut blocks
        with pytest.raises(ValueError, match="block alignment"):
            _validate_chunks(v8, 4, "_collective", "pmean")

    def test_misdeclared_nonchunkable_variant_rejects(self, shard_map_capable):
        """A quantized variant registered WITHOUT the chunk-safety
        declaration rejects a chunked session cleanly pre-lockstep —
        at the proposer seam and at the handler seam alike."""
        from incubator_brpc_tpu.parallel.mc_dispatch import _validate_chunks
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod

        base = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 32),
            width=WIDTH, chunkable=True,
        )
        bad = DeviceMethod(
            Q._make_quantized_pmean_kernel("int8", 32),
            width=WIDTH, chunkable=False,
        )
        bad.quant_mode = "int8"
        base.quant_variants["int8"] = bad
        with pytest.raises(ValueError, match="not registered chunkable"):
            _validate_chunks(base.quantized("int8"), 2, "svc", "m")

    def test_quantized_bvars_and_wire_accounting(self, pmean_registered):
        from incubator_brpc_tpu.parallel import mc_dispatch as M

        rows = _rows(3, WIDTH // 4, seed=13)
        q0 = M.dispatch_quantized_sessions.get_value()
        s0 = M.dispatch_bytes_saved.get_value()
        self._run(pmean_registered, rows, 2, quantize="int8")
        assert M.dispatch_quantized_sessions.get_value() == q0 + 1
        expect_saved = (WIDTH - Q.wire_bytes(WIDTH, "int8")) * 3 * 2
        assert M.dispatch_bytes_saved.get_value() - s0 == expect_saved

    def test_quantized_checkpoint_ring_shrinks_and_resumes(
        self, pmean_registered
    ):
        """The ring entry of a quantized session costs the WIRE bytes,
        not width float32 bytes — and a replay restored from it is
        byte-identical to the uninterrupted chain (idempotent
        round-trip)."""
        import jax

        from incubator_brpc_tpu.parallel import mc_dispatch as M

        rows = _rows(3, WIDTH // 4, seed=14)
        party_ids = [d.id for d in jax.devices()[:3]]
        ops = [r.tobytes() for r in rows]
        sid = "quantized-ring-unit"
        full = self._run(
            pmean_registered, rows, 4, quantize="int8",
            session_id=sid, checkpoint_every=2,
        )
        ring = M._checkpoint_lookup(sid, 0)
        assert ring is not None and ring.watermark() >= 2
        n_addr = 3  # single controller: every party shard is local
        assert ring.entry_bytes == n_addr * (
            Q.wire_bytes(WIDTH, "int8") + 4
        )
        entry = ring.get(2)
        assert entry is not None and isinstance(entry[0], M._QuantCk)
        # resume from step 2: replay only steps 3..4, byte-identical
        v8 = pmean_registered.quantized("int8")
        row, n, _ = M.run_dispatch_session(
            party_ids, 0, v8, ops, 4,
            service="_collective", method="pmean",
            session_id=sid, resume_from=2, checkpoint_every=2,
        )
        resumed = np.frombuffer(
            bytes(np.asarray(row[:n], np.uint8)), np.float32
        )
        assert resumed.tobytes() == full.tobytes()
        M.release_checkpoints(sid)

    def test_reshard_rows_dequantize_to_full_width(self, pmean_registered):
        """checkpoint_fetch of a quantized ring ships FULL-WIDTH rows:
        the reshard wire format never forks on representation."""
        from incubator_brpc_tpu.parallel import mc_dispatch as M

        rows = _rows(3, WIDTH // 4, seed=15)
        sid = "quantized-reshard-unit"
        self._run(
            pmean_registered, rows, 2, quantize="int8",
            session_id=sid, checkpoint_every=2,
        )
        fetched = M.checkpoint_fetch(sid, 2, [0, 1, 2])
        assert sorted(fetched) == [0, 1, 2]
        import base64

        for slot, info in fetched.items():
            raw = base64.b64decode(info["row"])
            assert len(raw) == WIDTH
            # the shipped row is the dequantized state: finite floats
            assert np.isfinite(np.frombuffer(raw, np.float32)).all()
        M.release_checkpoints(sid)


class TestQuantizedProposals:
    """The rpc-plane seams: accept validation and the session-uniform
    stamp."""

    @pytest.fixture
    def server_and_channel(self, shard_map_capable):
        from incubator_brpc_tpu.rpc import (
            Channel,
            Server,
            ServerOptions,
        )

        s = Server(
            ServerOptions(
                device_index=1,
                usercode_inline=True,
                enable_collective_service=True,
                collective_max_concurrency=0,
            )
        )
        assert s.start(0)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        yield s, ch
        s.stop()
        s.join(timeout=5)

    def _proposal(self, width, fingerprint, parties, **over):
        d = {
            "parties": parties,
            "index": 1,
            "steps": 2,
            "width": width,
            "service": "_collective",
            "method": "pmean",
            "fingerprint": fingerprint,
            "phase": "accept",
        }
        d.update(over)
        return json.dumps(d).encode()

    def test_accept_validates_quantized_fingerprint(self, server_and_channel):
        import jax

        from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
        from incubator_brpc_tpu.rpc import Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        _s, ch = server_and_channel
        parties = [d.id for d in jax.devices()[:3]]
        v8 = _pmean_dm(WIDTH).quantized("int8")

        ok = ch.call_method(
            "_tpu_transport", "collective_dispatch",
            self._proposal(WIDTH, v8.fingerprint(), parties, quantize="int8"),
            cntl=Controller(timeout_ms=30000),
        )
        assert ok.ok(), ok.error_text

        # the EXACT kernel's fingerprint under quantize=int8 is a
        # divergence: the party resolves the variant and must reject
        wrong = ch.call_method(
            "_tpu_transport", "collective_dispatch",
            self._proposal(
                WIDTH, _pmean_dm(WIDTH).fingerprint(), parties,
                quantize="int8",
            ),
            cntl=Controller(timeout_ms=30000),
        )
        assert wrong.failed()
        assert wrong.error_code == ErrorCode.EREQUEST
        assert "fingerprint mismatch" in wrong.error_text

        # a method with NO quantized variant: clean pre-lockstep reject
        from incubator_brpc_tpu.parallel.mc_collective import (
            _pmean_bytes_kernel,
        )
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )

        plain = DeviceMethod(_pmean_bytes_kernel, width=WIDTH)
        register_device_method("qsvc", "plain", plain)
        try:
            odd = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(
                    WIDTH, plain.fingerprint(), parties, quantize="int8",
                    service="qsvc", method="plain",
                ),
                cntl=Controller(timeout_ms=30000),
            )
            assert odd.failed()
            assert "no int8 quantized variant" in odd.error_text
        finally:
            from incubator_brpc_tpu.rpc.device_method import (
                unregister_device_method,
            )

            unregister_device_method("qsvc", "plain")

        # unknown quantize mode
        bad = ch.call_method(
            "_tpu_transport", "collective_dispatch",
            self._proposal(
                WIDTH, v8.fingerprint(), parties, quantize="fp8"
            ),
            cntl=Controller(timeout_ms=30000),
        )
        assert bad.failed()
        assert "unknown quantize mode" in bad.error_text

    def test_bad_chunk_order_rejects(self, server_and_channel):
        import jax

        from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
        from incubator_brpc_tpu.rpc import Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        _s, ch = server_and_channel
        parties = [d.id for d in jax.devices()[:2]]
        v8 = _pmean_dm(WIDTH).quantized("int8")
        run = ch.call_method(
            "_tpu_transport", "collective_dispatch",
            self._proposal(
                WIDTH, v8.fingerprint(), parties, quantize="int8",
                phase=None, chunks=2, chunk_order=[0, 0],
                operands=["", ""],
            ),
            cntl=Controller(timeout_ms=30000),
        )
        assert run.failed()
        assert run.error_code == ErrorCode.EREQUEST
        assert "chunk_order" in run.error_text


class TestTopologySchedule:
    """TASP ordering: synthetic skewed telemetry in, audited order out."""

    def test_slowest_measured_link_first(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            schedule_session_order,
        )

        prof = {
            10: {"gbps": 0.1, "rtt_us": 900.0},   # slowest
            11: {"gbps": 5.0, "rtt_us": 10.0},    # fastest
            12: {"gbps": 1.0, "rtt_us": 80.0},
        }
        order, chunk_order, note = schedule_session_order(
            [11, 12, 10], prof, chunks=6
        )
        # slowest first: pid 10 (index 2), then pid 12 (1), then pid 11
        assert order == [2, 1, 0]
        # slice j is route-LABELED to party j % 3: slices labeled to
        # the slowest party (index 2) dispatch first
        assert chunk_order == [2, 5, 1, 4, 0, 3]
        assert "link_order=[2, 1, 0]" in note
        assert "profile_gbps" in note

    def test_rtt_breaks_bandwidth_ties(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            schedule_session_order,
        )

        prof = {
            20: {"gbps": 1.0, "rtt_us": 500.0},  # slower: higher rtt
            21: {"gbps": 1.0, "rtt_us": 5.0},
        }
        order, _c, _n = schedule_session_order([21, 20], prof)
        assert order == [1, 0]

    def test_unmeasured_parties_keep_mesh_order_at_tail(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            schedule_session_order,
        )

        prof = {31: {"gbps": 0.5, "rtt_us": 100.0}}
        order, _c, _n = schedule_session_order([30, 31, 32, 33], prof)
        assert order == [1, 0, 2, 3]

    def test_no_telemetry_is_mesh_order(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            schedule_session_order,
        )

        order, chunk_order, note = schedule_session_order(
            [1, 2, 3], {}, chunks=4
        )
        assert order == [0, 1, 2]
        assert chunk_order == [0, 1, 2, 3]
        assert note == ""

    def test_propose_dispatch_orders_by_synthetic_profile(
        self, shard_map_capable
    ):
        """The acceptance check: a session proposed under skewed link
        telemetry demonstrably fans out slowest-first and front-loads
        that party's chunk slices — visible in the result's audit
        fields (the same strings the rpcz span records)."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
        from incubator_brpc_tpu.rpc import (
            Channel,
            Server,
            ServerOptions,
            device_method,
        )
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
            session_expected,
        )
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )

        register_device_method(
            "dsvc", "scale",
            DeviceMethod(
                _scale_psum_kernel, width=SESSION_WIDTH, chunkable=True
            ),
        )
        servers = []
        for i in range(2):
            s = Server(
                ServerOptions(
                    device_index=i + 1,
                    usercode_inline=True,
                    enable_collective_service=True,
                    collective_max_concurrency=0,
                )
            )
            s.add_service(
                "dsvc",
                {"scale": device_method(
                    _scale_psum_kernel, width=SESSION_WIDTH, chunkable=True
                )},
            )
            assert s.start(0)
            servers.append(s)
        try:
            chans = []
            for s in servers:
                ch = Channel()
                assert ch.init(f"127.0.0.1:{s.port}")
                chans.append(ch)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            # party 1 (second in mesh order) measures SLOWEST
            prof = {
                party_ids[0]: {"gbps": 4.0, "rtt_us": 10.0},
                party_ids[1]: {"gbps": 0.05, "rtt_us": 2000.0},
            }
            operands = [bytes(range(40)), bytes(range(80, 160))]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=None, timeout_ms=60000,
                chunks=4, double_buffer=True, link_profile=prof,
            )
            assert out["results"] == session_expected(operands, 2)
            assert out["link_order"] == [1, 0]
            # slice j's owner is j % 2: party 1 owns slices 1 and 3
            assert out["chunk_order"] == [1, 3, 0, 2]
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)


class TestLinkProfileAccessor:
    """DeviceLinkMap.link_profile(): the PR 1 recorders, structured."""

    def test_live_link_profile(self, shard_map_capable):
        from incubator_brpc_tpu.rpc import (
            Channel,
            ChannelOptions,
            Server,
            ServerOptions,
        )
        from incubator_brpc_tpu.transport import device_link as DL

        s = Server(ServerOptions(device_index=1))
        s.add_service("EchoService", {"Echo": lambda cntl, req: req})
        assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(transport="tpu", timeout_ms=60000),
            )
            for _ in range(3):
                c = ch.call_method("EchoService", "Echo", b"y" * 1500)
                assert c.ok(), c.error_text
            prof = DL.link_profile()
            assert prof, "no live link in the profile"
            peer_id = ch._device_sock.link.devices[1].id
            assert peer_id in prof
            entry = prof[peer_id]
            for key in (
                "rtt_us", "rtt_p99_us", "steps", "out_bytes_s",
                "in_bytes_s", "out_bytes", "in_bytes", "gbps", "link_id",
            ):
                assert key in entry
            assert entry["steps"] > 0
            assert entry["rtt_us"] > 0
            assert entry["out_bytes"] > 0 and entry["in_bytes"] > 0
        finally:
            s.stop()
            s.join(timeout=5)

    def test_rpc_view_links_table(self):
        """The scrape-side rendering groups per-link series into rows."""
        import sys

        sys.path.insert(0, "tools")
        from tools.rpc_view import links_table

        values = {
            'device_link_3_step_rtt_us{quantile="0.99"}': 450.0,
            "device_link_3_step_rtt_us_sum": 1000.0,
            "device_link_3_step_rtt_us_count": 10.0,
            "device_link_3_out_bytes_second": 2.0e6,
            "device_link_3_in_bytes_second": 1.0e6,
            "device_link_7_step_rtt_us_sum": 90.0,
            "device_link_7_step_rtt_us_count": 3.0,
            "unrelated_metric": 1.0,
        }
        rows = links_table(values)
        assert len(rows) == 2
        assert rows[0].startswith("device_link_3:")
        assert "rtt=100.0us" in rows[0]
        assert "p99=450.0us" in rows[0]
        assert "gbps=0.003000" in rows[0]
        assert rows[1].startswith("device_link_7:")
        assert "rtt=30.0us" in rows[1]
