"""MPEG-TS muxer (protocol/mpegts.py — reference ts.{h,cpp}): packet
alignment/sync, PSI tables with MPEG CRC, PES timestamps, continuity
counters, AVCC→Annex-B and AAC→ADTS conversion, and an FLV→TS pipe."""

from __future__ import annotations

import io
import struct

import pytest

from incubator_brpc_tpu.protocol import mpegts as ts


def _avc_seq_header(sps=b"\x67\x64\x00\x1e", pps=b"\x68\xee\x3c\x80"):
    """FLV video tag: keyframe+AVC, packet type 0, cts 0, then the
    AVCDecoderConfigurationRecord with one SPS and one PPS."""
    record = (
        b"\x01" + sps[1:4] + b"\xff"
        + bytes([0xE0 | 1]) + struct.pack(">H", len(sps)) + sps
        + bytes([1]) + struct.pack(">H", len(pps)) + pps
    )
    return b"\x17\x00\x00\x00\x00" + record


def _avc_frame(key: bool, nal: bytes, cts: int = 0):
    first = 0x17 if key else 0x27
    return bytes([first, 1]) + cts.to_bytes(3, "big") + struct.pack(
        ">I", len(nal)
    ) + nal


def _aac_seq_header(asc=b"\x12\x10"):  # AAC-LC 44.1kHz stereo
    return b"\xaf\x00" + asc


def _aac_frame(raw: bytes):
    return b"\xaf\x01" + raw


class TestPsi:
    def test_crc32_mpeg_vector(self):
        # classic check value for "123456789" under CRC-32/MPEG-2
        assert ts.crc32_mpeg(b"123456789") == 0x0376E6E7

    def test_pat_pmt_structure(self):
        pat = ts.build_pat()
        assert pat[0] == 0x00  # table id
        assert ts.crc32_mpeg(pat[:-4]) == struct.unpack(">I", pat[-4:])[0]
        # the single program maps to the PMT pid
        program, pmt = struct.unpack_from(">HH", pat, 8)
        assert program == 1 and (pmt & 0x1FFF) == ts.PID_PMT

        pmt_sec = ts.build_pmt()
        assert pmt_sec[0] == 0x02
        assert ts.crc32_mpeg(pmt_sec[:-4]) == struct.unpack(
            ">I", pmt_sec[-4:]
        )[0]
        assert bytes([ts.STREAM_TYPE_H264]) in pmt_sec
        assert bytes([ts.STREAM_TYPE_AAC]) in pmt_sec


class TestMux:
    def _mux(self, writes):
        out = io.BytesIO()
        w = ts.TsWriter(out)
        for kind, ts_ms, payload in writes:
            (w.write_video if kind == "v" else w.write_audio)(ts_ms, payload)
        return out.getvalue()

    def test_packets_aligned_and_synced(self):
        data = self._mux([
            ("v", 0, _avc_seq_header()),
            ("a", 0, _aac_seq_header()),
            ("v", 0, _avc_frame(True, b"\x65" + b"k" * 1000)),
            ("a", 23, _aac_frame(b"q" * 300)),
            ("v", 40, _avc_frame(False, b"\x41" + b"p" * 5000)),
        ])
        assert len(data) % ts.TS_PACKET == 0
        pkts = ts.demux_packets(data)
        # first two packets are PAT then PMT
        assert pkts[0][0] == ts.PID_PAT and pkts[0][1]
        assert pkts[1][0] == ts.PID_PMT and pkts[1][1]
        pids = {p for p, _, _, _ in pkts}
        assert ts.PID_VIDEO in pids and ts.PID_AUDIO in pids

    def test_continuity_counters_increment(self):
        data = self._mux([
            ("v", 0, _avc_seq_header()),
            ("v", 0, _avc_frame(True, b"\x65" + b"x" * 2000)),
            ("v", 40, _avc_frame(False, b"\x41" + b"y" * 2000)),
        ])
        ccs = [
            cc for pid, _, cc, _ in ts.demux_packets(data)
            if pid == ts.PID_VIDEO
        ]
        for a, b in zip(ccs, ccs[1:]):
            assert b == (a + 1) & 0x0F

    def test_keyframe_gets_sps_pps_annexb(self):
        sps, pps = b"\x67\x64\x00\x1e", b"\x68\xee\x3c\x80"
        data = self._mux([
            ("v", 0, _avc_seq_header(sps, pps)),
            ("v", 0, _avc_frame(True, b"\x65FRAME")),
        ])
        es = b"".join(
            body for pid, _, _, body in ts.demux_packets(data)
            if pid == ts.PID_VIDEO
        )
        assert b"\x00\x00\x00\x01" + sps in es
        assert b"\x00\x00\x00\x01" + pps in es
        assert b"\x00\x00\x00\x01\x65FRAME" in es
        assert b"\x00\x00\x00\x01\x09" in es  # access unit delimiter

    def test_pes_pts_dts_from_cts(self):
        data = self._mux([
            ("v", 0, _avc_seq_header()),
            ("v", 100, _avc_frame(True, b"\x65z", cts=40)),
        ])
        es = b"".join(
            body for pid, _, _, body in ts.demux_packets(data)
            if pid == ts.PID_VIDEO
        )
        assert es[:4] == b"\x00\x00\x01\xe0"
        flags, hlen = es[7], es[8]
        assert flags & 0xC0 == 0xC0  # PTS+DTS (cts != 0)

        def read_ts(b):
            return (
                ((b[0] >> 1) & 0x7) << 30
                | b[1] << 22 | ((b[2] >> 1) & 0x7F) << 15
                | b[3] << 7 | (b[4] >> 1) & 0x7F
            )

        pts = read_ts(es[9:14])
        dts = read_ts(es[14:19])
        assert dts == 100 * 90
        assert pts == (100 + 40) * 90

    def test_aac_adts_header(self):
        data = self._mux([
            ("a", 0, _aac_seq_header(b"\x12\x10")),
            ("a", 0, _aac_frame(b"RAWAAC")),
        ])
        es = b"".join(
            body for pid, _, _, body in ts.demux_packets(data)
            if pid == ts.PID_AUDIO
        )
        # skip the PES header to the ADTS syncword
        i = es.find(b"\xff\xf1")
        assert i >= 0
        adts = es[i : i + 7]
        frame_len = ((adts[3] & 0x3) << 11) | (adts[4] << 3) | (adts[5] >> 5)
        assert frame_len == 7 + len(b"RAWAAC")
        assert es.endswith(b"RAWAAC")

    def test_sequence_headers_emit_no_packets(self):
        out = io.BytesIO()
        w = ts.TsWriter(out)
        w.write_video(0, _avc_seq_header())
        w.write_audio(0, _aac_seq_header())
        assert out.getvalue() == b""  # PSI waits for the first real frame

    def test_demux_rejects_unaligned(self):
        with pytest.raises(ValueError):
            ts.demux_packets(b"\x47" * 100)


class TestFlvToTsPipe:
    def test_flv_tags_feed_the_ts_writer(self):
        """The same payload bytes flow FLV→TS (the rtmp→flv→hls path the
        reference serves)."""
        from incubator_brpc_tpu.protocol import flv

        fout = io.BytesIO()
        fw = flv.FlvWriter(fout)
        fw.write_video(0, _avc_seq_header())
        fw.write_audio(0, _aac_seq_header())
        fw.write_video(0, _avc_frame(True, b"\x65KEY"))
        fw.write_audio(23, _aac_frame(b"AUD"))

        tout = io.BytesIO()
        tw = ts.TsWriter(tout)
        for tag, ts_ms, payload in flv.FlvReader(fout.getvalue()):
            if tag == flv.TAG_VIDEO:
                tw.write_video(ts_ms, payload)
            elif tag == flv.TAG_AUDIO:
                tw.write_audio(ts_ms, payload)
        pkts = ts.demux_packets(tout.getvalue())
        assert {p for p, _, _, _ in pkts} >= {
            ts.PID_PAT, ts.PID_PMT, ts.PID_VIDEO, ts.PID_AUDIO
        }
