"""High fan-in: thousands of outstanding RPCs with bounded threads.

The reference parks any number of blocked RPCs on butexes without holding
workers (task_group.cpp:566-635, butex.cpp:607-690). This runtime's
documented deviation (PARITY: no M:N descheduling under the GIL) means a
*blocking* handler holds an OS thread — so the capability the reference
guarantees (huge concurrent fan-in) must come from the async surfaces and
from the pool's bounded elastic growth. These tests are the acceptance
proof for that deviation:

- async path: thousands of outstanding RPCs (async client callbacks +
  ``cntl.set_async()`` server handlers) hold ~zero extra threads;
- blocking path: when handlers DO park a worker (butex wait), the pool
  grows only to ``fiber_concurrency_max`` and the excess queues — bounded
  threads, eventual completion, no deadlock, no rejects by default
  (admission/ELIMIT is the explicit queue-or-reject knob, covered in
  test_rpc.py).
"""

from __future__ import annotations

import threading
import time

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller, Server
from incubator_brpc_tpu.runtime.butex import Butex
from incubator_brpc_tpu.runtime.timer_thread import global_timer_thread
from incubator_brpc_tpu.utils.flags import get_flag

N_ASYNC = 10000


def test_10k_outstanding_async_rpcs():
    """10000 RPCs in flight at once: server answers each 0.5 s later from a
    timer (no handler thread held), client collects async callbacks. The
    whole pileup must ride the existing threads — this is the shape the
    reference serves with parked bthreads."""
    timer = global_timer_thread()

    def slow_echo(cntl, req: bytes):
        cntl.set_async()
        timer.schedule(lambda: cntl.send_response(b"r:" + req), delay=0.5)
        return None

    server = Server()
    server.add_service("Bulk", {"Echo": slow_echo})
    assert server.start(0)
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}", options=ChannelOptions(timeout_ms=120000)
    )
    baseline_threads = threading.active_count()
    done_count = [0]
    failures = []
    all_done = threading.Event()
    lock = threading.Lock()

    def make_done(i):
        def done(cntl):
            with lock:
                if cntl.failed():
                    failures.append((i, cntl.error_code, cntl.error_text))
                elif cntl.response_payload != b"r:%06d" % i:
                    failures.append((i, -1, "payload mismatch"))
                done_count[0] += 1
                if done_count[0] == N_ASYNC:
                    all_done.set()

        return done

    try:
        t0 = time.monotonic()
        peak_threads = 0
        for i in range(N_ASYNC):
            ch.call_method(
                "Bulk", "Echo", b"%06d" % i,
                cntl=Controller(timeout_ms=120000),
                done=make_done(i),
            )
            if i % 500 == 0:
                peak_threads = max(peak_threads, threading.active_count())
        # everything is now in flight; watch the pileup drain
        while not all_done.wait(timeout=0.2):
            peak_threads = max(peak_threads, threading.active_count())
            assert time.monotonic() - t0 < 90, (
                f"only {done_count[0]}/{N_ASYNC} done"
            )
        assert not failures, f"{len(failures)} failed, first: {failures[:3]}"
        assert done_count[0] == N_ASYNC
        # bounded thread growth: the N_ASYNC-deep pileup must not have grown
        # the process by more than a handful of elastic workers
        growth = peak_threads - baseline_threads
        assert growth < 40, (
            f"thread growth {growth} (baseline {baseline_threads}, "
            f"peak {peak_threads}) — async fan-in is holding threads"
        )
    finally:
        server.stop()
        server.join(timeout=10)


def test_blocking_handlers_bounded_by_pool_cap():
    """600 concurrent RPCs into a handler that PARKS its worker on a butex
    for 150 ms (the no-M:N worst case). The pool may grow only to
    ``fiber_concurrency_max``; the rest queue and complete in waves. Total
    threads stay bounded and every call succeeds."""
    cap = int(get_flag("fiber_concurrency_max"))
    n = 600

    def parked_echo(cntl, req: bytes) -> bytes:
        b = Butex(0)
        b.wait(0, timeout=0.15)  # parks THIS worker (counts as blocked)
        return b"p:" + req

    server = Server()
    server.add_service("Parked", {"Echo": parked_echo})
    assert server.start(0)
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}", options=ChannelOptions(timeout_ms=120000)
    )
    done_count = [0]
    failures = []
    all_done = threading.Event()
    lock = threading.Lock()

    def make_done(i):
        def done(cntl):
            with lock:
                if cntl.failed():
                    failures.append((i, cntl.error_text))
                done_count[0] += 1
                if done_count[0] == n:
                    all_done.set()

        return done

    try:
        t0 = time.monotonic()
        peak_threads = 0
        for i in range(n):
            ch.call_method(
                "Parked", "Echo", b"%04d" % i,
                cntl=Controller(timeout_ms=120000),
                done=make_done(i),
            )
        while not all_done.wait(timeout=0.2):
            peak_threads = max(peak_threads, threading.active_count())
            assert time.monotonic() - t0 < 90, (
                f"only {done_count[0]}/{n} done"
            )
        assert not failures, f"{len(failures)} failed, first: {failures[:3]}"
        # the bound: elastic growth stops at the cap; queued fibers wait
        # for a worker instead of spawning threads 1:1 with the backlog
        assert peak_threads < cap + 80, (
            f"peak {peak_threads} threads vs cap {cap} — pool growth "
            f"is not bounded"
        )
    finally:
        server.stop()
        server.join(timeout=10)
