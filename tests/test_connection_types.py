"""Connection-type tests: single / pooled / short (reference
ChannelOptions.connection_type; Socket::GetPooledSocket/GetShortSocket,
test coverage shape of brpc_socket_unittest.cpp)."""

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server


@pytest.fixture
def server():
    s = Server()
    inflight = {"now": 0, "max": 0}
    lock = threading.Lock()

    def echo(cntl, req):
        with lock:
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
        time.sleep(0.02)
        with lock:
            inflight["now"] -= 1
        return req

    s.add_service("ct", {"echo": echo})
    assert s.start(0)
    yield s
    s.stop()
    s.join(timeout=5)


def _wait_conns(server, want, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if server.connection_count() == want:
            return True
        time.sleep(0.02)
    return server.connection_count() == want


class TestConnectionTypes:
    def test_single_shares_one_connection(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="single"),
        )
        for _ in range(5):
            assert ch.call_method("ct", "echo", b"x").ok()
        assert server.connection_count() == 1

    def test_short_closes_after_each_call(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="short"),
        )
        for _ in range(3):
            assert ch.call_method("ct", "echo", b"x").ok()
            assert _wait_conns(server, 0)  # connection gone after the call

    def test_pooled_reuses_sequentially(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="pooled"),
        )
        for _ in range(5):
            assert ch.call_method("ct", "echo", b"x").ok()
        # sequential calls reuse ONE pooled connection
        assert server.connection_count() == 1

    def test_lb_target_accepts_non_single(self, server):
        # pooled/short work with naming+LB (secondaries hang off each
        # endpoint's map entry); transport='tpu' resolves LB picks through
        # the DeviceLinkMap (one link per peer — the N-party fabric)
        ch = Channel()
        assert ch.init(
            f"list://127.0.0.1:{server.port}",
            "rr",
            options=ChannelOptions(connection_type="short"),
        )
        assert ch.call_method("ct", "echo", b"via-short-lb").ok()
        ch2 = Channel()
        assert ch2.init(
            f"list://127.0.0.1:{server.port}",
            "rr",
            options=ChannelOptions(transport="tpu", timeout_ms=60000),
        )
        assert ch2.call_method("ct", "echo", b"via-tpu-lb").ok()

    def test_backup_request_keeps_original_connection(self):
        """A backup attempt must NOT settle the original attempt's
        connection mid-call (the original response may still win)."""
        s = Server()

        def slow_echo(cntl, req):
            time.sleep(0.3)
            return b"original"

        s.add_service("ct", {"echo": slow_echo})
        assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(
                    connection_type="short",
                    timeout_ms=5000,
                    backup_request_ms=50,
                    max_retry=1,
                ),
            )
            cntl = ch.call_method("ct", "echo", b"x")
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"original"
            assert _wait_conns(s, 0)  # both attempts' connections settled
        finally:
            s.stop()
            s.join(timeout=5)

    def test_pooled_concurrent_calls_use_distinct_connections(self):
        # barrier-gated handler: all n calls are PROVABLY in flight at
        # once, so exactly n distinct pooled connections must exist
        n = 4
        barrier = threading.Barrier(n)
        s = Server()

        def gated_echo(cntl, req):
            barrier.wait(timeout=10)
            return req

        s.add_service("ct", {"echo": gated_echo})
        assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(connection_type="pooled", timeout_ms=10000),
            )
            errs = []

            def worker():
                c = ch.call_method("ct", "echo", b"y")
                if c.failed():
                    errs.append(c.error_text)

            ts = [threading.Thread(target=worker) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            # each in-flight call held its own connection; all parked now
            assert s.connection_count() == n
            # and they are reused, not re-dialed, by the next wave
            ts = [threading.Thread(target=worker) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            assert s.connection_count() == n
        finally:
            s.stop()
            s.join(timeout=5)


class TestConnectionTypesWithNaming:
    """Pooled/short for LB targets: secondaries hang off each endpoint's
    map entry (reference SharedPart design, socket_map.h:35)."""

    @pytest.fixture
    def two_servers(self):
        import tempfile

        servers = []
        for _ in range(2):
            s = Server()
            s.add_service("ct", {"echo": lambda cntl, req: req,
                                 "who": lambda cntl, req: str(s.port).encode()})
            assert s.start(0)
            servers.append(s)
        with tempfile.NamedTemporaryFile("w", suffix=".lst", delete=False) as f:
            for s in servers:
                f.write(f"127.0.0.1:{s.port}\n")
            path = f.name
        yield servers, path
        for s in servers:
            s.stop()
            s.join(timeout=5)

    @pytest.mark.parametrize("ctype", ["pooled", "short"])
    def test_lb_target_with_secondary_connections(self, two_servers, ctype):
        servers, path = two_servers
        ch = Channel()
        assert ch.init(
            f"file://{path}", "rr",
            options=ChannelOptions(connection_type=ctype, timeout_ms=5000),
        )
        seen = set()
        for i in range(8):
            cntl = ch.call_method("ct", "echo", f"m{i}".encode())
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == f"m{i}".encode()
            seen.add((cntl.remote_side.ip, cntl.remote_side.port))
        # rr across both servers, through secondary connections
        assert len(seen) == 2

    def test_pooled_lb_parks_per_endpoint(self, two_servers):
        servers, path = two_servers
        ch = Channel()
        assert ch.init(
            f"file://{path}", "rr",
            options=ChannelOptions(connection_type="pooled", timeout_ms=5000),
        )
        for i in range(4):
            assert ch.call_method("ct", "echo", b"x").ok()
        # idle pooled connections parked under BOTH endpoints' keys
        pooled_keys = {
            k for k, v in ch._socket_map._pooled.items() if v
        }
        ports = {int(k.split("|")[0].rsplit(":", 1)[1]) for k in pooled_keys}
        # superset: the shared client socket map may hold other tests' pools
        assert ports >= {s.port for s in servers}
