"""Connection-type tests: single / pooled / short (reference
ChannelOptions.connection_type; Socket::GetPooledSocket/GetShortSocket,
test coverage shape of brpc_socket_unittest.cpp)."""

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server


@pytest.fixture
def server():
    s = Server()
    inflight = {"now": 0, "max": 0}
    lock = threading.Lock()

    def echo(cntl, req):
        with lock:
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
        time.sleep(0.02)
        with lock:
            inflight["now"] -= 1
        return req

    s.add_service("ct", {"echo": echo})
    assert s.start(0)
    yield s
    s.stop()
    s.join(timeout=5)


def _wait_conns(server, want, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if server.connection_count() == want:
            return True
        time.sleep(0.02)
    return server.connection_count() == want


class TestConnectionTypes:
    def test_single_shares_one_connection(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="single"),
        )
        for _ in range(5):
            assert ch.call_method("ct", "echo", b"x").ok()
        assert server.connection_count() == 1

    def test_short_closes_after_each_call(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="short"),
        )
        for _ in range(3):
            assert ch.call_method("ct", "echo", b"x").ok()
            assert _wait_conns(server, 0)  # connection gone after the call

    def test_pooled_reuses_sequentially(self, server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="pooled"),
        )
        for _ in range(5):
            assert ch.call_method("ct", "echo", b"x").ok()
        # sequential calls reuse ONE pooled connection
        assert server.connection_count() == 1

    def test_lb_target_rejects_non_single(self, server):
        ch = Channel()
        with pytest.raises(ValueError):
            ch.init(
                f"list://127.0.0.1:{server.port}",
                "rr",
                options=ChannelOptions(connection_type="short"),
            )

    def test_backup_request_keeps_original_connection(self):
        """A backup attempt must NOT settle the original attempt's
        connection mid-call (the original response may still win)."""
        s = Server()

        def slow_echo(cntl, req):
            time.sleep(0.3)
            return b"original"

        s.add_service("ct", {"echo": slow_echo})
        assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(
                    connection_type="short",
                    timeout_ms=5000,
                    backup_request_ms=50,
                    max_retry=1,
                ),
            )
            cntl = ch.call_method("ct", "echo", b"x")
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"original"
            assert _wait_conns(s, 0)  # both attempts' connections settled
        finally:
            s.stop()
            s.join(timeout=5)

    def test_pooled_concurrent_calls_use_distinct_connections(self):
        # barrier-gated handler: all n calls are PROVABLY in flight at
        # once, so exactly n distinct pooled connections must exist
        n = 4
        barrier = threading.Barrier(n)
        s = Server()

        def gated_echo(cntl, req):
            barrier.wait(timeout=10)
            return req

        s.add_service("ct", {"echo": gated_echo})
        assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(connection_type="pooled", timeout_ms=10000),
            )
            errs = []

            def worker():
                c = ch.call_method("ct", "echo", b"y")
                if c.failed():
                    errs.append(c.error_text)

            ts = [threading.Thread(target=worker) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            # each in-flight call held its own connection; all parked now
            assert s.connection_count() == n
            # and they are reused, not re-dialed, by the next wave
            ts = [threading.Thread(target=worker) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            assert s.connection_count() == n
        finally:
            s.stop()
            s.join(timeout=5)
