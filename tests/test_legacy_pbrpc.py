"""The legacy Baidu protocol family (protocol/legacy_pbrpc.py — reference
policy/hulu_pbrpc_protocol.cpp, sofa_pbrpc_protocol.cpp,
nova_pbrpc_protocol.cpp, public_pbrpc_protocol.cpp, ubrpc2pb_protocol.cpp,
esp_protocol.cpp): wire fixtures, loopback round trips on the shared port,
error propagation, and the FIFO client correlation for the nshead family.
"""

from __future__ import annotations

import struct

import pytest

from incubator_brpc_tpu.protocol import legacy_pbrpc as lp
from incubator_brpc_tpu.protocol import mcpack
from incubator_brpc_tpu.protocol.tbus_std import Meta, ParseError
from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions


@pytest.fixture
def echo_server():
    srv = Server(ServerOptions(usercode_inline=True))

    def echo(cntl, req):
        return req

    def boom(cntl, req):
        cntl.set_failed(1007, "deliberate failure")
        return b""

    srv.add_service("svc", {"echo": echo, "boom": boom})
    assert srv.start(0)
    yield srv
    srv.stop()


def _call(port, protocol, service="svc", method="echo", payload=b"x",
          extra=None, timeout=5000):
    from incubator_brpc_tpu.rpc import Controller

    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{port}",
        options=ChannelOptions(protocol=protocol, timeout_ms=timeout),
    )
    cntl = Controller(timeout_ms=timeout)
    if extra:
        cntl.request_extra = dict(extra)
    return ch.call_method(service, method, payload, cntl=cntl)


class TestHuluWire:
    def test_header_fixture(self):
        # "HULU" + u32le(body=meta+payload) + u32le(meta) — host (LE) order
        wire = lp._hulu_frame(b"M" * 10, b"P" * 3)
        assert wire[:4] == b"HULU"
        assert struct.unpack_from("<II", wire, 4) == (13, 10)

    def test_request_roundtrip(self):
        meta = Meta(service="svc", method="echo", log_id=77,
                    extra={"method_index": 1})
        wire = lp.hulu_pack_request(meta, b"hello", 42, attachment=b"att")
        frame, consumed = lp.hulu_try_parse(wire)
        assert consumed == len(wire)
        assert not frame.is_response
        assert frame.meta.service == "svc"
        assert frame.meta.method == "echo"
        assert frame.meta.extra["method_index"] == 1
        assert frame.meta.log_id == 77
        assert frame.correlation_id == 42
        assert frame.payload == b"hello" and frame.attachment == b"att"

    def test_response_roundtrip_sint64_cid(self):
        # response correlation_id is sint64 (zigzag) on the wire
        wire = lp.hulu_pack_response(None, b"out", 99, error_code=0)
        frame, _ = lp.hulu_try_parse(wire)
        assert frame.is_response and frame.correlation_id == 99
        assert frame.payload == b"out" and frame.error_code == 0
        wire = lp.hulu_pack_response(
            Meta(error_text="nope"), b"", 7, error_code=1007
        )
        frame, _ = lp.hulu_try_parse(wire)
        assert frame.error_code == 1007
        assert frame.meta.error_text == "nope"

    def test_meta_size_overflow_rejected(self):
        bad = b"HULU" + struct.pack("<II", 4, 9) + b"xxxx"
        with pytest.raises(ParseError):
            lp.hulu_try_parse(bad)


class TestSofaWire:
    def test_header_fixture(self):
        # "SOFA" + u32le(meta) + u64le(body) + u64le(meta+body)
        wire = lp._sofa_frame(b"M" * 6, b"P" * 4)
        assert wire[:4] == b"SOFA"
        assert struct.unpack_from("<IQQ", wire, 4) == (6, 4, 10)

    def test_request_roundtrip(self):
        meta = Meta(service="pkg.EchoService", method="echo")
        wire = lp.sofa_pack_request(meta, b"ping", 5)
        frame, consumed = lp.sofa_try_parse(wire)
        assert consumed == len(wire)
        assert not frame.is_response
        assert frame.meta.service == "pkg.EchoService"
        assert frame.meta.method == "echo"
        assert frame.correlation_id == 5

    def test_response_failed(self):
        wire = lp.sofa_pack_response(
            Meta(error_text="broken"), b"", 8, error_code=2004
        )
        frame, _ = lp.sofa_try_parse(wire)
        assert frame.is_response and frame.correlation_id == 8
        assert frame.error_code == 2004
        assert frame.meta.error_text == "broken"

    def test_inconsistent_sizes_rejected(self):
        bad = b"SOFA" + struct.pack("<IQQ", 2, 2, 99) + b"abcd"
        with pytest.raises(ParseError):
            lp.sofa_try_parse(bad)


class TestHuluSofaLoopback:
    def test_hulu_end_to_end(self, echo_server):
        cntl = _call(echo_server.port, "hulu_pbrpc", payload=b"via-hulu")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"via-hulu"

    def test_hulu_by_method_index(self, echo_server):
        # no method name on the wire: index 1 = second registered = boom
        cntl = _call(echo_server.port, "hulu_pbrpc", method="",
                     extra={"method_index": 1})
        assert not cntl.ok() and cntl.error_code == 1007

    def test_hulu_error_propagates(self, echo_server):
        cntl = _call(echo_server.port, "hulu_pbrpc", method="boom")
        assert not cntl.ok()
        assert cntl.error_code == 1007
        assert "deliberate" in cntl.error_text

    def test_sofa_end_to_end(self, echo_server):
        cntl = _call(echo_server.port, "sofa_pbrpc", payload=b"via-sofa")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"via-sofa"

    def test_sofa_error_propagates(self, echo_server):
        cntl = _call(echo_server.port, "sofa_pbrpc", method="boom")
        assert not cntl.ok() and cntl.error_code == 1007

    def test_three_protocols_share_the_port(self, echo_server):
        # tbus_std, hulu and sofa multiplex on one listener
        for proto in ("tbus_std", "hulu_pbrpc", "sofa_pbrpc"):
            cntl = _call(echo_server.port, proto, payload=proto.encode())
            assert cntl.ok(), f"{proto}: {cntl.error_text}"
            assert cntl.response_payload == proto.encode()


class TestNovaLoopback:
    @pytest.fixture
    def nova_server(self):
        srv = Server(
            ServerOptions(
                usercode_inline=True,
                nshead_service=lp.NovaServiceAdaptor,
            )
        )
        srv.add_service(
            "svc",
            {"echo": lambda cntl, req: req,
             "rev": lambda cntl, req: req[::-1]},
        )
        assert srv.start(0)
        yield srv
        srv.stop()

    def test_nova_by_index(self, nova_server):
        cntl = _call(nova_server.port, "nova_pbrpc", payload=b"abc",
                     extra={"method_index": 1})
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"cba"

    def test_nova_default_index(self, nova_server):
        cntl = _call(nova_server.port, "nova_pbrpc", payload=b"abc")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"abc"


class TestPublicPbrpcLoopback:
    @pytest.fixture
    def pub_server(self):
        def boom(cntl, req):
            cntl.set_failed(1008, "public failure")
            return b""

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                nshead_service=lp.PublicPbrpcServiceAdaptor,
            )
        )
        srv.add_service(
            "svc", {"echo": lambda cntl, req: req, "boom": boom}
        )
        assert srv.start(0)
        yield srv
        srv.stop()

    def test_public_end_to_end(self, pub_server):
        cntl = _call(pub_server.port, "public_pbrpc", payload=b"wrapped")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"wrapped"

    def test_public_error_propagates(self, pub_server):
        cntl = _call(pub_server.port, "public_pbrpc", method="boom",
                     extra={"method_index": 1})
        assert not cntl.ok() and cntl.error_code == 1008
        assert "public failure" in cntl.error_text


class TestUbrpcLoopback:
    @pytest.fixture
    def ub_server(self):
        def add(cntl, req):
            params = mcpack.loads(req)
            return mcpack.dumps({"sum": params["a"] + params["b"]})

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                nshead_service=lp.UbrpcServiceAdaptor,
            )
        )
        srv.add_service("calc", {"add": add})
        assert srv.start(0)
        yield srv
        srv.stop()

    def test_ubrpc_end_to_end(self, ub_server):
        payload = mcpack.dumps({"a": 3, "b": 4})
        cntl = _call(ub_server.port, "ubrpc_mcpack2", service="calc",
                     method="add", payload=payload)
        assert cntl.ok(), cntl.error_text
        assert mcpack.loads(cntl.response_payload) == {"sum": 7}

    def test_ubrpc_unknown_method(self, ub_server):
        payload = mcpack.dumps({"a": 1, "b": 2})
        cntl = _call(ub_server.port, "ubrpc_mcpack2", service="calc",
                     method="mul", payload=payload)
        assert not cntl.ok()


class TestEsp:
    def test_head_fixture(self):
        wire = lp.esp_pack_request(
            Meta(extra={"to_stub": 2, "to_port": 8000, "to_ip": 0x7F000001,
                        "esp_msg": 9}),
            b"BODY", 1234,
        )
        assert len(wire) == lp.ESP_HEADER + 4
        vals = lp._ESP_HEAD.unpack_from(wire)
        assert vals[3:6] == (2, 8000, 0x7F000001)  # to
        assert vals[6] == 9 and vals[7] == 1234 and vals[8] == 4

    def test_parse_roundtrip(self):
        wire = lp.esp_pack_request(Meta(extra={"esp_msg": 5}), b"pp", 7)
        frame, consumed = lp.esp_try_parse(wire)
        assert consumed == len(wire)
        assert frame.head["msg"] == 5 and frame.head["msg_id"] == 7
        assert frame.payload == b"pp"

    def test_esp_against_mock_server(self, echo_server):
        # the reference has no esp server: drive the client against a raw
        # echo-the-esp-frame socket, the same shape its unittest uses
        import socket as pysock
        import threading

        lsock = pysock.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(1)
        port = lsock.getsockname()[1]

        def serve():
            conn, _ = lsock.accept()
            data = b""
            while len(data) < lp.ESP_HEADER:
                data += conn.recv(4096)
            body_len = struct.unpack_from("<i", data, lp.ESP_HEADER - 4)[0]
            while len(data) < lp.ESP_HEADER + body_len:
                data += conn.recv(4096)
            conn.sendall(data)  # echo the whole esp frame back
            conn.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        try:
            cntl = _call(port, "esp", payload=b"esp-body",
                         extra={"esp_msg": 3})
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"esp-body"
            assert cntl.response_meta.extra["esp_head"]["msg"] == 3
        finally:
            lsock.close()


class TestHuluEdgeCases:
    def test_empty_payload_with_attachment(self):
        # user_message_size=0 must be representable (present-with-zero):
        # an empty message whose body is ALL attachment
        wire = lp.hulu_pack_request(
            Meta(service="svc", method="echo"), b"", 3, attachment=b"ATT"
        )
        frame, _ = lp.hulu_try_parse(wire)
        assert frame.payload == b"" and frame.attachment == b"ATT"
        wire = lp.hulu_pack_response(None, b"", 3, attachment=b"RSP")
        frame, _ = lp.hulu_try_parse(wire)
        assert frame.is_response
        assert frame.payload == b"" and frame.attachment == b"RSP"

    def test_service_required(self):
        with pytest.raises(ValueError):
            lp.hulu_pack_request(Meta(service="", method="m"), b"x", 1)

    def test_method_name_only_still_a_request(self):
        # classification keys on service_name OR method_name presence
        mb = lp._hulu_request_meta(
            Meta(service="", method="echo"), 5, 0, None
        )
        frame, _ = lp.hulu_try_parse(lp._hulu_frame(mb, b"p"))
        assert not frame.is_response and frame.meta.method == "echo"


class TestFifoSocketPartition:
    def test_mixed_fifo_channels_get_separate_sockets(self, echo_server):
        # two fifo protocols to ONE endpoint must not share a socket: the
        # response framing would be undecodable (esp has no magic)
        from incubator_brpc_tpu.rpc.channel import _client_socket_map

        port = echo_server.port
        for proto in ("nova_pbrpc", "esp"):
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{port}",
                options=ChannelOptions(protocol=proto, timeout_ms=500),
            )
            # calls fail (the tbus server speaks neither) — the sockets
            # are what we are probing
            _ = ch.call_method("svc", "echo", b"x")
        keys = [
            k for k in _client_socket_map._map
            if k.endswith("fifo-nova_pbrpc") or k.endswith("fifo-esp")
        ]
        assert len({k.rsplit("|", 1)[1] for k in keys}) == 2, keys


class TestNovaSnappy:
    def test_compressed_request_decompressed_by_adaptor(self):
        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.rpc import Controller

        if not compress_mod.has_codec("snappy"):
            pytest.skip("snappy codec not present in this environment")

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                nshead_service=lp.NovaServiceAdaptor,
            )
        )
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(protocol="nova_pbrpc",
                                       timeout_ms=5000),
            )
            cntl = Controller(timeout_ms=5000)
            cntl.compress_type = "snappy"
            out = ch.call_method("svc", "echo", b"N" * 2048, cntl=cntl)
            assert out.ok(), out.error_text
            # adaptor decompressed: the echo returns the ORIGINAL bytes
            assert out.response_payload == b"N" * 2048
        finally:
            srv.stop()


class TestMultiProtocolStress:
    def test_four_protocols_hammer_one_port(self, echo_server):
        """The per-connection protocol scan under concurrency: tbus_std,
        baidu_std, hulu and sofa clients all hit ONE listener at once;
        every reply must come back on the right connection with the right
        payload (the reference's shared-port contract, global.cpp scan)."""
        import threading

        port = echo_server.port
        errs = []

        def hammer(proto, tid):
            try:
                ch = Channel()
                assert ch.init(
                    f"127.0.0.1:{port}",
                    options=ChannelOptions(protocol=proto, timeout_ms=15000),
                )
                for i in range(25):
                    want = f"{proto}:{tid}:{i}".encode()
                    c = ch.call_method("svc", "echo", want)
                    if not c.ok() or c.response_payload != want:
                        errs.append((proto, tid, i, c.error_text))
                        return
            except Exception as e:  # noqa: BLE001 — recorded for the assert
                errs.append((proto, tid, repr(e)))

        protos = ["tbus_std", "baidu_std", "hulu_pbrpc", "sofa_pbrpc"]
        threads = [
            threading.Thread(target=hammer, args=(p, t))
            for p in protos for t in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:5]


class TestLegacyPooledConnections:
    def test_nova_over_pooled_connections(self):
        """CONNECTION_TYPE_POOLED_AND_SHORT is the reference contract for
        the nshead family: exclusive connection per in-flight call."""
        import threading

        srv = Server(
            ServerOptions(
                usercode_inline=True, nshead_service=lp.NovaServiceAdaptor
            )
        )
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(
                    protocol="nova_pbrpc",
                    connection_type="pooled",
                    timeout_ms=15000,
                ),
            )
            errs = []

            def worker(tid):
                for i in range(10):
                    want = b"%d:%d" % (tid, i)
                    c = ch.call_method("svc", "echo", want)
                    if not c.ok() or c.response_payload != want:
                        errs.append((tid, i, c.error_text))

            threads = [
                threading.Thread(target=worker, args=(t,)) for t in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs[:5]
        finally:
            srv.stop()
