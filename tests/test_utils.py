"""utils tests: EndPoint parsing, Status, flags."""

import pytest

from incubator_brpc_tpu.utils import EndPoint, str2endpoint, Status, ErrorCode
from incubator_brpc_tpu.utils.flags import FlagRegistry


def test_endpoint_parse_v4():
    ep = str2endpoint("127.0.0.1:8787")
    assert (ep.ip, ep.port) == ("127.0.0.1", 8787)
    assert not ep.is_device()


def test_endpoint_parse_v6_literal():
    ep = str2endpoint("[::1]:80")
    assert ep.ip == "::1" and ep.port == 80


def test_endpoint_parse_device():
    ep = str2endpoint("tpu://10.0.0.1:9000/d2.3")
    assert ep.is_device() and ep.device == (2, 3)
    assert "tpu://" in str(ep)


def test_endpoint_unresolvable_raises_valueerror():
    with pytest.raises(ValueError):
        str2endpoint("no-such-host-xyz.invalid:1")


def test_status_and_berror():
    s = Status.OK()
    assert s.ok() and bool(s)
    f = Status(ErrorCode.ERPCTIMEDOUT)
    assert not f.ok()
    assert "timed out" in f.error_str().lower()


def test_flags_validator_gate():
    reg = FlagRegistry()
    reg.define("x", 5, "test", validator=lambda v: v > 0)
    reg.define("y", "a", "no validator")
    assert reg.get("x") == 5
    assert reg.set("x", 7) and reg.get("x") == 7
    assert not reg.set("x", -1) and reg.get("x") == 7
    reg.set_unchecked("y", "b")
    assert reg.get("y") == "b"


def test_flags_non_reloadable_rejected():
    # Runtime set() of a validator-less (non-reloadable) flag is rejected,
    # matching reference reloadable_flags gating (src/brpc/reloadable_flags.h).
    reg = FlagRegistry()
    reg.define("z", 1, "non-reloadable")
    assert not reg.set("z", 2)
    assert reg.get("z") == 1
    reg.set_unchecked("z", 3)  # internal writes stay possible
    assert reg.get("z") == 3


def test_errno_transport_block_mirrors_reference():
    # 3001/3002 are the transport slot (reference ERDMA/ERDMACM); framework-
    # only codes live at 4001+.
    assert ErrorCode.ETRANSPORT == 3001
    assert ErrorCode.ETRANSPORTCM == 3002
    assert ErrorCode.ECLOSE == 2005
    assert ErrorCode.ETERMINATED == 4001
