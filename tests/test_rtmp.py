"""RTMP (protocol/rtmp.py + protocol/amf0.py — reference rtmp.cpp +
policy/rtmp_protocol.cpp): AMF0 fixtures, chunk-stream framing (header
compression, size negotiation, interleaving), handshake, and the
publish→relay→play pipeline through a real server.
"""

from __future__ import annotations

import struct
import threading
import time

import pytest

from incubator_brpc_tpu.protocol import amf0, rtmp
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.rpc import Channel, Server, ServerOptions


class TestAmf0:
    def test_fixture_bytes(self):
        # spec-worked bytes: number 5.0, string "foo", object {k:"v"}
        assert amf0.encode_value(5.0) == b"\x00" + struct.pack(">d", 5.0)
        assert amf0.encode_value("foo") == b"\x02\x00\x03foo"
        assert (
            amf0.encode_value({"k": "v"})
            == b"\x03\x00\x01k\x02\x00\x01v\x00\x00\x09"
        )

    def test_roundtrip(self):
        values = [
            "connect",
            1.0,
            {"app": "live", "nested": {"a": 1.0}, "arr": [1.0, "x", None]},
            None,
            True,
            amf0.Undefined,
        ]
        data = amf0.encode_all(*values)
        assert amf0.decode_all(data) == values

    def test_long_string(self):
        s = "y" * 70000
        data = amf0.encode_value(s)
        assert data[0] == amf0.LONG_STRING
        v, off = amf0.decode_value(memoryview(data), 0)
        assert v == s and off == len(data)

    def test_ecma_array_decodes_as_dict(self):
        # ECMA array: marker 0x08, count, then key/value pairs + end marker
        body = (
            b"\x08\x00\x00\x00\x01"
            + b"\x00\x01n" + b"\x00" + struct.pack(">d", 7.0)
            + b"\x00\x00\x09"
        )
        v, _ = amf0.decode_value(memoryview(body), 0)
        assert v == {"n": 7.0}

    def test_truncations_raise(self):
        data = amf0.encode_all("hello", {"k": 1.0})
        for cut in (1, 3, len(data) - 1):
            with pytest.raises(ParseError):
                amf0.decode_all(data[:cut])


class TestChunkLayer:
    def test_roundtrip_single(self):
        payload = b"m" * 300
        wire = rtmp.chunk_message(3, rtmp.MSG_VIDEO, 5, 1234, payload, 128)
        reader = rtmp.ChunkReader()
        msgs, consumed = reader.feed(wire)
        assert consumed == len(wire)
        assert len(msgs) == 1
        m = msgs[0]
        assert (m.type_id, m.msg_stream_id, m.timestamp) == (rtmp.MSG_VIDEO, 5, 1234)
        assert m.payload == payload

    def test_incremental_feed(self):
        payload = bytes(range(256)) * 4
        wire = rtmp.chunk_message(9, rtmp.MSG_AUDIO, 2, 77, payload, 128)
        reader = rtmp.ChunkReader()
        got = []
        off = 0
        for i in range(0, len(wire), 7):  # drip-feed 7 bytes at a time
            chunk = wire[off : i + 7]
            msgs, used = reader.feed(chunk)
            off += used
            got.extend(msgs)
        msgs, used = reader.feed(wire[off:])
        got.extend(msgs)
        assert len(got) == 1 and got[0].payload == payload

    def test_extended_timestamp(self):
        payload = b"x" * 200
        ts = 0x1234567
        wire = rtmp.chunk_message(4, rtmp.MSG_VIDEO, 1, ts, payload, 128)
        msgs, _ = rtmp.ChunkReader().feed(wire)
        assert msgs[0].timestamp == ts and msgs[0].payload == payload

    def test_large_csid_encoding(self):
        for csid in (63, 64, 319, 320, 1000):
            wire = rtmp.chunk_message(csid, rtmp.MSG_AUDIO, 1, 0, b"pp", 128)
            msgs, consumed = rtmp.ChunkReader().feed(wire)
            assert consumed == len(wire) and msgs[0].payload == b"pp"

    def test_interleaved_chunk_streams(self):
        # two messages chunked at 64B interleave their chunks on the wire:
        # the reader keeps per-csid assembly state
        a = rtmp.chunk_message(3, rtmp.MSG_AUDIO, 1, 10, b"A" * 150, 64)
        b = rtmp.chunk_message(4, rtmp.MSG_VIDEO, 1, 20, b"B" * 150, 64)

        def split(wire, csid):
            # re-split one message's wire into its chunks (fmt0 first)
            reader_chunks = []
            off = 0
            first = True
            while off < len(wire):
                hdr = 12 if first else 1
                take = hdr + min(64, len(wire) - off - hdr)
                reader_chunks.append(wire[off : off + take])
                off += take
                first = False
            return reader_chunks

        ca, cb = split(a, 3), split(b, 4)
        wire = b"".join(x for pair in zip(ca, cb) for x in pair)
        reader = rtmp.ChunkReader()
        reader.chunk_size = 64  # negotiated: matches the writer above
        msgs, consumed = reader.feed(wire)
        assert consumed == len(wire)
        payloads = {m.payload[:1]: m.payload for m in msgs}
        assert payloads == {b"A": b"A" * 150, b"B": b"B" * 150}

    def test_delta_headers_idempotent_across_short_reads(self):
        # fmt1 delta header whose payload straddles a read boundary: the
        # re-parse after the short read must NOT re-apply the delta
        reader = rtmp.ChunkReader()
        first = rtmp.chunk_message(3, rtmp.MSG_AUDIO, 1, 1000, b"a" * 10, 128)
        msgs, used = reader.feed(first)
        assert used == len(first) and msgs[0].timestamp == 1000
        # hand-build a fmt1 continuation: +40 ms delta, 10-byte payload
        hdr = bytes([0x43]) + b"\x00\x00\x28" + b"\x00\x00\x0a" + bytes(
            [rtmp.MSG_AUDIO]
        )
        wire = hdr + b"b" * 10
        # drip: header only (payload short) → retry with the full chunk
        msgs, used = reader.feed(wire[: len(hdr) + 3])
        assert msgs == [] and used == 0
        msgs, used = reader.feed(wire)
        assert used == len(wire)
        assert msgs[0].timestamp == 1040  # 1000 + 40, applied exactly once

    def test_compressed_header_without_fmt0_rejected(self):
        # a 0xC3 flood (fmt3 on a virgin csid) must be a parse error, not
        # an amplification of zero-length fabricated messages
        with pytest.raises(ParseError):
            rtmp.ChunkReader().feed(b"\xc3" * 16)

    def test_timestamp_wraps_mod_2_32(self):
        # a >49.7-day stream wraps its 32-bit clock; accumulation must
        # wrap too or the relay-side packer dies on struct.pack('>I')
        reader = rtmp.ChunkReader()
        first = rtmp.chunk_message(3, rtmp.MSG_AUDIO, 1, 0xFFFFFFF0, b"x", 128)
        msgs, _ = reader.feed(first)
        assert msgs[0].timestamp == 0xFFFFFFF0
        hdr = bytes([0x43]) + b"\x00\x00\x20" + b"\x00\x00\x01" + bytes(
            [rtmp.MSG_AUDIO]
        )
        msgs, used = reader.feed(hdr + b"y")  # +0x20 past the wrap
        assert used == len(hdr) + 1
        assert msgs[0].timestamp == 0x10
        # and the packer accepts the wrapped value end-to-end
        rtmp.chunk_message(3, rtmp.MSG_AUDIO, 1, msgs[0].timestamp, b"y", 128)

    def test_assembly_memory_bounded(self):
        # partial assembly across many chunk streams must hit a hard cap,
        # not pin unbounded RAM
        reader = rtmp.ChunkReader()
        reader.chunk_size = 1 << 20
        reader.max_message = 4 * (1 << 20)
        wire = bytearray()
        for i in range(6):  # 6 x 1 MiB partials of declared-4MiB messages
            csid = 3 + i
            wire += rtmp.chunk_message(
                csid, rtmp.MSG_VIDEO, 1, 0, b"z" * (4 << 20), 1 << 20
            )[: 12 + (1 << 20)]  # fmt0 header + first chunk only
        with pytest.raises(ParseError):
            reader.feed(bytes(wire))

    def test_too_many_chunk_streams_rejected(self):
        reader = rtmp.ChunkReader()
        wire = bytearray()
        for i in range(rtmp.ChunkReader.MAX_STREAMS + 1):
            wire += rtmp.chunk_message(64 + i, rtmp.MSG_AUDIO, 1, 0, b"a", 128)
        with pytest.raises(ParseError):
            reader.feed(bytes(wire))

    def test_set_chunk_size_respected(self):
        reader = rtmp.ChunkReader()
        reader.chunk_size = 4096
        payload = b"z" * 3000
        wire = rtmp.chunk_message(5, rtmp.MSG_VIDEO, 1, 0, payload, 4096)
        msgs, consumed = reader.feed(wire)
        assert consumed == len(wire) and msgs[0].payload == payload


class _Service(rtmp.RtmpService):
    def __init__(self):
        self.events = []
        self.audio_frames = []

    def on_connect(self, conn, info):
        self.events.append(("connect", info.get("app")))
        return info.get("app") != "forbidden"

    def on_publish(self, stream):
        self.events.append(("publish", stream.name))
        return True

    def on_play(self, stream):
        self.events.append(("play", stream.name))
        return True

    def on_audio(self, stream, ts, payload):
        self.audio_frames.append((ts, payload))


@pytest.fixture
def rtmp_server():
    service = _Service()
    srv = Server(ServerOptions(usercode_inline=True, rtmp_service=service))
    srv.add_service("svc", {"echo": lambda cntl, req: req})
    assert srv.start(0)
    yield srv, service
    srv.stop()


class TestEndToEnd:
    def test_connect_and_create_stream(self, rtmp_server):
        srv, service = rtmp_server
        client = rtmp.RtmpClient("127.0.0.1", srv.port, app="live")
        stream = client.create_stream()
        assert stream.msid >= 1
        assert ("connect", "live") in service.events
        client.close()

    def test_connect_rejected(self, rtmp_server):
        srv, _ = rtmp_server
        with pytest.raises((TimeoutError, ConnectionError)):
            rtmp.RtmpClient("127.0.0.1", srv.port, app="forbidden", timeout=2)

    def test_publish_play_relay(self, rtmp_server):
        srv, service = rtmp_server
        pub = rtmp.RtmpClient("127.0.0.1", srv.port)
        pub_stream = pub.create_stream()
        assert pub_stream.publish("room1")

        received = []
        got_enough = threading.Event()

        def on_media(msg):
            received.append((msg.type_id, msg.timestamp, msg.payload))
            if len(received) >= 4:
                got_enough.set()

        sub = rtmp.RtmpClient("127.0.0.1", srv.port)
        sub_stream = sub.create_stream()
        assert sub_stream.play("room1", on_media=on_media)

        pub_stream.send_metadata({"width": 640.0, "height": 480.0})
        pub_stream.send_audio(100, b"\xaf\x01AUDIO")
        pub_stream.send_video(110, b"\x17\x01VIDEO")
        pub_stream.send_audio(120, b"\xaf\x01MORE")
        assert got_enough.wait(5), f"only got {received}"

        kinds = [k for k, _, _ in received]
        assert rtmp.MSG_DATA_AMF0 in kinds
        assert rtmp.MSG_AUDIO in kinds and rtmp.MSG_VIDEO in kinds
        audio = [(ts, p) for k, ts, p in received if k == rtmp.MSG_AUDIO]
        assert (100, b"\xaf\x01AUDIO") in audio
        # service media hook observed the publisher's frames too
        assert (100, b"\xaf\x01AUDIO") in service.audio_frames
        pub.close()
        sub.close()

    def test_late_joiner_gets_cached_headers(self, rtmp_server):
        srv, _ = rtmp_server
        pub = rtmp.RtmpClient("127.0.0.1", srv.port)
        ps = pub.create_stream()
        assert ps.publish("vod")
        ps.send_metadata({"fps": 30.0})
        ps.send_audio(0, b"\xaf\x00SEQ")   # AAC sequence header
        ps.send_video(0, b"\x17\x00SPS")   # AVC sequence header
        ps.send_video(40, b"\x27\x01FRAME")
        time.sleep(0.3)  # let the server cache before the late join

        received = []
        headers_seen = threading.Event()

        def on_media(msg):
            received.append((msg.type_id, msg.payload))
            if len(received) >= 3:
                headers_seen.set()

        sub = rtmp.RtmpClient("127.0.0.1", srv.port)
        ss = sub.create_stream()
        assert ss.play("vod", on_media=on_media)
        assert headers_seen.wait(5), f"late joiner got {received}"
        payloads = [p for _, p in received]
        assert b"\xaf\x00SEQ" in payloads  # cached AAC header replayed
        assert b"\x17\x00SPS" in payloads  # cached AVC header replayed
        pub.close()
        sub.close()

    def test_double_publish_refused(self, rtmp_server):
        srv, _ = rtmp_server
        a = rtmp.RtmpClient("127.0.0.1", srv.port)
        sa = a.create_stream()
        assert sa.publish("solo")
        b = rtmp.RtmpClient("127.0.0.1", srv.port)
        sb = b.create_stream()
        sb.name = "solo"
        b._send_command(sb.msid, "publish", 0.0, None, "solo", "live")
        assert sb.wait_status("NetStream.Publish.BadName", timeout=5)
        a.close()
        b.close()

    def test_rtmp_and_tbus_share_the_port(self, rtmp_server):
        srv, _ = rtmp_server
        client = rtmp.RtmpClient("127.0.0.1", srv.port)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{srv.port}")
        c = ch.call_method("svc", "echo", b"both-worlds")
        assert c.ok() and c.response_payload == b"both-worlds"
        client.close()

    def test_no_service_kills_rtmp_conn(self):
        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            with pytest.raises((TimeoutError, ConnectionError, OSError)):
                rtmp.RtmpClient("127.0.0.1", srv.port, timeout=2)
        finally:
            srv.stop()
