"""LogSink + rate-limited logging tests (reference
test/logging_unittest.cc LogSink cases)."""

import logging

from incubator_brpc_tpu.utils import logging as tblog


class CapturingSink(tblog.LogSink):
    def __init__(self, consume=True):
        self.records = []
        self.consume = consume

    def on_log_message(self, record):
        self.records.append(record)
        return self.consume


def test_sink_sees_framework_records():
    sink = CapturingSink()
    old = tblog.set_log_sink(sink)
    try:
        logging.getLogger("incubator_brpc_tpu.test").warning("hello %s", "sink")
    finally:
        tblog.set_log_sink(old)
    assert any(r.getMessage() == "hello sink" for r in sink.records)


def test_sink_swap_returns_old_and_restores():
    a, b = CapturingSink(), CapturingSink()
    old0 = tblog.set_log_sink(a)
    try:
        assert tblog.set_log_sink(b) is a
        logging.getLogger("incubator_brpc_tpu.test").error("to-b")
        assert any(r.getMessage() == "to-b" for r in b.records)
        assert not any(r.getMessage() == "to-b" for r in a.records)
    finally:
        tblog.set_log_sink(old0)


def test_propagation_disabled_while_sink_active():
    pkg = logging.getLogger("incubator_brpc_tpu")
    assert pkg.propagate is True
    sink = CapturingSink()
    old = tblog.set_log_sink(sink)
    try:
        assert pkg.propagate is False
    finally:
        tblog.set_log_sink(old)
    assert pkg.propagate is True


def test_sink_sees_info_and_debug():
    """The package logger opens to DEBUG while a sink is installed —
    otherwise root's WARNING default would drop these before any handler."""
    sink = CapturingSink()
    old = tblog.set_log_sink(sink)
    try:
        logging.getLogger("incubator_brpc_tpu.lvl").info("info-rec")
        logging.getLogger("incubator_brpc_tpu.lvl").debug("debug-rec")
    finally:
        tblog.set_log_sink(old)
    msgs = [r.getMessage() for r in sink.records]
    assert "info-rec" in msgs and "debug-rec" in msgs


def test_level_counters_advance():
    before = tblog.log_counts[logging.WARNING].get_value()
    logging.getLogger("incubator_brpc_tpu.counting").warning("count me")
    assert tblog.log_counts[logging.WARNING].get_value() == before + 1


def test_log_every_n_and_first_n():
    logger = logging.getLogger("incubator_brpc_tpu.rl")
    sink = CapturingSink()
    old = tblog.set_log_sink(sink)
    try:
        emitted = [tblog.log_every_n(logger, logging.INFO, 3, "n") for _ in range(9)]
        assert emitted == [True, False, False] * 3
        emitted = [tblog.log_first_n(logger, logging.INFO, 2, "f") for _ in range(5)]
        assert emitted == [True, True, False, False, False]
    finally:
        tblog.set_log_sink(old)


def test_log_every_second():
    logger = logging.getLogger("incubator_brpc_tpu.rl2")
    # same call site (one line in a loop): only the first emits
    emitted = [tblog.log_every_second(logger, logging.INFO, "s") for _ in range(3)]
    assert emitted == [True, False, False]
