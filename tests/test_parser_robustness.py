"""Parser robustness — every registered protocol parser must answer
arbitrary bytes with exactly one of: (None, 0) (incomplete), a cut frame,
ParseError (not mine / resync), or FatalParseError (mine but
unacceptable). Anything else escaping the cut loop would wedge or
misreport connections (the InputMessenger contract,
transport/messenger.py). The reference leans on the same discipline —
every policy parser returns ParseResult codes, never throws
(src/brpc/protocol.h:64-158).

Deterministic pseudo-fuzz: seeded random bytes, truncations of valid
frames, and single-byte corruptions of valid frames.
"""

import random

import pytest

from incubator_brpc_tpu.protocol.registry import protocol_registry
from incubator_brpc_tpu.protocol.tbus_std import FatalParseError, ParseError

ALLOWED = (ParseError, FatalParseError)


def _valid_seeds():
    """A few valid frames across protocols, as corruption bases."""
    from incubator_brpc_tpu.protocol.tbus_std import Meta, pack_frame

    seeds = [
        pack_frame(Meta(service="s", method="m"), b"payload" * 10, 3),
        (
            b"POST /a/b HTTP/1.1\r\nHost: t\r\nContent-Length: 5\r\n\r\nhello"
        ),
        b"GET /x HTTP/1.1\r\n\r\n",
    ]
    try:
        from incubator_brpc_tpu.protocol.baidu_std import pack_request
        from incubator_brpc_tpu.protocol.tbus_std import Meta as _M

        seeds.append(pack_request(_M(service="svc", method="mth"), b"body", 7))
    except Exception:  # noqa: BLE001 — signature drift: seeds are optional
        pass
    return [bytes(s) for s in seeds]


def _drive_parser(fn, data: bytes):
    try:
        out = fn(data)
    except ALLOWED:
        return
    except Exception as e:  # noqa: BLE001
        raise AssertionError(
            f"{fn.__module__}.{getattr(fn, '__name__', fn)} leaked "
            f"{type(e).__name__}: {e!r} on {data[:40]!r}..."
        ) from e
    if out is None:
        return
    if isinstance(out, tuple):
        frame, consumed = out
        assert frame is None or consumed >= 0
    else:
        assert isinstance(out, int) or out is None  # parse_header total


@pytest.mark.parametrize("seed", range(8))
def test_random_bytes_never_leak_exceptions(seed):
    rng = random.Random(seed)
    protos = protocol_registry.ordered()
    for _ in range(40):
        n = rng.choice((1, 4, 16, 64, 300, 5000))
        data = bytes(rng.getrandbits(8) for _ in range(n))
        for proto in protos:
            if proto.parse is not None:
                _drive_parser(proto.parse, data)
            if proto.parse_header is not None:
                _drive_parser(proto.parse_header, data[:64])


@pytest.mark.parametrize("seed", range(4))
def test_http_parse_conn_never_leaks_exceptions(seed):
    """The stateful pinned path (chunked decode) under garbage: only the
    contract exceptions may escape, and consumed must never exceed what
    was buffered."""
    from incubator_brpc_tpu.iobuf import IOBuf
    from incubator_brpc_tpu.protocol import http as http_mod

    class FakeSock:
        def __init__(self):
            self.context = {}
            self.on_failed = []

    rng = random.Random(2000 + seed)
    bases = _valid_seeds() + [
        b"POST /u HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"5\r\nhello\r\n0\r\n\r\n",
    ]
    for base in bases:
        for _ in range(25):
            data = bytearray(base)
            i = rng.randrange(len(data))
            if rng.random() < 0.5:
                data = data[:i]
            else:
                data[i] ^= 1 << rng.randrange(8)
            sock = FakeSock()
            buf = IOBuf()
            buf.append(bytes(data))
            # feed in two windows like the messenger would
            for _round in range(2):
                try:
                    frame, consumed = http_mod.parse_conn(sock, buf)
                except ALLOWED:
                    break
                except Exception as e:  # noqa: BLE001
                    raise AssertionError(
                        f"parse_conn leaked {type(e).__name__}: {e!r} "
                        f"on {bytes(data)[:40]!r}"
                    ) from e
                assert consumed >= 0


@pytest.mark.parametrize("seed", range(4))
def test_corrupted_valid_frames_never_leak_exceptions(seed):
    rng = random.Random(1000 + seed)
    protos = protocol_registry.ordered()
    for base in _valid_seeds():
        for _ in range(30):
            data = bytearray(base)
            mode = rng.randrange(3)
            if mode == 0:  # truncate
                data = data[: rng.randrange(len(data))]
            elif mode == 1:  # flip one byte
                i = rng.randrange(len(data))
                data[i] ^= 1 << rng.randrange(8)
            else:  # splice garbage into the middle
                i = rng.randrange(len(data))
                data[i:i] = bytes(
                    rng.getrandbits(8) for _ in range(rng.randrange(1, 9))
                )
            blob = bytes(data)
            for proto in protos:
                if proto.parse is not None:
                    _drive_parser(proto.parse, blob)
                if proto.parse_header is not None:
                    _drive_parser(proto.parse_header, blob[:64])
