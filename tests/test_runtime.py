"""runtime (L2) tests — one scenario per primitive, mirroring the
reference's per-primitive suites (test/bthread_butex_unittest.cpp,
bthread_id_unittest.cpp, execution_queue_unittest.cpp, ...)."""

import threading
import time

import pytest

from incubator_brpc_tpu.runtime import (
    Butex,
    CallIdSpace,
    DeviceCompletionButex,
    ETIMEDOUT,
    EWOULDBLOCK,
    ExecutionQueue,
    TimerThread,
    WAIT_OK,
    WorkerPool,
    WorkStealingQueue,
    spawn,
)


def wait_until(cond, timeout=5.0):
    """Poll until ``cond()`` — deadline-bounded, never a bare sleep whose
    margin a loaded host can blow through."""
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.002)
    return True


# ---------------------------------------------------------------- butex ----

def test_butex_wake_before_wait_returns_ewouldblock():
    b = Butex(0)
    b.add(1)
    assert b.wait(0) == EWOULDBLOCK  # value moved: never parks, never loses a wake


def test_butex_timed_wait():
    b = Butex(0)
    t0 = time.monotonic()
    assert b.wait(0, timeout=0.05) == ETIMEDOUT
    assert 0.04 <= time.monotonic() - t0 < 1.0


def test_butex_wake_n_and_wake_all():
    b = Butex(0)
    results = []

    def waiter():
        results.append(b.wait(0))

    threads = [threading.Thread(target=waiter) for _ in range(4)]
    for t in threads:
        t.start()
    while not b.has_waiters() or len(b._waiters) < 4:
        time.sleep(0.001)
    assert b.wake(1) == 1
    assert b.wake_all() == 3
    for t in threads:
        t.join()
    assert results == [WAIT_OK] * 4


def test_butex_wake_except_skips_token():
    b = Butex(0)
    woken = []

    def waiter(tok):
        b.wait(0, token=tok)
        woken.append(tok)

    t1 = threading.Thread(target=waiter, args=("me",))
    t2 = threading.Thread(target=waiter, args=("other",))
    t1.start(), t2.start()
    while len(b._waiters) < 2:
        time.sleep(0.001)
    assert b.wake_except("me") == 1
    t2.join(timeout=2)
    assert woken == ["other"]
    b.wake_all()
    t1.join(timeout=2)


def test_butex_timeout_then_normal_wake_race():
    # A wake arriving after the timer fired must not double-release.
    b = Butex(0)
    assert b.wait(0, timeout=0.01) == ETIMEDOUT
    assert b.wake(1) == 0


# ---------------------------------------------------------------- timer ----

def test_timer_schedule_and_order():
    tt = TimerThread("test-timer")
    try:
        fired = []
        tt.schedule(lambda: fired.append("b"), delay=0.04)
        tt.schedule(lambda: fired.append("a"), delay=0.01)
        assert wait_until(lambda: len(fired) == 2)
        assert fired == ["a", "b"]
    finally:
        tt.stop_and_join()


def test_timer_unschedule_prevents_run():
    tt = TimerThread("test-timer-2")
    try:
        fired = []
        tid = tt.schedule(lambda: fired.append(1), delay=0.05)
        assert tt.unschedule(tid) is True
        assert tt.unschedule(tid) is False  # already cancelled
        time.sleep(0.12)
        assert fired == []
        assert tt.stats()["pending"] == 0
    finally:
        tt.stop_and_join()


def test_timer_earlier_schedule_preempts():
    tt = TimerThread("test-timer-3")
    try:
        fired = []
        tt.schedule(lambda: fired.append("late"), delay=5.0)
        tt.schedule(lambda: fired.append("early"), delay=0.02)
        assert wait_until(lambda: fired == ["early"])
        assert fired == ["early"]  # did not wait behind the 5s head
    finally:
        tt.stop_and_join()


# ----------------------------------------------------------- worker pool ----

def test_fiber_spawn_join_result():
    f = spawn(lambda a, b: a + b, 2, 3)
    assert f.join(timeout=5)
    assert f.get() == 5


def test_fiber_exception_propagates_via_get():
    def boom():
        raise ValueError("boom")

    f = spawn(boom)
    assert f.join(timeout=5)
    with pytest.raises(ValueError):
        f.get()


def test_fiber_join_timeout():
    gate = threading.Event()
    f = spawn(gate.wait)
    assert f.join(timeout=0.05) is False
    gate.set()
    assert f.join(timeout=5)


def test_pool_runs_many_fibers_and_nested_spawn():
    pool = WorkerPool(concurrency=4, name="test_pool_many")
    try:
        total = 64
        done = []
        lock = threading.Lock()

        def leaf(i):
            with lock:
                done.append(i)

        def parent(i):
            # spawn from inside a worker: exercises the local-queue path
            return pool.spawn(leaf, i)

        parents = [pool.spawn(parent, i) for i in range(total)]
        leaves = [p.get(timeout=10) for p in parents]
        for leaf_fiber in leaves:
            assert leaf_fiber.join(timeout=10)
        assert sorted(done) == list(range(total))
        assert int(pool.nfibers_run.get_value()) == 2 * total
    finally:
        pool.stop_and_join()


def test_work_stealing_queue_order():
    q = WorkStealingQueue()
    for i in range(5):
        q.push(i)
    assert q.pop() == 4  # owner pops LIFO
    assert q.steal() == 0  # thief steals FIFO
    assert len(q) == 3


# ------------------------------------------------------- execution queue ----

def test_execution_queue_n_producers_per_producer_order():
    seen = []

    def consumer(it):
        for item in it:
            seen.append(item)

    q = ExecutionQueue(consumer)
    nproducers, nitems = 8, 200

    def producer(p):
        for i in range(nitems):
            assert q.execute((p, i)) == 0

    threads = [threading.Thread(target=producer, args=(p,)) for p in range(nproducers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q.stop()
    assert q.join(timeout=10)
    assert len(seen) == nproducers * nitems
    # single-consumer actor: each producer's items arrive in order
    for p in range(nproducers):
        mine = [i for (pp, i) in seen if pp == p]
        assert mine == list(range(nitems))


def test_execution_queue_single_consumer_at_a_time():
    active = [0]
    max_active = [0]
    lock = threading.Lock()

    def consumer(it):
        with lock:
            active[0] += 1
            max_active[0] = max(max_active[0], active[0])
        for _ in it:
            time.sleep(0.0005)
        with lock:
            active[0] -= 1

    q = ExecutionQueue(consumer, max_batch=4)
    threads = [
        threading.Thread(target=lambda: [q.execute(i) for i in range(50)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q.stop()
    assert q.join(timeout=10)
    assert max_active[0] == 1


def test_execution_queue_high_priority_lane():
    seen = []
    gate = threading.Event()

    def consumer(it):
        gate.wait()
        for item in it:
            seen.append(item)

    q = ExecutionQueue(consumer)
    q.execute("n1")
    q.execute("n2")
    q.execute("hi", high_priority=True)
    gate.set()
    q.stop()
    assert q.join(timeout=10)
    # the batch drained after the gate put the high-priority item first
    assert seen.index("hi") < seen.index("n1") or seen[0] == "n1"
    assert set(seen) == {"n1", "n2", "hi"}


def test_execution_queue_stop_rejects_and_reports():
    stopped_seen = []

    def consumer(it):
        list(it)
        stopped_seen.append(it.is_queue_stopped())

    q = ExecutionQueue(consumer)
    q.execute(1)
    q.stop()
    assert q.execute(2) != 0  # EINVAL after stop
    assert q.join(timeout=10)
    assert stopped_seen[-1] is True


# -------------------------------------------------------- correlation id ----

def test_call_id_lock_unlock_and_destroy():
    space = CallIdSpace()
    cid = space.create(data={"x": 1})
    code, data = space.lock(cid)
    assert code == 0 and data == {"x": 1}
    assert space.unlock(cid) == 0
    code, _ = space.lock(cid)
    assert code == 0
    assert space.unlock_and_destroy(cid) == 0
    assert not space.valid(cid)
    code, _ = space.lock(cid)
    assert code != 0  # stale id: EINVAL, no fault (never-freed slot)


def test_call_id_error_when_unlocked_runs_handler_inline():
    space = CallIdSpace()
    handled = []

    def on_error(call_id, data, code, text):
        handled.append((data, code, text))
        space.unlock_and_destroy(call_id)

    cid = space.create(data="D", on_error=on_error)
    assert space.error(cid, 1008, "timeout") == 0
    assert handled == [("D", 1008, "timeout")]
    assert not space.valid(cid)


def test_call_id_error_while_locked_is_queued_until_unlock():
    space = CallIdSpace()
    handled = []

    def on_error(call_id, data, code, text):
        handled.append(code)
        space.unlock_and_destroy(call_id)

    cid = space.create(data="D", on_error=on_error)
    code, _ = space.lock(cid)
    assert code == 0
    assert space.error(cid, 1009) == 0  # queued, not delivered
    assert handled == []
    assert space.unlock(cid) == 0  # delivery point
    assert handled == [1009]
    assert not space.valid(cid)


def test_call_id_join_wakes_on_destroy():
    space = CallIdSpace()
    cid = space.create()
    joined = []

    def joiner():
        joined.append(space.join(cid, timeout=10))

    t = threading.Thread(target=joiner)
    t.start()
    time.sleep(0.05)
    assert joined == []  # still parked
    code, _ = space.lock(cid)
    assert code == 0
    space.unlock_and_destroy(cid)
    t.join(timeout=5)
    assert joined == [True]
    assert space.join(cid) is True  # joining a destroyed id returns at once


def test_call_id_ranged_versions_shared_across_retries():
    # One RPC + retries share a slot via a version range (channel.cpp:307).
    space = CallIdSpace()
    cid = space.create(data="rpc", version_range=3)
    assert space.valid(cid)
    assert space.valid(cid + 1)
    assert space.valid(cid + 2)
    assert not space.valid(cid + 3)
    code, data = space.lock(cid + 2)  # a retry's version resolves to the slot
    assert code == 0 and data == "rpc"
    space.unlock_and_destroy(cid + 2)
    for d in range(3):
        assert not space.valid(cid + d)


def test_call_id_lock_contention():
    space = CallIdSpace()
    cid = space.create(data=[])
    order = []

    def contender(i):
        code, data = space.lock(cid)
        assert code == 0
        order.append(i)
        time.sleep(0.005)
        space.unlock(cid)

    threads = [threading.Thread(target=contender, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(order) == list(range(6))  # all got the lock exactly once


def test_call_id_unlock_with_pending_error_and_no_handler_destroys():
    # Default on_error is destroy (reference default_bthread_id_on_error):
    # a queued error delivered by unlock() must not leave the id locked.
    space = CallIdSpace()
    cid = space.create(data="d")
    code, _ = space.lock(cid)
    assert code == 0
    assert space.error(cid, 1008) == 0  # queued (locked, no handler)
    assert space.unlock(cid) == 0
    assert not space.valid(cid)  # destroyed, not stuck locked


def test_butex_requeue_preserves_timeout():
    b1, b2 = Butex(0), Butex(0)
    results = []

    def timed_waiter():
        results.append(b1.wait(0, timeout=0.15))

    def plain_waiter():
        results.append(b1.wait(0))

    t0 = threading.Thread(target=plain_waiter)
    t0.start()
    while len(b1._waiters) < 1:
        time.sleep(0.001)
    t1 = threading.Thread(target=timed_waiter)
    t1.start()
    while len(b1._waiters) < 2:
        time.sleep(0.001)
    # requeue wakes the first (plain) waiter, moves the timed one to b2
    assert b1.requeue(b2) == 1
    t0.join(timeout=2)
    # the moved timed waiter must still honor its timeout on b2
    t1.join(timeout=2)
    assert not t1.is_alive()
    assert results[-1] == ETIMEDOUT


def test_execution_queue_consumer_exception_does_not_drop_batch_remainder():
    seen = []

    def consumer(it):
        for item in it:
            if item == 2:
                raise RuntimeError("bad item")
            seen.append(item)

    q = ExecutionQueue(consumer)
    for i in range(6):
        q.execute(i)
    q.stop()
    assert q.join(timeout=10)
    # item 2 was consumed by the raising call (at-most-once); 3..5 survive
    assert seen == [0, 1, 3, 4, 5]


# ------------------------------------------------------ device completion ----

def test_device_completion_butex_wakes_on_ready():
    import jax
    import jax.numpy as jnp

    cq = DeviceCompletionButex()
    out = jax.jit(lambda x: x * 2 + 1)(jnp.arange(1024.0))
    cq.watch(out)
    assert cq.wait_for(1, timeout=30)
    assert cq.load() == 1
    assert float(out[1]) == 3.0


def test_device_completion_callback_and_multiple_ops():
    import jax
    import jax.numpy as jnp

    cq = DeviceCompletionButex()
    done = []
    outs = [jax.jit(lambda x: x + i)(jnp.ones(8)) for i in range(3)]
    for o in outs:
        cq.watch(o, on_complete=lambda arr, err: done.append(err))
    assert cq.wait_for(3, timeout=30)
    assert done == [None, None, None]
    assert cq.inflight == 0
    assert cq.errors == []


def test_device_completion_failure_counts_and_records():
    # A failing readiness wait must still settle the butex (no hung
    # waiters) and surface the error.
    class _Boom:
        def block_until_ready(self):
            raise RuntimeError("device melted")

    cq = DeviceCompletionButex()
    cb = []
    cq.watch(_Boom(), on_complete=lambda arr, err: cb.append(type(err).__name__))
    assert cq.wait_for(1, timeout=10)
    assert len(cq.errors) == 1
    assert cb == ["RuntimeError"]


def test_device_completion_raising_callback_does_not_strand_waiters():
    import jax
    import jax.numpy as jnp

    cq = DeviceCompletionButex()

    def bad_cb(arr, err):
        raise ValueError("callback bug")

    cq.watch(jax.jit(lambda x: x * 2)(jnp.ones(4)), on_complete=bad_cb)
    assert cq.wait_for(1, timeout=10)  # bump/wake happened before the callback
