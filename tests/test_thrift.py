"""Thrift framed-binary protocol tests (reference
test/brpc_thrift_*: codec conformance on hand-built frames + loopback
round trips)."""

import struct
import threading

import pytest

from incubator_brpc_tpu.protocol import thrift as tt


class TestCodec:
    def test_call_roundtrip(self):
        frame = tt.pack_call("echo", b"payload", 7)
        msg, consumed = tt.parse_frame(frame)
        assert consumed == len(frame)
        assert msg["type"] == tt.T_CALL
        assert msg["method"] == "echo"
        assert msg["seqid"] == 7
        assert msg["payload"] == b"payload"

    def test_reply_and_exception(self):
        msg, _ = tt.parse_frame(tt.pack_reply("m", b"out", 3))
        assert msg["type"] == tt.T_REPLY and msg["payload"] == b"out"
        msg, _ = tt.parse_frame(tt.pack_exception("m", "boom", 3, type_id=6))
        assert isinstance(msg["error"], tt.TApplicationException)
        assert msg["error"].type_id == 6

    def test_incomplete_frames(self):
        frame = tt.pack_call("echo", b"x" * 100, 1)
        for cut in (0, 2, 10, len(frame) - 1):
            assert tt.parse_frame(frame[:cut]) == (None, -1)

    def test_bad_version_raises(self):
        body = struct.pack(">I", 0xDEAD0001) + b"junk"
        with pytest.raises(tt.ThriftError):
            tt.parse_frame(struct.pack(">i", len(body)) + body)

    def test_unknown_field_skipped(self):
        # a reply with an extra i32 field 5 before the result field
        body = (
            struct.pack(">I", tt.VERSION_1 | tt.T_REPLY)
            + struct.pack(">i", 1) + b"m"
            + struct.pack(">i", 9)
            + struct.pack(">bh", tt.TT_I32, 5) + struct.pack(">i", 42)
            + struct.pack(">bh", tt.TT_STRING, 0) + struct.pack(">i", 2) + b"ok"
            + struct.pack(">b", tt.TT_STOP)
        )
        frame = struct.pack(">i", len(body)) + body
        msg, consumed = tt.parse_frame(frame)
        assert consumed == len(frame)
        assert msg["payload"] == b"ok"


@pytest.fixture
def pair():
    server = tt.MockThriftServer()
    assert server.start()
    client = tt.ThriftClient(f"127.0.0.1:{server.port}")
    yield server, client
    client.close()
    server.stop()


class TestClient:
    def test_echo_roundtrip(self, pair):
        _, c = pair
        assert c.call("echo", b"hello-thrift") == b"hello-thrift"

    def test_unknown_method_raises(self, pair):
        _, c = pair
        with pytest.raises(tt.TApplicationException):
            c.call("nosuch", b"")

    def test_concurrent_calls_matched_by_seqid(self, pair):
        _, c = pair
        errs = []

        def worker(i):
            try:
                for j in range(25):
                    body = b"t%d-%d" % (i, j)
                    assert c.call("echo", body) == body
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs


class TestMalformedFrames:
    def test_negative_string_length_raises(self):
        import struct

        from incubator_brpc_tpu.protocol.thrift import (
            VERSION_1,
            ThriftError,
            parse_frame,
        )

        # frame: version|T_REPLY, method "m", seqid, then a field header
        # claiming a string with negative length — must raise, not loop
        body = (
            struct.pack(">I", VERSION_1 | 2)
            + struct.pack(">i", 1)
            + b"m"
            + struct.pack(">i", 7)
            + struct.pack(">bh", 11, 0)  # TT_STRING, fid 0
            + struct.pack(">i", -5)  # poisoned length
        )
        buf = struct.pack(">i", len(body)) + body
        import pytest as _pytest

        with _pytest.raises(ThriftError):
            parse_frame(buf)

    def test_overlong_skip_length_raises(self):
        import struct

        from incubator_brpc_tpu.protocol.thrift import (
            VERSION_1,
            ThriftError,
            parse_frame,
        )

        body = (
            struct.pack(">I", VERSION_1 | 2)
            + struct.pack(">i", 1)
            + b"m"
            + struct.pack(">i", 7)
            + struct.pack(">bh", 11, 9)  # unknown fid → skipped
            + struct.pack(">i", 1 << 20)  # claims 1MiB that isn't there
        )
        buf = struct.pack(">i", len(body)) + body
        import pytest as _pytest

        with _pytest.raises(ThriftError):
            parse_frame(buf)


class TestThriftServer:
    """ServerOptions.thrift_service (reference ThriftService +
    ProcessThriftRequest thrift_protocol.cpp:314): framed thrift served on
    the shared port next to tbus_std."""

    @pytest.fixture
    def thrift_server(self):
        from incubator_brpc_tpu.rpc import Server, ServerOptions
        from incubator_brpc_tpu.utils.status import ErrorCode

        def service(cntl, method, payload):
            if method == "echo":
                return payload
            if method == "upper":
                return payload.upper()
            cntl.set_failed(ErrorCode.ENOMETHOD, f"unknown method {method}")
            return b""

        srv = Server(ServerOptions(usercode_inline=True,
                                   thrift_service=service))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        yield srv
        srv.stop()

    def test_call_through_real_server(self, thrift_server):
        c = tt.ThriftClient(f"127.0.0.1:{thrift_server.port}")
        assert c.call("echo", b"framed") == b"framed"
        assert c.call("upper", b"abc") == b"ABC"
        c.close()

    def test_unknown_method_maps_to_exception(self, thrift_server):
        c = tt.ThriftClient(f"127.0.0.1:{thrift_server.port}")
        with pytest.raises(tt.TApplicationException) as ei:
            c.call("nope", b"x")
        assert ei.value.type_id == 1  # UNKNOWN_METHOD
        c.close()

    def test_thrift_and_tbus_share_the_port(self, thrift_server):
        from incubator_brpc_tpu.rpc import Channel

        c = tt.ThriftClient(f"127.0.0.1:{thrift_server.port}")
        assert c.call("echo", b"t") == b"t"
        ch = Channel()
        assert ch.init(f"127.0.0.1:{thrift_server.port}")
        r = ch.call_method("svc", "echo", b"b")
        assert r.ok() and r.response_payload == b"b"
        c.close()

    def test_no_service_rejects_thrift_bytes(self):
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            c = tt.ThriftClient(f"127.0.0.1:{srv.port}")
            with pytest.raises((tt.ThriftError, TimeoutError)):
                c.call("echo", b"x", timeout=2)
            c.close()
        finally:
            srv.stop()

    def test_registered_without_explicit_import(self):
        # the package __init__ must register the server protocol: apps
        # construct ServerOptions(thrift_service=...) without importing
        # protocol.thrift themselves
        import incubator_brpc_tpu.protocol  # noqa: F401 — the registrar
        from incubator_brpc_tpu.protocol.registry import protocol_registry

        assert "thrift" in protocol_registry
