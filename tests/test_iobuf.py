"""IOBuf + native runtime tests — the acceptance subset the reference keeps
in test/iobuf_unittest.cpp (share/cut semantics, refcounts via the
block_shared_count white-box hook, external-block release ordering) plus
region-pool and ResourcePool coverage."""

import errno
import os
import socket
import zlib

import pytest

from incubator_brpc_tpu import iobuf as iob
from incubator_brpc_tpu import native
from incubator_brpc_tpu.iobuf import _NativeIOBuf, _PyIOBuf

IMPLS = [_PyIOBuf] + ([_NativeIOBuf] if native.NATIVE_AVAILABLE else [])


def test_native_loaded():
    # The image bakes g++; the native path must be live in CI.
    assert native.NATIVE_AVAILABLE


@pytest.mark.parametrize("impl", IMPLS)
class TestIOBufSemantics:
    def test_append_roundtrip(self, impl):
        b = impl()
        b.append(b"hello ")
        b.append(b"world")
        assert len(b) == 11
        assert b.to_bytes() == b"hello world"

    def test_large_append_spans_blocks(self, impl):
        b = impl()
        data = os.urandom(50_000)  # > several 8 KB blocks
        b.append(data)
        assert len(b) == len(data)
        assert b.to_bytes() == data
        if impl is _NativeIOBuf:
            assert b.block_count >= 5

    def test_cutn_moves_bytes(self, impl):
        b = impl()
        b.append(b"abcdefghij")
        head = b.cutn(4)
        assert head.to_bytes() == b"abcd"
        assert b.to_bytes() == b"efghij"
        assert len(b) == 6

    def test_cut_more_than_size(self, impl):
        b = impl()
        b.append(b"xy")
        out = b.cutn(10)
        assert out.to_bytes() == b"xy"
        assert len(b) == 0

    def test_share_bumps_refcount_no_copy(self, impl):
        a = impl()
        a.append(b"shared-bytes")
        c = impl()
        c.append_iobuf(a)
        assert c.to_bytes() == b"shared-bytes"
        assert a.to_bytes() == b"shared-bytes"
        assert a.block_shared_count(0) == 2
        c.clear()
        assert a.block_shared_count(0) == 1

    def test_partial_cut_shares_block(self, impl):
        a = impl()
        a.append(b"0123456789")
        head = a.cutn(3)
        # both halves reference the same block
        assert head.block_shared_count(0) == 2
        assert a.block_shared_count(0) == 2
        assert head.to_bytes() == b"012"
        assert a.to_bytes() == b"3456789"

    def test_popn(self, impl):
        b = impl()
        b.append(b"0123456789")
        assert b.popn(4) == 4
        assert b.to_bytes() == b"456789"
        assert b.popn(100) == 6
        assert len(b) == 0

    def test_copy_to_with_pos(self, impl):
        b = impl()
        b.append(b"0123")
        b.append(b"4567")
        assert b.to_bytes(4, pos=2) == b"2345"
        assert len(b) == 8  # non-consuming

    def test_external_release_after_last_ref(self, impl):
        released = []
        buf = bytearray(b"external-payload")
        a = impl()
        a.append_external(buf, release_cb=lambda o: released.append(o))
        c = impl()
        c.append_iobuf(a)
        a.clear()
        assert released == []  # c still holds a ref
        c.clear()
        assert len(released) == 1
        assert released[0] is buf

    def test_external_zero_copy_read(self, impl):
        buf = bytearray(b"zcview")
        a = impl()
        a.append_external(buf)
        assert a.to_bytes() == b"zcview"
        views = a.views()
        assert b"".join(bytes(v) for v in views) == b"zcview"
        a.clear()

    def test_views_concat_equals_bytes(self, impl):
        b = impl()
        b.append(b"abc")
        b.append(os.urandom(20_000))
        total = b.to_bytes()
        assert b"".join(bytes(v) for v in b.views()) == total

    def test_append_after_cut_does_not_corrupt_shared_tail(self, impl):
        # Appending to `a` after sharing its tail block must never change
        # bytes already visible through the share (CAS-claim contract).
        a = impl()
        a.append(b"AAAA")
        c = impl()
        c.append_iobuf(a)
        a.append(b"BBBB")
        assert c.to_bytes() == b"AAAA"
        assert a.to_bytes() == b"AAAABBBB"

    def test_fd_roundtrip(self, impl):
        s1, s2 = socket.socketpair()
        try:
            out = impl()
            payload = os.urandom(100_000)
            out.append(payload)
            received = impl()
            while len(out) > 0:
                nw = out.cut_into_fd(s1.fileno())
                assert nw > 0
                while True:
                    nr = received.append_from_fd(s2.fileno(), 1 << 20)
                    if nr <= 0 or len(received) >= len(payload) - len(out):
                        break
            while len(received) < len(payload):
                nr = received.append_from_fd(s2.fileno(), 1 << 20)
                assert nr > 0
            assert received.to_bytes() == payload
        finally:
            s1.close()
            s2.close()

    def test_fd_eagain(self, impl):
        s1, s2 = socket.socketpair()
        try:
            s2.setblocking(False)
            got = impl()
            rc = got.append_from_fd(s2.fileno())
            assert rc == -errno.EAGAIN or rc == -errno.EWOULDBLOCK
        finally:
            s1.close()
            s2.close()


@pytest.mark.skipif(not native.NATIVE_AVAILABLE, reason="native only")
class TestNativeOnly:
    def test_crc32_matches_zlib(self):
        data = os.urandom(4096)
        assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF

    def test_fast_rand(self):
        vals = {native.fast_rand() for _ in range(64)}
        assert len(vals) > 60
        assert all(native.LIB.tb_fast_rand_less_than(10) < 10 for _ in range(100))

    def test_block_pool_reuses(self):
        b = _NativeIOBuf()
        b.append(os.urandom(64_000))
        mid = iob.block_pool_stats()
        assert mid["live"] >= 8  # 64 KB over 8 KB blocks
        b.clear()
        after = iob.block_pool_stats()
        # clear() parks blocks in the caches instead of freeing them
        assert after["cached"] > mid["cached"]
        assert after["live"] == mid["live"]

    def test_region_allocator_exhaust_and_reuse(self):
        slab = bytearray(4 * 1024)
        rid = iob.register_region(slab, 1024)
        assert rid >= 0
        assert iob.region_free_blocks(rid) == 4
        b = _NativeIOBuf()
        assert b.append_from_region(rid, b"x" * 3000)
        assert iob.region_free_blocks(rid) == 1
        # exhaustion: only 1 block (1024 B) left but 2000 B requested
        c = _NativeIOBuf()
        assert not c.append_from_region(rid, b"y" * 2000)
        c.clear()
        b.clear()
        assert iob.region_free_blocks(rid) == 4  # release returned blocks
        # region data actually lives in the caller's slab
        d = _NativeIOBuf()
        assert d.append_from_region(rid, b"Z" * 10)
        assert bytes(slab[:10]) == b"Z" * 10 or b"Z" * 10 in bytes(slab)
        d.clear()

    def test_resource_pool_versioned_ids(self):
        pool = native.ResourcePool(16)
        rid1 = pool.get()
        assert pool.address(rid1) is not None
        assert pool.live == 1
        assert pool.return_(rid1)
        assert pool.address(rid1) is None  # stale after return
        assert not pool.return_(rid1)  # double-return rejected
        rid2 = pool.get()
        # slot reused but version moved on — old id still dead (ABA-safe)
        assert (rid2 & 0xFFFFFFFF) == (rid1 & 0xFFFFFFFF)
        assert rid2 != rid1
        assert pool.address(rid1) is None
        assert pool.address(rid2) is not None

    def test_monotonic_ns_advances(self):
        t1 = native.monotonic_ns()
        t2 = native.monotonic_ns()
        assert t2 >= t1 > 0
