"""Framing + tensor-echo tests — analog of the reference's protocol
conformance suites that call parse/pack handlers directly on hand-built
buffers (SURVEY.md §4, brpc_*_protocol_unittest pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from incubator_brpc_tpu.ops import framing
from incubator_brpc_tpu.models.tensor_echo import TensorEchoService, make_echo_step


def test_frame_parse_roundtrip():
    payload = jnp.arange(100, dtype=jnp.uint32)
    framed = framing.frame(payload, correlation_id=0x1234567890, method_id=7, flags=framing.FLAG_STREAM)
    header, out, ok = framing.parse(framed)
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payload))
    assert int(header.method_id) == 7
    assert int(header.flags) == framing.FLAG_STREAM
    assert int(header.cid_lo) == 0x34567890
    assert int(header.cid_hi) == 0x12
    assert int(header.body_words) == 100


def test_parse_rejects_corruption():
    payload = jnp.arange(64, dtype=jnp.uint32)
    framed = framing.frame(payload, correlation_id=1)
    corrupt = framed.at[framing.HEADER_WORDS + 3].add(1)  # flip a payload word
    _, _, ok = framing.parse(corrupt)
    assert not bool(ok)
    bad_magic = framed.at[0].set(0)
    _, _, ok2 = framing.parse(bad_magic)
    assert not bool(ok2)


def test_echo_step_roundtrip():
    step, request = make_echo_step(payload_words=128)
    response = step(request)
    header, payload, ok = framing.parse(response)
    assert bool(ok)
    assert int(header.flags) & framing.FLAG_RESPONSE
    assert int(header.error_code) == 0
    np.testing.assert_array_equal(
        np.asarray(payload), np.asarray(request[framing.HEADER_WORDS :])
    )


def test_echo_step_bad_frame_gets_error_response():
    step, request = make_echo_step(payload_words=128)
    corrupt = request.at[6].add(1)  # break checksum
    response = step(corrupt)
    header, payload, ok = framing.parse(response)
    assert bool(ok)  # response itself is well-formed
    assert int(header.error_code) == 1003  # EREQUEST
    assert int(np.asarray(payload).sum()) == 0


def test_multi_method_dispatch():
    svc = TensorEchoService()
    svc.add_method(1, lambda p: p + jnp.uint32(1))
    step = jax.jit(svc.step)
    payload = jnp.arange(32, dtype=jnp.uint32)
    req = framing.frame(payload, correlation_id=9, method_id=1)
    _, out, _ = framing.parse(step(req))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payload) + 1)
    with pytest.raises(ValueError):
        svc.add_method(1, lambda p: p)


def test_float_payload_bitcast_roundtrip():
    x = jnp.array([2.5, -1.0, 0.1, 3e38], jnp.float32)
    framed = framing.frame(x, correlation_id=2)
    _, words, ok = framing.parse(framed)
    assert bool(ok)
    back = framing.from_words(words, jnp.float32, x.shape)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_sparse_method_ids_and_enomethod():
    svc = TensorEchoService()
    svc.add_method(3, lambda p: p * jnp.uint32(2))
    svc.add_method(5, lambda p: p + jnp.uint32(10))
    step = jax.jit(svc.step)
    payload = jnp.arange(16, dtype=jnp.uint32)
    # sparse id 3 must hit ITS handler, not an index-3 slot
    h3, out3, _ = framing.parse(step(framing.frame(payload, 1, method_id=3)))
    np.testing.assert_array_equal(np.asarray(out3), np.asarray(payload) * 2)
    h5, out5, _ = framing.parse(step(framing.frame(payload, 1, method_id=5)))
    np.testing.assert_array_equal(np.asarray(out5), np.asarray(payload) + 10)
    # unknown id -> ENOMETHOD error frame with zeroed payload
    h99, out99, _ = framing.parse(step(framing.frame(payload, 1, method_id=99)))
    assert int(h99.error_code) == 1002
    assert int(np.asarray(out99).sum()) == 0
