"""RESP client/pipelining + http→rpc gateway tests (reference
test/brpc_redis_unittest.cpp command/reply cases, and the pb-over-http
behavior of http_rpc_protocol.cpp)."""

import threading

import pytest

from incubator_brpc_tpu.protocol import resp
from incubator_brpc_tpu.protocol.http import http_call
from incubator_brpc_tpu.rpc import Channel, Server


class TestRespCodec:
    def test_pack_command(self):
        assert (
            resp.pack_command("SET", "k", "v")
            == b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
        )
        assert resp.pack_command("INCRBY", "k", 5) == (
            b"*3\r\n$6\r\nINCRBY\r\n$1\r\nk\r\n$1\r\n5\r\n"
        )

    def test_parse_simple_types(self):
        assert resp.parse_reply(b"+OK\r\n") == ("OK", 5)
        assert resp.parse_reply(b":42\r\n") == (42, 5)
        r, off = resp.parse_reply(b"$5\r\nhello\r\n")
        assert (r, off) == (b"hello", 11)
        r, off = resp.parse_reply(b"$-1\r\n")
        assert r is None and off == 5
        err, _ = resp.parse_reply(b"-ERR nope\r\n")
        assert isinstance(err, resp.RespError)

    def test_parse_array_and_nested(self):
        buf = b"*2\r\n$1\r\na\r\n*2\r\n:1\r\n:2\r\n"
        r, off = resp.parse_reply(buf)
        assert r == [b"a", [1, 2]]
        assert off == len(buf)

    def test_incomplete_returns_sentinel(self):
        for partial in (b"", b"$5\r\nhel", b"*2\r\n:1\r\n", b"+OK"):
            r, off = resp.parse_reply(partial)
            assert off == -1


@pytest.fixture
def redis_pair():
    server = resp.MockRedisServer()
    assert server.start()
    client = resp.RedisClient(f"127.0.0.1:{server.port}")
    yield server, client
    client.close()
    server.stop()


class TestRedisClient:
    def test_basic_commands(self, redis_pair):
        _, c = redis_pair
        assert c.ping() == "PONG"
        assert c.set("k", "v1") == "OK"
        assert c.get("k") == b"v1"
        assert c.get("missing") is None
        assert c.incr("n") == 1
        assert c.incr("n") == 2
        assert c.delete("k", "missing") == 1

    def test_pipeline_order(self, redis_pair):
        _, c = redis_pair
        replies = c.pipeline(
            [("SET", "a", "1"), ("INCR", "a"), ("GET", "a"), ("MGET", "a", "nope")]
        )
        assert replies == ["OK", 2, b"2", [b"2", None]]

    def test_error_reply_raises(self, redis_pair):
        _, c = redis_pair
        with pytest.raises(resp.RespError):
            c.execute("NOSUCHCMD")

    def test_concurrent_pipelines(self, redis_pair):
        _, c = redis_pair
        errs = []

        def worker(i):
            try:
                for j in range(50):
                    key = f"t{i}"
                    c.execute("SET", key, f"{j}")
                    assert c.get(key) is not None
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs


@pytest.fixture
def dual_server():
    server = Server()

    def upper(cntl, request):
        return request.upper()

    def fail(cntl, request):
        from incubator_brpc_tpu.utils.status import ErrorCode

        cntl.set_failed(ErrorCode.EINTERNAL, "nope")
        return b""

    def later(cntl, request):
        cntl.set_async()
        threading.Timer(0.05, lambda: cntl.send_response(b"async:" + request)).start()
        return None

    server.add_service("svc", {"upper": upper, "fail": fail, "later": later})
    assert server.start(0)
    yield server
    server.stop()
    server.join(timeout=5)


class TestHttpGateway:
    def test_same_method_over_both_protocols(self, dual_server):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{dual_server.port}")
        assert ch.call_method("svc", "upper", b"abc").response_payload == b"ABC"
        status, _, body = http_call(
            "127.0.0.1", dual_server.port, "/svc/upper", method="POST", body=b"abc"
        )
        assert status == 200 and body == b"ABC"

    def test_gateway_counts_in_method_stats(self, dual_server):
        http_call("127.0.0.1", dual_server.port, "/svc/upper", method="POST", body=b"x")
        st = dual_server.method_status("svc", "upper")
        assert st.latency.count() >= 1

    def test_gateway_errors_map_to_500(self, dual_server):
        status, _, body = http_call(
            "127.0.0.1", dual_server.port, "/svc/fail", method="POST", body=b""
        )
        assert status == 500 and b"nope" in body

    def test_gateway_unknown_is_404(self, dual_server):
        status, _, _ = http_call(
            "127.0.0.1", dual_server.port, "/svc/zzz", method="POST", body=b""
        )
        assert status == 404

    def test_gateway_async_handler(self, dual_server):
        status, _, body = http_call(
            "127.0.0.1", dual_server.port, "/svc/later", method="POST", body=b"hi"
        )
        assert status == 200 and body == b"async:hi"


class TestRedisAuth:
    """RedisAuthenticator semantics (policy/redis_authenticator.cpp): AUTH
    is the first command on the connection; unauthenticated commands are
    refused."""

    def test_auth_then_commands(self):
        from incubator_brpc_tpu.protocol.resp import MockRedisServer, RedisClient

        srv = MockRedisServer(password="s3cret")
        assert srv.start()
        try:
            c = RedisClient(f"127.0.0.1:{srv.port}", password="s3cret")
            assert c.execute("SET", "k", "v") in (b"OK", "OK")
            assert c.execute("GET", "k") == b"v"
            c.close()
        finally:
            srv.stop()

    def test_wrong_password_fails_loudly(self):
        from incubator_brpc_tpu.protocol.resp import (
            MockRedisServer,
            RedisClient,
            RespError,
        )

        srv = MockRedisServer(password="s3cret")
        assert srv.start()
        try:
            with pytest.raises(RespError):
                RedisClient(f"127.0.0.1:{srv.port}", password="wrong")
        finally:
            srv.stop()

    def test_unauthenticated_commands_refused(self):
        from incubator_brpc_tpu.protocol.resp import (
            MockRedisServer,
            RedisClient,
            RespError,
        )

        srv = MockRedisServer(password="s3cret")
        assert srv.start()
        try:
            c = RedisClient(f"127.0.0.1:{srv.port}")  # no AUTH
            with pytest.raises(RespError):
                c.execute("GET", "k")
            c.close()
        finally:
            srv.stop()


class TestMemcacheBinary:
    """The binary memcache wire (policy/memcache_binary_protocol.cpp):
    header fixtures, opaque correlation, full op coverage, SASL auth."""

    def test_header_fixture(self):
        from incubator_brpc_tpu.protocol import memcache_binary as mb

        wire = mb.pack_request(mb.OP_SET, b"k", b"v",
                               extras=b"\x00" * 8, opaque=7)
        assert wire[0] == 0x80 and wire[1] == mb.OP_SET
        assert wire[2:4] == b"\x00\x01"          # key length
        assert wire[4] == 8                       # extras length
        import struct as s
        assert s.unpack_from(">I", wire, 8)[0] == 8 + 1 + 1  # total body
        assert wire[12:16] == b"\x00\x00\x00\x07"  # opaque
        assert wire[24:32] == b"\x00" * 8          # extras
        assert wire[32:33] == b"k" and wire[33:34] == b"v"

    @pytest.fixture
    def binary_server(self):
        from incubator_brpc_tpu.protocol.memcache_binary import (
            MockMemcacheBinaryServer,
        )

        srv = MockMemcacheBinaryServer()
        assert srv.start()
        yield srv
        srv.stop()

    def test_full_op_matrix(self, binary_server):
        from incubator_brpc_tpu.protocol.memcache_binary import (
            MemcacheBinaryClient,
        )

        c = MemcacheBinaryClient(f"127.0.0.1:{binary_server.port}")
        assert c.set("k", b"v1", flags=42)
        assert c.get("k") == b"v1"
        assert c.get("missing") is None
        assert not c.add("k", b"nope")        # exists
        assert c.add("k2", b"fresh")
        assert c.replace("k", b"v2")
        assert not c.replace("ghost", b"x")   # missing
        assert c.append("k", b"+tail")
        assert c.prepend("k", b"head+")
        assert c.get("k") == b"head+v2+tail"
        assert c.set("n", b"10")
        assert c.incr("n", 5) == 15
        assert c.decr("n", 3) == 12
        assert c.incr("missing") is None
        assert c.delete("k") and not c.delete("k")
        assert "tbrpc" in c.version()
        got = c.get_multi("k2", "n", "missing")
        assert got == {"k2": b"fresh", "n": b"12"}
        assert c.flush_all()
        assert c.get("k2") is None
        c.close()

    def test_sasl_auth(self):
        from incubator_brpc_tpu.protocol.memcache_binary import (
            MemcacheBinaryClient,
            MemcacheBinaryError,
            MockMemcacheBinaryServer,
        )

        srv = MockMemcacheBinaryServer(password="hunter2")
        assert srv.start()
        try:
            c = MemcacheBinaryClient(
                f"127.0.0.1:{srv.port}", password="hunter2"
            )
            assert c.set("a", b"1") and c.get("a") == b"1"
            c.close()
            with pytest.raises(MemcacheBinaryError):
                MemcacheBinaryClient(
                    f"127.0.0.1:{srv.port}", password="wrong"
                )
            # unauthenticated commands refused
            plain = MemcacheBinaryClient(f"127.0.0.1:{srv.port}")
            with pytest.raises(MemcacheBinaryError):
                plain.get("a")
            plain.close()
        finally:
            srv.stop()
