"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 —
multi-node behavior is validated in-process, the reference's loopback-test
shape; here the 'loopback' is xla_force_host_platform_device_count).

Note: this machine's sitecustomize registers the axon TPU plugin and calls
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start, so
setting JAX_PLATFORMS in the environment is NOT enough — we must override the
config after importing jax (backends are still uninitialized at that point).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long multi-process orchestrations (tier-1 runs -m 'not slow')",
    )


@pytest.fixture
def tuned_flags():
    """Snapshot/restore any process-global flag a test retunes — shared
    by every test file that tweaks flags (rpcz, telemetry, auto_cl...),
    so one implementation owns the restore discipline."""
    from incubator_brpc_tpu.utils.flags import (
        flag_registry,
        set_flag_unchecked,
    )

    touched = {}

    def tune(name, value):
        if name not in touched:
            touched[name] = flag_registry.get(name)
        set_flag_unchecked(name, value)

    yield tune
    for name, value in touched.items():
        set_flag_unchecked(name, value)
