"""FabricNet end-to-end: full train step over the 8-device virtual mesh,
plus single-device equivalence (sharded forward == unsharded math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from incubator_brpc_tpu.models import fabricnet
from incubator_brpc_tpu.parallel.mesh import default_axis_sizes, make_fabric_mesh


def _setup(n_devices, axis_sizes=None, **cfg_kw):
    mesh = make_fabric_mesh(n_devices, axis_sizes=axis_sizes)
    sizes = dict(mesh.shape)
    defaults = dict(
        d_model=16,
        d_ff=32,
        d_expert=16,
        experts_per_rank=2,
        batch=max(8, sizes["dp"] * sizes["ep"] * 4),
        seq=max(8, sizes["sp"] * 8),
        microbatches=2,
    )
    defaults.update(cfg_kw)
    cfg = fabricnet.FabricNetConfig(**defaults)
    fabricnet.validate_config(cfg, mesh)
    params = fabricnet.init_params(cfg, mesh)
    x, y = fabricnet.make_batch(cfg, mesh)
    return cfg, mesh, params, x, y


def test_forward_shapes_single_device():
    cfg, mesh, params, x, _ = _setup(1)
    out = fabricnet.make_forward_step(cfg, mesh)(params, x)
    assert out.shape == (cfg.batch, cfg.seq, cfg.d_model)
    assert np.isfinite(np.asarray(out)).all()


def test_train_step_decreases_loss_8dev():
    cfg, mesh, params, x, y = _setup(8)
    step = fabricnet.make_train_step(cfg, mesh)
    losses = []
    for _ in range(8):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_sharded_forward_matches_single_device():
    """The 8-way sharded forward must compute the same function as the
    1-device mesh (collective lowerings preserve semantics)."""
    cfg, mesh1, params1, x1, _ = _setup(1, batch=8, seq=8)
    out1 = fabricnet.make_forward_step(cfg, mesh1)(params1, x1)

    # pp/ep stay 1 so param shapes match the 1-device init; shard dp/tp/sp
    mesh8 = make_fabric_mesh(
        8, axis_sizes={"dp": 2, "pp": 1, "tp": 2, "sp": 2, "ep": 1}
    )
    fabricnet.validate_config(cfg, mesh8)
    # move identical params/batch onto the 8-device mesh shardings
    from jax.sharding import NamedSharding

    specs = fabricnet.param_specs(cfg.heads)
    params8 = {
        k: jax.device_put(np.asarray(v), NamedSharding(mesh8, specs[k]))
        for k, v in params1.items()
    }
    x8 = jax.device_put(np.asarray(x1), NamedSharding(mesh8, fabricnet.batch_specs()[0]))
    out8 = fabricnet.make_forward_step(cfg, mesh8)(params8, x8)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out8), rtol=2e-4, atol=2e-5)


def test_heads_zero_ring_mean_path():
    """The heads=0 fallback (ring-mean context instead of ring attention)
    must keep training — otherwise the branch rots untested."""
    import jax

    from incubator_brpc_tpu.parallel.mesh import make_fabric_mesh

    mesh = make_fabric_mesh(
        8, axis_sizes={"dp": 2, "pp": 1, "tp": 2, "sp": 2, "ep": 1}
    )
    cfg = fabricnet.FabricNetConfig(heads=0)
    fabricnet.validate_config(cfg, mesh)
    params = fabricnet.init_params(cfg, mesh)
    assert "wqkv" not in params
    x, y = fabricnet.make_batch(cfg, mesh)
    step = fabricnet.make_train_step(cfg, mesh)
    params, l0 = step(params, x, y)
    for _ in range(5):
        params, loss = step(params, x, y)
    assert float(loss) < float(l0)


class TestOverlapSchedule:
    """The T3 microbatch overlap schedule (ISSUE 13): serialized and
    overlapped are the SAME sliced dataflow differing only in the
    optimization_barrier, so loss AND updated params must match
    BITWISE; both must agree with the fused (pre-overlap) path to
    float rounding."""

    CONFIGS = [
        # (axis_sizes, cfg overrides) — two genuinely different fabrics:
        # the pp=2/dp=2/tp=2 default spread, and a dp/tp/sp mesh with the
        # ring-attention sequence axis live
        (None, {}),
        ({"dp": 2, "pp": 1, "tp": 2, "sp": 2, "ep": 1}, {}),
    ]

    @pytest.mark.parametrize("axis_sizes,cfg_kw", CONFIGS)
    def test_overlapped_bit_identical_to_serialized(
        self, axis_sizes, cfg_kw
    ):
        cfg, mesh, params, x, y = _setup(8, axis_sizes, **cfg_kw)
        ser = fabricnet.make_train_step(cfg, mesh, schedule="serialized")
        ovl = fabricnet.make_train_step(cfg, mesh, schedule="overlapped")

        def run(step):
            p = jax.tree_util.tree_map(lambda a: a.copy(), params)
            p2, loss = step(p, x, y)
            return p2, np.asarray(loss)

        ps, ls = run(ser)
        po, lo = run(ovl)
        assert ls.tobytes() == lo.tobytes(), "loss diverged"
        for k in ps:
            assert (
                np.asarray(ps[k]).tobytes() == np.asarray(po[k]).tobytes()
            ), f"param {k} diverged between schedules"

    @pytest.mark.parametrize("axis_sizes,cfg_kw", CONFIGS)
    def test_sliced_schedule_matches_fused_grads(
        self, axis_sizes, cfg_kw
    ):
        """The sliced schedule's accumulated per-leaf psums compute the
        same gradients as the fused boundary transpose — only summation
        order differs (float rounding, not math)."""
        cfg, mesh, params, x, y = _setup(8, axis_sizes, **cfg_kw)
        fused = fabricnet.make_train_step(cfg, mesh)
        ovl = fabricnet.make_train_step(cfg, mesh, schedule="overlapped")

        def run(step):
            p = jax.tree_util.tree_map(lambda a: a.copy(), params)
            p2, loss = step(p, x, y)
            return p2, float(loss)

        pf, lf = run(fused)
        po, lo = run(ovl)
        assert abs(lf - lo) < 1e-6
        for k in pf:
            np.testing.assert_allclose(
                np.asarray(pf[k]), np.asarray(po[k]),
                rtol=2e-4, atol=2e-5, err_msg=f"param {k}",
            )

    def test_overlapped_schedule_trains(self):
        cfg, mesh, params, x, y = _setup(8)
        step = fabricnet.make_train_step(cfg, mesh, schedule="overlapped")
        losses = []
        for _ in range(6):
            params, loss = step(params, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    def test_unknown_schedule_rejected(self):
        cfg, mesh, _p, _x, _y = _setup(1)
        with pytest.raises(ValueError, match="schedule"):
            fabricnet.make_train_step(cfg, mesh, schedule="eager")


def test_graft_entry_dryrun():
    # the dryrun's multi-controller gate needs real cross-process
    # collectives; probe that capability in seconds instead of letting
    # the pair burn its whole handshake deadline on a backend without it
    # (the driver still runs dryrun_multichip directly, probe-free)
    import pytest

    from incubator_brpc_tpu.transport.mc_worker import multiprocess_capable

    if not multiprocess_capable():
        pytest.skip("jax backend cannot run multi-process computations")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_single():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out, echo_resp = jax.jit(fn)(*args) if callable(fn) else (None, None)
    assert np.isfinite(np.asarray(out)).all()
    assert echo_resp.dtype == jnp.uint32
