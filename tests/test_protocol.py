"""tbus_std wire-protocol unit tests — the protocol-conformance shape of the
reference's suites (test/brpc_baidu_rpc_protocol_unittest pattern: call
parse/pack handlers directly on hand-built buffers)."""

from dataclasses import replace

import pytest

from incubator_brpc_tpu.protocol import tbus_std
from incubator_brpc_tpu.protocol.tbus_std import (
    FLAG_RESPONSE,
    HEADER_BYTES,
    Meta,
    ParseError,
    pack_frame,
    try_parse_frame,
)


def test_roundtrip_basic():
    meta = Meta(service="Echo", method="echo", log_id=7)
    wire = pack_frame(meta, b"hello", correlation_id=42)
    frame, consumed = try_parse_frame(wire)
    assert consumed == len(wire)
    assert frame.payload == b"hello"
    assert frame.attachment == b""
    assert frame.correlation_id == 42
    assert frame.meta.service == "Echo"
    assert frame.meta.method == "echo"
    assert frame.meta.log_id == 7
    assert not frame.is_response


def test_roundtrip_attachment():
    meta = Meta(service="S", method="m")
    wire = pack_frame(meta, b"payload", correlation_id=1, attachment=b"ATTACH")
    frame, _ = try_parse_frame(wire)
    assert frame.payload == b"payload"
    assert frame.attachment == b"ATTACH"


def test_pack_does_not_mutate_caller_meta():
    meta = Meta(service="S", method="m")
    pack_frame(meta, b"p", correlation_id=1, attachment=b"1234")
    assert meta.attachment_size == 0


def test_attachment_size_is_authoritative_per_frame():
    # Reusing a Meta whose attachment_size was set by a previous frame must
    # not carve a phantom attachment from a frame with no attachment.
    stale = Meta(service="S", method="m", attachment_size=4)
    wire = pack_frame(stale, b"payload!", correlation_id=2, attachment=b"")
    frame, _ = try_parse_frame(wire)
    assert frame.payload == b"payload!"
    assert frame.attachment == b""


def test_attachment_without_meta_rejected():
    with pytest.raises(ValueError):
        pack_frame(None, b"p", correlation_id=1, attachment=b"x")


def test_resumable_parse_contract():
    # (None, 0) on short reads at every split point — the InputMessenger
    # CutInputMessage contract (reference input_messenger.cpp:60-129).
    meta = Meta(service="S", method="m")
    wire = pack_frame(meta, b"x" * 100, correlation_id=3)
    for cut in (0, 1, HEADER_BYTES - 1, HEADER_BYTES, len(wire) - 1):
        frame, consumed = try_parse_frame(wire[:cut])
        assert frame is None and consumed == 0
    frame, consumed = try_parse_frame(wire + b"tail")
    assert frame is not None and consumed == len(wire)


def test_bad_magic_and_crc_raise():
    from incubator_brpc_tpu.utils.flags import set_flag

    meta = Meta(service="S", method="m")
    with pytest.raises(ParseError):
        try_parse_frame(b"\x00" * HEADER_BYTES)
    # the crc always covers the meta (routing info)
    wire = bytearray(pack_frame(meta, b"abc", correlation_id=4))
    wire[HEADER_BYTES + 2] ^= 0xFF  # corrupt a meta byte
    with pytest.raises(ParseError):
        try_parse_frame(bytes(wire))
    # payload bytes are covered only under tbus_body_crc (the default
    # trusts the transport, like baidu_std which carries no checksum)
    set_flag("tbus_body_crc", True)
    try:
        wire = bytearray(pack_frame(meta, b"abc", correlation_id=4))
        wire[-1] ^= 0xFF  # corrupt body
        with pytest.raises(ParseError):
            try_parse_frame(bytes(wire))
    finally:
        set_flag("tbus_body_crc", False)


def test_parse_frame_iobuf_matches_bytes_path():
    from incubator_brpc_tpu.iobuf import IOBuf
    from incubator_brpc_tpu.native import NATIVE_AVAILABLE
    from incubator_brpc_tpu.protocol.tbus_std import parse_frame_iobuf

    if not NATIVE_AVAILABLE:
        pytest.skip("native runtime unavailable")
    meta = Meta(service="S", method="m", log_id=9)
    wire = pack_frame(meta, b"pay" * 1000, correlation_id=(7 << 32) | 5,
                      attachment=b"att" * 10)
    ref, ref_consumed = try_parse_frame(wire)
    buf = IOBuf()
    # split the frame across appends so the native cut walks multiple blocks
    buf.append(wire[:10])
    buf.append(wire[10:200])
    buf.append(wire[200:])
    buf.append(b"nextframe-prefix")
    frame, consumed = parse_frame_iobuf(buf)
    assert consumed == ref_consumed
    assert frame.meta.service == "S" and frame.meta.log_id == 9
    assert frame.payload == ref.payload
    assert frame.attachment == ref.attachment
    assert frame.correlation_id == (7 << 32) | 5
    assert len(buf) == len(b"nextframe-prefix")  # only the frame consumed


def test_parse_frame_iobuf_incomplete_and_corrupt():
    from incubator_brpc_tpu.iobuf import IOBuf
    from incubator_brpc_tpu.native import NATIVE_AVAILABLE
    from incubator_brpc_tpu.protocol.tbus_std import parse_frame_iobuf

    if not NATIVE_AVAILABLE:
        pytest.skip("native runtime unavailable")
    wire = pack_frame(Meta(service="S", method="m"), b"xyz", correlation_id=1)
    for cut in (1, HEADER_BYTES - 1, HEADER_BYTES, len(wire) - 1):
        buf = IOBuf()
        buf.append(wire[:cut])
        assert parse_frame_iobuf(buf) == (None, 0)
        assert len(buf) == cut  # nothing consumed on incomplete
    corrupt = bytearray(wire)
    corrupt[HEADER_BYTES + 1] ^= 0xFF  # meta byte: always crc-covered
    buf = IOBuf()
    buf.append(bytes(corrupt))
    with pytest.raises(ParseError):
        parse_frame_iobuf(buf)
    buf = IOBuf()
    buf.append(b"\x00" * HEADER_BYTES)
    with pytest.raises(ParseError):
        parse_frame_iobuf(buf)


def test_response_flag_and_error_code():
    wire = pack_frame(
        Meta(error_text="boom"), b"", correlation_id=5,
        flags=FLAG_RESPONSE, error_code=2001,
    )
    frame, _ = try_parse_frame(wire)
    assert frame.is_response
    assert frame.error_code == 2001
    assert frame.meta.error_text == "boom"


def test_64bit_correlation_id():
    cid = (123 << 32) | 456
    wire = pack_frame(Meta(), b"", correlation_id=cid)
    frame, _ = try_parse_frame(wire)
    assert frame.correlation_id == cid


def test_meta_roundtrip_defaults_elided():
    m = Meta(service="S", method="m", extra={"k": 1})
    m2 = Meta.from_bytes(m.to_bytes())
    assert replace(m2, extra={}) == replace(m, extra={})
    assert m2.extra == {"k": 1}
    assert Meta.from_bytes(Meta().to_bytes()) == Meta()
