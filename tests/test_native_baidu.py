"""Native baidu_std (PRPC) on the C++ plane (src/tbnet).

The canonical wire protocol cut, dispatched and packed without the
interpreter — and proven byte-exact against the Python codec
(protocol/baidu_std.py) in BOTH directions, the interop oracle SURVEY §7
step 4 calls for. Covers: C++ server responses vs pack_response (success,
attachment, error), C++ client frames vs pack_request, compress_type
passthrough over the Python route, padded-varint acceptance, native↔Python
cross-client echo over one port, and the pipelined PRPC pump.
"""

from __future__ import annotations

import socket
import struct
import threading

import pytest

from incubator_brpc_tpu.protocol import baidu_std
from incubator_brpc_tpu.protocol.tbus_std import Meta
from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    native_echo,
    native_nop,
)
from incubator_brpc_tpu.transport import native_plane
from incubator_brpc_tpu.utils.status import ErrorCode

pytestmark = pytest.mark.skipif(
    not native_plane.NET_AVAILABLE, reason="native runtime unavailable"
)


@pytest.fixture
def native_server():
    created = []

    def make(services=None, options=None):
        opts = options or ServerOptions(native_plane=True, usercode_inline=True)
        opts.native_plane = True
        srv = Server(opts)
        for name, handlers in (services or {}).items():
            srv.add_service(name, handlers)
        created.append(srv)
        assert srv.start(0)
        assert srv._native_plane is not None, "native plane did not engage"
        return srv

    yield make
    for srv in created:
        srv.stop()


def _read_prpc_frame(sock: socket.socket, buf: bytes = b"") -> bytes:
    """Read exactly one PRPC frame off a raw socket."""
    while True:
        if len(buf) >= 12:
            total = 12 + struct.unpack(">I", buf[4:8])[0]
            if len(buf) >= total:
                return buf[:total]
        data = sock.recv(65536)
        assert data, "connection closed mid-frame"
        buf += data


class TestServerWireExactness:
    """C++-packed PRPC responses must be byte-identical to what
    protocol/baidu_std.py's pack_response emits for the same fields."""

    def _roundtrip(self, port: int, wire: bytes) -> bytes:
        s = socket.create_connection(("127.0.0.1", port))
        try:
            s.sendall(wire)
            return _read_prpc_frame(s)
        finally:
            s.close()

    def test_native_echo_response_byte_exact(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        req = baidu_std.pack_request(
            Meta(service="svc", method="echo"), b"payload", correlation_id=77
        )
        resp = self._roundtrip(srv.port, req)
        assert resp == baidu_std.pack_response(
            None, b"payload", correlation_id=77
        )
        # answered in C++, not via the frame callback
        assert srv._native_plane.stats()["native_reqs"] >= 1
        assert srv._native_plane.stats()["cb_frames"] == 0

    def test_native_echo_with_attachment_byte_exact(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        att = b"AT" * 500
        req = baidu_std.pack_request(
            Meta(service="svc", method="echo"), b"pp", correlation_id=3,
            attachment=att,
        )
        resp = self._roundtrip(srv.port, req)
        assert resp == baidu_std.pack_response(
            None, b"pp", correlation_id=3, attachment=att
        )

    def test_native_nop_response_byte_exact(self, native_server):
        srv = native_server({"svc": {"nop": native_nop}})
        req = baidu_std.pack_request(
            Meta(service="svc", method="nop"), b"ignored", correlation_id=9
        )
        resp = self._roundtrip(srv.port, req)
        assert resp == baidu_std.pack_response(None, b"", correlation_id=9)

    def test_error_response_decode_reencode_stable(self, native_server):
        # unknown method: the response must decode with the Python codec
        # and re-encode to the identical bytes (pack path exactness for
        # error responses, whatever plane answered)
        srv = native_server({"svc": {"echo": native_echo}})
        req = baidu_std.pack_request(
            Meta(service="svc", method="nope"), b"", correlation_id=11
        )
        resp = self._roundtrip(srv.port, req)
        frame, consumed = baidu_std.try_parse_frame(resp)
        assert consumed == len(resp)
        assert frame.error_code == ErrorCode.ENOMETHOD
        assert frame.correlation_id == 11
        assert frame.meta.error_text
        again = baidu_std.pack_response(
            frame.meta,
            frame.payload,
            frame.correlation_id,
            error_code=frame.error_code,
        )
        assert again == resp

    def test_padded_varint_correlation_id_accepted(self, native_server):
        # non-minimal varints are wire-legal proto2; the C++ parser (and
        # the pump's fixed-width cid template relies on this) must accept
        # them and echo the decoded cid back minimally encoded
        srv = native_server({"svc": {"echo": native_echo}})
        sub = baidu_std.encode_request_submeta("svc", "echo")
        cid = 5
        cid10 = bytes(
            ((cid >> (7 * i)) & 0x7F) | 0x80 for i in range(9)
        ) + bytes([(cid >> 63) & 0x7F])
        meta = b"\x0a" + bytes([len(sub)]) + sub + b"\x20" + cid10
        wire = (
            b"PRPC"
            + struct.pack(">II", len(meta) + 3, len(meta))
            + meta
            + b"abc"
        )
        resp = self._roundtrip(srv.port, wire)
        frame, _ = baidu_std.try_parse_frame(resp)
        assert frame.correlation_id == 5
        assert frame.payload == b"abc"

    def test_unknown_fixed_width_field_routes_to_python(self, native_server):
        # fixed64/fixed32 are legal proto2 the RpcMeta tables don't use:
        # the C++ scanner must route such frames to the Python plane (whose
        # _walk_fields skips them), not kill the connection
        srv = native_server({"svc": {"echo": native_echo}})
        sub = baidu_std.encode_request_submeta("svc", "echo")
        meta = (
            b"\x0a" + bytes([len(sub)]) + sub
            + b"\x20\x08"  # correlation_id = 8
            + b"\x79" + b"\x00" * 8  # field 15, wire type 1 (fixed64)
        )
        wire = (
            b"PRPC"
            + struct.pack(">II", len(meta) + 2, len(meta))
            + meta
            + b"hi"
        )
        resp = self._roundtrip(srv.port, wire)
        frame, _ = baidu_std.try_parse_frame(resp)
        assert frame.correlation_id == 8
        assert frame.error_code == 0
        assert frame.payload == b"hi"
        assert srv._native_plane.stats()["cb_frames"] >= 1

    def test_overflowing_field_length_kills_conn_only(self, native_server):
        # a length-delimited meta field claiming a ~2^64 length must fail
        # the bounds check (subtraction form), not wrap past it into an
        # out-of-bounds read — the connection dies, the server survives
        srv = native_server({"svc": {"echo": native_echo}})
        evil = b"\x0a" + b"\xff" * 9 + b"\x01"  # field 1, len ≈ 2^64-1
        wire = b"PRPC" + struct.pack(">II", len(evil) + 2, len(evil)) + evil + b"xx"
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(wire)
        s.settimeout(5)
        assert s.recv(1) == b""  # killed cleanly
        s.close()
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        c = ch.call_method("svc", "echo", b"alive")
        assert c.ok() and c.response_payload == b"alive"

    def test_garbage_after_prpc_magic_kills_conn_only(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        s = socket.create_connection(("127.0.0.1", srv.port))
        # meta_size > body_size: unrecoverable
        s.sendall(b"PRPC" + struct.pack(">II", 1, 99))
        s.settimeout(5)
        assert s.recv(1) == b""  # server closed the connection
        s.close()
        # the server survives and keeps answering
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        c = ch.call_method("svc", "echo", b"still-alive")
        assert c.ok(), c.error_text
        assert c.response_payload == b"still-alive"


class TestClientWireExactness:
    """The native client's PRPC frames must be byte-identical to
    pack_request for the same service/method/payload/attachment."""

    def _capture_one_call(self, payload: bytes, attachment: bytes, **ids):
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        got = {}

        def server():
            conn, _ = lst.accept()
            req = _read_prpc_frame(conn)
            got["req"] = req
            frame, _ = baidu_std.try_parse_frame(req)
            conn.sendall(
                baidu_std.pack_response(None, b"ok", frame.correlation_id)
            )
            conn.close()

        t = threading.Thread(target=server)
        t.start()
        nch = native_plane.NativeClientChannel(
            "127.0.0.1", port, protocol="baidu_std"
        )
        try:
            # the first cid a channel mints is 1 in its own shard's cid
            # partition (top 8 bits carry the client reactor shard pinned
            # at connect) — the comparison frame must use the same cid
            first_cid = (nch.reactor << 56) | 1
            rc, ec, meta, body = nch.call(
                "svc", "mth", payload, attachment, timeout_ms=5000, **ids
            )
            t.join(timeout=10)
        finally:
            nch.close()
            lst.close()
        assert rc >= 0 and ec == 0, (rc, ec)
        assert body.to_bytes() == b"ok"
        return got["req"], first_cid

    # every call stamps its remaining deadline budget on the wire
    # (RpcRequestMeta.timeout_ms, field 8) — the expected frames carry
    # the capture helper's timeout_ms=5000

    def test_request_frame_byte_exact(self):
        req, cid = self._capture_one_call(b"the-payload", b"")
        assert req == baidu_std.pack_request(
            Meta(service="svc", method="mth", timeout_ms=5000),
            b"the-payload",
            correlation_id=cid,
        )

    def test_request_frame_with_attachment_byte_exact(self):
        att = b"ATTACH" * 20
        req, cid = self._capture_one_call(b"pp", att)
        assert req == baidu_std.pack_request(
            Meta(service="svc", method="mth", timeout_ms=5000), b"pp",
            correlation_id=cid, attachment=att,
        )

    def test_traced_request_carries_dapper_ids_byte_exact(self):
        # log_id + trace/span ids must reach the wire exactly as the
        # Python packer sends them — the server parents its rpcz span
        # into the client's trace off these fields
        ids = dict(log_id=42, trace_id=0xDEADBEEF01, span_id=7)
        req, cid = self._capture_one_call(b"pp", b"", **ids)
        assert req == baidu_std.pack_request(
            Meta(service="svc", method="mth", timeout_ms=5000, **ids),
            b"pp", correlation_id=cid,
        )


class TestCrossClientEcho:
    """native↔Python cross-client echo over ONE port, both wire protocols
    live on the same native server (the reference's one-port-every-
    protocol story, input_messenger.cpp:60-129)."""

    def test_native_and_python_clients_one_port(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        port = srv.port
        # native client, baidu_std wire
        ch_native = Channel()
        assert ch_native.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        # pure-Python client, baidu_std wire (Socket reactor + Python codec)
        ch_py = Channel()
        assert ch_py.init(
            f"127.0.0.1:{port}", options=ChannelOptions(protocol="baidu_std")
        )
        # native client, tbus_std wire — the same server answers each
        # connection in its own protocol
        ch_tbus = Channel()
        assert ch_tbus.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        for ch, tag in ((ch_native, b"n"), (ch_py, b"p"), (ch_tbus, b"t")):
            c = ch.call_method("svc", "echo", b"x-" + tag, attachment=b"A" + tag)
            assert c.ok(), c.error_text
            assert c.response_payload == b"x-" + tag
            assert c.response_attachment == b"A" + tag
        # ALL three echoes were served without the interpreter — the
        # pure-Python client's frames carry rpcz trace ids, and the C++
        # parser now decodes them natively (trace context is a fast-path
        # citizen; the drain parents the server spans).  Nobody was
        # handed off: baidu_std is a native protocol.
        stats = srv._native_plane.stats()
        assert stats["native_reqs"] >= 3
        assert stats["cb_frames"] == 0
        assert stats["handoffs"] == 0

    def test_native_baidu_client_against_python_server(self):
        # the native client's PRPC bytes parse on the pure-Python plane
        # (protocol scan + baidu_std codec) and its response parses back
        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        try:
            nch = native_plane.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                rc, ec, meta, body = nch.call(
                    "svc", "echo", b"cross", b"att-bytes", timeout_ms=5000
                )
                assert rc >= 0 and ec == 0, (rc, ec)
                m = nch.decode_resp_meta(meta)
                blen = len(body)
                assert m.attachment_size == len(b"att-bytes")
                assert body.to_bytes(blen - m.attachment_size) == b"cross"
                assert (
                    body.to_bytes(
                        m.attachment_size, pos=blen - m.attachment_size
                    )
                    == b"att-bytes"
                )
            finally:
                nch.close()
        finally:
            srv.stop()


class TestPythonRouteSemantics:
    def test_python_handler_error_text_over_prpc(self, native_server):
        def boom(cntl, req):
            cntl.set_failed(ErrorCode.EINTERNAL, "prpc boom")
            return b""

        srv = native_server({"svc": {"boom": boom}})
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        c = ch.call_method("svc", "boom", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.EINTERNAL
        assert "prpc boom" in c.error_text

    def test_compress_type_passthrough(self, native_server):
        # a compressed request from the PURE-PYTHON client still round-
        # trips: its frames carry rpcz trace ids, which route them to the
        # Python plane regardless of compression — the Python route's
        # codecs must keep working now that the native plane has its own
        # (TestNativeCompressAuth covers the native codec table)
        from incubator_brpc_tpu.rpc import Controller

        srv = native_server({"svc": {"echo": lambda cntl, req: req}})
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(protocol="baidu_std"),
        )
        cntl = Controller()
        cntl.compress_type = "gzip"
        payload = b"z" * 4096
        c = ch.call_method("svc", "echo", payload, cntl=cntl)
        assert c.ok(), c.error_text
        assert c.response_payload == payload
        assert srv._native_plane.stats()["cb_frames"] >= 1

    def test_correlation_ids_interleave(self, native_server):
        # concurrent callers over ONE shared PRPC connection: the varint
        # correlation ids must land each response on its own caller
        srv = native_server({"svc": {"echo": native_echo}})
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        errs = []

        def worker(i):
            for j in range(25):
                body = b"w%d-%d" % (i, j)
                c = ch.call_method("svc", "echo", body)
                if c.failed() or c.response_payload != body:
                    errs.append((i, j, c.error_text, c.response_payload))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:3]


class TestPrpcFuzzRobustness:
    """Fuzz-shaped adversarial wire input against the C++ PRPC cutter:
    truncated headers, oversized body_size, garbage and overflowing
    RpcMeta varints, mid-frame connection close. The invariant is always
    the same — the SERVER survives (no crash, no wedge): bad frames cost
    at most their own connection (clean teardown) or route harmlessly to
    the Python plane; the port keeps answering well-formed traffic."""

    def _assert_still_serving(self, srv):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        c = ch.call_method("svc", "echo", b"probe")
        assert c.ok(), c.error_text
        assert c.response_payload == b"probe"

    def _open(self, srv):
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.settimeout(5)
        return s

    def test_truncated_header_then_close(self, native_server):
        # every prefix of a valid 12-byte header, connection closed
        # mid-header: the cutter must just drop the conn state
        srv = native_server({"svc": {"echo": native_echo}})
        whole = b"PRPC" + struct.pack(">II", 10, 4)
        for cut in range(1, len(whole)):
            s = self._open(srv)
            s.sendall(whole[:cut])
            s.close()
        self._assert_still_serving(srv)

    def test_oversized_body_size_rejected(self, native_server):
        # body_size beyond the configured max: the connection dies
        # without the server ever allocating/buffering the claimed blob
        srv = native_server({"svc": {"echo": native_echo}})
        s = self._open(srv)
        s.sendall(b"PRPC" + struct.pack(">II", 0xFFFFFFF0, 8))
        assert s.recv(1) == b""  # killed cleanly
        s.close()
        self._assert_still_serving(srv)

    def test_garbage_meta_varints(self, native_server):
        # RpcMeta bytes that are pure garbage: unknown tags, truncated
        # varints, wire-type soup — at most the connection dies; several
        # of these decode as unknown-field frames and route to Python,
        # which answers ENOSERVICE/EREQUEST instead of crashing
        srv = native_server({"svc": {"echo": native_echo}})
        metas = [
            b"\xff" * 16,  # unterminated varint tag run
            b"\x0a\xff",  # length-delimited field, truncated length
            b"\x20" + b"\x80" * 11,  # cid varint longer than 10 bytes
            b"\x07\x01\x02",  # wire type 7 (invalid)
            bytes(range(1, 32)),  # tag/wire-type soup
        ]
        for meta in metas:
            s = self._open(srv)
            wire = b"PRPC" + struct.pack(">II", len(meta) + 2, len(meta))
            s.sendall(wire + meta + b"xx")
            try:
                s.recv(4096)  # server may answer an error or close; both fine
            except (TimeoutError, socket.timeout):
                pass
            s.close()
        self._assert_still_serving(srv)

    def test_overflowing_varint_field_length(self, native_server):
        # a nested submeta whose length varint overflows 64 bits: bounds
        # math must not wrap into an out-of-bounds read
        srv = native_server({"svc": {"echo": native_echo}})
        evil = b"\x0a" + b"\xff" * 10 + b"\x7f"
        s = self._open(srv)
        s.sendall(b"PRPC" + struct.pack(">II", len(evil) + 1, len(evil)) + evil + b"y")
        assert s.recv(1) == b""
        s.close()
        self._assert_still_serving(srv)

    def test_mid_frame_close_after_header(self, native_server):
        # header promises 1000 body bytes; the peer dies after 100: the
        # half-read frame must be discarded with the connection
        srv = native_server({"svc": {"echo": native_echo}})
        sub = baidu_std.encode_request_submeta("svc", "echo")
        meta = b"\x0a" + bytes([len(sub)]) + sub + b"\x20\x05"
        s = self._open(srv)
        s.sendall(b"PRPC" + struct.pack(">II", len(meta) + 1000, len(meta)))
        s.sendall(meta + b"z" * 100)  # 900 bytes short
        s.close()
        self._assert_still_serving(srv)

    def test_mid_frame_close_inside_meta(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        s = self._open(srv)
        s.sendall(b"PRPC" + struct.pack(">II", 600, 500) + b"\x0a\x10garb")
        s.close()
        self._assert_still_serving(srv)

    def test_interleaved_garbage_and_valid_connections(self, native_server):
        # a hostile client must not degrade service for a well-behaved
        # neighbor connection open at the same time
        srv = native_server({"svc": {"echo": native_echo}})
        good = Channel()
        assert good.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        assert good.call_method("svc", "echo", b"a").ok()
        for i in range(8):
            s = self._open(srv)
            s.sendall(b"PRPC" + struct.pack(">II", 0xFFFFFFF0, i))
            s.close()
            c = good.call_method("svc", "echo", b"b%d" % i)
            assert c.ok(), c.error_text
            assert c.response_payload == b"b%d" % i


class TestNativeCompressAuth:
    """Production-shaped PRPC traffic on the C++ plane: compressed and/or
    authenticated frames are cut, verified, decompressed, dispatched and
    recompressed natively — and the bytes answered are IDENTICAL to what
    the pure-Python plane answers for the same wire input (the PR 2
    byte-identity discipline extended to codecs and auth), including the
    ERPCAUTH reject frame and the deterministic decompress errors."""

    TOKEN = "sekrit-token"

    def _twin_roundtrip(self, wire: bytes, auth=None, services=None):
        """Send the SAME wire bytes to a native-plane server and a
        pure-Python server (same services/auth) and return both raw
        responses."""
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        services = services or {"svc": {"echo": native_echo}}
        out = []
        for native in (True, False):
            srv = Server(
                ServerOptions(
                    native_plane=native, usercode_inline=True, auth=auth
                )
            )
            for name, handlers in services.items():
                srv.add_service(name, handlers)
            assert srv.start(0)
            try:
                if native:
                    assert srv._native_plane is not None
                s = socket.create_connection(("127.0.0.1", srv.port))
                s.settimeout(10)
                try:
                    s.sendall(wire)
                    out.append(_read_prpc_frame(s))
                finally:
                    s.close()
                if native:
                    out.append(srv._native_plane.stats())
            finally:
                srv.stop()
        return out  # [native_resp, native_stats, python_resp]

    def _auth(self):
        from incubator_brpc_tpu.rpc import TokenAuthenticator

        return TokenAuthenticator([self.TOKEN])

    @pytest.mark.parametrize("codec", ["snappy", "gzip", "zlib1"])
    def test_compressed_authed_echo_byte_identical(self, codec):
        from incubator_brpc_tpu.protocol import compress as compress_mod

        payload = b"compressible payload " * 300
        meta = Meta(service="svc", method="echo", compress=codec)
        meta.extra["auth"] = self.TOKEN
        wire = baidu_std.pack_request(
            meta, compress_mod.compress(codec, payload), correlation_id=77
        )
        native_resp, stats, python_resp = self._twin_roundtrip(
            wire, auth=self._auth()
        )
        assert native_resp == python_resp
        # the native plane answered without the interpreter
        assert stats["native_reqs"] >= 1 and stats["cb_frames"] == 0
        frame, _ = baidu_std.try_parse_frame(native_resp)
        assert frame.error_code == 0
        assert frame.meta.compress == codec
        assert compress_mod.decompress(codec, frame.payload) == payload

    def test_compressed_echo_with_attachment_byte_identical(self):
        from incubator_brpc_tpu.protocol import compress as compress_mod

        payload, att = b"pp" * 600, b"ATTACH" * 40
        meta = Meta(service="svc", method="echo", compress="snappy")
        wire = baidu_std.pack_request(
            meta,
            compress_mod.compress("snappy", payload),
            correlation_id=5,
            attachment=att,
        )
        native_resp, stats, python_resp = self._twin_roundtrip(wire)
        assert native_resp == python_resp
        assert stats["cb_frames"] == 0
        frame, _ = baidu_std.try_parse_frame(native_resp)
        # the attachment travels uncompressed on both planes
        assert frame.attachment == att
        assert (
            compress_mod.decompress("snappy", frame.payload) == payload
        )

    def test_response_compression_floor_byte_identical(self):
        # a payload below native_compress_min_bytes answers UNCOMPRESSED
        # on both planes (the reference's response_compress_type
        # discipline) — and still byte-identically
        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.utils.flags import (
            get_flag,
            set_flag_unchecked,
        )

        old = get_flag("native_compress_min_bytes")
        set_flag_unchecked("native_compress_min_bytes", 1024)
        try:
            payload = b"tiny"
            meta = Meta(service="svc", method="echo", compress="snappy")
            wire = baidu_std.pack_request(
                meta,
                compress_mod.compress("snappy", payload),
                correlation_id=3,
            )
            native_resp, stats, python_resp = self._twin_roundtrip(wire)
            assert native_resp == python_resp
            assert stats["cb_frames"] == 0
            frame, _ = baidu_std.try_parse_frame(native_resp)
            assert frame.meta.compress == ""  # floor skipped the codec
            assert frame.payload == payload
        finally:
            set_flag_unchecked("native_compress_min_bytes", old)

    def test_erpcauth_reject_byte_identical(self):
        meta = Meta(service="svc", method="echo")
        meta.extra["auth"] = "wrong-token"
        wire = baidu_std.pack_request(meta, b"x", correlation_id=9)
        native_resp, stats, python_resp = self._twin_roundtrip(
            wire, auth=self._auth()
        )
        assert native_resp == python_resp
        assert stats["auth_rejects"] == 1
        frame, _ = baidu_std.try_parse_frame(native_resp)
        assert frame.error_code == ErrorCode.ERPCAUTH
        assert frame.meta.error_text == "Unauthorized"

    def test_unknown_compress_type_byte_identical(self):
        # out-of-enum compress_type: a clean EREQUEST with the same
        # deterministic text on both planes, connection survives
        rm = baidu_std.RpcMeta(
            service_name="svc",
            method_name="echo",
            compress_type=9,
            correlation_id=4,
        )
        wire = baidu_std.pack_frame(rm, b"zzzz")
        native_resp, _stats, python_resp = self._twin_roundtrip(wire)
        assert native_resp == python_resp
        frame, _ = baidu_std.try_parse_frame(native_resp)
        assert frame.error_code == ErrorCode.EREQUEST
        assert "unknown compression codec 'wire-9'" in frame.meta.error_text

    def test_decompress_ceiling_byte_identical(self):
        # a tiny bomb claiming a huge expansion rejects EREQUEST on both
        # planes with the identical ceiling text — server memory never
        # grows past max_decompress_bytes
        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.utils.flags import (
            get_flag,
            set_flag_unchecked,
        )

        old = get_flag("max_decompress_bytes")
        set_flag_unchecked("max_decompress_bytes", 4096)
        try:
            # gzip: a real deflate bomb (1 MB of zeros in ~1 KB).  snappy:
            # a stream whose length PREAMBLE claims 1 GiB — the decoder
            # must reject on the claim, before any expansion.
            gzip_bomb = compress_mod.compress("gzip", b"\0" * 1_000_000)
            assert len(gzip_bomb) < 5000  # it IS a bomb
            claim = 1 << 30
            pre = bytearray()
            v = claim
            while v >= 0x80:
                pre.append((v & 0x7F) | 0x80)
                v >>= 7
            pre.append(v)
            snappy_bomb = bytes(pre) + b"\x00a"  # 1-byte literal follows
            for codec, bomb in (("gzip", gzip_bomb), ("snappy", snappy_bomb)):
                meta = Meta(service="svc", method="echo", compress=codec)
                wire = baidu_std.pack_request(meta, bomb, correlation_id=6)
                native_resp, _stats, python_resp = self._twin_roundtrip(wire)
                assert native_resp == python_resp, codec
                frame, _ = baidu_std.try_parse_frame(native_resp)
                assert frame.error_code == ErrorCode.EREQUEST
                assert (
                    "exceeds max_decompress_bytes (4096)"
                    in frame.meta.error_text
                )
        finally:
            set_flag_unchecked("max_decompress_bytes", old)

    def test_ceiling_disabled_still_serves(self):
        # max_decompress_bytes=0 means UNLIMITED: the bounded-inflate
        # chunk math must not wrap to a zero budget (a wrap starves
        # inflate of output space and spins the reactor forever), and a
        # hostile snappy length claim must not become a giant up-front
        # allocation
        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.utils.flags import (
            get_flag,
            set_flag_unchecked,
        )

        old = get_flag("max_decompress_bytes")
        set_flag_unchecked("max_decompress_bytes", 0)
        try:
            payload = b"unlimited " * 400
            for codec in ("gzip", "snappy"):
                meta = Meta(service="svc", method="echo", compress=codec)
                wire = baidu_std.pack_request(
                    meta,
                    compress_mod.compress(codec, payload),
                    correlation_id=8,
                )
                native_resp, stats, python_resp = self._twin_roundtrip(wire)
                assert native_resp == python_resp, codec
                frame, _ = baidu_std.try_parse_frame(native_resp)
                assert frame.error_code == 0, codec
                assert (
                    compress_mod.decompress(codec, frame.payload) == payload
                )
            # snappy claiming 2^60 bytes: rejected as corrupt (the stream
            # is shorter than its claim), never allocated up front
            claim = 1 << 60
            pre = bytearray()
            v = claim
            while v >= 0x80:
                pre.append((v & 0x7F) | 0x80)
                v >>= 7
            pre.append(v)
            meta = Meta(service="svc", method="echo", compress="snappy")
            wire = baidu_std.pack_request(
                meta, bytes(pre) + b"\x00a", correlation_id=9
            )
            native_resp, _stats, python_resp = self._twin_roundtrip(wire)
            assert native_resp == python_resp
            frame, _ = baidu_std.try_parse_frame(native_resp)
            assert frame.error_code == ErrorCode.EREQUEST
        finally:
            set_flag_unchecked("max_decompress_bytes", old)

    def test_native_client_compressed_authed_request_byte_exact(self):
        # the C++ channel's compressed+authenticated request frames are
        # byte-identical to protocol/baidu_std.py pack_request — and the
        # credential stops stamping once the connection is proven
        from incubator_brpc_tpu.protocol import compress as compress_mod

        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        got = {}

        def server():
            conn, _ = lst.accept()
            buf = b""
            for key in ("first", "second"):
                req = _read_prpc_frame(conn, buf)
                buf = b""
                got[key] = req
                frame, _ = baidu_std.try_parse_frame(req)
                conn.sendall(
                    baidu_std.pack_response(None, b"ok", frame.correlation_id)
                )
            conn.close()

        t = threading.Thread(target=server)
        t.start()
        payload = b"data to compress " * 100
        comp = compress_mod.compress("snappy", payload)
        nch = native_plane.NativeClientChannel(
            "127.0.0.1", port, protocol="baidu_std"
        )
        try:
            nch.set_auth(self.TOKEN)
            shard = nch.reactor
            for _ in range(2):
                rc, ec, _m, body = nch.call(
                    "svc", "mth", comp, timeout_ms=5000, compress="snappy"
                )
                assert rc >= 0 and ec == 0, (rc, ec)
            t.join(timeout=10)
        finally:
            nch.close()
            lst.close()
        m1 = Meta(service="svc", method="mth", compress="snappy",
                  timeout_ms=5000)
        m1.extra["auth"] = self.TOKEN
        assert got["first"] == baidu_std.pack_request(
            m1, comp, correlation_id=(shard << 56) | 1
        )
        # proven connection: the second frame carries NO credential
        m2 = Meta(service="svc", method="mth", compress="snappy",
                  timeout_ms=5000)
        assert got["second"] == baidu_std.pack_request(
            m2, comp, correlation_id=(shard << 56) | 2
        )

    def test_python_authenticator_trampoline(self, native_server):
        # an arbitrary Python Authenticator still guards the native
        # plane: the verifier crosses into the interpreter ONCE per
        # connection (callback deferral, not the frame route — cb_frames
        # stays 0) and the verdict caches on the conn
        from incubator_brpc_tpu.rpc import (
            ServerOptions,
            SharedSecretAuthenticator,
        )

        auth = SharedSecretAuthenticator("shh", identity="press")
        srv = native_server(
            {"svc": {"echo": native_echo}},
            options=ServerOptions(
                native_plane=True, usercode_inline=True, auth=auth
            ),
        )
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(
                native_plane=True,
                protocol="baidu_std",
                auth=SharedSecretAuthenticator("shh", identity="press"),
            ),
        )
        for i in range(3):
            c = ch.call_method("svc", "echo", b"n%d" % i)
            assert c.ok(), c.error_text
        stats = srv._native_plane.stats()
        assert stats["native_reqs"] >= 3
        assert stats["cb_frames"] == 0
        # wrong secret: rejected natively through the same trampoline
        bad = Channel()
        assert bad.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(
                native_plane=True,
                protocol="baidu_std",
                auth=SharedSecretAuthenticator("not-it", identity="x"),
            ),
        )
        c = bad.call_method("svc", "echo", b"x")
        assert c.failed() and c.error_code == ErrorCode.ERPCAUTH
        assert srv._native_plane.stats()["auth_rejects"] >= 1

    def test_compressed_authed_pump_interpreter_free(self, native_server):
        # ISSUE 11 acceptance: the compressed+authenticated flood never
        # enters the interpreter — the extension of PR 2's proof to
        # production-shaped frames
        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.rpc import ServerOptions

        srv = native_server(
            {"svc": {"echo": native_echo}},
            options=ServerOptions(
                native_plane=True, usercode_inline=True, auth=self._auth()
            ),
        )
        payload = b"flood payload " * 300  # ~4 KiB
        comp = compress_mod.compress("snappy", payload)
        nch = native_plane.NativeClientChannel(
            "127.0.0.1", srv.port, protocol="baidu_std"
        )
        try:
            nch.set_auth(self.TOKEN)
            nch.set_request_compress("snappy")
            ns = nch.pump("svc", "echo", comp, 3000, inflight=64)
            assert ns > 0
            stats = srv._native_plane.stats()
            assert stats["native_reqs"] >= 3000
            assert stats["cb_frames"] == 0
            cs = srv._native_plane.compress_stats()
            # every request decompressed and every response recompressed
            assert cs["in_raw"] > cs["in_wire"] > 0
            assert cs["out_raw"] > cs["out_wire"] > 0
        finally:
            nch.close()

    def test_python_route_after_native_auth(self, native_server):
        # a natively-authenticated connection's Python-routed frames
        # (trace ids) must NOT be re-challenged: the verdict rides the
        # callback flags into sock.context
        from incubator_brpc_tpu.rpc import ServerOptions

        def py_echo(cntl, request):
            return request

        srv = native_server(
            {"svc": {"echo": native_echo, "pyecho": py_echo}},
            options=ServerOptions(
                native_plane=True, usercode_inline=True, auth=self._auth()
            ),
        )
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(
                native_plane=True, protocol="baidu_std", auth=self._auth()
            ),
        )
        # first call authenticates natively (traced frames stay native
        # now, so a plain-Python-handler method is the route trigger)
        assert ch.call_method("svc", "echo", b"a").ok()
        # a Python-dispatched method's frame carries no credential (the
        # first response proved the connection), so only the cached
        # verdict can admit it
        c = ch.call_method("svc", "pyecho", b"pyroute")
        assert c.ok(), (c.error_code, c.error_text)
        assert srv._native_plane.stats()["cb_frames"] >= 1


class TestCompressFuzzRobustness:
    """Adversarial compressed frames against the native codec round:
    truncated/corrupt bodies, bombs, out-of-enum codec ids, attachment
    disagreements, and oversized auth data.  Invariant: the server
    answers a clean error (or kills at most the offending connection)
    and keeps serving — never crashes, never expands a bomb."""

    def _assert_still_serving(self, srv):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True, protocol="baidu_std"),
        )
        c = ch.call_method("svc", "echo", b"probe")
        assert c.ok(), c.error_text
        assert c.response_payload == b"probe"

    def _send(self, srv, wire: bytes) -> bytes:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.settimeout(5)
        try:
            s.sendall(wire)
            try:
                return _read_prpc_frame(s)
            except AssertionError:
                return b""  # connection killed: also acceptable
        finally:
            s.close()

    def _compressed_req(self, codec_wire: int, body: bytes, cid: int = 1,
                        attachment_size: int = 0) -> bytes:
        rm = baidu_std.RpcMeta(
            service_name="svc",
            method_name="echo",
            compress_type=codec_wire,
            correlation_id=cid,
            attachment_size=attachment_size,
        )
        mb = rm.encode()
        hdr = b"PRPC" + struct.pack(">II", len(mb) + len(body), len(mb))
        return hdr + mb + body

    @pytest.mark.parametrize("codec_wire", [1, 2, 3])
    def test_corrupt_bodies_clean_error(self, native_server, codec_wire):
        from incubator_brpc_tpu.protocol import compress as compress_mod

        srv = native_server({"svc": {"echo": native_echo}})
        name = {1: "snappy", 2: "gzip", 3: "zlib1"}[codec_wire]
        good = compress_mod.compress(name, b"payload " * 200)
        # (body, strict): strict cases MUST reject EREQUEST; a flipped
        # byte mid-stream may legally still decode (snappy has no
        # checksum in the block format), so those only require a clean
        # answer — the invariant throughout is "no crash, keeps serving"
        bodies = [
            (b"\xff" * 64, True),                 # garbage
            (good[: len(good) // 2], True),        # truncated
            (good[:-1] + b"\x00", False),          # corrupted tail
            (bytes([good[0] ^ 0xFF]) + good[1:], False),  # corrupted head
            (b"", True),                           # empty compressed body
        ]
        for i, (body, strict) in enumerate(bodies):
            resp = self._send(
                srv, self._compressed_req(codec_wire, body, cid=i + 1)
            )
            if resp:
                frame, _ = baidu_std.try_parse_frame(resp)
                assert frame.error_code in (
                    (ErrorCode.EREQUEST,)
                    if strict
                    else (0, ErrorCode.EREQUEST)
                ), (name, i, frame.error_code, frame.meta.error_text)
        self._assert_still_serving(srv)

    def test_attachment_size_vs_decompressed_length(self, native_server):
        # attachment_size larger than the wire body routes off the fast
        # path; attachment_size eating INTO the compressed payload makes
        # the codec see a truncated stream — a clean error either way
        from incubator_brpc_tpu.protocol import compress as compress_mod

        srv = native_server({"svc": {"echo": native_echo}})
        comp = compress_mod.compress("snappy", b"data " * 400)
        # claim the last 32 compressed bytes are attachment: the codec
        # input is truncated mid-stream
        wire = self._compressed_req(1, comp, cid=2, attachment_size=32)
        resp = self._send(srv, wire)
        if resp:
            frame, _ = baidu_std.try_parse_frame(resp)
            assert frame.error_code == ErrorCode.EREQUEST
        # attachment_size beyond the whole body
        wire = self._compressed_req(
            1, comp, cid=3, attachment_size=len(comp) + 1000
        )
        self._send(srv, wire)
        self._assert_still_serving(srv)

    def test_auth_data_at_meta_bound(self, native_server):
        # a 64 KiB credential (the meta scratch boundary) must be read,
        # rejected, and survived — on an auth server AND a no-auth one
        from incubator_brpc_tpu.rpc import ServerOptions, TokenAuthenticator

        srv = native_server(
            {"svc": {"echo": native_echo}},
            options=ServerOptions(
                native_plane=True,
                usercode_inline=True,
                auth=TokenAuthenticator(["short"]),
            ),
        )
        big_cred = b"A" * (64 * 1024)
        rm = baidu_std.RpcMeta(
            service_name="svc",
            method_name="echo",
            correlation_id=5,
            authentication_data=big_cred,
        )
        wire = baidu_std.pack_frame(rm, b"x")
        resp = self._send(srv, wire)
        assert resp
        frame, _ = baidu_std.try_parse_frame(resp)
        assert frame.error_code == ErrorCode.ERPCAUTH
        # correct token still admitted afterwards on a fresh conn
        rm2 = baidu_std.RpcMeta(
            service_name="svc",
            method_name="echo",
            correlation_id=6,
            authentication_data=b"short",
        )
        resp = self._send(srv, baidu_std.pack_frame(rm2, b"ok"))
        frame, _ = baidu_std.try_parse_frame(resp)
        assert frame.error_code == 0 and frame.payload == b"ok"

    def test_snappy_decoder_fuzz_no_crash(self):
        # the decoder itself against random tags: errors, never crashes,
        # and the native decoder agrees with the pure-Python twin on
        # accept/reject for every case
        import random

        from incubator_brpc_tpu.protocol import compress as compress_mod
        from incubator_brpc_tpu.protocol import snappy_codec

        rng = random.Random(11)
        for _ in range(300):
            blob = bytes(
                rng.getrandbits(8) for _ in range(rng.randrange(1, 80))
            )
            native_err = python_err = False
            try:
                native_out = compress_mod.decompress("snappy", blob)
            except ValueError:
                native_err = True
            try:
                python_out = snappy_codec.decompress(blob)
            except ValueError:
                python_err = True
            assert native_err == python_err, blob.hex()
            if not native_err:
                assert native_out == python_out, blob.hex()


class TestPrpcPump:
    def test_pump_interpreter_free(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        nch = native_plane.NativeClientChannel(
            "127.0.0.1", srv.port, protocol="baidu_std"
        )
        try:
            ns = nch.pump("svc", "echo", b"x" * 64, 3000, inflight=64)
            assert ns > 0
            # every request of the pump dispatched natively
            stats = srv._native_plane.stats()
            assert stats["native_reqs"] >= 3000
            assert stats["cb_frames"] == 0
            # the scrapeable record landed in the prpc recorder
            from incubator_brpc_tpu.transport.native_plane import prpc_pump_ns

            assert prpc_pump_ns.sum() > 0
        finally:
            nch.close()
