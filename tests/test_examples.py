"""Every example pair must keep running (the reference treats example/
as living documentation; SURVEY §1 L7). Each runs in-process on the
virtual mesh — tensor_echo_tpu is exercised via its own module path in
test_device_transport, so only the host-plane examples run here."""

import runpy
import sys

import pytest

EXAMPLES = [
    "examples/echo.py",
    "examples/parallel_echo.py",
    "examples/streaming_echo.py",
    "examples/partition_echo.py",
    "examples/backup_request.py",
    "examples/multi_protocol.py",
    "examples/tls_echo.py",
    "examples/rtmp_relay.py",
    "examples/naming_failover.py",
    "examples/overload_and_breaker.py",
    "examples/cache_clients.py",
    "examples/link_performance.py",
    "examples/http_upload.py",
    "examples/session_data_and_thread_local.py",
    "examples/dynamic_partition_echo.py",
    "examples/multi_threaded_echo.py",
    "examples/cancel_echo.py",
    "examples/cascade_echo.py",
    "examples/selective_echo.py",
    "examples/asynchronous_echo.py",
    "examples/ubrpc_compack.py",
    "examples/nshead_extension.py",
]


@pytest.mark.parametrize("path", EXAMPLES)
def test_example_runs(path, monkeypatch, capsys):
    monkeypatch.setattr(sys, "argv", [path])
    runpy.run_path(path, run_name="__main__")
    out = capsys.readouterr().out
    assert out.strip()  # every example prints its result
