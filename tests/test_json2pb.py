"""json2pb typed-schema tests: proto2 wire compatibility, JSON
transcoding, and the dual-access typed echo (binary RPC + curl-style
JSON through the gateway) — the reference's src/json2pb role."""

import json

import pytest

from incubator_brpc_tpu.protocol.json2pb import (
    Message,
    field,
    make_typed_service,
)
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.rpc import Channel, Server
from tests.test_http import fetch


class Inner(Message):
    tag = field(1, str)


class EchoRequest(Message):
    msg = field(1, str)
    count = field(2, int)
    blob = field(3, bytes)
    ratio = field(4, float)
    flags = field(5, int, repeated=True)
    inner = field(6, Inner)


class EchoResponse(Message):
    msg = field(1, str)
    total = field(2, int)


class TestSchemaCodec:
    def test_binary_roundtrip(self):
        m = EchoRequest(
            msg="hi", count=7, blob=b"\x00\x01", ratio=2.5,
            flags=[1, 2, 3], inner=Inner(tag="t"),
        )
        back = EchoRequest.from_binary(m.to_binary())
        assert back == m

    def test_proto2_wire_bytes_exact(self):
        # field 1 "hi": tag 0x0A len 2; field 2 varint 7: 0x10 0x07
        m = EchoRequest(msg="hi", count=7)
        assert m.to_binary() == b"\x0a\x02hi\x10\x07"

    def test_unknown_fields_skipped(self):
        # append field 99 (varint): decoder must ignore it
        blob = EchoRequest(msg="x").to_binary()
        tag = (99 << 3) | 0
        blob += bytes([tag & 0x7F | 0x80, tag >> 7]) + b"\x05"
        m = EchoRequest.from_binary(blob)
        assert m.msg == "x"

    def test_json_roundtrip_and_base64_bytes(self):
        m = EchoRequest(msg="J", blob=b"\xff\xfe", inner=Inner(tag="i"))
        j = json.loads(m.to_json())
        assert j["msg"] == "J"
        assert j["inner"] == {"tag": "i"}
        back = EchoRequest.from_json(m.to_json())
        assert back.blob == b"\xff\xfe"
        assert back.inner.tag == "i"

    def test_bad_json_raises(self):
        with pytest.raises(ParseError):
            EchoRequest.from_json(b"not json")
        with pytest.raises(ParseError):
            EchoRequest.from_json(b"[1,2]")
        with pytest.raises(ParseError):
            EchoRequest.from_json(b'{"count": "not-an-int-at-all"}')

    def test_duplicate_field_numbers_rejected(self):
        with pytest.raises(TypeError):
            class Bad(Message):
                a = field(1, str)
                b = field(1, int)


class TestTypedService:
    @pytest.fixture
    def typed_server(self):
        srv = Server()

        def echo(cntl, req: EchoRequest) -> EchoResponse:
            return EchoResponse(
                msg=req.msg * max(1, req.count or 1),
                total=(req.count or 0) + sum(req.flags),
            )

        srv.add_service(
            "TypedEcho",
            make_typed_service({"Echo": (echo, EchoRequest, EchoResponse)}),
        )
        assert srv.start(0)
        yield srv
        srv.stop()
        srv.join(timeout=5)

    def test_binary_rpc_call(self, typed_server):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{typed_server.port}")
        req = EchoRequest(msg="ab", count=2, flags=[10])
        cntl = ch.call_method("TypedEcho", "Echo", req.to_binary())
        assert cntl.ok(), cntl.error_text
        resp = EchoResponse.from_binary(cntl.response_payload)
        assert resp.msg == "abab"
        assert resp.total == 12

    def test_curl_style_json_call(self, typed_server):
        # the Done criterion: curl -d '{"msg":...}' /svc/method
        status, headers, body = fetch(
            typed_server,
            "/TypedEcho/Echo",
            method="POST",
            body=json.dumps({"msg": "z", "count": 3}).encode(),
        )
        assert status == 200
        assert "json" in headers.get("content-type", "")
        obj = json.loads(body)
        assert obj["msg"] == "zzz"
        assert obj["total"] == 3

    def test_json_error_is_400(self, typed_server):
        status, _, body = fetch(
            typed_server, "/TypedEcho/Echo", method="POST",
            body=b'{"count": "garbage-string"}',
        )
        assert status == 400
        assert b"bad request json" in body

    def test_binary_body_still_passes_through_gateway(self, typed_server):
        # a binary (proto) body via HTTP skips transcoding and returns bytes
        req = EchoRequest(msg="q", count=2).to_binary()
        status, headers, body = fetch(
            typed_server, "/TypedEcho/Echo", method="POST", body=req
        )
        assert status == 200
        assert "octet-stream" in headers.get("content-type", "")
        assert EchoResponse.from_binary(body).msg == "qq"
