"""bvar tests — per-primitive suites like the reference's
bvar_{variable,reducer,recorder,...}_unittest.cpp (SURVEY.md §4)."""

import threading

from incubator_brpc_tpu import bvar


def test_adder_multi_thread():
    a = bvar.Adder()
    n_threads, per_thread = 8, 10000

    def work():
        for _ in range(per_thread):
            a << 1

    ts = [threading.Thread(target=work) for _ in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert a.get_value() == n_threads * per_thread


def test_maxer_miner():
    m = bvar.Maxer()
    for v in (3, 9, 1):
        m << v
    assert m.get_value() == 9
    mn = bvar.Miner()
    for v in (3, 9, 1):
        mn << v
    assert mn.get_value() == 1


def test_int_recorder_average():
    r = bvar.IntRecorder()
    for v in range(1, 101):
        r << v
    assert abs(r.average() - 50.5) < 1e-9


def test_latency_recorder():
    lr = bvar.LatencyRecorder(window_size=2)
    for v in range(1000):
        lr << v
    assert lr.count() == 1000
    assert 0 <= lr.latency_percentile(0.5) <= 999
    assert lr.max_latency() == 999
    assert lr.latency() == sum(range(1000)) / 1000


def test_expose_registry_and_normalize():
    from incubator_brpc_tpu.bvar.variable import normalize_name

    assert normalize_name("FooBar::BazQps") == "foo_bar_baz_qps"
    a = bvar.Adder(name="test_expose_adder_xyz")
    a << 5
    dump = bvar.dump_exposed("test_expose_adder")
    assert dump.get("test_expose_adder_xyz") == "5"
    # duplicate exposure refused (reference variable.cpp behavior)
    b = bvar.Adder()
    assert not b.expose("test_expose_adder_xyz")
    assert a.hide()


def test_re_expose_drops_old_registry_entry():
    a = bvar.Adder(name="test_reexpose_old")
    assert a.expose("test_reexpose_new")
    assert bvar.dump_exposed("test_reexpose_old") == {}
    assert "test_reexpose_new" in bvar.dump_exposed("test_reexpose_new")
    assert a.hide()
    assert bvar.dump_exposed("test_reexpose") == {}


def test_passive_status():
    x = {"v": 1}
    p = bvar.PassiveStatus(lambda: x["v"] * 2)
    assert p.get_value() == 2
    x["v"] = 21
    assert p.get_value() == 42


def test_adder_reset_rebase():
    a = bvar.Adder()
    for _ in range(10):
        a << 1
    assert a.reset() == 10
    assert a.get_value() == 0
    a << 5
    assert a.get_value() == 5
    assert a.reset() == 5


def test_per_second_returns_float_fraction():
    from incubator_brpc_tpu.bvar.window import PerSecond

    a = bvar.Adder()
    ps = PerSecond(a, window_size=10)
    a << 9
    ps._take_sample()  # seed one sample so the span is tiny but nonzero
    import time

    time.sleep(0.05)
    v = ps.get_value()
    assert isinstance(v, float)
