"""Mesh + collective-lowering tests on the virtual 8-device CPU mesh —
the reference's 'many servers as many local sockets' trick (SURVEY.md §4)
mapped to 'many chips as many virtual devices'."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from incubator_brpc_tpu.parallel import (
    default_axis_sizes,
    make_fabric_mesh,
    fanout,
    merge,
    partition_exchange,
    ring_allgather,
)


def test_default_axis_sizes():
    assert default_axis_sizes(1) == {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
    s8 = default_axis_sizes(8)
    assert s8["dp"] == 2 and s8["tp"] == 2 and s8["pp"] == 2
    assert np.prod(list(s8.values())) == 8
    s32 = default_axis_sizes(32)
    assert all(v == 2 for v in s32.values())
    assert np.prod(list(default_axis_sizes(6).values())) == 6


def test_make_fabric_mesh():
    mesh = make_fabric_mesh(8)
    assert mesh.axis_names == ("dp", "pp", "tp", "sp", "ep")
    assert np.prod(list(mesh.shape.values())) == 8


@pytest.fixture(scope="module")
def shard_map_capable():
    """Fast capability probe (the module-scoped gate test_mc_link.py uses
    for its fabric pair): some environments ship a jax whose public
    ``jax.shard_map`` entry point (or its ``check_vma`` kwarg) does not
    exist — every collect below would fail identically, so skip them in
    one cheap step instead of burning four collects on a doomed API."""
    try:
        jax.shard_map  # noqa: B018 — the probe IS the attribute access
    except AttributeError:
        pytest.skip("jax.shard_map unavailable in this jax build")
    return True


@pytest.fixture
def flat_mesh(shard_map_capable):
    """One-axis view for collective semantics tests: all 8 devices on dp."""
    return make_fabric_mesh(8, axis_sizes={"dp": 8, "pp": 1, "tp": 1, "sp": 1, "ep": 1})


def _smap(mesh, fn, in_spec, out_spec):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False)
    )


def test_merge_psum(flat_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    f = _smap(flat_mesh, partial(merge, axis="dp", merger="sum"), P("dp"), P())
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((1,), 28.0))


def test_fanout_allgather(flat_mesh):
    x = jnp.arange(8, dtype=jnp.float32)
    # all_gather result is identical on every rank -> replicated out_spec
    f = _smap(flat_mesh, partial(fanout, axis="dp"), P("dp"), P(None, None))
    out = f(x)
    assert out.shape == (8, 1)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.arange(8.0))


def test_partition_exchange_is_transpose(flat_mesh):
    # 8 ranks each hold one row; all_to_all over columns == distributed transpose
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    f = _smap(
        flat_mesh,
        partial(partition_exchange, axis="dp", split_dim=1, concat_dim=1),
        P("dp", None),
        P("dp", None),
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.arange(64.0).reshape(8, 8).T)


def test_ring_allgather_matches_native(flat_mesh):
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)

    def body(xl):
        return ring_allgather(xl.reshape(2), "dp")

    # every rank ends with the full (8, 2) table -> replicated
    f = _smap(flat_mesh, body, P("dp", None), P(None, None))
    out = np.asarray(f(x))
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out, np.arange(16.0).reshape(8, 2))


class TestCollectiveAcceptPreAck:
    """propose_collective's two-phase shape (ADVICE r5): every server
    answers an explicit accept pre-ack before any party enters its
    session — no fixed grace window, rejections surface immediately."""

    def _server(self):
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        srv = Server(ServerOptions(enable_collective_service=True))
        assert srv.start(0)
        return srv

    def test_accept_phase_validates_without_running(self, monkeypatch):
        import json as _json

        from incubator_brpc_tpu.parallel import mc_collective
        from incubator_brpc_tpu.rpc import Channel

        def _boom(*a, **kw):  # the accept phase must never run a session
            raise AssertionError("accept phase ran the session")

        monkeypatch.setattr(mc_collective, "run_collective_session", _boom)
        srv = self._server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            payload = _json.dumps(
                {
                    "parties": [0, 1],
                    "index": 1,
                    "steps": 3,
                    "width": 4,
                    "seed": 7,
                    "phase": "accept",
                }
            ).encode()
            cntl = ch.call_method("_tpu_transport", "collective", payload)
            assert cntl.ok(), cntl.error_text
            ack = _json.loads(cntl.response_payload.decode())
            assert ack == {"accept": True, "index": 1}
            # and a bad proposal is REJECTED at the accept phase
            bad = _json.dumps(
                {
                    "parties": [0, 1],
                    "index": 1,
                    "steps": 0,  # out of bounds
                    "width": 4,
                    "seed": 7,
                    "phase": "accept",
                }
            ).encode()
            cntl = ch.call_method("_tpu_transport", "collective", bad)
            assert cntl.failed()
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_propose_runs_without_grace_window(self, monkeypatch):
        import time as _time

        from incubator_brpc_tpu.parallel import mc_collective
        from incubator_brpc_tpu.rpc import Channel

        calls = []

        def _stub(parties, idx, steps, width, seed):
            calls.append(idx)
            return np.zeros(width, np.float32), 0.001

        monkeypatch.setattr(mc_collective, "run_collective_session", _stub)
        srv = self._server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            t0 = _time.monotonic()
            out = mc_collective.propose_collective(
                [ch], [0, 1], client_index=0, steps=3, width=4, seed=7,
                timeout_ms=30000,
            )
            elapsed = _time.monotonic() - t0
            assert len(out["server_checksums"]) == 1
            # client (index 0) and server party (index 1) both ran
            assert sorted(calls) == [0, 1]
            # the old fixed 0.5 s grace window is gone: the only fixed
            # pause left is the short rejection watch (structural check —
            # a tight wall-clock bound here would flake on loaded CI),
            # plus a generous sanity ceiling on the whole stubbed round
            assert mc_collective._REJECT_WATCH_S <= 0.1
            assert elapsed < 5.0, f"proposal round unexpectedly slow: {elapsed}"
        finally:
            srv.stop()
            srv.join(timeout=5)
