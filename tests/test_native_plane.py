"""Native network plane (src/tbnet + transport/native_plane.py).

Covers the shapes the reference exercises for its I/O core + protocol
layer (brpc_server_unittest.cpp, brpc_channel_unittest.cpp): echo through
the native dispatcher, the Python callback route (admission, errors,
async handlers), wire interop with the Python plane in both directions,
protocol-sniff handoff (HTTP portal on the same port), streams over a
native connection, and the pipelined pump harness.
"""

from __future__ import annotations

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
    StreamHandler,
    StreamOptions,
    native_echo,
    native_nop,
    stream_accept,
    stream_create,
)
from incubator_brpc_tpu.transport import native_plane
from incubator_brpc_tpu.utils.status import ErrorCode

pytestmark = pytest.mark.skipif(
    not native_plane.NET_AVAILABLE, reason="native runtime unavailable"
)


@pytest.fixture
def native_server():
    created = []

    def make(options=None, services=None):
        opts = options or ServerOptions(
            native_plane=True, usercode_inline=True
        )
        opts.native_plane = True
        srv = Server(opts)
        for name, handlers in (services or {}).items():
            srv.add_service(name, handlers)
        created.append(srv)
        return srv

    yield make
    for srv in created:
        srv.stop()


def _start(srv):
    assert srv.start(0)
    assert srv._native_plane is not None, "native plane did not engage"
    return srv.port


class TestNativeDispatch:
    def test_native_echo_roundtrip(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "echo", b"payload-bytes")
        assert c.ok(), c.error_text
        assert c.response_payload == b"payload-bytes"
        # served without the interpreter
        assert srv._native_plane.stats()["native_reqs"] >= 1

    def test_native_echo_with_attachment(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "echo", b"pp", attachment=b"A" * 1000)
        assert c.ok(), c.error_text
        assert c.response_payload == b"pp"
        assert c.response_attachment == b"A" * 1000

    def test_native_nop(self, native_server):
        srv = native_server(services={"svc": {"nop": native_nop}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "nop", b"ignored")
        assert c.ok(), c.error_text
        assert c.response_payload == b""

    def test_body_crc_flag_roundtrip(self, native_server):
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        set_flag_unchecked("tbus_body_crc", True)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
            )
            c = ch.call_method("svc", "echo", b"crc-covered")
            assert c.ok(), c.error_text
            assert c.response_payload == b"crc-covered"
        finally:
            set_flag_unchecked("tbus_body_crc", False)

    def test_unknown_method_fails_cleanly(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "missing", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ENOMETHOD
        c = ch.call_method("ghost", "echo", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ENOSERVICE


class TestPythonRoute:
    def test_python_handler_and_error(self, native_server):
        def boom(cntl, req):
            cntl.set_failed(ErrorCode.EINTERNAL, "deliberate")
            return b""

        srv = native_server(
            services={"svc": {"up": lambda cntl, req: req.upper(), "boom": boom}}
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "up", b"abc")
        assert c.ok() and c.response_payload == b"ABC"
        c = ch.call_method("svc", "boom", b"")
        assert c.failed() and "deliberate" in c.error_text

    def test_async_handler_responds_from_other_thread(self, native_server):
        def slow(cntl, req):
            cntl.set_async()

            def later():
                time.sleep(0.05)
                cntl.send_response(b"late:" + req)

            threading.Thread(target=later).start()
            return None

        srv = native_server(services={"svc": {"slow": slow}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=2000),
        )
        c = ch.call_method("svc", "slow", b"x")
        assert c.ok(), c.error_text
        assert c.response_payload == b"late:x"

    def test_method_admission_via_python_route(self, native_server):
        gate = threading.Event()
        entered = threading.Event()

        def hold(cntl, req):
            entered.set()
            gate.wait(2)
            return b"done"

        opts = ServerOptions(native_plane=True, usercode_inline=False)
        srv = native_server(
            options=opts, services={"svc": {"hold": hold}}
        )
        srv._methods.get("svc.hold").status.max_concurrency = 1
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=3000),
        )
        results = []

        def call():
            results.append(ch.call_method("svc", "hold", b""))

        t1 = threading.Thread(target=call)
        t1.start()
        assert entered.wait(2)
        c2 = ch.call_method("svc", "hold", b"")
        assert c2.failed()
        assert c2.error_code == ErrorCode.ELIMIT
        gate.set()
        t1.join()
        assert results[0].ok()


class TestInterop:
    """Both planes speak the same wire: each client against each server."""

    def test_python_client_native_server(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}")  # plain Python-plane client
        c = ch.call_method("svc", "echo", b"from-python-plane")
        assert c.ok(), c.error_text
        assert c.response_payload == b"from-python-plane"

    def test_native_client_python_server(self):
        srv = Server(ServerOptions(usercode_inline=True))  # Python acceptor
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            assert srv._native_plane is None
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}", options=ChannelOptions(native_plane=True)
            )
            c = ch.call_method("svc", "echo", b"native-to-python")
            assert c.ok(), c.error_text
            assert c.response_payload == b"native-to-python"
        finally:
            srv.stop()

    def test_http_handoff_same_port(self, native_server):
        import urllib.request

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=5
        ).read()
        assert body == b"OK\n" or body.startswith(b"OK")
        assert srv._native_plane.stats()["handoffs"] >= 1

    def test_fallback_when_channel_dies(self, native_server):
        """Kill the server mid-conversation: the native channel reports the
        break, the regular path's dial/retry owns the recovery."""
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=1000),
        )
        assert ch.call_method("svc", "echo", b"1").ok()
        srv.stop()
        c = ch.call_method("svc", "echo", b"2")
        assert c.failed()  # recovered into a clean failure, no hang


class TestUserNativeMethods:
    """tb_server_register_native_fn: user bytes-in/bytes-out C methods run
    entirely on the loop thread (VERDICT r3 item 4a — the generalization
    of the built-in echo/nop kinds)."""

    SRC = r"""
    #include <stdlib.h>
    #include <string.h>
    extern "C" int reverse_method(void* ud, const char* req, size_t n,
                                  char** resp, size_t* resp_len) {
      (void)ud;
      char* out = (char*)malloc(n ? n : 1);
      for (size_t i = 0; i < n; ++i) out[i] = req[n - 1 - i];
      *resp = out;
      *resp_len = n;
      return 0;
    }
    extern "C" int failing_method(void* ud, const char* req, size_t n,
                                  char** resp, size_t* resp_len) {
      (void)ud; (void)req; (void)n; (void)resp; (void)resp_len;
      return 1008;  /* an application error code */
    }
    """

    @pytest.fixture(scope="class")
    def method_lib(self, tmp_path_factory):
        import subprocess

        d = tmp_path_factory.mktemp("native_methods")
        src = d / "methods.cc"
        so = d / "libmethods.so"
        src.write_text(self.SRC)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-o", str(so), str(src)],
            check=True,
            capture_output=True,
        )
        return str(so)

    def _py_reverse(self, cntl, req):
        return req[::-1]

    def test_so_method_never_crosses_into_python(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "reverse": native_method_lib(
                        method_lib, "reverse_method", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        before = srv._native_plane.stats()
        for payload in (b"abc", b"", b"x" * 10000):
            cntl = ch.call_method("user", "reverse", payload)
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == payload[::-1]
        after = srv._native_plane.stats()
        assert after["native_reqs"] - before["native_reqs"] == 3
        assert after["cb_frames"] == before["cb_frames"]  # zero Python frames

    def test_so_method_error_code_surfaces(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "boom": native_method_lib(
                        method_lib, "failing_method", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        cntl = ch.call_method("user", "boom", b"q")
        assert cntl.failed()
        assert cntl.error_code == 1008

    def test_missing_symbol_falls_back_to_python_route(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "reverse": native_method_lib(
                        method_lib, "no_such_symbol", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        cntl = ch.call_method("user", "reverse", b"abc")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"cba"  # the Python fallback served
        assert srv._native_plane.stats()["cb_frames"] > 0


class TestStreamsOverNative:
    def test_stream_over_native_conn(self, native_server):
        got = []
        done = threading.Event()

        class Sink(StreamHandler):
            def on_received_messages(self, s, msgs):
                got.extend(msgs)
                if sum(len(m) for m in got) >= 4096:
                    done.set()

        def open_stream(cntl, req):
            stream_accept(cntl, StreamOptions(handler=Sink()))
            return b""

        srv = native_server(services={"svc": {"open": open_stream}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=3000),
        )
        s = stream_create(StreamOptions())
        c = ch.call_method("svc", "open", b"", request_stream=s)
        assert c.ok(), c.error_text
        assert s.wait_connected(3)
        chunk = b"z" * 1024
        for _ in range(4):
            assert s.write(chunk, timeout=3) == 0
        assert done.wait(5)
        assert b"".join(got) == chunk * 4
        s.close()


class TestNativeClientModes:
    def test_pooled_connection_type(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, connection_type="pooled"),
        )
        errs = []

        def worker():
            for i in range(50):
                c = ch.call_method("svc", "echo", b"t%d" % i)
                if c.failed() or c.response_payload != b"t%d" % i:
                    errs.append(c.error_text)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_concurrent_callers_shared_conn(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        errs = []

        def worker(tag):
            for i in range(100):
                payload = f"{tag}-{i}".encode()
                c = ch.call_method("svc", "echo", payload)
                if c.failed() or c.response_payload != payload:
                    errs.append((tag, i, c.error_text))

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:3]

    def test_pump_harness(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        nch = native_plane.NativeClientChannel("127.0.0.1", port)
        try:
            ns = nch.pump("svc", "echo", b"x" * 64, 2000, inflight=32)
            assert ns > 0
            # sanity: pipelined per-request cost must be far below the
            # Python plane's sync round trip
            assert ns < 1_000_000  # < 1 ms/req even on a loaded CI host
        finally:
            nch.close()

    def test_timeout_maps_to_rpc_timeout(self, native_server):
        def sleepy(cntl, req):
            time.sleep(0.5)
            return b""

        srv = native_server(services={"svc": {"sleepy": sleepy}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=100),
        )
        t0 = time.monotonic()
        c = ch.call_method("svc", "sleepy", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ERPCTIMEDOUT
        assert time.monotonic() - t0 < 0.45


class TestGatesStayEnforced:
    def test_auth_server_keeps_native_methods_on_python_route(self, native_server):
        """An Authenticator is a per-request gate the C++ fast path does not
        implement: with auth configured, even native-kind methods must go
        through Server.process_request (and reject bad credentials)."""
        from incubator_brpc_tpu.rpc import SharedSecretAuthenticator

        auth = SharedSecretAuthenticator("secret", identity="svc-a")
        srv = native_server(
            options=ServerOptions(
                native_plane=True, usercode_inline=True, auth=auth
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        # authenticated python-plane client works
        ch_ok = Channel()
        assert ch_ok.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(
                auth=SharedSecretAuthenticator("secret", identity="svc-a")
            ),
        )
        assert ch_ok.call_method("svc", "echo", b"hi").ok()
        # an unauthenticated native-plane client must be rejected, not
        # silently served by the C++ dispatcher
        ch_bad = Channel()
        assert ch_bad.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        c = ch_bad.call_method("svc", "echo", b"hi")
        assert c.failed()
        assert c.error_code == ErrorCode.ERPCAUTH
        assert srv._native_plane.stats()["native_reqs"] == 0

    def test_server_max_concurrency_disables_native_kinds(self, native_server):
        srv = native_server(
            options=ServerOptions(
                native_plane=True, usercode_inline=True, max_concurrency=64
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        assert ch.call_method("svc", "echo", b"x").ok()
        # served via the Python route so the server-wide gate applies
        assert srv._native_plane.stats()["native_reqs"] == 0
        assert srv.nrequest.get_value() >= 1


class TestGarbageAndEdge:
    def test_garbage_after_magic_kills_conn_only(self, native_server):
        import socket as pysock
        import struct

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        # valid magic, corrupt frame (crc mismatch)
        raw = pysock.create_connection(("127.0.0.1", port))
        hdr = struct.pack("<8I", 0x54505243, 8, 0, 1, 0, 0, 0xDEAD, 0)
        raw.sendall(hdr + b"xxxxxxxx")
        raw.settimeout(2)
        assert raw.recv(1024) == b""  # server killed the connection
        raw.close()
        # the server itself is fine
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        assert ch.call_method("svc", "echo", b"still-up").ok()

    def test_large_payload(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=10000),
        )
        blob = bytes(range(256)) * (4 * 1024 * 16)  # 16 MiB
        c = ch.call_method("svc", "echo", blob)
        assert c.ok(), c.error_text
        assert c.response_payload == blob

    def test_unix_endpoint_falls_back_to_python_acceptor(self, tmp_path):
        srv = Server(ServerOptions(native_plane=True, usercode_inline=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(f"unix://{tmp_path}/np.sock")
        try:
            assert srv._native_plane is None  # fell back
            ch = Channel()
            assert ch.init(f"unix://{tmp_path}/np.sock")
            assert ch.call_method("svc", "echo", b"via-unix").ok()
        finally:
            srv.stop()


@pytest.mark.slow
class TestTelemetryRingStress:
    """Multi-producer hammer on the C++ telemetry ring against a live
    concurrent drain (the satellite workload `make san` runs under TSAN:
    every assert here doubles as the race-detector's coverage).

    Sizing comes from the environment so the sanitizer harness can run a
    shorter burn: TBNET_STRESS_THREADS (default 8) producer threads x
    TBNET_STRESS_N (default 2000) echoes each.
    """

    @pytest.mark.parametrize("num_reactors", [None, 4])
    def test_multi_producer_append_vs_concurrent_drain(
        self, tuned_flags, num_reactors
    ):
        # num_reactors=4 is the multi-reactor variant: producers spread
        # over four per-reactor rings, the drain walks them all, and the
        # produced == drained + dropped invariant must hold ACROSS rings
        import os

        import numpy as np

        from incubator_brpc_tpu.transport.native_plane import (
            NativeClientChannel,
            NativeServerPlane,
        )

        nthreads = int(os.environ.get("TBNET_STRESS_THREADS", "8"))
        per_thread = int(os.environ.get("TBNET_STRESS_N", "2000"))
        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_ring_size", 4096)
        tuned_flags("native_telemetry_sample_every", 64)
        # background cadence tight so the drain genuinely races producers
        tuned_flags("native_telemetry_drain_ms", 1)
        srv = Server(
            ServerOptions(
                native_plane=True, usercode_inline=True,
                num_reactors=num_reactors,
            )
        )
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        plane = srv._native_plane
        assert plane is not None
        # capture every drained batch (post clock conversion) while the
        # real fan-out still runs — instance-level wrap, hot path intact
        captured = []
        cap_lock = threading.Lock()
        orig = plane._consume_records
        dtype = NativeServerPlane._rec_dtype()

        def capture(batch, n):
            arr = np.frombuffer(batch, dtype=dtype, count=n).copy()
            with cap_lock:
                captured.append(arr)
            orig(batch, n)

        plane._consume_records = capture
        errors = []

        def producer(tid):
            try:
                ch = NativeClientChannel("127.0.0.1", srv.port)
                # distinct payload size per thread: request_size becomes
                # the stream id for the per-producer monotonicity check
                payload = b"x" * (64 + tid)
                for _ in range(per_thread):
                    rc, err, _meta, _body = ch.call(
                        "svc", "echo", payload, timeout_ms=10000
                    )
                    if rc < 0 or err != 0:
                        errors.append((tid, rc, err))
                        return
                ch.close()
            except Exception as e:  # noqa: BLE001 - surface in main thread
                errors.append((tid, repr(e), None))

        stop_drain = threading.Event()

        def drainer():
            while not stop_drain.is_set():
                plane.drain_telemetry()

        threads = [
            threading.Thread(target=producer, args=(t,), name=f"prod-{t}")
            for t in range(nthreads)
        ]
        dr = threading.Thread(target=drainer, name="stress-drain")
        dr.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop_drain.set()
        dr.join()
        assert not errors, f"producer failures: {errors[:5]}"
        produced = plane.stats()["native_reqs"]
        assert produced == nthreads * per_thread
        srv.stop()  # final drain happens in stop()
        drained = plane._tel_drained
        dropped = plane.telemetry_dropped()
        # no lost-unless-counted: every dispatched request either reached
        # the drain or is accounted in the drop counter (ring overflow /
        # clock-invalid discard) — nothing vanishes silently
        assert drained + dropped == produced, (
            f"drained {drained} + dropped {dropped} != produced {produced}"
        )
        all_recs = np.concatenate(captured) if captured else np.zeros(0, dtype)
        assert len(all_recs) == drained
        if not len(all_recs):
            return
        # per-producer monotone drain timestamps: each client thread runs
        # serial round trips on its own connection, so its records'
        # converted start_ns must be non-decreasing in correlation order.
        # Tolerance covers the drain's continuously-refined tick->ns
        # calibration shifting between batches (sub-millisecond).
        tol_ns = 2_000_000
        streams = 0
        for size in np.unique(all_recs["request_size"]):
            grp = all_recs[all_recs["request_size"] == size]
            grp = grp[np.argsort(grp["correlation_id"], kind="stable")]
            starts = grp["start_ns"].astype(np.int64)
            regress = np.diff(starts)
            assert (regress >= -tol_ns).all(), (
                f"stream size={size}: drain timestamps regressed "
                f"{int(-regress.min())} ns"
            )
            streams += 1
        assert streams == nthreads
        # every sampled flag is the exact 1/N election — counter-based
        # over claimed ring positions, and claims never exceed produced
        # requests, so the count is bounded by ceil(ring_produced/N)
        # summed across the per-reactor rings (each elects independently)
        nrings = plane.num_reactors
        assert int(all_recs["sampled"].sum()) <= produced // 64 + nrings


class TestMultiReactor:
    """Per-core reactor sharding (ISSUE 9): connection→reactor affinity,
    cross-reactor cid routing, and the reactor-aware lame-duck / idle
    reap that PR 8 assumed a single loop thread for."""

    def _capture_drained(self, plane):
        """Wrap the plane's record fan-out to keep a copy of every
        drained batch (the ring-stress capture pattern)."""
        import numpy as np

        captured = []
        lock = threading.Lock()
        orig = plane._consume_records
        dtype = native_plane.NativeServerPlane._rec_dtype()

        def capture(batch, n):
            arr = np.frombuffer(batch, dtype=dtype, count=n).copy()
            with lock:
                captured.append(arr)
            orig(batch, n)

        plane._consume_records = capture
        return captured

    def test_connection_shard_affinity(self, native_server, tuned_flags):
        """Every frame of a connection is cut/packed on its owning
        reactor: each client channel's records (keyed by the cid's
        client-shard tag) carry exactly ONE reactor_id, and round-robin
        sharding uses every reactor."""
        import numpy as np

        tuned_flags("native_telemetry", True)
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        plane = srv._native_plane
        assert plane.num_reactors == 4
        captured = self._capture_drained(plane)
        chans = [
            native_plane.NativeClientChannel("127.0.0.1", port)
            for _ in range(4)
        ]
        try:
            for _round in range(20):
                for ch in chans:
                    rc, err, _m, _b = ch.call(
                        "svc", "echo", b"affinity", timeout_ms=5000
                    )
                    assert rc >= 0 and err == 0, (rc, err)
        finally:
            shards = [ch.reactor for ch in chans]
            for ch in chans:
                ch.close()
        plane.drain_telemetry()
        recs = np.concatenate(captured)
        assert len(recs) == 80
        seen_reactors = set()
        for shard in shards:
            grp = recs[(recs["correlation_id"] >> 56) == shard]
            assert len(grp) == 20
            reactors = set(int(r) for r in np.unique(grp["reactor_id"]))
            # the affinity contract: one connection, one reactor, forever
            assert len(reactors) == 1, (shard, reactors)
            seen_reactors |= reactors
        # round-robin accept sharding: 4 connections cover all 4 reactors
        assert seen_reactors == {0, 1, 2, 3}
        # the per-reactor gauges tell the same story
        for i in range(4):
            st = plane.reactor_stats(i)
            assert st["conns"] == 1 or st["conns"] == 0  # closed by now
            assert st["reqs"] >= 20

    def test_interleaved_cross_reactor_calls_no_misroutes(
        self, native_server
    ):
        """Interleaved responses across reactors route back by cid with
        zero misroutes and zero cross-talk."""
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        chans = [
            native_plane.NativeClientChannel("127.0.0.1", port)
            for _ in range(4)
        ]
        errs = []

        def hammer(idx, ch):
            payload = bytes([65 + idx]) * (16 + idx)
            for _ in range(200):
                rc, err, _m, body = ch.call(
                    "svc", "echo", payload, timeout_ms=5000
                )
                if rc < 0 or err != 0 or body.to_bytes(len(body)) != payload:
                    errs.append((idx, rc, err))
                    return

        try:
            ts = [
                threading.Thread(target=hammer, args=(i, ch))
                for i, ch in enumerate(chans)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, errs[:3]
            assert sum(ch.cid_misroutes() for ch in chans) == 0
        finally:
            for ch in chans:
                ch.close()

    @staticmethod
    def _wrong_shard_server():
        """A raw tbus_std 'server' that echoes request frames with the
        cid's shard byte flipped — the cross-reactor misroute fuzz."""
        import socket as pysocket
        import struct

        lst = pysocket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)

        def serve():
            while True:
                try:
                    conn, _ = lst.accept()
                except OSError:
                    return
                buf = b""
                while True:
                    try:
                        d = conn.recv(65536)
                    except OSError:
                        break
                    if not d:
                        break
                    buf += d
                    while len(buf) >= 32:
                        h = struct.unpack("<8I", buf[:32])
                        if len(buf) < 32 + h[1]:
                            break
                        frame, buf = buf[: 32 + h[1]], buf[32 + h[1]:]
                        hdr = list(struct.unpack("<8I", frame[:32]))
                        hdr[2] |= 1  # response flag
                        hdr[4] ^= 0xFF000000  # corrupt the shard tag
                        try:
                            conn.sendall(
                                struct.pack("<8I", *hdr) + frame[32:]
                            )
                        except OSError:
                            break

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        return lst, lst.getsockname()[1]

    def test_wrong_shard_cid_answered_erequest_not_crash(self):
        """A response whose cid carries another shard's tag completes
        the caller with EREQUEST (via -EBADMSG) instead of crashing or
        stranding it; the channel survives and counts the misroute."""
        import errno

        lst, port = self._wrong_shard_server()
        try:
            ch = native_plane.NativeClientChannel("127.0.0.1", port)
            try:
                rc, err, _m, _b = ch.call(
                    "svc", "echo", b"payload", timeout_ms=3000
                )
                assert rc == -errno.EBADMSG
                assert ch.cid_misroutes() == 1
                assert ch.healthy()  # survived: no sticky failure
            finally:
                ch.close()
        finally:
            lst.close()

    def test_wrong_shard_cid_surfaces_erequest_at_l5(self):
        """The Python Channel maps the misroute to EREQUEST (the
        'answered EREQUEST' half of the fuzz contract)."""
        lst, port = self._wrong_shard_server()
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{port}",
                options=ChannelOptions(native_plane=True, timeout_ms=3000),
            )
            cntl = ch.call_method("svc", "echo", b"q")
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.EREQUEST
        finally:
            lst.close()

    def test_lame_duck_multi_reactor(self, native_server):
        """pause_accept tears down EVERY reactor's listener (on its own
        loop thread) while existing connections keep being served — the
        PR 8 single-loop assumption, retired."""
        import socket as pysocket

        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        chans = [
            native_plane.NativeClientChannel("127.0.0.1", port)
            for _ in range(4)
        ]
        try:
            for ch in chans:
                rc, err, _m, _b = ch.call("svc", "echo", b"x", timeout_ms=5000)
                assert rc >= 0 and err == 0
            srv._native_plane.pause_accept()
            # every reactor's listener closes asynchronously (sub-ms
            # wakeup, 500 ms epoll backstop): new connects must fail
            deadline = time.monotonic() + 3.0
            refused = False
            while time.monotonic() < deadline:
                try:
                    probe = pysocket.create_connection(
                        ("127.0.0.1", port), timeout=0.2
                    )
                    # accepted by a not-yet-torn-down listener: the conn
                    # may still die immediately; retry until refused
                    probe.close()
                    time.sleep(0.05)
                except OSError:
                    refused = True
                    break
            assert refused, "listeners still accepting after pause_accept"
            # existing connections keep working on every reactor
            for ch in chans:
                rc, err, _m, _b = ch.call("svc", "echo", b"y", timeout_ms=5000)
                assert rc >= 0 and err == 0
        finally:
            for ch in chans:
                ch.close()

    def test_close_idle_multi_reactor(self, native_server):
        """Idle reap walks every reactor's connection list; the owning
        loop reaps via EPOLLHUP."""
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        chans = [
            native_plane.NativeClientChannel("127.0.0.1", port)
            for _ in range(4)
        ]
        try:
            for ch in chans:
                rc, err, _m, _b = ch.call("svc", "echo", b"x", timeout_ms=5000)
                assert rc >= 0 and err == 0
            time.sleep(0.15)
            culled = srv._native_plane.close_idle(0.05)
            assert culled == 4  # one idle conn per reactor, all reaped
        finally:
            for ch in chans:
                ch.close()


class TestDispatchPool:
    """Work-stealing dispatch pool: long-running native methods defer to
    pool workers so one slow handler can't stall its reactor's cut/pack
    work."""

    SRC = r"""
    #include <stdlib.h>
    #include <string.h>
    #include <unistd.h>
    extern "C" int slow_reverse_method(void* ud, const char* req, size_t n,
                                       char** resp, size_t* resp_len) {
      (void)ud;
      usleep(400000);  /* 400 ms: long enough to prove the loop is free */
      char* out = (char*)malloc(n ? n : 1);
      for (size_t i = 0; i < n; ++i) out[i] = req[n - 1 - i];
      *resp = out;
      *resp_len = n;
      return 0;
    }
    """

    @pytest.fixture(scope="class")
    def slow_lib(self, tmp_path_factory):
        import subprocess

        d = tmp_path_factory.mktemp("slow_methods")
        src = d / "slow.cc"
        so = d / "libslow.so"
        src.write_text(self.SRC)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-o", str(so), str(src)],
            check=True,
            capture_output=True,
        )
        return str(so)

    def _py_reverse(self, cntl, req):
        return req[::-1]

    def test_long_running_method_does_not_stall_reactor(
        self, native_server, slow_lib
    ):
        """ONE reactor, a 400 ms flagged-long-running native method in
        flight — echoes on the same reactor still answer fast because
        the slow handler runs on a pool worker, not the loop thread."""
        from incubator_brpc_tpu.rpc import native_long_running
        from incubator_brpc_tpu.transport.native_plane import (
            native_method_lib,
        )

        slow = native_long_running(
            native_method_lib(slow_lib, "slow_reverse_method",
                              self._py_reverse)
        )
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=1,
                native_dispatch_workers=2,
            ),
            services={"user": {"slow": slow, "echo": native_echo}},
        )
        port = _start(srv)
        slow_ch = native_plane.NativeClientChannel("127.0.0.1", port)
        echo_ch = native_plane.NativeClientChannel("127.0.0.1", port)
        result = {}

        def run_slow():
            t0 = time.perf_counter()
            rc, err, _m, body = slow_ch.call(
                "user", "slow", b"abcdef", timeout_ms=10000
            )
            result["rc"] = rc
            result["err"] = err
            result["body"] = body.to_bytes(len(body))
            result["dt"] = time.perf_counter() - t0

        try:
            t = threading.Thread(target=run_slow)
            t.start()
            time.sleep(0.08)  # the slow call is now inside usleep
            t0 = time.perf_counter()
            rc, err, _m, body = echo_ch.call(
                "user", "echo", b"fast", timeout_ms=5000
            )
            echo_dt = time.perf_counter() - t0
            assert rc >= 0 and err == 0
            assert body.to_bytes(len(body)) == b"fast"
            t.join(timeout=15)
            assert result["rc"] >= 0 and result["err"] == 0, result
            assert result["body"] == b"fedcba"
            assert result["dt"] >= 0.4  # the method really slept
            # the echo answered while the slow call was still in flight:
            # the reactor loop was NOT blocked behind the 400 ms method
            assert echo_dt < 0.2, f"echo stalled {echo_dt * 1e3:.0f} ms"
            # both dispatched natively, zero Python frames
            stats = srv._native_plane.stats()
            assert stats["cb_frames"] == 0
            assert stats["native_reqs"] >= 2
        finally:
            slow_ch.close()
            echo_ch.close()

    def test_pool_telemetry_records_carry_reactor(
        self, native_server, slow_lib, tuned_flags
    ):
        """Deferred dispatches still record completions into the OWNING
        reactor's ring (latency spans queue + run)."""
        import numpy as np

        from incubator_brpc_tpu.rpc import native_long_running
        from incubator_brpc_tpu.transport.native_plane import (
            native_method_lib,
        )

        tuned_flags("native_telemetry", True)
        slow = native_long_running(
            native_method_lib(slow_lib, "slow_reverse_method",
                              self._py_reverse)
        )
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=2,
                native_dispatch_workers=1,
            ),
            services={"user": {"slow": slow}},
        )
        port = _start(srv)
        plane = srv._native_plane
        captured = TestMultiReactor._capture_drained(
            TestMultiReactor(), plane
        )
        ch = native_plane.NativeClientChannel("127.0.0.1", port)
        try:
            rc, err, _m, body = ch.call(
                "user", "slow", b"xy", timeout_ms=10000
            )
            assert rc >= 0 and err == 0
            assert body.to_bytes(len(body)) == b"yx"
        finally:
            shard = ch.reactor
            ch.close()
        plane.drain_telemetry()
        recs = np.concatenate(captured)
        mine = recs[(recs["correlation_id"] >> 56) == shard]
        assert len(mine) == 1
        rec = mine[0]
        assert int(rec["error_code"]) == 0
        assert int(rec["latency_ns"]) >= 400_000_000  # the sleep is in it
        assert int(rec["reactor_id"]) in (0, 1)


class TestWorkStealingDeque:
    """tb_wsq_*: the dispatch pool's Chase–Lev deque driven directly."""

    def test_push_pop_fifo_lifo_contract(self):
        from incubator_brpc_tpu.native import LIB
        import ctypes

        q = LIB.tb_wsq_create(64)
        try:
            for v in (10, 20, 30):
                assert LIB.tb_wsq_push(q, v) == 0
            assert LIB.tb_wsq_size(q) == 3
            out = ctypes.c_uint64()
            # owner pops the BOTTOM (LIFO)
            assert LIB.tb_wsq_pop(q, ctypes.byref(out)) == 1
            assert out.value == 30
            # thief steals the TOP (FIFO)
            assert LIB.tb_wsq_steal(q, ctypes.byref(out)) == 1
            assert out.value == 10
            assert LIB.tb_wsq_pop(q, ctypes.byref(out)) == 1
            assert out.value == 20
            assert LIB.tb_wsq_pop(q, ctypes.byref(out)) == 0  # empty
            assert LIB.tb_wsq_steal(q, ctypes.byref(out)) == 0
        finally:
            LIB.tb_wsq_destroy(q)

    def test_full_deque_rejects_push(self):
        from incubator_brpc_tpu.native import LIB
        import ctypes

        q = LIB.tb_wsq_create(1)  # rounds up to the 64 minimum
        try:
            pushed = 0
            while LIB.tb_wsq_push(q, pushed) == 0:
                pushed += 1
                assert pushed < 10000  # must hit the cap
            assert pushed >= 64
            out = ctypes.c_uint64()
            assert LIB.tb_wsq_pop(q, ctypes.byref(out)) == 1
            assert LIB.tb_wsq_push(q, 999) == 0  # space freed
        finally:
            LIB.tb_wsq_destroy(q)


@pytest.mark.slow
class TestWorkStealingDequeStress:
    """Steal storm racing owner push/pop + stop — the `make san` TSAN
    workload for the Chase–Lev deque (WSQ_STRESS_* sized, like the ring
    stress).  Conservation: every pushed value is consumed exactly once
    (owner pop or a thief's steal), nothing lost, nothing duplicated."""

    def test_steal_storm_conservation(self):
        import ctypes
        import os

        from incubator_brpc_tpu.native import LIB

        nthieves = int(os.environ.get("WSQ_STRESS_THREADS", "4"))
        n_items = int(os.environ.get("WSQ_STRESS_N", "20000"))
        q = LIB.tb_wsq_create(1024)
        stop = threading.Event()
        stolen: list = [[] for _ in range(nthieves)]
        popped: list = []

        def thief(idx):
            out = ctypes.c_uint64()
            got = stolen[idx]
            while not stop.is_set() or LIB.tb_wsq_size(q) > 0:
                if LIB.tb_wsq_steal(q, ctypes.byref(out)) == 1:
                    got.append(out.value)

        ts = [
            threading.Thread(target=thief, args=(i,), name=f"thief-{i}")
            for i in range(nthieves)
        ]
        for t in ts:
            t.start()
        # owner: push everything, interleaving pops (the stop-time drain
        # shape) so pop-vs-steal races on the last element get exercised
        out = ctypes.c_uint64()
        pushed = 0
        while pushed < n_items:
            if LIB.tb_wsq_push(q, pushed) == 0:
                pushed += 1
            else:  # full: drain a few from our own bottom like the pool
                if LIB.tb_wsq_pop(q, ctypes.byref(out)) == 1:
                    popped.append(out.value)
            if pushed % 97 == 0 and LIB.tb_wsq_pop(
                q, ctypes.byref(out)
            ) == 1:
                popped.append(out.value)
        stop.set()
        for t in ts:
            t.join()
        # owner drains the leftovers (reactor stop discipline)
        while LIB.tb_wsq_pop(q, ctypes.byref(out)) == 1:
            popped.append(out.value)
        LIB.tb_wsq_destroy(q)
        consumed = popped + [v for lst in stolen for v in lst]
        assert len(consumed) == n_items, (
            f"consumed {len(consumed)} != pushed {n_items}"
        )
        assert len(set(consumed)) == n_items  # exactly-once, no dups


class TestMultiReactorReviewFixes:
    """Regressions for the review findings on the multi-reactor plane."""

    def test_explicit_port_double_bind_still_eaddrinuse(self, native_server):
        """SO_REUSEPORT on the per-reactor listeners must NOT let a
        second multi-reactor server bind the same explicit port — the
        kernel would silently split connections between unrelated
        servers.  An exclusive probe bind preserves the EADDRINUSE
        contract."""
        srv1 = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv1)
        srv2 = Server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4,
                has_builtin_services=False,
            )
        )
        srv2.add_service("svc2", {"echo": native_echo})
        try:
            # the native listen refuses (EADDRINUSE probe), and the
            # Python-acceptor fallback then fails the same way — the
            # double start is LOUD, not silent connection splitting
            with pytest.raises(OSError) as exc:
                assert not srv2.start(port)
            import errno as _errno

            assert exc.value.errno == _errno.EADDRINUSE
        finally:
            srv2.stop()
        # the first server still owns the port
        ch = native_plane.NativeClientChannel("127.0.0.1", port)
        try:
            rc, err, _m, _b = ch.call("svc", "echo", b"x", timeout_ms=5000)
            assert rc >= 0 and err == 0
        finally:
            ch.close()

    def test_queue_expired_deadline_shed_in_pool(
        self, native_server, tmp_path_factory
    ):
        """A deferred task whose propagated deadline expires while it
        waits in the work-stealing deque is shed EDEADLINE by the pool
        worker instead of running the slow method for a caller that
        already gave up."""
        import subprocess

        from incubator_brpc_tpu.rpc import native_long_running
        from incubator_brpc_tpu.transport.native_plane import (
            native_method_lib,
        )

        d = tmp_path_factory.mktemp("shed_methods")
        src = d / "slow.cc"
        so = d / "libslow.so"
        src.write_text(TestDispatchPool.SRC)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-o", str(so), str(src)],
            check=True, capture_output=True,
        )
        slow = native_long_running(
            native_method_lib(
                str(so), "slow_reverse_method",
                TestDispatchPool._py_reverse,
            )
        )
        srv = native_server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=1,
                native_dispatch_workers=1,  # ONE worker: second call queues
            ),
            services={"user": {"slow": slow}},
        )
        port = _start(srv)
        ch1 = native_plane.NativeClientChannel("127.0.0.1", port)
        ch2 = native_plane.NativeClientChannel("127.0.0.1", port)
        results = {}

        def first():
            results["first"] = ch1.call(
                "user", "slow", b"ab", timeout_ms=10000
            )[:2]

        try:
            t = threading.Thread(target=first)
            t.start()
            time.sleep(0.1)  # the worker is now inside the 400 ms sleep
            # 150 ms budget: expires ~250 ms before the worker frees up
            rc, err, _m, _b = ch2.call(
                "user", "slow", b"cd", timeout_ms=150
            )
            t.join(timeout=15)
            assert results["first"] == (2, 0), results  # ran fine
            # the queued call was shed with the deadline error, not run
            assert err == ErrorCode.EDEADLINE or rc < 0, (rc, err)
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline:
                if srv._native_plane.stats()["deadline_sheds"] >= 1:
                    break
                time.sleep(0.02)
            assert srv._native_plane.stats()["deadline_sheds"] >= 1
        finally:
            ch1.close()
            ch2.close()
