"""Native network plane (src/tbnet + transport/native_plane.py).

Covers the shapes the reference exercises for its I/O core + protocol
layer (brpc_server_unittest.cpp, brpc_channel_unittest.cpp): echo through
the native dispatcher, the Python callback route (admission, errors,
async handlers), wire interop with the Python plane in both directions,
protocol-sniff handoff (HTTP portal on the same port), streams over a
native connection, and the pipelined pump harness.
"""

from __future__ import annotations

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
    StreamHandler,
    StreamOptions,
    native_echo,
    native_nop,
    stream_accept,
    stream_create,
)
from incubator_brpc_tpu.transport import native_plane
from incubator_brpc_tpu.utils.status import ErrorCode

pytestmark = pytest.mark.skipif(
    not native_plane.NET_AVAILABLE, reason="native runtime unavailable"
)


@pytest.fixture
def native_server():
    created = []

    def make(options=None, services=None):
        opts = options or ServerOptions(
            native_plane=True, usercode_inline=True
        )
        opts.native_plane = True
        srv = Server(opts)
        for name, handlers in (services or {}).items():
            srv.add_service(name, handlers)
        created.append(srv)
        return srv

    yield make
    for srv in created:
        srv.stop()


def _start(srv):
    assert srv.start(0)
    assert srv._native_plane is not None, "native plane did not engage"
    return srv.port


class TestNativeDispatch:
    def test_native_echo_roundtrip(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "echo", b"payload-bytes")
        assert c.ok(), c.error_text
        assert c.response_payload == b"payload-bytes"
        # served without the interpreter
        assert srv._native_plane.stats()["native_reqs"] >= 1

    def test_native_echo_with_attachment(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "echo", b"pp", attachment=b"A" * 1000)
        assert c.ok(), c.error_text
        assert c.response_payload == b"pp"
        assert c.response_attachment == b"A" * 1000

    def test_native_nop(self, native_server):
        srv = native_server(services={"svc": {"nop": native_nop}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "nop", b"ignored")
        assert c.ok(), c.error_text
        assert c.response_payload == b""

    def test_body_crc_flag_roundtrip(self, native_server):
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        set_flag_unchecked("tbus_body_crc", True)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
            )
            c = ch.call_method("svc", "echo", b"crc-covered")
            assert c.ok(), c.error_text
            assert c.response_payload == b"crc-covered"
        finally:
            set_flag_unchecked("tbus_body_crc", False)

    def test_unknown_method_fails_cleanly(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "missing", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ENOMETHOD
        c = ch.call_method("ghost", "echo", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ENOSERVICE


class TestPythonRoute:
    def test_python_handler_and_error(self, native_server):
        def boom(cntl, req):
            cntl.set_failed(ErrorCode.EINTERNAL, "deliberate")
            return b""

        srv = native_server(
            services={"svc": {"up": lambda cntl, req: req.upper(), "boom": boom}}
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        c = ch.call_method("svc", "up", b"abc")
        assert c.ok() and c.response_payload == b"ABC"
        c = ch.call_method("svc", "boom", b"")
        assert c.failed() and "deliberate" in c.error_text

    def test_async_handler_responds_from_other_thread(self, native_server):
        def slow(cntl, req):
            cntl.set_async()

            def later():
                time.sleep(0.05)
                cntl.send_response(b"late:" + req)

            threading.Thread(target=later).start()
            return None

        srv = native_server(services={"svc": {"slow": slow}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=2000),
        )
        c = ch.call_method("svc", "slow", b"x")
        assert c.ok(), c.error_text
        assert c.response_payload == b"late:x"

    def test_method_admission_via_python_route(self, native_server):
        gate = threading.Event()
        entered = threading.Event()

        def hold(cntl, req):
            entered.set()
            gate.wait(2)
            return b"done"

        opts = ServerOptions(native_plane=True, usercode_inline=False)
        srv = native_server(
            options=opts, services={"svc": {"hold": hold}}
        )
        srv._methods.get("svc.hold").status.max_concurrency = 1
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=3000),
        )
        results = []

        def call():
            results.append(ch.call_method("svc", "hold", b""))

        t1 = threading.Thread(target=call)
        t1.start()
        assert entered.wait(2)
        c2 = ch.call_method("svc", "hold", b"")
        assert c2.failed()
        assert c2.error_code == ErrorCode.ELIMIT
        gate.set()
        t1.join()
        assert results[0].ok()


class TestInterop:
    """Both planes speak the same wire: each client against each server."""

    def test_python_client_native_server(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}")  # plain Python-plane client
        c = ch.call_method("svc", "echo", b"from-python-plane")
        assert c.ok(), c.error_text
        assert c.response_payload == b"from-python-plane"

    def test_native_client_python_server(self):
        srv = Server(ServerOptions(usercode_inline=True))  # Python acceptor
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            assert srv._native_plane is None
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}", options=ChannelOptions(native_plane=True)
            )
            c = ch.call_method("svc", "echo", b"native-to-python")
            assert c.ok(), c.error_text
            assert c.response_payload == b"native-to-python"
        finally:
            srv.stop()

    def test_http_handoff_same_port(self, native_server):
        import urllib.request

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=5
        ).read()
        assert body == b"OK\n" or body.startswith(b"OK")
        assert srv._native_plane.stats()["handoffs"] >= 1

    def test_fallback_when_channel_dies(self, native_server):
        """Kill the server mid-conversation: the native channel reports the
        break, the regular path's dial/retry owns the recovery."""
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=1000),
        )
        assert ch.call_method("svc", "echo", b"1").ok()
        srv.stop()
        c = ch.call_method("svc", "echo", b"2")
        assert c.failed()  # recovered into a clean failure, no hang


class TestUserNativeMethods:
    """tb_server_register_native_fn: user bytes-in/bytes-out C methods run
    entirely on the loop thread (VERDICT r3 item 4a — the generalization
    of the built-in echo/nop kinds)."""

    SRC = r"""
    #include <stdlib.h>
    #include <string.h>
    extern "C" int reverse_method(void* ud, const char* req, size_t n,
                                  char** resp, size_t* resp_len) {
      (void)ud;
      char* out = (char*)malloc(n ? n : 1);
      for (size_t i = 0; i < n; ++i) out[i] = req[n - 1 - i];
      *resp = out;
      *resp_len = n;
      return 0;
    }
    extern "C" int failing_method(void* ud, const char* req, size_t n,
                                  char** resp, size_t* resp_len) {
      (void)ud; (void)req; (void)n; (void)resp; (void)resp_len;
      return 1008;  /* an application error code */
    }
    """

    @pytest.fixture(scope="class")
    def method_lib(self, tmp_path_factory):
        import subprocess

        d = tmp_path_factory.mktemp("native_methods")
        src = d / "methods.cc"
        so = d / "libmethods.so"
        src.write_text(self.SRC)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-o", str(so), str(src)],
            check=True,
            capture_output=True,
        )
        return str(so)

    def _py_reverse(self, cntl, req):
        return req[::-1]

    def test_so_method_never_crosses_into_python(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "reverse": native_method_lib(
                        method_lib, "reverse_method", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        before = srv._native_plane.stats()
        for payload in (b"abc", b"", b"x" * 10000):
            cntl = ch.call_method("user", "reverse", payload)
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == payload[::-1]
        after = srv._native_plane.stats()
        assert after["native_reqs"] - before["native_reqs"] == 3
        assert after["cb_frames"] == before["cb_frames"]  # zero Python frames

    def test_so_method_error_code_surfaces(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "boom": native_method_lib(
                        method_lib, "failing_method", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        cntl = ch.call_method("user", "boom", b"q")
        assert cntl.failed()
        assert cntl.error_code == 1008

    def test_missing_symbol_falls_back_to_python_route(self, native_server, method_lib):
        from incubator_brpc_tpu.transport.native_plane import native_method_lib

        srv = native_server(
            services={
                "user": {
                    "reverse": native_method_lib(
                        method_lib, "no_such_symbol", self._py_reverse
                    )
                }
            }
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        cntl = ch.call_method("user", "reverse", b"abc")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"cba"  # the Python fallback served
        assert srv._native_plane.stats()["cb_frames"] > 0


class TestStreamsOverNative:
    def test_stream_over_native_conn(self, native_server):
        got = []
        done = threading.Event()

        class Sink(StreamHandler):
            def on_received_messages(self, s, msgs):
                got.extend(msgs)
                if sum(len(m) for m in got) >= 4096:
                    done.set()

        def open_stream(cntl, req):
            stream_accept(cntl, StreamOptions(handler=Sink()))
            return b""

        srv = native_server(services={"svc": {"open": open_stream}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=3000),
        )
        s = stream_create(StreamOptions())
        c = ch.call_method("svc", "open", b"", request_stream=s)
        assert c.ok(), c.error_text
        assert s.wait_connected(3)
        chunk = b"z" * 1024
        for _ in range(4):
            assert s.write(chunk, timeout=3) == 0
        assert done.wait(5)
        assert b"".join(got) == chunk * 4
        s.close()


class TestNativeClientModes:
    def test_pooled_connection_type(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, connection_type="pooled"),
        )
        errs = []

        def worker():
            for i in range(50):
                c = ch.call_method("svc", "echo", b"t%d" % i)
                if c.failed() or c.response_payload != b"t%d" % i:
                    errs.append(c.error_text)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_concurrent_callers_shared_conn(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        errs = []

        def worker(tag):
            for i in range(100):
                payload = f"{tag}-{i}".encode()
                c = ch.call_method("svc", "echo", payload)
                if c.failed() or c.response_payload != payload:
                    errs.append((tag, i, c.error_text))

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:3]

    def test_pump_harness(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        nch = native_plane.NativeClientChannel("127.0.0.1", port)
        try:
            ns = nch.pump("svc", "echo", b"x" * 64, 2000, inflight=32)
            assert ns > 0
            # sanity: pipelined per-request cost must be far below the
            # Python plane's sync round trip
            assert ns < 1_000_000  # < 1 ms/req even on a loaded CI host
        finally:
            nch.close()

    def test_timeout_maps_to_rpc_timeout(self, native_server):
        def sleepy(cntl, req):
            time.sleep(0.5)
            return b""

        srv = native_server(services={"svc": {"sleepy": sleepy}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=100),
        )
        t0 = time.monotonic()
        c = ch.call_method("svc", "sleepy", b"")
        assert c.failed()
        assert c.error_code == ErrorCode.ERPCTIMEDOUT
        assert time.monotonic() - t0 < 0.45


class TestGatesStayEnforced:
    def test_auth_server_keeps_native_methods_on_python_route(self, native_server):
        """An Authenticator is a per-request gate the C++ fast path does not
        implement: with auth configured, even native-kind methods must go
        through Server.process_request (and reject bad credentials)."""
        from incubator_brpc_tpu.rpc import SharedSecretAuthenticator

        auth = SharedSecretAuthenticator("secret", identity="svc-a")
        srv = native_server(
            options=ServerOptions(
                native_plane=True, usercode_inline=True, auth=auth
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        # authenticated python-plane client works
        ch_ok = Channel()
        assert ch_ok.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(
                auth=SharedSecretAuthenticator("secret", identity="svc-a")
            ),
        )
        assert ch_ok.call_method("svc", "echo", b"hi").ok()
        # an unauthenticated native-plane client must be rejected, not
        # silently served by the C++ dispatcher
        ch_bad = Channel()
        assert ch_bad.init(
            f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True)
        )
        c = ch_bad.call_method("svc", "echo", b"hi")
        assert c.failed()
        assert c.error_code == ErrorCode.ERPCAUTH
        assert srv._native_plane.stats()["native_reqs"] == 0

    def test_server_max_concurrency_disables_native_kinds(self, native_server):
        srv = native_server(
            options=ServerOptions(
                native_plane=True, usercode_inline=True, max_concurrency=64
            ),
            services={"svc": {"echo": native_echo}},
        )
        port = _start(srv)
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        assert ch.call_method("svc", "echo", b"x").ok()
        # served via the Python route so the server-wide gate applies
        assert srv._native_plane.stats()["native_reqs"] == 0
        assert srv.nrequest.get_value() >= 1


class TestGarbageAndEdge:
    def test_garbage_after_magic_kills_conn_only(self, native_server):
        import socket as pysock
        import struct

        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        # valid magic, corrupt frame (crc mismatch)
        raw = pysock.create_connection(("127.0.0.1", port))
        hdr = struct.pack("<8I", 0x54505243, 8, 0, 1, 0, 0, 0xDEAD, 0)
        raw.sendall(hdr + b"xxxxxxxx")
        raw.settimeout(2)
        assert raw.recv(1024) == b""  # server killed the connection
        raw.close()
        # the server itself is fine
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(native_plane=True))
        assert ch.call_method("svc", "echo", b"still-up").ok()

    def test_large_payload(self, native_server):
        srv = native_server(services={"svc": {"echo": native_echo}})
        port = _start(srv)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(native_plane=True, timeout_ms=10000),
        )
        blob = bytes(range(256)) * (4 * 1024 * 16)  # 16 MiB
        c = ch.call_method("svc", "echo", blob)
        assert c.ok(), c.error_text
        assert c.response_payload == blob

    def test_unix_endpoint_falls_back_to_python_acceptor(self, tmp_path):
        srv = Server(ServerOptions(native_plane=True, usercode_inline=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(f"unix://{tmp_path}/np.sock")
        try:
            assert srv._native_plane is None  # fell back
            ch = Channel()
            assert ch.init(f"unix://{tmp_path}/np.sock")
            assert ch.call_method("svc", "echo", b"via-unix").ok()
        finally:
            srv.stop()


@pytest.mark.slow
class TestTelemetryRingStress:
    """Multi-producer hammer on the C++ telemetry ring against a live
    concurrent drain (the satellite workload `make san` runs under TSAN:
    every assert here doubles as the race-detector's coverage).

    Sizing comes from the environment so the sanitizer harness can run a
    shorter burn: TBNET_STRESS_THREADS (default 8) producer threads x
    TBNET_STRESS_N (default 2000) echoes each.
    """

    def test_multi_producer_append_vs_concurrent_drain(self, tuned_flags):
        import os

        import numpy as np

        from incubator_brpc_tpu.transport.native_plane import (
            NativeClientChannel,
            NativeServerPlane,
        )

        nthreads = int(os.environ.get("TBNET_STRESS_THREADS", "8"))
        per_thread = int(os.environ.get("TBNET_STRESS_N", "2000"))
        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_ring_size", 4096)
        tuned_flags("native_telemetry_sample_every", 64)
        # background cadence tight so the drain genuinely races producers
        tuned_flags("native_telemetry_drain_ms", 1)
        srv = Server(ServerOptions(native_plane=True, usercode_inline=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        plane = srv._native_plane
        assert plane is not None
        # capture every drained batch (post clock conversion) while the
        # real fan-out still runs — instance-level wrap, hot path intact
        captured = []
        cap_lock = threading.Lock()
        orig = plane._consume_records
        dtype = NativeServerPlane._rec_dtype()

        def capture(batch, n):
            arr = np.frombuffer(batch, dtype=dtype, count=n).copy()
            with cap_lock:
                captured.append(arr)
            orig(batch, n)

        plane._consume_records = capture
        errors = []

        def producer(tid):
            try:
                ch = NativeClientChannel("127.0.0.1", srv.port)
                # distinct payload size per thread: request_size becomes
                # the stream id for the per-producer monotonicity check
                payload = b"x" * (64 + tid)
                for _ in range(per_thread):
                    rc, err, _meta, _body = ch.call(
                        "svc", "echo", payload, timeout_ms=10000
                    )
                    if rc < 0 or err != 0:
                        errors.append((tid, rc, err))
                        return
                ch.close()
            except Exception as e:  # noqa: BLE001 - surface in main thread
                errors.append((tid, repr(e), None))

        stop_drain = threading.Event()

        def drainer():
            while not stop_drain.is_set():
                plane.drain_telemetry()

        threads = [
            threading.Thread(target=producer, args=(t,), name=f"prod-{t}")
            for t in range(nthreads)
        ]
        dr = threading.Thread(target=drainer, name="stress-drain")
        dr.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop_drain.set()
        dr.join()
        assert not errors, f"producer failures: {errors[:5]}"
        produced = plane.stats()["native_reqs"]
        assert produced == nthreads * per_thread
        srv.stop()  # final drain happens in stop()
        drained = plane._tel_drained
        dropped = plane.telemetry_dropped()
        # no lost-unless-counted: every dispatched request either reached
        # the drain or is accounted in the drop counter (ring overflow /
        # clock-invalid discard) — nothing vanishes silently
        assert drained + dropped == produced, (
            f"drained {drained} + dropped {dropped} != produced {produced}"
        )
        all_recs = np.concatenate(captured) if captured else np.zeros(0, dtype)
        assert len(all_recs) == drained
        if not len(all_recs):
            return
        # per-producer monotone drain timestamps: each client thread runs
        # serial round trips on its own connection, so its records'
        # converted start_ns must be non-decreasing in correlation order.
        # Tolerance covers the drain's continuously-refined tick->ns
        # calibration shifting between batches (sub-millisecond).
        tol_ns = 2_000_000
        streams = 0
        for size in np.unique(all_recs["request_size"]):
            grp = all_recs[all_recs["request_size"] == size]
            grp = grp[np.argsort(grp["correlation_id"], kind="stable")]
            starts = grp["start_ns"].astype(np.int64)
            regress = np.diff(starts)
            assert (regress >= -tol_ns).all(), (
                f"stream size={size}: drain timestamps regressed "
                f"{int(-regress.min())} ns"
            )
            streams += 1
        assert streams == nthreads
        # every sampled flag is the exact 1/N election — counter-based
        # over claimed ring positions, and claims never exceed produced
        # requests, so the count is bounded by ceil(produced/N)
        assert int(all_recs["sampled"].sum()) <= produced // 64 + 1
