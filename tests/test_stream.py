"""Streaming RPC tests (reference test/brpc_streaming_rpc_unittest.cpp:
handshake, ordered delivery, credit-window flow control, close)."""

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import (
    Channel,
    Server,
    StreamHandler,
    StreamOptions,
    stream_accept,
    stream_create,
)
from incubator_brpc_tpu.rpc import stream as stream_mod
from incubator_brpc_tpu.utils.status import ErrorCode


class Recorder(StreamHandler):
    def __init__(self, delay=0.0):
        self.messages = []
        self.closed = threading.Event()
        self.failed = threading.Event()
        self.delay = delay

    def on_received_messages(self, stream, messages):
        if self.delay:
            time.sleep(self.delay)
        self.messages.extend(messages)

    def on_closed(self, stream):
        self.closed.set()

    def on_failed(self, stream, code, reason):
        self.failed.set()
        self.closed.set()


@pytest.fixture
def echo_server():
    server = Server()
    accepted = {}

    def open_stream(cntl, request):
        opts = StreamOptions(handler=accepted.get("handler") or Recorder())
        s = stream_accept(cntl, opts)
        assert s is not None
        accepted["stream"] = s
        return b"accepted"

    def plain(cntl, request):
        return request

    server.add_service("test", {"open_stream": open_stream, "plain": plain})
    assert server.start(0)
    yield server, accepted
    server.stop()
    server.join(timeout=5)


def _connect(server, accepted, handler=None, client_opts=None):
    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")
    accepted["handler"] = handler
    s = stream_create(client_opts or StreamOptions(handler=Recorder()))
    cntl = ch.call_method("test", "open_stream", b"", request_stream=s)
    assert cntl.ok(), cntl.error_text
    assert s.wait_connected(timeout=5)
    return ch, s


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


class TestHandshake:
    def test_accept_connects_both_sides(self, echo_server):
        server, accepted = echo_server
        _, s = _connect(server, accepted, handler=Recorder())
        srv_stream = accepted["stream"]
        assert s.state == stream_mod.CONNECTED
        assert srv_stream.state == stream_mod.CONNECTED
        assert s.remote_id == srv_stream.id
        assert srv_stream.remote_id == s.id
        s.close()

    def test_unaccepted_stream_fails(self, echo_server):
        server, accepted = echo_server
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        s = stream_create(StreamOptions(handler=Recorder()))
        # "plain" never calls stream_accept → response meta has no stream id
        cntl = ch.call_method("test", "plain", b"x", request_stream=s)
        assert cntl.ok()
        assert _wait(lambda: s.state == stream_mod.CLOSED)
        assert s.write(b"data") == ErrorCode.EINVAL

    def test_failed_rpc_kills_stream(self, echo_server):
        server, accepted = echo_server
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        s = stream_create(StreamOptions(handler=Recorder()))
        cntl = ch.call_method("test", "nosuch", b"", request_stream=s)
        assert cntl.failed()
        assert _wait(lambda: s.state == stream_mod.CLOSED)


class TestDataPath:
    def test_ordered_delivery_client_to_server(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(server, accepted, handler=rec)
        msgs = [f"msg-{i}".encode() for i in range(50)]
        for m in msgs:
            assert s.write(m) == 0
        assert _wait(lambda: len(rec.messages) == 50)
        assert rec.messages == msgs
        s.close()

    def test_bidirectional(self, echo_server):
        server, accepted = echo_server
        client_rec = Recorder()
        _, s = _connect(
            server,
            accepted,
            handler=Recorder(),
            client_opts=StreamOptions(handler=client_rec),
        )
        srv_stream = accepted["stream"]
        assert srv_stream.write(b"from-server") == 0
        assert _wait(lambda: client_rec.messages == [b"from-server"])
        s.close()

    def test_large_messages(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(server, accepted, handler=rec)
        big = bytes(range(256)) * 4096  # 1 MiB
        assert s.write(big, timeout=10) == 0
        assert _wait(lambda: rec.messages == [big])
        s.close()


class TestFlowControl:
    def test_window_blocks_writer_and_feedback_resumes(self, echo_server):
        """The core credit-window property (stream.cpp:263-300): a slow
        consumer stalls the writer at max_buf_size; its feedback un-stalls."""
        server, accepted = echo_server
        rec = Recorder(delay=0.15)  # slow consumer
        _, s = _connect(server, accepted, handler=rec)
        s.options.max_buf_size = 4096
        chunk = b"x" * 2048

        # two chunks fill the window; the third must hit EAGAIN immediately
        assert s.write(chunk) == 0
        assert s.write(chunk) == 0
        assert s.write(chunk, timeout=0) == ErrorCode.EAGAIN
        assert s.unconsumed_bytes == 4096

        # blocking write parks until the consumer's feedback lifts the window
        t0 = time.monotonic()
        assert s.write(chunk, timeout=10) == 0
        waited = time.monotonic() - t0
        assert waited > 0.05  # it actually blocked on the butex
        assert _wait(lambda: len(rec.messages) == 3)
        s.close()

    def test_unlimited_window_never_blocks(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(
            server, accepted, handler=rec,
        )
        s.options.max_buf_size = 0
        for _ in range(20):
            assert s.write(b"y" * 1024, timeout=0) == 0
        assert _wait(lambda: len(rec.messages) == 20)
        s.close()


class TestClose:
    def test_close_notifies_peer_after_data(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(server, accepted, handler=rec)
        s.write(b"last-words")
        s.close()
        assert rec.closed.wait(timeout=5)
        assert rec.messages == [b"last-words"]  # data seen before close
        assert s.state == stream_mod.CLOSED
        assert s.write(b"after") == ErrorCode.EINVAL

    def test_registry_cleanup(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(server, accepted, handler=rec)
        sid, srv_sid = s.id, accepted["stream"].id
        assert stream_mod.get_stream(sid) is not None
        s.close()
        assert rec.closed.wait(timeout=5)
        assert stream_mod.get_stream(sid) is None
        assert _wait(lambda: stream_mod.get_stream(srv_sid) is None)

    def test_socket_failure_fails_stream(self, echo_server):
        server, accepted = echo_server
        rec = Recorder()
        ch, s = _connect(server, accepted, handler=Recorder())
        # fail the client's underlying socket out from under the stream
        client_rec = Recorder()
        s2 = stream_create(StreamOptions(handler=client_rec))
        cntl = ch.call_method("test", "open_stream", b"", request_stream=s2)
        assert cntl.ok()
        assert s2.wait_connected(timeout=5)
        s2._sock.set_failed(ErrorCode.EFAILEDSOCKET, "injected")
        assert client_rec.failed.wait(timeout=5)
        assert s2.write(b"z") == ErrorCode.EINVAL


class TestOversizedMessage:
    def test_message_larger_than_window_still_goes_out(self, echo_server):
        # A single message bigger than max_buf_size must be admitted on an
        # idle stream (one in-flight message may overshoot the window;
        # reference AppendIfNotFull stream.cpp:263). Before the fix this
        # parked the writer forever.
        server, accepted = echo_server
        rec = Recorder()
        _, s = _connect(
            server,
            accepted,
            handler=rec,
            client_opts=StreamOptions(handler=Recorder(), max_buf_size=64 * 1024),
        )
        big = bytes(256 * 1024)  # 4x the window
        assert s.write(big, timeout=5) == 0
        assert _wait(lambda: len(rec.messages) == 1)
        assert rec.messages[0] == big
        # and the window still functions afterwards: feedback caught up
        assert _wait(lambda: s.unconsumed_bytes == 0)
        s.close()


class TestStreamOverDeviceLink:
    """Streaming RPC with transport='tpu': the handshake piggybacks on an
    RPC over the device link and stream frames ride the link's byte
    stream — the 'bidirectional tensor stream over ICI' row of SURVEY
    §2.5 running on the real device plane."""

    def test_stream_rides_the_device_link(self, echo_server):
        from incubator_brpc_tpu.rpc import ChannelOptions
        from incubator_brpc_tpu.transport.device_link import DeviceSocket

        server, accepted = echo_server
        rec = Recorder()
        accepted["handler"] = rec
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(transport="tpu", timeout_ms=60000),
        )
        s = stream_create(StreamOptions(handler=Recorder()))
        cntl = ch.call_method("test", "open_stream", b"", request_stream=s)
        assert cntl.ok(), cntl.error_text
        assert s.wait_connected(timeout=10)
        # the RPC (and therefore the stream frames) rode a DeviceSocket
        assert isinstance(ch._device_sock, DeviceSocket)
        blob = bytes(range(256)) * 64
        for i in range(20):
            assert s.write(b"%03d:" % i + blob, timeout=30) == 0
        assert _wait(lambda: len(rec.messages) == 20, timeout=30)
        assert rec.messages[0][:4] == b"000:"
        assert rec.messages[19][:4] == b"019:"
        assert all(m[4:] == blob for m in rec.messages)
        s.close()
        assert rec.closed.wait(10)


class TestRawMessages:
    """StreamOptions(raw_messages=True): handlers receive zero-copy IOBuf
    objects (the reference hands butil::IOBufs, stream.h) — and the
    contract holds on parse paths that materialized bytes (the wrap
    fallback in Stream._consume)."""

    def test_raw_handler_gets_iobufs_with_correct_content(self):
        import threading

        from incubator_brpc_tpu.iobuf import IOBuf
        from incubator_brpc_tpu.rpc import (
            Channel,
            Server,
            ServerOptions,
            StreamHandler,
            StreamOptions,
            stream_accept,
            stream_create,
        )

        got = []
        done = threading.Event()

        class RawSink(StreamHandler):
            def on_received_messages(self, s, msgs):
                got.extend(msgs)
                if sum(len(m) for m in got) >= 3 * 65536:
                    done.set()

        def open_stream(cntl, req):
            stream_accept(
                cntl, StreamOptions(handler=RawSink(), raw_messages=True)
            )
            return b""

        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("raw", {"open": open_stream})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            s = stream_create(StreamOptions())
            c = ch.call_method("raw", "open", b"", request_stream=s)
            assert c.ok(), c.error_text
            assert s.wait_connected(5)
            msgs = [bytes([i]) * 65536 for i in range(3)]
            for m in msgs:
                assert s.write(m, timeout=10) == 0
            assert done.wait(10), "raw messages not delivered"
            # every delivered message is an IOBuf whose bytes round-trip
            assert all(not isinstance(m, (bytes, bytearray)) for m in got)
            assert [m.to_bytes() for m in got] == msgs or b"".join(
                m.to_bytes() for m in got
            ) == b"".join(msgs)
            s.close()
        finally:
            srv.stop()
            srv.join(timeout=10)

    def test_bytes_are_wrapped_for_raw_handlers(self):
        """Parse paths that produce bytes (pure-python fallback) still
        honor the IOBuf contract via the _consume wrap."""
        from incubator_brpc_tpu.rpc.stream import (
            FT_DATA,
            Stream,
            StreamHandler,
            StreamOptions,
        )

        got = []

        class RawSink(StreamHandler):
            def on_received_messages(self, s, msgs):
                got.extend(msgs)

        s = Stream(999001, StreamOptions(handler=RawSink(), raw_messages=True),
                   is_client=False)
        s._rq.execute((FT_DATA, b"plain-bytes-payload"))
        deadline = __import__("time").monotonic() + 5
        while not got and __import__("time").monotonic() < deadline:
            __import__("time").sleep(0.01)
        assert got, "message not consumed"
        assert not isinstance(got[0], (bytes, bytearray))
        assert got[0].to_bytes() == b"plain-bytes-payload"
