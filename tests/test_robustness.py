"""Overload control + failure isolation (reference
policy/auto_concurrency_limiter.cpp + circuit_breaker.cpp + the
fault-injection proof plane).

Three layers of proof:

- unit: the gradient limiter driven on a SYNTHETIC clock (every
  ``on_responded`` carries ``now_us``) — overload shrinks the limit,
  recovery raises it, all-fail windows halve it, the periodic probe-down
  remeasures the no-load floor; the breaker's EMA windows and exponential
  isolation; the injector's counter-based determinism.
- integration: a real server with ``max_concurrency="auto"`` sheds a 4x
  flood with ELIMIT while admitted p99 stays within 2x the unloaded
  baseline; a 3-backend round-robin channel isolates a browned-out
  backend within the breaker's short window and revives it after the
  fault clears — deterministic via FaultInjector, waits are bounded
  condition polls, never bare sleeps-as-synchronization.
- plumbing: adaptive limits pushed into the native plane
  (tb_server_set_native_max_concurrency), the /circuit_breakers page,
  the scrapeable gauges, device-link re-handshake backoff.
"""

from __future__ import annotations

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    FaultInjector,
    Server,
    ServerOptions,
    install_socket_injector,
)
from incubator_brpc_tpu.rpc.circuit_breaker import (
    CircuitBreaker,
    breaker_registry,
)
from incubator_brpc_tpu.rpc.concurrency_limiter import (
    AutoConcurrencyLimiter,
    ConstantConcurrencyLimiter,
    create_concurrency_limiter,
)
from incubator_brpc_tpu.utils.flags import flag_registry, set_flag_unchecked
from incubator_brpc_tpu.utils.status import ErrorCode


@pytest.fixture
def flags(tuned_flags):
    """Snapshot/restore any flag a test retunes — delegates to the shared
    ``tuned_flags`` fixture (conftest.py) so ONE implementation owns the
    restore discipline; kept under the historical local name."""
    yield tuned_flags


def wait_until(cond, timeout=10.0, interval=0.02):
    """Bounded condition poll (allowed: the condition is the
    synchronization; a bare sleep would not be)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# unit: the gradient limiter on a synthetic clock
# ---------------------------------------------------------------------------


class TestAutoLimiterUnit:
    def _feed(self, lim, n, latency_us, interval_us, now):
        """n completions, one per interval (so qps == 1e6/interval)."""
        for _ in range(n):
            now += interval_us
            lim.on_responded(0, latency_us, now_us=now)
        return now

    def test_initial_limit_from_flag(self, flags):
        flags("auto_cl_initial_max_concurrency", 17)
        lim = AutoConcurrencyLimiter()
        assert lim.max_concurrency() == 17
        assert lim.on_requested(17)
        assert not lim.on_requested(18)

    def test_overload_shrinks_then_recovery_raises(self, flags):
        flags("auto_cl_sampling_interval_us", 0)
        flags("auto_cl_initial_max_concurrency", 40)
        # keep the probe-down out of this test's horizon
        flags("auto_cl_noload_latency_remeasure_interval_ms", 10**7)
        lim = AutoConcurrencyLimiter()
        now = 1_000_000
        # healthy: 10k qps at 1ms -> Little's law concurrency ~10
        now = self._feed(lim, 1500, 1000.0, 100, now)
        healthy = lim.max_concurrency()
        assert 10 <= healthy <= 14, lim.describe()
        # saturated brownout: latency 6x the floor, throughput collapses
        # to 2.5k qps -> the gradient walks the limit down toward ~3
        for _ in range(15):
            now = self._feed(lim, 250, 6000.0, 400, now)
        overloaded = lim.max_concurrency()
        assert overloaded < healthy, lim.describe()
        assert overloaded <= 6, lim.describe()
        # recovery: latency back at the floor, qps ceiling re-proven ->
        # the limit converges back up
        now = self._feed(lim, 1500, 1000.0, 100, now)
        recovered = lim.max_concurrency()
        assert recovered > overloaded, lim.describe()
        assert recovered >= 10, lim.describe()

    def test_all_fail_window_halves(self, flags):
        flags("auto_cl_sampling_interval_us", 0)
        flags("auto_cl_initial_max_concurrency", 32)
        lim = AutoConcurrencyLimiter()
        now = 1_000_000
        for _ in range(int(flag_registry.get("auto_cl_max_sample_count"))):
            now += 100
            lim.on_responded(ErrorCode.EINTERNAL, 1000.0, now_us=now)
        assert lim.max_concurrency() == 16

    def test_probe_down_remeasures_floor(self, flags):
        flags("auto_cl_sampling_interval_us", 0)
        flags("auto_cl_initial_max_concurrency", 40)
        flags("auto_cl_noload_latency_remeasure_interval_ms", 50)
        # min == max: every 100th sample settles a window exactly
        flags("auto_cl_min_sample_count", 100)
        flags("auto_cl_max_sample_count", 100)
        lim = AutoConcurrencyLimiter()
        now = 1_000_000
        now = self._feed(lim, 100, 1000.0, 100, now)
        settled = lim.max_concurrency()
        assert lim.describe()["min_latency_us"] > 0
        # cross the remeasure horizon: the next settled window probes down
        # to reduce_ratio of the limit and opens the 2-RTT drain window
        now += 60_000
        now = self._feed(lim, 100, 1000.0, 100, now)
        d = lim.describe()
        assert d["remeasuring"], d
        assert d["max_concurrency"] < settled, d
        # the drain passes: the floor resets and is re-measured fresh
        now += 10_000
        now = self._feed(lim, 201, 1000.0, 100, now)
        d2 = lim.describe()
        assert not d2["remeasuring"], d2
        assert d2["min_latency_us"] > 0

    def test_sampling_interval_thins_samples(self, flags):
        flags("auto_cl_sampling_interval_us", 1000)
        lim = AutoConcurrencyLimiter()
        # two completions inside one interval: only the first is taken
        lim.on_responded(0, 500.0, now_us=5_000_000)
        lim.on_responded(0, 500.0, now_us=5_000_100)
        assert lim._sw_succ == 1

    def test_create_limiter_specs(self):
        assert create_concurrency_limiter(0) is None
        assert create_concurrency_limiter(None) is None
        assert create_concurrency_limiter("constant") is None
        assert isinstance(
            create_concurrency_limiter(5), ConstantConcurrencyLimiter
        )
        assert isinstance(
            create_concurrency_limiter("auto"), AutoConcurrencyLimiter
        )
        assert create_concurrency_limiter("12").max_concurrency() == 12
        with pytest.raises(ValueError):
            create_concurrency_limiter("sideways")


# ---------------------------------------------------------------------------
# unit: breaker windows + injector determinism
# ---------------------------------------------------------------------------


class TestCircuitBreakerUnit:
    def test_initializing_phase_trips_on_error_count(self, flags):
        flags("circuit_breaker_short_window_size", 50)
        flags("circuit_breaker_short_window_error_percent", 10)
        flags("circuit_breaker_long_window_size", 1000)
        cb = CircuitBreaker()
        # the initializing budget is window * percent = 5 errors
        for _ in range(4):
            assert cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        assert not cb.broken
        assert not cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        assert cb.broken
        assert cb.isolated_times == 1

    def test_errors_within_budget_stay_closed(self, flags):
        flags("circuit_breaker_short_window_size", 100)
        flags("circuit_breaker_short_window_error_percent", 10)
        flags("circuit_breaker_long_window_size", 1000)
        cb = CircuitBreaker()
        # 5% errors through the whole initializing window: healthy
        for i in range(100):
            code = ErrorCode.EINTERNAL if i % 20 == 0 else 0
            assert cb.on_call_end(code, 1000.0)
        assert not cb.broken

    def test_isolation_duration_doubles_on_fast_retrip(self, flags):
        flags("circuit_breaker_short_window_size", 20)
        flags("circuit_breaker_min_isolation_duration_ms", 100)
        flags("circuit_breaker_max_isolation_duration_ms", 1000)
        cb = CircuitBreaker()
        for _ in range(3):
            cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        assert cb.broken
        assert cb.isolation_duration_ms == 100
        cb.reset()  # half-open
        assert cb.state() == "half_open"
        for _ in range(3):
            cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        assert cb.broken
        assert cb.isolation_duration_ms == 200  # doubled
        cb.reset()
        for _ in range(3):
            cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        assert cb.isolation_duration_ms == 400

    def test_ema_error_cost_decays_on_success(self, flags):
        # window 100 @ 10%: a single error is far under the trip budget,
        # so the breaker stays closed and keeps feeding the recorders
        flags("circuit_breaker_short_window_size", 100)
        cb = CircuitBreaker()
        cb.on_call_end(0, 1000.0)
        cb.on_call_end(ErrorCode.EINTERNAL, 1000.0)
        cost1 = cb._short.describe()["ema_error_cost_us"]
        assert cost1 > 0
        for _ in range(50):
            assert cb.on_call_end(0, 1000.0)
        assert cb._short.describe()["ema_error_cost_us"] < cost1


class TestFaultInjectorUnit:
    def test_counter_schedule_is_deterministic_and_exact(self):
        inj = FaultInjector(error_rate=0.5)
        decisions = [inj.decide() for _ in range(100)]
        assert decisions.count("error") == 50
        # evenly interleaved, same positions every run
        inj2 = FaultInjector(error_rate=0.5)
        assert [inj2.decide() for _ in range(100)] == decisions

    def test_rates_compose(self):
        inj = FaultInjector(error_rate=0.25, delay_rate=0.25, delay_ms=0)
        decisions = [inj.decide() for _ in range(400)]
        assert decisions.count("error") == 100
        # delays only fire on operations the error schedule passed over
        assert 0 < decisions.count("delay") <= 100

    def test_close_takes_priority(self):
        inj = FaultInjector(error_rate=1.0, close_rate=1.0)
        assert inj.decide() == "close"


# ---------------------------------------------------------------------------
# integration: auto limiter on a live server
# ---------------------------------------------------------------------------


class TestServerAutoLimiter:
    def _start_capacity_server(self, capacity: int, work_s: float):
        """A server whose REAL capacity is ``capacity`` concurrent
        requests (a semaphore models the backend resource): admitted
        requests beyond it queue, so latency genuinely inflates when the
        limit overshoots — the world the gradient limiter regulates.
        Each handler records its own (monotonic, span_s) so 'latency of
        admitted requests' is measured at the server, where over-admission
        queueing shows up, not through this 1-core host's client-side GIL
        scheduling noise."""
        sem = threading.Semaphore(capacity)
        spans = []
        span_lock = threading.Lock()

        def handler(cntl, req):
            t0 = time.perf_counter()
            with sem:
                time.sleep(work_s)
            span = time.perf_counter() - t0
            with span_lock:
                spans.append((time.monotonic(), span))
            return b"ok"

        srv = Server(ServerOptions(max_concurrency="auto"))
        srv.add_service("cap", {"work": handler})
        assert srv.start(0)
        return srv, spans

    @staticmethod
    def _p99(values):
        values = sorted(values)
        return values[int(len(values) * 0.99)]

    def test_flood_sheds_with_bounded_latency_then_converges(self, flags):
        flags("auto_cl_sampling_interval_us", 0)
        # windows: 10 samples settle one (baseline serial traffic at
        # ~19 qps settles in ~550ms), 20 cap a flood window
        flags("auto_cl_min_sample_count", 10)
        flags("auto_cl_max_sample_count", 20)
        flags("auto_cl_sample_window_size_ms", 2000)
        flags("auto_cl_initial_max_concurrency", 6)
        flags("auto_cl_noload_latency_remeasure_interval_ms", 3600 * 1000)
        # the qps ceiling decays toward the true (saturated) throughput
        # faster than the production default so a seconds-long test flood
        # reaches convergence, not just the direction of travel
        flags("auto_cl_qps_alpha_factor_for_ema", 0.3)
        flags("auto_cl_change_rate_of_explore_ratio", 0.06)
        # geometry constraints of this shared 1-core host: work_s must
        # dominate GIL scheduling noise (spans then measure queueing, the
        # thing the limiter regulates), and capacity + the initial limit
        # must sit BELOW the worker pool's ~8 handler slots, or the pool —
        # not the limiter — becomes the admission gate and nothing sheds
        capacity, work_s = 2, 0.05
        srv, spans = self._start_capacity_server(capacity, work_s)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(timeout_ms=10000, max_retry=0),
        )
        try:
            # unloaded baseline: serial calls; p99 of the handler span
            for _ in range(20):
                c = ch.call_method("cap", "work", b"")
                assert c.ok(), c.error_text
            assert srv._server_limiter.describe()["min_latency_us"] > 0, (
                "baseline window never settled", srv._server_limiter.describe(),
            )
            p99_base = self._p99([s for _, s in spans])
            spans.clear()

            # 4x overload flood (8 callers vs capacity 2): shed or melt
            codes = []
            code_lock = threading.Lock()
            flood_s = 6.0
            stop_at = time.monotonic() + flood_s

            def flood():
                while time.monotonic() < stop_at:
                    c = ch.call_method("cap", "work", b"")
                    if c.failed():
                        with code_lock:
                            codes.append(c.error_code)
                        time.sleep(0.02)  # a shed caller backs off a tick

            threads = [threading.Thread(target=flood) for _ in range(8)]
            t_start = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            assert ErrorCode.ELIMIT in codes, (
                "flood was never shed",
                srv._server_limiter.describe(),
                len(spans),
            )
            # once the limiter has converged (last 40% of the flood), the
            # p99 latency of ADMITTED requests is within 2x the unloaded
            # baseline: the limit stopped queueing from forming
            tail_from = t_start + flood_s * 0.7
            tail = [s for t, s in spans if t >= tail_from]
            assert tail, "no admitted requests in the flood tail"
            p99_tail = self._p99(tail)
            assert p99_tail <= 2.0 * p99_base, (
                f"admitted p99 {p99_tail * 1e3:.1f}ms vs unloaded "
                f"{p99_base * 1e3:.1f}ms (limit={srv.max_concurrency})"
            )
            # the limit itself converged toward true capacity, below the
            # 6 it started from
            assert srv.max_concurrency <= capacity * 2, srv.max_concurrency
            limit_after_flood = srv.max_concurrency

            # the flood is gone: moderate healthy traffic re-proves the
            # floor and the limit converges back up (explore widens)
            def limit_recovered():
                for _ in range(10):
                    ch.call_method("cap", "work", b"")
                return srv.max_concurrency >= limit_after_flood

            assert wait_until(limit_recovered, timeout=8.0), (
                limit_after_flood, srv.max_concurrency,
            )
        finally:
            srv.stop()
            srv.join(5)

    def test_constant_limit_still_works(self):
        srv = Server(ServerOptions(max_concurrency=1))
        gate = threading.Event()
        srv.add_service("s", {"m": lambda cntl, req: (gate.wait(5), b"")[1]})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(max_retry=0, timeout_ms=8000),
            )
            held = threading.Thread(
                target=lambda: ch.call_method("s", "m", b"")
            )
            held.start()
            assert wait_until(lambda: srv._nprocessing >= 1, 5.0)
            c = ch.call_method("s", "m", b"")
            gate.set()
            held.join(10)
            assert c.failed() and c.error_code == ErrorCode.ELIMIT
        finally:
            gate.set()
            srv.stop()
            srv.join(5)

    def test_runtime_reset_to_auto(self, flags):
        flags("auto_cl_initial_max_concurrency", 9)
        srv = Server()
        srv.add_service("s", {"m": lambda cntl, req: b""})
        assert srv.start(0)
        try:
            assert srv.max_concurrency == 0
            prev = srv.reset_max_concurrency("auto")
            assert prev == 0
            assert srv.max_concurrency == 9
            assert srv.reset_max_concurrency(25) == "auto"
            assert srv.max_concurrency == 25
        finally:
            srv.stop()
            srv.join(5)

    def test_per_method_auto_spec(self, flags):
        flags("auto_cl_initial_max_concurrency", 6)
        srv = Server()
        srv.add_service(
            "s", {"m": lambda cntl, req: b""}, max_concurrency="auto"
        )
        status = srv.method_status("s", "m")
        assert isinstance(status.limiter, AutoConcurrencyLimiter)
        assert status.max_concurrency == 6
        assert srv.set_method_max_concurrency("s.m", 3)
        assert status.max_concurrency == 3


@pytest.mark.skipif(
    not __import__(
        "incubator_brpc_tpu.transport.native_plane", fromlist=["NET_AVAILABLE"]
    ).NET_AVAILABLE,
    reason="native runtime unavailable",
)
class TestNativePlaneAdaptiveLimit:
    def test_adaptive_limit_reaches_native_dispatch(self, flags):
        from incubator_brpc_tpu.rpc import native_echo

        flags("auto_cl_sampling_interval_us", 0)
        flags("auto_cl_min_sample_count", 20)
        flags("auto_cl_max_sample_count", 40)
        flags("auto_cl_initial_max_concurrency", 16)
        srv = Server(
            ServerOptions(max_concurrency="auto", native_plane=True)
        )
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        try:
            plane = srv._native_plane
            assert plane is not None
            assert "svc.echo" in plane.native_method_names()
            # seeded at start with the initial adaptive limit
            assert plane.native_max_concurrency("svc.echo") == 16
            # drive the SERVER limiter with a synthetic overload (the
            # deterministic path) and watch the push reach the C++ table
            now = 1_000_000
            for _ in range(20):
                for _ in range(50):
                    now += 400
                    srv._server_limiter.on_responded(0, 6000.0, now_us=now)
            new_limit = srv.max_concurrency
            assert new_limit != 16, srv._server_limiter.describe()
            assert plane.native_max_concurrency("svc.echo") == new_limit
            # and the C++ dispatch path ENFORCES what was pushed: clamp to
            # 1, hold that slot with a slow Python-routed request? native
            # methods have no slow path — instead prove the limit value is
            # read per request by the existing ELIMIT machinery: set 0
            # (unlimited) and 1 and observe both accepted
            assert plane.set_native_max_concurrency("svc.echo", 1)
            assert plane.native_max_concurrency("svc.echo") == 1
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(native_plane=True),
            )
            c = ch.call_method("svc", "echo", b"x")
            assert c.ok(), c.error_text
        finally:
            srv.stop()
            srv.join(5)

    def test_numeric_string_limit_keeps_python_route(self):
        # "12" resolves to a CONSTANT limiter (same as 12): native-kind
        # methods must stay on the Python route where the server-wide
        # gate is enforced, exactly as with an int spec
        from incubator_brpc_tpu.rpc import native_echo

        srv = Server(ServerOptions(max_concurrency="12", native_plane=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        try:
            assert srv._native_plane is not None
            assert srv._native_plane.native_method_names() == []
            assert srv.max_concurrency == 12
        finally:
            srv.stop()
            srv.join(5)

    def test_runtime_method_limit_stops_following_server_pushes(self, flags):
        # a per-method limit set at runtime must not be clobbered by the
        # next server-wide adaptive push on the C++ plane
        from incubator_brpc_tpu.rpc import native_echo

        flags("auto_cl_initial_max_concurrency", 8)
        srv = Server(ServerOptions(max_concurrency="auto", native_plane=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        try:
            plane = srv._native_plane
            assert "svc.echo" in plane.auto_limit_targets()
            assert srv.set_method_max_concurrency("svc.echo", 5)
            assert plane.native_max_concurrency("svc.echo") == 5
            assert "svc.echo" not in plane.auto_limit_targets()
            srv._on_server_limit_change(80)  # a server-wide adaptive move
            assert plane.native_max_concurrency("svc.echo") == 5  # kept
            # clearing back to unlimited resumes following
            assert srv.set_method_max_concurrency("svc.echo", 0)
            assert "svc.echo" in plane.auto_limit_targets()
        finally:
            srv.stop()
            srv.join(5)

    def test_reset_away_from_auto_clears_native_ceiling(self, flags):
        from incubator_brpc_tpu.rpc import native_echo

        flags("auto_cl_initial_max_concurrency", 5)
        srv = Server(ServerOptions(max_concurrency="auto", native_plane=True))
        srv.add_service("svc", {"echo": native_echo})
        assert srv.start(0)
        try:
            plane = srv._native_plane
            assert plane.native_max_concurrency("svc.echo") == 5
            # operator disables limiting: the stale adaptive ceiling must
            # not keep shedding natively-dispatched requests
            srv.reset_max_concurrency(0)
            assert plane.native_max_concurrency("svc.echo") == 0
            # and back to auto re-seeds the fresh limiter's limit
            srv.reset_max_concurrency("auto")
            assert plane.native_max_concurrency("svc.echo") == 5
        finally:
            srv.stop()
            srv.join(5)


# ---------------------------------------------------------------------------
# integration: brownout recovery through the circuit breaker (acceptance)
# ---------------------------------------------------------------------------


class TestBrownoutRecovery:
    def _echo_server(self, options=None):
        srv = Server(options)
        hits = []
        srv.add_service(
            "e", {"m": lambda cntl, req: (hits.append(1), b"ok")[1]}
        )
        assert srv.start(0)
        return srv, hits

    def test_breaker_isolates_brownout_and_revives(self, flags):
        flags("circuit_breaker_short_window_size", 30)
        flags("circuit_breaker_long_window_size", 300)
        flags("circuit_breaker_min_isolation_duration_ms", 400)
        flags("fault_injection", True)
        flags("enable_circuit_breaker", True)
        servers = []
        ch = None
        try:
            a, hits_a = self._echo_server()
            b, hits_b = self._echo_server()
            # backend c browns out: 50% of its dispatches fail (injected,
            # deterministic — every 2nd request)
            c, hits_c = self._echo_server(
                ServerOptions(fault_injector=FaultInjector(error_rate=0.5))
            )
            servers = [a, b, c]
            url = "list://" + ",".join(
                f"127.0.0.1:{s.port}" for s in servers
            )
            ch = Channel()
            assert ch.init(
                url, lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=4000),
            )
            lb = ch._lb

            # phase 1: drive calls until the breaker trips. The short
            # window (30 samples, 10%) must isolate c within its
            # initializing budget: 3 errors = 6 calls to c = ~18 total.
            fails_before = 0
            for i in range(120):
                if lb.isolated_servers():
                    break
                if ch.call_method("e", "m", b"x").failed():
                    fails_before += 1
            iso = lb.isolated_servers()
            assert len(iso) == 1 and iso[0].port == c.port, (
                iso, fails_before,
            )
            assert fails_before >= 3  # the trips that tripped it

            # phase 2: with c isolated, the channel's error rate returns
            # to <2% (here: zero) within the next short window of traffic
            window = 30
            fails_after = sum(
                1
                for _ in range(window)
                if ch.call_method("e", "m", b"x").failed()
            )
            assert fails_after / window < 0.02, fails_after
            assert lb.breaker_states()[f"127.0.0.1:{c.port}"][
                "state"
            ] == "isolated"

            # phase 3: the fault clears; after the isolation window the
            # node revives (half-open) and serves real traffic again
            c.fault_injector = None
            assert wait_until(
                lambda: not (
                    ch.call_method("e", "m", b"x") and lb.isolated_servers()
                ),
                timeout=10.0,
            )
            before_c = len(hits_c)
            fails_revived = 0
            for _ in range(60):
                if ch.call_method("e", "m", b"x").failed():
                    fails_revived += 1
            assert fails_revived == 0
            assert len(hits_c) > before_c, "revived backend got no traffic"
            state = lb.breaker_states()[f"127.0.0.1:{c.port}"]["state"]
            assert state in ("half_open", "closed"), state
        finally:
            if ch is not None and ch._lb is not None:
                ch._lb.stop()  # unregister breakers from the global registry
            for s in servers:
                s.stop()

    def test_all_isolated_is_ehostdown(self, flags):
        flags("circuit_breaker_short_window_size", 20)
        flags("circuit_breaker_min_isolation_duration_ms", 2000)
        flags("fault_injection", True)
        flags("enable_circuit_breaker", True)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            for _ in range(10):
                c = ch.call_method("e", "m", b"x")
                if ch._lb.isolated_servers():
                    break
            assert ch._lb.isolated_servers()
            c = ch.call_method("e", "m", b"x")
            assert c.failed() and c.error_code == ErrorCode.EHOSTDOWN, (
                c.error_code, c.error_text,
            )
            ch._lb.stop()  # unregister breakers from the global registry
        finally:
            srv.stop()

    def test_breaker_disabled_by_flag(self, flags):
        flags("fault_injection", True)
        flags("enable_circuit_breaker", False)
        flags("circuit_breaker_short_window_size", 10)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            for _ in range(30):
                ch.call_method("e", "m", b"x")
            assert not ch._lb.isolated_servers()
            ch._lb.stop()
        finally:
            srv.stop()

    def test_stragglers_do_not_reisolate_or_extend(self, flags):
        # completions landing AFTER the trip (the breaker reports
        # unhealthy for all of them) must not re-extend the isolation
        # deadline — only the trip transition isolates
        flags("circuit_breaker_short_window_size", 10)
        flags("circuit_breaker_min_isolation_duration_ms", 5000)
        flags("fault_injection", True)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            lb = ch._lb
            for _ in range(5):
                ch.call_method("e", "m", b"x")
            ep = lb.isolated_servers()[0]
            deadline = lb._isolated[ep]
            # straggler feedback on the already-broken breaker: the
            # deadline must not move
            sock = next(iter(lb._ep_by_sid))
            class FakeSock:
                id = sock
            lb.feedback(FakeSock(), 1000.0, ErrorCode.EINTERNAL)
            assert lb._isolated[ep] == deadline
            lb.stop()
        finally:
            srv.stop()

    def test_backup_superseded_original_spares_breaker(self, flags):
        # the backup-raced ORIGINAL attempt settles as EBACKUPREQUEST in
        # LB feedback: a healthy-but-slow node must not accrue error cost
        # from backup accounting
        flags("enable_circuit_breaker", True)
        flags("circuit_breaker_short_window_size", 10)
        slow_evt = threading.Event()

        def slow(cntl, req):
            slow_evt.wait(0.2)
            return b"slow"

        s1 = Server()
        s1.add_service("e", {"m": slow})
        assert s1.start(0)
        s2 = Server()
        s2.add_service("e", {"m": lambda cntl, req: b"fast"})
        assert s2.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                lb_name="rr",
                options=ChannelOptions(
                    max_retry=1, timeout_ms=4000, backup_request_ms=20
                ),
            )
            for _ in range(12):
                c = ch.call_method("e", "m", b"x")
                assert c.ok(), c.error_text
            slow_evt.set()
            # the slow node was repeatedly backup-raced but never errored:
            # its breaker must hold zero error cost and stay closed
            states = ch._lb.breaker_states()
            row = states.get(f"127.0.0.1:{s1.port}")
            if row is not None:
                assert row["state"] == "closed", row
                assert row["short_window"]["errors"] == 0, row
            assert not ch._lb.isolated_servers()
            ch._lb.stop()
        finally:
            slow_evt.set()
            s1.stop()
            s2.stop()

    def test_connect_refused_feeds_breaker(self, flags):
        # a hard-down node (connect refused) is the most common failure
        # mode: it must accrue breaker error cost from the select path
        # and isolate, not stay in rotation burning a dial per pick
        import socket as pysocket

        flags("enable_circuit_breaker", True)
        flags("circuit_breaker_short_window_size", 20)
        flags("circuit_breaker_min_isolation_duration_ms", 5000)
        up = Server()
        up.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert up.start(0)
        probe = pysocket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{up.port},127.0.0.1:{dead_port}",
                lb_name="rr",
                options=ChannelOptions(max_retry=1, timeout_ms=2000),
            )
            for _ in range(15):
                c = ch.call_method("e", "m", b"x")
                assert c.ok(), c.error_text
                if ch._lb.isolated_servers():
                    break
            iso = ch._lb.isolated_servers()
            assert iso and iso[0].port == dead_port, (
                iso, ch._lb.breaker_states(),
            )
            ch._lb.stop()
        finally:
            up.stop()

    def test_naming_churn_drops_breaker(self, flags):
        # a departed endpoint's breaker + registry row + isolation entry
        # go with it (autoscaling pools must not accumulate ghosts)
        flags("enable_circuit_breaker", True)
        srv = Server()
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            assert ch.call_method("e", "m", b"x").ok()
            lb = ch._lb
            ep_key = f"127.0.0.1:{srv.port}"
            assert ep_key in lb.breaker_states()
            from incubator_brpc_tpu.utils.endpoint import EndPoint

            lb.remove_server(EndPoint(ip="127.0.0.1", port=srv.port))
            assert ep_key not in lb.breaker_states()
            assert not any(
                owner == lb._cb_tag
                for (owner, _), _cb in breaker_registry.snapshot()
            )
            lb.stop()
        finally:
            srv.stop()

    def test_lb_stop_unhooks_revival_callbacks(self, flags):
        # sockets are process-global and outlive channels: a stopped LB
        # must remove the on_revived closures it appended, or every
        # create/destroy channel cycle leaks one per endpoint
        flags("enable_circuit_breaker", True)
        srv = Server()
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            assert ch.call_method("e", "m", b"x").ok()
            hooks = ch._lb._revival_hooks
            assert hooks, "revival hook was never installed"
            sock, cb = hooks[0]
            assert cb in sock.on_revived
            ch._lb.stop()
            assert cb not in sock.on_revived
            assert not ch._lb._revival_hooks
        finally:
            srv.stop()

    def test_extended_isolation_reschedules_revival_timer(self, flags):
        # straggler failures that EXTEND an isolation window must arm a
        # fresh timer: an idle channel would otherwise stay isolated
        # until its next select
        flags("circuit_breaker_short_window_size", 10)
        flags("circuit_breaker_min_isolation_duration_ms", 300)
        flags("fault_injection", True)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            lb = ch._lb
            for _ in range(5):
                ch.call_method("e", "m", b"x")
            assert lb.isolated_servers()
            ep = lb.isolated_servers()[0]
            # a straggler error arrives while isolated: the deadline
            # extends and a fresh timer must own it
            lb._isolate(ep)
            # no traffic at all from here on: revival must be TIMER-driven
            assert wait_until(
                lambda: ep not in lb._isolated, timeout=5.0
            ), lb._isolated
            lb.stop()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# fault-injection seams + observability plumbing
# ---------------------------------------------------------------------------


class TestFaultSeams:
    def test_socket_write_seam(self, flags):
        flags("fault_injection", True)
        srv = Server()
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            assert ch.call_method("e", "m", b"x").ok()
            install_socket_injector(FaultInjector(error_rate=1.0))
            try:
                c = ch.call_method("e", "m", b"x")
                assert c.failed(), "injected write error did not surface"
            finally:
                install_socket_injector(None)
            c = ch.call_method("e", "m", b"x")
            assert c.ok(), c.error_text
        finally:
            install_socket_injector(None)
            srv.stop()

    def test_master_flag_gates_everything(self, flags):
        flags("fault_injection", False)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            install_socket_injector(FaultInjector(error_rate=1.0))
            try:
                ch = Channel()
                assert ch.init(
                    f"127.0.0.1:{srv.port}",
                    options=ChannelOptions(max_retry=0, timeout_ms=2000),
                )
                c = ch.call_method("e", "m", b"x")
                assert c.ok(), c.error_text  # both seams dormant
            finally:
                install_socket_injector(None)
        finally:
            srv.stop()

    def test_dispatch_delay_seam(self, flags):
        flags("fault_injection", True)
        inj = FaultInjector(delay_rate=1.0, delay_ms=30)
        srv = Server(ServerOptions(fault_injector=inj))
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(max_retry=0, timeout_ms=4000),
            )
            t0 = time.perf_counter()
            c = ch.call_method("e", "m", b"x")
            dt = time.perf_counter() - t0
            assert c.ok() and dt >= 0.03, dt
            assert inj.injected["delay"] >= 1
        finally:
            srv.stop()


class TestObservability:
    def test_circuit_breakers_page_renders(self, flags):
        flags("fault_injection", True)
        flags("circuit_breaker_short_window_size", 10)
        flags("circuit_breaker_min_isolation_duration_ms", 5000)
        srv = Server(
            ServerOptions(fault_injector=FaultInjector(error_rate=1.0))
        )
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{srv.port}", lb_name="rr",
                options=ChannelOptions(max_retry=0, timeout_ms=2000),
            )
            for _ in range(6):
                ch.call_method("e", "m", b"x")
            assert ch._lb.isolated_servers()

            from incubator_brpc_tpu.builtin import pages

            class Frame:
                path = "/circuit_breakers"
                query = {}

            status, ctype, body = pages.handle(None, Frame())
            text = body.decode()
            assert status == 200
            assert f"127.0.0.1:{srv.port}" in text
            assert "[isolated]" in text

            class JsonFrame:
                path = "/circuit_breakers"
                query = {"json": "1"}

            status, ctype, body = pages.handle(None, JsonFrame())
            assert status == 200 and ctype == "application/json"
            assert b"isolated" in body

            # the isolated-node gauge is scrapeable
            from incubator_brpc_tpu.builtin.prometheus import render_metrics

            metrics = render_metrics("circuit_breaker")
            assert "circuit_breaker_isolated_count 1" in metrics, metrics
            ch._lb.stop()
        finally:
            srv.stop()

    def test_auto_limit_gauge_scrapeable(self, flags):
        flags("auto_cl_initial_max_concurrency", 11)
        srv = Server(ServerOptions(max_concurrency="auto"))
        srv.add_service("e", {"m": lambda cntl, req: b"ok"})
        assert srv.start(0)
        try:
            from incubator_brpc_tpu.builtin.prometheus import render_metrics

            metrics = render_metrics(f"server_{srv.port}")
            assert f"server_{srv.port}_max_concurrency 11" in metrics, metrics
        finally:
            srv.stop()
            srv.join(5)
            # gauges hidden at stop: the name is free for the next server
            from incubator_brpc_tpu.builtin.prometheus import render_metrics

            assert (
                f"server_{srv.port}_max_concurrency"
                not in render_metrics(f"server_{srv.port}")
            )


class TestDeviceLinkBackoff:
    def test_rehandshake_backs_off_exponentially(self, flags):
        import socket as pysocket

        from incubator_brpc_tpu.transport.device_link import DeviceLinkMap

        flags("device_link_backoff_initial_ms", 200)
        flags("device_link_backoff_max_ms", 1000)
        # a port with NOTHING listening: the bootstrap dial fails fast
        probe = pysocket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        from incubator_brpc_tpu.utils.endpoint import EndPoint

        dlm = DeviceLinkMap()
        ep = EndPoint(ip="127.0.0.1", port=dead_port)
        with pytest.raises((OSError, ConnectionError)):
            dlm.get_or_create(ep, timeout_ms=500)
        # the SECOND attempt inside the backoff window fails instantly
        # without dialing
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError, match="backing off"):
            dlm.get_or_create(ep, timeout_ms=500)
        assert time.perf_counter() - t0 < 0.1
        key = next(iter(dlm._backoff))
        assert dlm._backoff[key][0] == 1
        # after the window, a real (failing) attempt doubles the backoff
        assert wait_until(
            lambda: time.monotonic() >= dlm._backoff[key][1], timeout=2.0
        )
        with pytest.raises((OSError, ConnectionError)):
            dlm.get_or_create(ep, timeout_ms=500)
        assert dlm._backoff[key][0] == 2


# ---------------------------------------------------------------------------
# Fabric-wide failure semantics (PR 8): deadline propagation, collective
# session abort/recovery, lame-duck drain
# ---------------------------------------------------------------------------


class _CaptureSock:
    """Duck-typed connection for driving Server.process_request directly:
    captures response bytes (materialized) in wire order."""

    def __init__(self):
        self.remote = None
        self.context = {}
        self.written = []

    def write(self, data, **kw):
        self.written.append(
            data.to_bytes() if hasattr(data, "to_bytes") else bytes(data)
        )
        return 0


class TestDeadlinePropagation:
    """The propagated deadline (tbus_std JSON meta / PRPC RpcRequestMeta
    field 8 ``timeout_ms``): servers shed expired work with EDEADLINE
    before dispatch; the budget decrements across hops."""

    def _shed_server(self):
        srv = Server()
        hits = []
        srv.add_service("S", {"m": lambda c, r: (hits.append(1), b"ok")[1]})
        assert srv.start(0)
        return srv, hits

    def test_expired_at_arrival_is_shed_without_dispatch(self):
        from incubator_brpc_tpu.protocol.tbus_std import (
            Meta,
            ParsedFrame,
            try_parse_frame,
        )
        from incubator_brpc_tpu.rpc.server import deadline_shed_count

        srv, hits = self._shed_server()
        try:
            sock = _CaptureSock()
            frame = ParsedFrame(
                meta=Meta(service="S", method="m", timeout_ms=50),
                payload=b"x",
                correlation_id=7,
            )
            frame.arrival_ts = time.monotonic() - 0.2  # 200 ms in queue
            before = deadline_shed_count.get_value()
            srv.process_request(sock, frame)
            assert not hits, "shed request must never invoke the method"
            resp, _ = try_parse_frame(sock.written[0])
            assert resp.error_code == ErrorCode.EDEADLINE
            assert resp.meta.error_text == "Deadline expired before dispatch"
            assert deadline_shed_count.get_value() == before + 1
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_unexpired_budget_dispatches_and_sets_deadline_left(self):
        from incubator_brpc_tpu.protocol.tbus_std import Meta, ParsedFrame

        srv = Server()
        seen = {}

        def handler(cntl, req):
            seen["left"] = cntl.deadline_left_ms()
            seen["timeout"] = cntl.timeout_ms
            return b"ok"

        srv.add_service("S", {"m": handler})
        assert srv.start(0)
        try:
            frame = ParsedFrame(
                meta=Meta(service="S", method="m", timeout_ms=5000),
                payload=b"x",
                correlation_id=8,
            )
            frame.arrival_ts = time.monotonic()
            srv.process_request(_CaptureSock(), frame)
            assert seen["timeout"] == 5000
            assert 0 < seen["left"] <= 5000
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_budget_decrements_across_hops(self):
        """edge -> A -> B: B sees strictly less budget than A stamped,
        shrunk by at least A's handler time — the Controller decrement."""
        seen = {}

        srv_b = Server()
        srv_b.add_service(
            "B",
            {
                "m": lambda c, r: (
                    seen.__setitem__(
                        "b_budget", c.request_meta.timeout_ms
                    ),
                    b"ok",
                )[1]
            },
        )
        assert srv_b.start(0)

        def a_handler(cntl, req):
            seen["a_budget"] = cntl.request_meta.timeout_ms
            time.sleep(0.12)  # burn budget before the downstream hop
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv_b.port}")
            # NOTE: no explicit timeout — the downstream call inherits
            # what is LEFT of the caller's propagated budget
            c2 = ch.call_method("B", "m", b"y")
            assert c2.ok(), c2.error_text
            return b"ok"

        srv_a = Server()
        srv_a.add_service("A", {"m": a_handler})
        assert srv_a.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv_a.port}",
                options=ChannelOptions(timeout_ms=2000),
            )
            c = ch.call_method("A", "m", b"x")
            assert c.ok(), c.error_text
            assert 0 < seen["a_budget"] <= 2000
            assert seen["b_budget"] < seen["a_budget"] - 100, seen
        finally:
            srv_a.stop()
            srv_b.stop()
            srv_a.join(timeout=5)
            srv_b.join(timeout=5)

    def test_spent_budget_fails_fast_without_wire_traffic(self):
        from incubator_brpc_tpu.rpc import deadline as dl

        srv, hits = self._shed_server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            prev = dl.push_deadline(time.monotonic() - 0.01)
            try:
                c = ch.call_method("S", "m", b"x")
            finally:
                dl.pop_deadline(prev)
            assert c.error_code == ErrorCode.EDEADLINE
            assert not hits, "an expired budget must not reach the wire"
        finally:
            srv.stop()
            srv.join(timeout=5)


def _build_slow_native_lib(tmp_path):
    """Compile a tb_native_fn that sleeps 80 ms — the burst-delay that
    makes the SECOND frame of a batch expire mid-queue on the C++ plane.
    Skips when no C toolchain is available."""
    import subprocess

    src = tmp_path / "slow.c"
    src.write_text(
        "#include <stddef.h>\n"
        "#include <stdlib.h>\n"
        "#include <unistd.h>\n"
        "int tb_slow80(void* ud, const char* req, size_t n, char** resp,\n"
        "              size_t* resp_len) {\n"
        "    usleep(80000);\n"
        "    *resp = (char*)malloc(1);\n"
        "    (*resp)[0] = 's';\n"
        "    *resp_len = 1;\n"
        "    return 0;\n"
        "}\n"
    )
    so = tmp_path / "slow.so"
    try:
        subprocess.run(
            ["cc", "-shared", "-fPIC", "-O1", "-o", str(so), str(src)],
            check=True,
            capture_output=True,
            timeout=60,
        )
    except (OSError, subprocess.SubprocessError):
        pytest.skip("no C toolchain for the slow native method")
    return so


def _read_prpc_frames(sock, n):
    import struct as _struct

    out = []
    buf = b""
    for _ in range(n):
        while len(buf) < 12:
            buf += sock.recv(4096)
        body, _meta = _struct.unpack_from(">II", buf, 4)
        total = 12 + body
        while len(buf) < total:
            buf += sock.recv(4096)
        out.append(buf[:total])
        buf = buf[total:]
    return out


class TestNativeDeadlineShed:
    """The C++ cutter sheds expired-mid-queue work natively — EDEADLINE
    byte-identical to the Python route, counted and telemetry-recorded."""

    @pytest.fixture
    def native_shed(self, tmp_path):
        from incubator_brpc_tpu.transport import native_plane as np_mod

        if not np_mod.NET_AVAILABLE:
            pytest.skip("native plane unavailable")
        so = _build_slow_native_lib(tmp_path)
        srv = Server(ServerOptions(native_plane=True))
        slow = np_mod.native_method_lib(
            str(so), "tb_slow80", lambda c, r: b"s"
        )
        srv.add_service(
            "svc", {"slow": slow, "echo": np_mod.native_echo}
        )
        assert srv.start(0)
        if "svc.slow" not in srv._native_plane.native_method_names():
            srv.stop()
            pytest.skip("slow method did not register natively")
        yield srv
        srv.stop()
        srv.join(timeout=5)

    def test_native_shed_byte_identical_to_python_plane(self, native_shed):
        import socket as pysocket

        from incubator_brpc_tpu.protocol import baidu_std
        from incubator_brpc_tpu.protocol.tbus_std import Meta, ParsedFrame
        from incubator_brpc_tpu.rpc.server import deadline_shed_count

        srv = native_shed
        before = deadline_shed_count.get_value()
        # one burst: [slow (80 ms, no deadline), echo (30 ms budget)] —
        # the second frame expires while the first monopolizes the loop
        f1 = baidu_std.pack_request(
            Meta(service="svc", method="slow"), b"a", correlation_id=1
        )
        f2 = baidu_std.pack_request(
            Meta(service="svc", method="echo", timeout_ms=30),
            b"b",
            correlation_id=2,
        )
        with pysocket.create_connection(
            ("127.0.0.1", srv.port), timeout=10
        ) as s:
            s.sendall(f1 + f2)
            r1, r2 = _read_prpc_frames(s, 2)
        ok1, _ = baidu_std.try_parse_frame(r1)
        shed, _ = baidu_std.try_parse_frame(r2)
        assert ok1.error_code == 0
        assert shed.error_code == ErrorCode.EDEADLINE
        assert shed.meta.error_text == "Deadline expired before dispatch"

        # the Python plane's shed for the SAME request: byte-identical
        py_srv = Server()
        py_srv.add_service("svc", {"echo": lambda c, r: r})
        assert py_srv.start(0)
        try:
            cap = _CaptureSock()
            frame = ParsedFrame(
                meta=Meta(service="svc", method="echo", timeout_ms=30),
                payload=b"b",
                correlation_id=2,
            )
            frame.wire_protocol = "baidu_std"
            frame.arrival_ts = time.monotonic() - 0.08
            py_srv.process_request(cap, frame)
            assert cap.written[0] == r2, "native and Python sheds differ"
        finally:
            py_srv.stop()
            py_srv.join(timeout=5)

        # counted: the per-port C++ counter immediately; the global
        # deadline_shed_count once the telemetry drain folds it in
        assert srv._native_plane.stats()["deadline_sheds"] == 1
        srv._native_plane.drain_telemetry()
        assert deadline_shed_count.get_value() >= before + 1

    def test_fresh_deadline_rides_the_fast_path(self, native_shed):
        """A deadline-carrying frame with budget left stays on the
        interpreter-free plane (the scanner parses timeout_ms instead of
        routing to Python)."""
        srv = native_shed
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(
                native_plane=True, protocol="baidu_std", timeout_ms=2000
            ),
        )
        base = srv._native_plane.stats()
        c = ch.call_method("svc", "echo", b"hello")
        assert c.ok() and c.response_payload == b"hello"
        after = srv._native_plane.stats()
        assert after["native_reqs"] == base["native_reqs"] + 1
        assert after["cb_frames"] == base["cb_frames"]


class TestSessionAbortChaosDrill:
    """The acceptance chaos drill: kill one party mid multi-step session;
    survivors unblock with ESESSION within 2x the session deadline, the
    dead node's breaker trips, and a re-proposed session over the
    survivors succeeds."""

    DEADLINE_MS = 4000

    @pytest.fixture
    def mesh(self, tuned_flags):
        import jax

        from incubator_brpc_tpu.parallel.compat import resolve_shard_map

        try:
            resolve_shard_map()
        except ImportError:
            pytest.skip("no shard_map in this jax build")
        if len(jax.devices()) < 4:
            pytest.skip("needs a 4+ device mesh")
        # breaker windows sized so the dead party's refused dials trip it
        # within a screenful of calls (the TestBrownoutRecovery tuning)
        tuned_flags("circuit_breaker_short_window_size", 30)
        tuned_flags("circuit_breaker_long_window_size", 300)
        tuned_flags("circuit_breaker_min_isolation_duration_ms", 60000)
        tuned_flags("enable_circuit_breaker", True)
        from incubator_brpc_tpu.rpc import device_method
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            lookup_device_method,
            register_device_method,
        )
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
        )

        prev = lookup_device_method("dsvc", "scale")
        register_device_method(
            "dsvc", "scale", DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        )
        servers, channels = [], []
        for i in range(3):
            s = Server(
                ServerOptions(
                    device_index=i + 1,
                    enable_collective_service=True,
                    collective_max_concurrency=0,
                )
            )
            s.add_service(
                "dsvc",
                {"scale": device_method(_scale_psum_kernel, width=SESSION_WIDTH)},
            )
            assert s.start(0)
            servers.append(s)
            ch = Channel()
            # every party behind its own breaker-owning LB (list:// =
            # LoadBalancerWithNaming), so the drill can prove WHO gets
            # charged for the death
            assert ch.init(
                f"list://127.0.0.1:{s.port}",
                lb_name="rr",
                options=ChannelOptions(max_retry=1, timeout_ms=8000),
            )
            channels.append(ch)
        party_ids = [d.id for d in jax.devices()[1:4]]
        yield servers, channels, party_ids
        from incubator_brpc_tpu.parallel import mc_dispatch

        mc_dispatch.set_step_hook(None)
        for ch in channels:
            if ch._lb is not None:
                ch._lb.stop()
        for s in servers:
            s.stop()
            s.join(timeout=5)

    def test_party_death_aborts_survivors_and_recovery_succeeds(self, mesh):
        from incubator_brpc_tpu.parallel import mc_dispatch

        servers, channels, party_ids = mesh
        operands = [bytes([i + 1]) * 8 for i in range(3)]
        before_aborts = mc_dispatch.dispatch_aborts.get_value()

        # park every party between steps so the kill lands MID-session
        mc_dispatch.set_step_hook(lambda step: time.sleep(0.03))
        killer = threading.Timer(
            0.4, lambda: (servers[0].stop(), servers[0].join(timeout=3))
        )
        killer.start()
        t0 = time.monotonic()
        try:
            with pytest.raises(mc_dispatch.SessionAborted) as exc:
                mc_dispatch.propose_dispatch(
                    channels,
                    party_ids,
                    "dsvc",
                    "scale",
                    operands,
                    steps=120,
                    proposer_index=None,
                    timeout_ms=30000,
                    session_deadline_ms=self.DEADLINE_MS,
                )
        finally:
            killer.cancel()
        elapsed = time.monotonic() - t0
        assert elapsed < 2 * self.DEADLINE_MS / 1000.0
        assert exc.value.dead_indexes == (0,)
        assert exc.value.survivor_indexes == (1, 2)
        assert exc.value.error_code == ErrorCode.ESESSION

        # every survivor's handler unblocked (returned ESESSION) within
        # 2x the session deadline — not wedged in the lockstep barrier
        deadline = t0 + 2 * self.DEADLINE_MS / 1000.0
        assert wait_until(
            lambda: servers[1]._nprocessing == 0
            and servers[2]._nprocessing == 0
            and mc_dispatch.active_sessions() == 0,
            timeout=max(0.1, deadline - time.monotonic()),
        )
        assert mc_dispatch.dispatch_aborts.get_value() > before_aborts
        mc_dispatch.set_step_hook(None)

        # the dead node's breaker trips (connect-refused selects feed it);
        # the survivors' breakers stay closed — their ESESSION answers are
        # excluded from error cost
        for _ in range(30):
            if channels[0]._lb.isolated_servers():
                break
            channels[0].call_method("dsvc", "scale", b"x")
        assert channels[0]._lb.isolated_servers(), (
            "dead party's breaker never tripped"
        )
        for i in (1, 2):
            assert not channels[i]._lb.isolated_servers(), (
                f"survivor {i}'s breaker tripped off cooperative aborts"
            )

        # recovery: the next session over the surviving set completes
        out = mc_dispatch.propose_dispatch(
            channels[1:],
            party_ids[1:],
            "dsvc",
            "scale",
            operands[1:],
            steps=2,
            proposer_index=None,
            timeout_ms=30000,
        )
        assert out["final_steps"] == 2
        assert all(r is not None for r in out["results"])

    def test_propose_with_recovery_drops_dead_party(self, mesh):
        from incubator_brpc_tpu.parallel import mc_dispatch

        servers, channels, party_ids = mesh
        operands = [bytes([i + 1]) * 8 for i in range(3)]
        mc_dispatch.set_step_hook(lambda step: time.sleep(0.03))
        killer = threading.Timer(
            0.3, lambda: (servers[0].stop(), servers[0].join(timeout=3))
        )
        killer.start()
        try:
            out = mc_dispatch.propose_with_recovery(
                channels,
                party_ids,
                "dsvc",
                "scale",
                operands,
                steps=40,
                proposer_index=None,
                timeout_ms=30000,
                session_deadline_ms=self.DEADLINE_MS,
            )
        finally:
            killer.cancel()
            mc_dispatch.set_step_hook(None)
        # the re-proposed session ran over the survivors only
        assert out["dead_party_ids"] == [party_ids[0]]
        assert out["final_steps"] == 40
        assert out["results"][0] is not None and out["results"][1] is not None

    def test_esession_excluded_from_breaker_cost(self, tuned_flags):
        """Unit: N ESESSION completions never charge a node's breaker;
        the same N EFAILEDSOCKET completions trip it."""
        tuned_flags("circuit_breaker_short_window_size", 10)
        tuned_flags("enable_circuit_breaker", True)
        srv = Server()
        srv.add_service("e", {"m": lambda c, r: b"ok"})
        assert srv.start(0)
        ch = Channel()
        assert ch.init(
            f"list://127.0.0.1:{srv.port}",
            lb_name="rr",
            options=ChannelOptions(max_retry=0, timeout_ms=2000),
        )
        try:
            lb = ch._lb
            assert ch.call_method("e", "m", b"x").ok()
            sock = lb.select_server()
            for _ in range(50):
                lb.feedback(sock, 1000.0, ErrorCode.ESESSION)
                lb.feedback(sock, 1000.0, ErrorCode.EDEADLINE)
            assert not lb.isolated_servers(), (
                "cooperative failure codes charged the breaker"
            )
            for _ in range(50):
                lb.feedback(sock, 1000.0, ErrorCode.EFAILEDSOCKET)
                if lb.isolated_servers():
                    break
            assert lb.isolated_servers(), "real errors must still trip it"
        finally:
            if ch._lb is not None:
                ch._lb.stop()
            srv.stop()
            srv.join(timeout=5)


class TestLameDuck:
    """enter_lame_duck / /quitquitquit: accepting stops, /health flips,
    in-flight work drains with zero connection resets, then hard stop."""

    def test_drains_inflight_flood_cleanly(self):
        srv = Server()
        srv.add_service(
            "S", {"slow": lambda c, r: (time.sleep(0.25), b"done")[1]}
        )
        assert srv.start(0)
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}", options=ChannelOptions(timeout_ms=8000)
        )
        results = []
        lock = threading.Lock()

        def call():
            c = ch.call("S", "slow", b"x")
            with lock:
                results.append(c.error_code)

        ts = [threading.Thread(target=call) for _ in range(6)]
        for t in ts:
            t.start()
        assert wait_until(lambda: srv._nprocessing > 0, timeout=5.0)

        drain = srv.enter_lame_duck(grace_s=10)
        assert drain is not None
        assert srv.lame_duck

        # /health flips immediately
        from incubator_brpc_tpu.builtin.pages import _health

        class F:
            query = {}
            path = "/health"

        assert _health(srv, F)[0] == 503

        # NEW work is refused with (retriable) ELOGOFF, never a reset
        c2 = ch.call("S", "slow", b"y")
        assert c2.error_code == ErrorCode.ELOGOFF

        for t in ts:
            t.join()
        drain.join(timeout=15)
        assert not drain.is_alive()
        # zero connection-reset errors: every in-flight call completed OK
        assert results and all(code == 0 for code in results), results
        assert srv._stopping

    def test_quitquitquit_page_triggers_drain(self, flags):
        from incubator_brpc_tpu.builtin.pages import _quitquitquit

        flags("enable_quitquitquit", True)
        srv = Server()
        srv.add_service("S", {"m": lambda c, r: b"ok"})
        assert srv.start(0)

        class F:
            query = {"grace_s": "5"}
            path = "/quitquitquit"

        status, _ct, body = _quitquitquit(srv, F)
        assert status == 200 and b"lame-duck" in body
        assert srv.lame_duck
        srv._lame_duck_thread.join(timeout=10)
        assert srv._stopping

        class Bad:
            query = {"grace_s": "-1"}
            path = "/quitquitquit"

        assert _quitquitquit(srv, Bad)[0] == 400

    def test_quitquitquit_gated_off_by_default(self):
        """An unauthenticated remote stop must be opt-in (the /dir
        discipline): with the flag at its default the page refuses."""
        from incubator_brpc_tpu.builtin.pages import _quitquitquit

        srv = Server()
        srv.add_service("S", {"m": lambda c, r: b"ok"})
        assert srv.start(0)
        try:
            class F:
                query = {}
                path = "/quitquitquit"

            status, _ct, body = _quitquitquit(srv, F)
            assert status == 403 and b"enable_quitquitquit" in body
            assert not srv.lame_duck
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_lame_duck_drill_tool(self, flags):
        """The one-command drain-under-load run: rpc_press
        --lame-duck-drill against a live server reports a clean drain."""
        import sys

        flags("enable_quitquitquit", True)
        sys.path.insert(0, ".")
        from tools.rpc_press import run_lame_duck_drill

        srv = Server()
        srv.add_service("S", {"echo": lambda c, r: r})
        assert srv.start(0)
        counts = run_lame_duck_drill(
            f"127.0.0.1:{srv.port}",
            "S",
            "echo",
            b"x" * 32,
            threads=3,
            duration=2.0,
            timeout_ms=3000,
        )
        assert counts["drained_clean"], counts
        assert counts["ok"] > 0
        assert counts["reset"] == 0
        assert srv._stopping  # the drill terminated the target

    def test_sigterm_flag_installs_handler(self, tuned_flags):
        import signal

        from incubator_brpc_tpu.rpc import server as server_mod

        tuned_flags("graceful_quit_on_sigterm", True)
        prev_state = dict(server_mod._sigterm_state)
        prev_handler = signal.getsignal(signal.SIGTERM)
        server_mod._sigterm_state["installed"] = False
        try:
            srv = Server()
            srv.add_service("S", {"m": lambda c, r: b"ok"})
            assert srv.start(0)
            assert signal.getsignal(signal.SIGTERM) is server_mod._on_sigterm
            srv.stop()
            srv.join(timeout=5)
        finally:
            signal.signal(signal.SIGTERM, prev_handler)
            server_mod._sigterm_state.update(prev_state)


class TestNativeIdleReap:
    def test_idle_native_connection_reaped(self):
        """idle_timeout_s is enforced on native-plane ports now: an idle
        connection is culled from the C++ loops (satellite — the old
        behavior was a warning and an immortal connection)."""
        import socket as pysocket

        from incubator_brpc_tpu.transport import native_plane as np_mod

        if not np_mod.NET_AVAILABLE:
            pytest.skip("native plane unavailable")
        srv = Server(
            ServerOptions(native_plane=True, idle_timeout_s=0.4)
        )
        srv.add_service("svc", {"echo": np_mod.native_echo})
        assert srv.start(0)
        try:
            from incubator_brpc_tpu.protocol import baidu_std
            from incubator_brpc_tpu.protocol.tbus_std import Meta

            s = pysocket.create_connection(("127.0.0.1", srv.port), timeout=10)
            s.sendall(
                baidu_std.pack_request(
                    Meta(service="svc", method="echo"), b"hi", correlation_id=1
                )
            )
            (r1,) = _read_prpc_frames(s, 1)
            frame, _ = baidu_std.try_parse_frame(r1)
            assert frame.error_code == 0
            # now idle: the reap (scan at idle/2) must close it within a
            # few scan periods — recv returns b"" on the culled fd
            s.settimeout(5.0)
            got = s.recv(1)
            assert got == b"", "idle native connection was not reaped"
            s.close()
        finally:
            srv.stop()
            srv.join(timeout=5)


class TestNativeFaultSeam:
    """tb_channel_set_fault: the counter-scheduled client fault seam on
    the C++ plane (rpc_press --native-plane --fault-rate no longer forces
    the Python route)."""

    def test_deterministic_fail_schedule(self, flags):
        from incubator_brpc_tpu.transport import native_plane as np_mod

        if not np_mod.NET_AVAILABLE:
            pytest.skip("native plane unavailable")
        flags("fault_injection", True)
        np_mod.install_native_client_fault(fail_every=4)
        srv = Server(ServerOptions(native_plane=True))
        srv.add_service("svc", {"echo": np_mod.native_echo})
        assert srv.start(0)
        nch = None
        try:
            nch = np_mod.NativeClientChannel("127.0.0.1", srv.port)
            codes = []
            for _ in range(12):
                _rc, ec, _m, _b = nch.call(
                    "svc", "echo", b"x", timeout_ms=2000
                )
                codes.append(ec)
            # exact-rate counter schedule: every 4th call, same every run
            assert [i for i, ec in enumerate(codes) if ec] == [3, 7, 11]
            assert all(
                ec == ErrorCode.EINTERNAL for ec in codes if ec
            )
        finally:
            np_mod.install_native_client_fault()  # clear
            if nch is not None:
                nch.close()
            srv.stop()
            srv.join(timeout=5)

    def test_master_flag_gates_arming(self, flags):
        from incubator_brpc_tpu.transport import native_plane as np_mod

        if not np_mod.NET_AVAILABLE:
            pytest.skip("native plane unavailable")
        flags("fault_injection", False)  # master flag OFF
        np_mod.install_native_client_fault(fail_every=2)
        srv = Server(ServerOptions(native_plane=True))
        srv.add_service("svc", {"echo": np_mod.native_echo})
        assert srv.start(0)
        nch = None
        try:
            nch = np_mod.NativeClientChannel("127.0.0.1", srv.port)
            for _ in range(6):
                _rc, ec, _m, _b = nch.call(
                    "svc", "echo", b"x", timeout_ms=2000
                )
                assert ec == 0  # nothing injected without the master flag
        finally:
            np_mod.install_native_client_fault()
            if nch is not None:
                nch.close()
            srv.stop()
            srv.join(timeout=5)


# ---------------------------------------------------------------------------
# elastic collective sessions: checkpoint/resume, replacement, watchdog
# ---------------------------------------------------------------------------


class TestResumePointJoin:
    """The resume barrier's min-join (parallel/mc_dispatch.resume_point):
    last COMMON checkpointed step over the survivors — pure units."""

    def _info(self, wm, steps):
        return {"watermark": wm, "steps": list(steps)}

    def test_min_join_over_skewed_watermarks(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import resume_point

        wms = {
            0: self._info(4, [2, 4]),
            1: self._info(6, [2, 4, 6]),
            2: self._info(2, [2]),
        }
        assert resume_point(wms) == 2

    def test_zero_checkpoint_falls_back_to_full_restart(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import resume_point

        # one survivor never checkpointed: the whole join is 0 — restart
        assert resume_point(
            {0: self._info(6, [2, 4, 6]), 1: self._info(0, [])}
        ) == 0
        # a survivor that answered nothing at all drags to 0 too
        assert resume_point({0: self._info(6, [2, 4, 6]), 1: None}) == 0
        assert resume_point({}) == 0

    def test_evicted_min_falls_back_to_deepest_common(self):
        from incubator_brpc_tpu.parallel.mc_dispatch import resume_point

        # min watermark 4 was EVICTED from survivor 1's ring: fall back
        # to the deepest step everyone still retains
        wms = {
            0: self._info(4, [2, 4]),
            1: self._info(6, [2, 6]),
        }
        assert resume_point(wms) == 2
        # nothing common at all: full restart
        assert resume_point(
            {0: self._info(4, [4]), 1: self._info(6, [6])}
        ) == 0


class TestCheckpointRings:
    """Ring census/release/eviction units (no device traffic — the ring
    only retains references; materialization is a resume-path affair)."""

    def test_census_release_and_gauge(self):
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd

        sid = "t-ring-census"
        ring = mcd._checkpoint_ring(sid, 1, (10, 11, 12), entry_bytes=100)
        for step in (2, 4, 6):
            ring.put(step, object(), object(), depth=2)
        # depth=2: step 2 evicted, watermark = newest retained
        assert ring.steps() == [4, 6]
        wm = mcd.checkpoint_watermarks(sid)
        assert wm[1]["watermark"] == 6 and wm[1]["steps"] == [4, 6]
        assert mcd.checkpoint_bytes_retained() >= 200
        assert mcd.release_checkpoints(sid)
        assert not mcd.checkpoint_watermarks(sid)
        assert not mcd.release_checkpoints(sid)  # idempotent

    def test_unready_entries_excluded_from_census(self):
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd

        class _Arr:
            def __init__(self, ready):
                self._r = ready

            def is_ready(self):
                return self._r

        sid = "t-ring-ready"
        ring = mcd._checkpoint_ring(sid, 0, (1, 2), entry_bytes=10)
        ring.put(2, _Arr(True), _Arr(True), depth=4)
        ring.put(4, _Arr(False), _Arr(True), depth=4)
        # a dispatched-but-never-completed step (wedged behind a dead
        # party's collective) must not be elected as the resume point
        assert ring.steps() == [2]
        assert mcd.checkpoint_watermarks(sid)[0]["watermark"] == 2
        mcd.release_checkpoints(sid)

    def test_resumed_replay_replaces_stale_same_step_entry(self):
        """A resumed run re-checkpoints step numbers the aborted run
        already put(): the fresh entry must REPLACE the stale one (which
        may be wedged and never-ready), not shadow behind it."""
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd

        class _Arr:
            def __init__(self, ready):
                self._r = ready

            def is_ready(self):
                return self._r

        sid = "t-ring-replace"
        ring = mcd._checkpoint_ring(sid, 0, (1, 2), entry_bytes=10)
        stale = _Arr(False)
        ring.put(2, _Arr(True), _Arr(True), depth=4)
        ring.put(4, stale, _Arr(True), depth=4)  # wedged, never ready
        assert ring.watermark() == 2
        fresh = _Arr(True)
        ring.put(4, fresh, _Arr(True), depth=4)  # the replayed step 4
        assert ring.steps() == [2, 4]
        assert ring.get(4)[0] is fresh  # not the stale shadow
        assert ring.watermark() == 4
        mcd.release_checkpoints(sid)

    def test_cap_eviction_spares_active_sessions(self):
        """Ring eviction prefers sessions with no live registrant: short
        -session churn must not strip a long-running session of the
        checkpoints its resume depends on."""
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd

        sid = "t-ring-active"
        st = mcd._register_session(sid, (1, 2), deadline=0.0)
        try:
            mcd._checkpoint_ring(sid, 0, (1, 2), entry_bytes=1)
            for i in range(mcd._MAX_CHECKPOINT_SESSIONS + 4):
                mcd._checkpoint_ring(f"t-ring-churn-{i}", 0, (1,), entry_bytes=1)
            assert mcd._checkpoint_lookup(sid, 0) is not None, (
                "churn evicted a LIVE session's ring"
            )
        finally:
            mcd._unregister_session(st)
            mcd.release_checkpoints(sid)
            for i in range(mcd._MAX_CHECKPOINT_SESSIONS + 4):
                mcd.release_checkpoints(f"t-ring-churn-{i}")

    def test_session_cap_evicts_oldest(self):
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd

        sids = [f"t-ring-cap-{i}" for i in range(mcd._MAX_CHECKPOINT_SESSIONS + 2)]
        for sid in sids:
            mcd._checkpoint_ring(sid, 0, (1,), entry_bytes=1)
        assert not mcd.checkpoint_watermarks(sids[0])  # evicted
        assert mcd._checkpoint_lookup(sids[-1], 0) is not None
        for sid in sids:
            mcd.release_checkpoints(sid)


def _shard_map_or_skip(min_devices=4):
    import jax

    from incubator_brpc_tpu.parallel.compat import resolve_shard_map

    try:
        resolve_shard_map()
    except ImportError:
        pytest.skip("no shard_map in this jax build")
    if len(jax.devices()) < min_devices:
        pytest.skip(f"needs a {min_devices}+ device mesh")
    return jax.devices()


class TestElasticSessionUnits:
    """run_dispatch_session's checkpoint/restore seam, driven directly
    (single process, all shards addressable)."""

    def test_resume_replays_only_steps_past_checkpoint(self):
        devices = _shard_map_or_skip(3)
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
            session_expected,
        )

        pids = [d.id for d in devices[:3]]
        ops = [bytes([i + 1]) * 16 for i in range(3)]
        dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        sid = "t-unit-resume"
        try:
            mcd.run_dispatch_session(
                pids, 0, dm, ops, 6, session_id=sid, checkpoint_every=2
            )
            assert mcd.checkpoint_watermarks(sid)[0]["watermark"] == 6
            before = mcd.dispatch_steps.get_value()
            row, n, _el = mcd.run_dispatch_session(
                pids, 0, dm, ops, 12, session_id=sid, resume_from=6,
                checkpoint_every=2,
            )
            # only the steps past the checkpoint re-ran
            assert mcd.dispatch_steps.get_value() - before == 6
            # and the result is byte-identical to an undisturbed 12-step run
            assert dm.unpack(row, n) == session_expected(ops, 12)[0]
        finally:
            mcd.release_checkpoints(sid)

    def test_resume_point_equal_to_final_replays_nothing(self):
        devices = _shard_map_or_skip(3)
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
            session_expected,
        )

        pids = [d.id for d in devices[:3]]
        ops = [b"\x05" * 8, b"\x06" * 8, b"\x07" * 8]
        dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        sid = "t-unit-resume-final"
        try:
            mcd.run_dispatch_session(
                pids, 0, dm, ops, 4, session_id=sid, checkpoint_every=2
            )
            before = mcd.dispatch_steps.get_value()
            row, n, _el = mcd.run_dispatch_session(
                pids, 0, dm, ops, 4, session_id=sid, resume_from=4,
            )
            assert mcd.dispatch_steps.get_value() - before == 0
            assert dm.unpack(row, n) == session_expected(ops, 4)[0]
        finally:
            mcd.release_checkpoints(sid)

    def test_replacement_reshard_round_trip(self):
        """The reshard wire format: checkpoint_fetch's b64 rows restore a
        party with NO local ring (the replacement's bootstrap) and the
        replayed chain lands byte-identical."""
        devices = _shard_map_or_skip(4)
        import base64

        from incubator_brpc_tpu.parallel import mc_dispatch as mcd
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
            session_expected,
        )

        pids = [d.id for d in devices[:3]]
        ops = [bytes([7 * i + 1]) * 12 for i in range(3)]
        dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        sid = "t-unit-reshard"
        try:
            mcd.run_dispatch_session(
                pids, 0, dm, ops, 4, session_id=sid, checkpoint_every=2
            )
            rows = mcd.checkpoint_fetch(sid, 4, [0, 1, 2])
            assert set(rows) == {0, 1, 2}
            state = {
                i: (base64.b64decode(v["row"]), int(v["n"]))
                for i, v in rows.items()
            }
            assert all(len(r) == SESSION_WIDTH for r, _n in state.values())
            # a DIFFERENT device takes slot 0, restoring purely from the
            # resharded bytes under a session id with no local ring
            new_pids = [devices[3].id] + pids[1:]
            row, n, _el = mcd.run_dispatch_session(
                new_pids, 1, dm, ops, 8, session_id="t-unit-reshard-2",
                resume_from=4, resume_state=state,
            )
            assert dm.unpack(row, n) == session_expected(ops, 8)[1]
        finally:
            mcd.release_checkpoints(sid)
            mcd.release_checkpoints("t-unit-reshard-2")

    def test_missing_checkpoint_raises_lookup_error(self):
        devices = _shard_map_or_skip(3)
        from incubator_brpc_tpu.parallel import mc_dispatch as mcd
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
        )

        pids = [d.id for d in devices[:3]]
        ops = [b"\x01" * 8] * 3
        dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        with pytest.raises(LookupError):
            mcd.run_dispatch_session(
                pids, 0, dm, ops, 8, session_id="t-no-such-ring",
                resume_from=4,
            )


class TestElasticResumeChaosDrill:
    """The acceptance drill: kill 1 of 3 parties mid multi-step session;
    the session HEALS — the spare party fills the dead slot, the resume
    barrier min-joins the survivors' checkpoint watermarks, only steps
    past the resume point re-run, and the merged result is byte-identical
    to an undisturbed run.  The dead party's breaker trips while the
    survivors' stay closed, and `mc_dispatch_resumes` /
    `mc_dispatch_replaced_parties` advance."""

    DEADLINE_MS = 6000
    STEPS = 80

    @pytest.fixture
    def mesh(self, tuned_flags):
        import jax

        from incubator_brpc_tpu.parallel.compat import resolve_shard_map

        try:
            resolve_shard_map()
        except ImportError:
            pytest.skip("no shard_map in this jax build")
        if len(jax.devices()) < 5:
            pytest.skip("needs a 5+ device mesh (3 parties + spare)")
        tuned_flags("circuit_breaker_short_window_size", 30)
        tuned_flags("circuit_breaker_long_window_size", 300)
        tuned_flags("circuit_breaker_min_isolation_duration_ms", 60000)
        tuned_flags("enable_circuit_breaker", True)
        from incubator_brpc_tpu.rpc import device_method
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
        )

        register_device_method(
            "dsvc", "scale", DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        )
        servers, channels = [], []
        for i in range(4):  # 3 parties + 1 spare
            s = Server(
                ServerOptions(
                    device_index=i + 1,
                    enable_collective_service=True,
                    collective_max_concurrency=0,
                )
            )
            s.add_service(
                "dsvc",
                {"scale": device_method(_scale_psum_kernel, width=SESSION_WIDTH)},
            )
            assert s.start(0)
            servers.append(s)
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{s.port}",
                lb_name="rr",
                options=ChannelOptions(max_retry=1, timeout_ms=10000),
            )
            channels.append(ch)
        party_ids = [d.id for d in jax.devices()[1:4]]
        spare_dev = jax.devices()[4].id
        yield servers, channels, party_ids, spare_dev
        from incubator_brpc_tpu.parallel import mc_dispatch

        mc_dispatch.set_step_hook(None)
        for ch in channels:
            if ch._lb is not None:
                ch._lb.stop()
        for s in servers:
            s.stop()
            s.join(timeout=5)

    def test_kill_at_step_k_heals_byte_identical(self, mesh):
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.transport.mc_worker import session_expected

        servers, channels, party_ids, spare_dev = mesh
        operands = [bytes([i + 1]) * 8 for i in range(3)]
        before_resumes = mc_dispatch.dispatch_resumes.get_value()
        before_replaced = mc_dispatch.dispatch_replaced_parties.get_value()

        # pace every party so the kill lands mid-session (K ~ step 12
        # of an 80-step run at 30 ms/step, killer at 0.35 s)
        mc_dispatch.set_step_hook(lambda step, idx: time.sleep(0.03))
        killer = threading.Timer(
            0.35, lambda: (servers[0].stop(), servers[0].join(timeout=3))
        )
        killer.start()
        try:
            out = mc_dispatch.propose_with_recovery(
                channels[:3],
                party_ids,
                "dsvc",
                "scale",
                operands,
                steps=self.STEPS,
                proposer_index=None,
                timeout_ms=60000,
                session_deadline_ms=self.DEADLINE_MS,
                spares=[(channels[3], spare_dev)],
                checkpoint_every=2,
            )
        finally:
            killer.cancel()
            mc_dispatch.set_step_hook(None)

        # healed, not shrunk: the spare filled the dead slot, the session
        # resumed from a COMMON checkpoint instead of step 0
        assert out["dead_party_ids"] == [party_ids[0]]
        assert out["replaced_party_ids"] == [spare_dev]
        assert out["resumed_from"] is not None and out["resumed_from"] > 0
        assert out["resumed_from"] % 2 == 0  # a checkpointed step
        assert out["final_steps"] == self.STEPS

        # byte-identity with an undisturbed run of the SAME party count
        want = session_expected(operands, self.STEPS)
        for i, (got, exp) in enumerate(zip(out["results"], want)):
            assert got == exp, f"slot {i} diverged after resume"

        assert mc_dispatch.dispatch_resumes.get_value() > before_resumes
        assert (
            mc_dispatch.dispatch_replaced_parties.get_value()
            > before_replaced
        )

        # blame: the dead party's breaker trips (connect-refused selects
        # feed it); the survivors' stay closed
        for _ in range(30):
            if channels[0]._lb.isolated_servers():
                break
            channels[0].call_method("dsvc", "scale", b"x")
        assert channels[0]._lb.isolated_servers(), (
            "dead party's breaker never tripped"
        )
        for i in (1, 2):
            assert not channels[i]._lb.isolated_servers(), (
                f"survivor {i}'s breaker tripped off the resumed session"
            )

    def test_two_party_session_heals_with_spare(self, mesh):
        """A 2-party session + one death CAN heal when a spare preserves
        the width — the survivor-count guard only gates the shrink path."""
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.transport.mc_worker import session_expected

        servers, channels, party_ids, spare_dev = mesh
        ops = [b"\x01" * 8, b"\x02" * 8]
        mc_dispatch.set_step_hook(lambda step, idx: time.sleep(0.03))
        killer = threading.Timer(
            0.3, lambda: (servers[0].stop(), servers[0].join(timeout=3))
        )
        killer.start()
        try:
            out = mc_dispatch.propose_with_recovery(
                channels[:2],
                party_ids[:2],
                "dsvc",
                "scale",
                ops,
                steps=40,
                proposer_index=None,
                timeout_ms=60000,
                session_deadline_ms=self.DEADLINE_MS,
                spares=[(channels[3], spare_dev)],
                checkpoint_every=2,
            )
        finally:
            killer.cancel()
            mc_dispatch.set_step_hook(None)
        assert out["replaced_party_ids"] == [spare_dev]
        assert out["dead_party_ids"] == [party_ids[0]]
        want = session_expected(ops, out["final_steps"])
        assert [bytes(r) for r in out["results"]] == want

    def test_quantized_overlapped_session_resumes_byte_identical(self, mesh):
        """The quantized-collective composition drill (ISSUE 14): an
        int8 chunked double-buffered pmean session killed mid-run heals
        through the SAME elastic path — and because quantized
        checkpoint rings store the block-quantized representation with
        power-of-two scales (dequantize→requantize is exactly
        idempotent), the healed chain's bytes equal an undisturbed
        run's.  No silent float32 inflation on resume: the retained
        entry is the quantized twin, at the wire's ~4x discount."""
        import numpy as np

        from incubator_brpc_tpu.parallel import mc_dispatch, quantized
        from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
        from incubator_brpc_tpu.rpc.device_method import (
            register_device_method,
        )

        from incubator_brpc_tpu.rpc.device_method import (
            lookup_device_method,
            unregister_device_method,
        )

        servers, channels, party_ids, spare_dev = mesh
        width = 256  # 64 floats = 2 blocks; chunks=2 stays block-aligned
        prev = lookup_device_method("_collective", "pmean")
        register_device_method("_collective", "pmean", _pmean_dm(width))
        rng = np.random.default_rng(21)
        rows = [
            (rng.standard_normal(width // 4) * (i + 1)).astype(np.float32)
            for i in range(3)
        ]
        operands = [r.tobytes() for r in rows]
        kw = dict(
            steps=40,
            proposer_index=None,
            timeout_ms=60000,
            session_deadline_ms=self.DEADLINE_MS,
            checkpoint_every=2,
            quantize="int8",
            chunks=2,
            double_buffer=True,
        )
        mc_dispatch.set_step_hook(lambda step, idx: time.sleep(0.03))
        try:
            # the undisturbed control: same schedule, nobody dies
            control = mc_dispatch.propose_with_recovery(
                channels[:3], party_ids, "_collective", "pmean",
                operands, **kw,
            )
            killer = threading.Timer(
                0.35, lambda: (servers[0].stop(), servers[0].join(timeout=3))
            )
            killer.start()
            try:
                out = mc_dispatch.propose_with_recovery(
                    channels[:3], party_ids, "_collective", "pmean",
                    operands, spares=[(channels[3], spare_dev)], **kw,
                )
            finally:
                killer.cancel()
        finally:
            mc_dispatch.set_step_hook(None)
            # restore exactly: a leaked registration shadows the
            # width-minting pmean resolver for later suites
            if prev is not None:
                register_device_method("_collective", "pmean", prev)
            else:
                unregister_device_method("_collective", "pmean")
        assert out["replaced_party_ids"] == [spare_dev]
        assert out["resumed_from"] is not None and out["resumed_from"] > 0
        assert out["resumed_from"] % 2 == 0
        # replay byte-identity for the quantized session killed mid-run
        for i in range(3):
            assert out["results"][i] == control["results"][i], (
                f"slot {i} diverged after quantized resume"
            )
        # the wire accounting carried the quantized footprint, counted
        # over the REPLAYED steps only (the healed run re-moved just
        # the steps past the resume point)
        assert out["quantize"] == "int8"
        replayed = out["final_steps"] - out["resumed_from"]
        assert out["wire_bytes"] == (
            quantized.wire_bytes(width, "int8") * 3 * replayed
        )
        # and the result sits inside the documented error bound of the
        # exact mean (steps compound conservatively)
        exact = np.mean(np.stack(rows), axis=0, dtype=np.float32)
        bound = quantized.pmean_error_bound(rows, out["final_steps"], "int8")
        got = np.frombuffer(out["results"][0], dtype=np.float32)
        assert float(np.abs(got - exact).max()) <= bound

    def test_no_spare_falls_back_to_shrink_restart(self, mesh):
        """Without a spare the recovery path is PR-8's: a fresh session
        from step 0 over the survivors only — never a divergent resume."""
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.transport.mc_worker import session_expected

        servers, channels, party_ids, _spare = mesh
        operands = [bytes([i + 1]) * 8 for i in range(3)]
        mc_dispatch.set_step_hook(lambda step, idx: time.sleep(0.03))
        killer = threading.Timer(
            0.3, lambda: (servers[0].stop(), servers[0].join(timeout=3))
        )
        killer.start()
        try:
            out = mc_dispatch.propose_with_recovery(
                channels[:3],
                party_ids,
                "dsvc",
                "scale",
                operands,
                steps=30,
                proposer_index=None,
                timeout_ms=60000,
                session_deadline_ms=self.DEADLINE_MS,
                checkpoint_every=2,
            )
        finally:
            killer.cancel()
            mc_dispatch.set_step_hook(None)
        assert out["dead_party_ids"] == [party_ids[0]]
        assert out["replaced_party_ids"] == []
        assert out["resumed_from"] is None  # restart, not resume
        # the shrunk session's result matches the SURVIVOR-set model
        want = session_expected(operands[1:], out["final_steps"])
        assert out["results"][0] == want[0] and out["results"][1] == want[1]


class TestStepWatchdog:
    """`mc_dispatch_step_deadline_ms` bounds a single lockstep step:
    a party wedged INSIDE one step aborts the session fabric-wide at
    step granularity instead of burning the whole session deadline
    (PR 8's documented gap)."""

    @pytest.fixture
    def mesh(self, tuned_flags):
        import jax

        from incubator_brpc_tpu.parallel.compat import resolve_shard_map

        try:
            resolve_shard_map()
        except ImportError:
            pytest.skip("no shard_map in this jax build")
        if len(jax.devices()) < 4:
            pytest.skip("needs a 4+ device mesh")
        from incubator_brpc_tpu.rpc import device_method
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )
        from incubator_brpc_tpu.transport.mc_worker import (
            SESSION_WIDTH,
            _scale_psum_kernel,
        )

        register_device_method(
            "dsvc", "scale", DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
        )
        servers, channels = [], []
        for i in range(3):
            s = Server(
                ServerOptions(
                    device_index=i + 1,
                    enable_collective_service=True,
                    collective_max_concurrency=0,
                )
            )
            s.add_service(
                "dsvc",
                {"scale": device_method(_scale_psum_kernel, width=SESSION_WIDTH)},
            )
            assert s.start(0)
            servers.append(s)
            ch = Channel()
            assert ch.init(f"127.0.0.1:{s.port}")
            channels.append(ch)
        party_ids = [d.id for d in jax.devices()[1:4]]
        yield servers, channels, party_ids
        from incubator_brpc_tpu.parallel import mc_dispatch

        mc_dispatch.set_step_hook(None)
        for s in servers:
            s.stop()
            s.join(timeout=5)

    def test_watchdog_fires_inside_stuck_step(self, mesh):
        from incubator_brpc_tpu.parallel import mc_dispatch

        servers, channels, party_ids = mesh
        operands = [bytes([i + 1]) * 8 for i in range(3)]
        before_aborts = mc_dispatch.dispatch_aborts.get_value()

        STALL_S = 2.5
        SESSION_DEADLINE_MS = 30000

        def hook(step, idx):
            if idx == 1 and step == 2:
                time.sleep(STALL_S)  # wedged inside step 2

        mc_dispatch.set_step_hook(hook)
        t0 = time.monotonic()
        with pytest.raises(mc_dispatch.SessionAborted) as exc:
            mc_dispatch.propose_dispatch(
                channels,
                party_ids,
                "dsvc",
                "scale",
                operands,
                steps=30,
                proposer_index=None,
                timeout_ms=60000,
                session_deadline_ms=SESSION_DEADLINE_MS,
                step_deadline_ms=150,
            )
        elapsed = time.monotonic() - t0
        mc_dispatch.set_step_hook(None)
        # the watchdog (not the 30 s session deadline) took it down, and
        # the blame names the step deadline
        assert elapsed < STALL_S + 4.0
        assert "step deadline" in str(exc.value)
        assert mc_dispatch.dispatch_aborts.get_value() > before_aborts
        assert wait_until(
            lambda: mc_dispatch.active_sessions() == 0, timeout=10
        )


# ---------------------------------------------------------------------------
# retry budget (SRE-style token bucket on the Channel)
# ---------------------------------------------------------------------------


class TestRetryBudget:
    def test_token_bucket_unit(self):
        from incubator_brpc_tpu.rpc.channel import (
            _RETRY_BUDGET_CAP,
            RetryBudget,
        )

        b = RetryBudget(0.5)
        for _ in range(int(_RETRY_BUDGET_CAP)):
            assert b.acquire(ErrorCode.EFAILEDSOCKET)
        assert not b.acquire(ErrorCode.EFAILEDSOCKET)  # drained
        # deposits refill at the ratio: 4 calls fund 2 retries
        for _ in range(4):
            b.on_call()
        assert b.balance() == pytest.approx(2.0)
        assert b.acquire(ErrorCode.EFAILEDSOCKET)
        assert b.acquire(ErrorCode.EFAILEDSOCKET)
        assert not b.acquire(ErrorCode.EFAILEDSOCKET)
        # the cap bounds accumulation
        for _ in range(10_000):
            b.on_call()
        assert b.balance() == pytest.approx(_RETRY_BUDGET_CAP)

    def test_exempt_codes_never_draw(self):
        from incubator_brpc_tpu.rpc.channel import (
            RETRY_BUDGET_EXEMPT,
            RetryBudget,
        )

        b = RetryBudget(0.1)
        while b.acquire(ErrorCode.EFAILEDSOCKET):
            pass  # drain it
        for code in RETRY_BUDGET_EXEMPT:
            assert b.acquire(code)  # exempt: passes without a token
        assert b.balance() < 1.0
        assert {
            ErrorCode.EDEADLINE, ErrorCode.ESESSION, ErrorCode.ELIMIT
        } == set(RETRY_BUDGET_EXEMPT)

    def test_zero_ratio_disables(self, flags):
        from incubator_brpc_tpu.rpc.channel import RetryBudget

        b = RetryBudget(0.0)
        for _ in range(200):
            assert b.acquire(ErrorCode.EFAILEDSOCKET)

    def test_exhaustion_fails_fast_with_original_error(self, flags):
        """A drained budget means the FIRST error settles the call — no
        retry storm — and the error text says why."""
        from incubator_brpc_tpu.rpc.channel import retry_budget_exhausted

        srv = Server()
        srv.add_service("e", {"m": lambda c, r: b"ok"})
        assert srv.start(0)
        port = srv.port
        srv.stop()
        srv.join(timeout=5)  # the port now refuses connections

        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}",
            options=ChannelOptions(
                max_retry=3, timeout_ms=2000, connect_timeout=0.25
            ),
        )
        # control: with budget, a connectivity failure burns its retries
        cntl = ch.call_method("e", "m", b"x")
        assert cntl.failed()
        assert cntl.retried_count == 3, (
            f"expected retries before exhaustion, got {cntl.retried_count}"
        )
        # drain the bucket below one token: the next failure cannot retry
        before = retry_budget_exhausted.get_value()
        with ch._retry_budget._lock:
            ch._retry_budget._tokens = 0.2
        cntl = ch.call_method("e", "m", b"x")
        assert cntl.failed()
        assert cntl.retried_count == 0, "budget-exhausted call retried"
        assert "retry budget exhausted" in cntl.error_text
        assert retry_budget_exhausted.get_value() > before

    def test_budget_visible_in_vars(self, flags):
        from incubator_brpc_tpu.bvar.variable import expose_registry

        names = dict(expose_registry.snapshot())
        assert "retry_budget_tokens" in names
        assert "retry_budget_exhausted" in names
        srv = Server()
        srv.add_service("e", {"m": lambda c, r: b"ok"})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            assert ch.call_method("e", "m", b"x").ok()
            from incubator_brpc_tpu.rpc.channel import retry_budget_tokens

            # the live channel's full bucket shows up in the aggregate
            assert retry_budget_tokens.get_value() >= 50.0
        finally:
            srv.stop()
            srv.join(timeout=5)


# ---------------------------------------------------------------------------
# lame-duck drain covers open streaming RPCs
# ---------------------------------------------------------------------------


class TestLameDuckStreamDrain:
    @pytest.fixture
    def stream_server(self):
        from incubator_brpc_tpu.rpc import StreamHandler, StreamOptions, stream_accept

        class Recorder(StreamHandler):
            def __init__(self):
                self.closed = threading.Event()

            def on_closed(self, stream):
                self.closed.set()

        server = Server()
        accepted = {}

        def open_stream(cntl, request):
            rec = Recorder()
            s = stream_accept(cntl, StreamOptions(handler=rec))
            assert s is not None
            accepted["stream"], accepted["rec"] = s, rec
            return b"accepted"

        server.add_service("t", {"open_stream": open_stream})
        assert server.start(0)
        yield server, accepted, Recorder
        server.stop()
        server.join(timeout=5)

    def _open(self, server, Recorder):
        from incubator_brpc_tpu.rpc import StreamOptions, stream_create

        rec = Recorder()
        s = stream_create(StreamOptions(handler=rec))
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        cntl = ch.call_method("t", "open_stream", b"", request_stream=s)
        assert cntl.ok(), cntl.error_text
        assert s.wait_connected(5)
        return s, rec

    def test_drain_waits_for_stream_close(self, stream_server):
        server, accepted, Recorder = stream_server
        s, _rec = self._open(server, Recorder)
        assert server._open_streams(), "server does not see its stream"
        t0 = time.monotonic()
        t = server.enter_lame_duck(grace_s=8.0)
        assert t is not None
        # the drain is blocked on the stream, not done instantly
        time.sleep(0.4)
        assert t.is_alive(), "drain finished under an open stream"
        s.close()
        t.join(timeout=6)
        assert not t.is_alive()
        # it proceeded on the close, long before grace expiry
        assert time.monotonic() - t0 < 6.0
        assert server._stopping

    def test_grace_expiry_rsts_open_streams(self, stream_server):
        server, accepted, Recorder = stream_server
        s, rec = self._open(server, Recorder)
        t = server.enter_lame_duck(grace_s=0.6)
        assert t is not None
        t.join(timeout=8)
        assert not t.is_alive()
        # the straggler stream died on a clean RST at grace expiry: the
        # client handler observed the close instead of a dirty socket cut
        assert rec.closed.wait(3), "client never saw the stream end"
        from incubator_brpc_tpu.rpc import stream as stream_mod

        assert s.state == stream_mod.CLOSED
        assert server._stopping
