"""Two-party device transport tests (reference shape:
test/brpc_rdma_unittest.cpp — handshake, data path, flow control, teardown
— run loopback on the virtual device mesh, SURVEY §4's prescription)."""

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server
from incubator_brpc_tpu.utils.status import ErrorCode


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


@pytest.fixture
def echo_server():
    server = Server()

    def echo(cntl, req):
        cntl.response_attachment = cntl.request_attachment
        return req

    server.add_service("EchoService", {"Echo": echo})
    assert server.start(0)
    yield server
    server.stop()
    server.join(timeout=5)


def _tpu_channel(server, **opts) -> Channel:
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(transport="tpu", timeout_ms=30000, **opts),
    )
    return ch


class TestDeviceEcho:
    def test_echo_roundtrip_crosses_two_devices(self, echo_server):
        import jax

        ch = _tpu_channel(echo_server)
        cntl = ch.call_method("EchoService", "Echo", b"over the device plane")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"over the device plane"
        ds = ch._device_sock
        assert ds is not None
        if len(jax.devices()) > 1:
            # the two halves really sit on different mesh devices
            assert ds.link.devices[0] != ds.link.devices[1]
            assert ds.link._mesh is not None  # shard_map/ppermute path

    def test_attachment_and_meta_survive(self, echo_server):
        ch = _tpu_channel(echo_server)
        cntl = ch.call_method(
            "EchoService", "Echo", b"payload", attachment=b"piggyback"
        )
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"payload"
        assert cntl.response_attachment == b"piggyback"

    def test_payload_larger_than_slot_spans_steps(self, echo_server):
        # slot_words=256 -> 1 KiB slots; a 64 KiB frame needs ~64 steps of
        # byte-stream chunking each way
        ch = _tpu_channel(echo_server, link_slot_words=256, link_window=4)
        big = bytes(range(256)) * 256
        cntl = ch.call_method("EchoService", "Echo", big)
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == big

    def test_many_sequential_calls_share_one_link(self, echo_server):
        ch = _tpu_channel(echo_server)
        first = None
        for i in range(20):
            cntl = ch.call_method("EchoService", "Echo", f"msg-{i}".encode())
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == f"msg-{i}".encode()
            if first is None:
                first = ch._device_sock
        assert ch._device_sock is first  # one handshake, one link

    def test_handshake_used_host_socket(self, echo_server):
        ch = _tpu_channel(echo_server)
        assert ch.call_method("EchoService", "Echo", b"x").ok()
        # the bootstrap TCP socket exists in the client map independently
        # of the device link
        host = ch._socket_map.get_or_create(ch._single_server)
        assert host is not ch._device_sock


class TestContentionAndFlowControl:
    def test_contended_writers(self, echo_server):
        ch = _tpu_channel(echo_server, link_slot_words=512, link_window=2)
        errs = []

        def worker(i):
            for j in range(10):
                body = (f"t{i}-{j}-".encode()) + bytes((i * 31 + j) % 256 for _ in range(3000))
                c = ch.call_method("EchoService", "Echo", body)
                if c.failed() or c.response_payload != body:
                    errs.append((i, j, c.error_code, c.error_text))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:3]

    def test_window_bounds_inflight_steps(self, echo_server):
        ch = _tpu_channel(echo_server, link_slot_words=256, link_window=2)
        big = b"w" * 50000
        cntl = ch.call_method("EchoService", "Echo", big)
        assert cntl.ok(), cntl.error_text
        link = ch._device_sock.link
        # the credit window held dispatched-but-undrained steps at <= window
        assert link.inflight_steps <= link.window

    def test_writer_stalls_then_resumes_on_backlog(self, echo_server):
        # direct link-level test: a tiny window and slot make the byte
        # budget small; a burst of sends must block (not error) and all
        # bytes must still arrive in order
        ch = _tpu_channel(echo_server, link_slot_words=64, link_window=1)
        assert ch.call_method("EchoService", "Echo", b"warm").ok()
        link = ch._device_sock.link
        blob = b"AB" * 4000  # far past the 1-slot byte budget

        rc = link.send(0, blob)  # blocks internally while draining
        assert rc == 0

        # server side got the byte stream appended to its read buffer; the
        # messenger will reject it as garbage eventually, but the transport
        # delivered every byte in order first — assert via the socket's
        # buffer growth before the parse error fails the link
        assert _wait(lambda: link._closed or link._out_nbytes[0] == 0)


class TestTeardown:
    def test_server_stop_fails_client_link(self, echo_server):
        ch = _tpu_channel(echo_server)
        assert ch.call_method("EchoService", "Echo", b"x").ok()
        ds = ch._device_sock
        echo_server.stop()
        assert _wait(lambda: ds.state != 0)  # CONNECTED == 0
        # subsequent calls fail fast or re-handshake-fail, never hang
        c = ch.call_method("EchoService", "Echo", b"y")
        assert c.failed()

    def test_link_failure_reports_not_hangs(self, echo_server):
        ch = _tpu_channel(echo_server)
        assert ch.call_method("EchoService", "Echo", b"x").ok()
        ch._device_sock.link.fail("injected")
        c = ch.call_method("EchoService", "Echo", b"y")
        # the failed link is detected and re-handshaken (fresh link), or
        # the call fails visibly — either way no hang
        assert c.ok() or c.error_code != 0

    def test_reconnect_after_link_failure(self, echo_server):
        ch = _tpu_channel(echo_server)
        assert ch.call_method("EchoService", "Echo", b"x").ok()
        old = ch._device_sock
        old.link.fail("injected")
        assert _wait(lambda: old.state != 0)
        c = ch.call_method("EchoService", "Echo", b"again")
        assert c.ok(), c.error_text
        assert ch._device_sock is not old  # fresh handshake, fresh link


class _CountingSink:
    """Messenger stand-in that drains the socket read buffer and counts."""

    def __init__(self):
        self.nbytes = 0
        self.chunks = []

    def process(self, sock):
        n = len(sock._read_buf)
        if n:
            self.chunks.append(sock._read_buf.to_bytes(n))
            sock._read_buf.popn(n)
            self.nbytes += n


class TestHostLoopbackFastPath:
    """Shared-device geometry: the exchange is a host swap — no device
    dispatch, no readback (VERDICT r3 item 1's on-chip fast path)."""

    def _make_link(self, **kw):
        import jax

        from incubator_brpc_tpu.transport.device_link import (
            DeviceLink,
            DeviceSocket,
        )

        dev = jax.devices()[0]
        link = DeviceLink([dev, dev], **kw)
        sinks = (_CountingSink(), _CountingSink())
        socks = (
            DeviceSocket(link, side=0, messenger=sinks[0]),
            DeviceSocket(link, side=1, messenger=sinks[1]),
        )
        return link, socks, sinks

    def test_same_device_defaults_to_host_swap(self):
        link, socks, sinks = self._make_link(slot_words=1024)
        assert link._step is None  # no jitted step compiled at all
        payload = bytes(range(256)) * 16
        assert link.send(0, payload) == 0
        assert _wait(lambda: sinks[1].nbytes == len(payload))
        assert b"".join(sinks[1].chunks) == payload
        # and the reverse direction
        assert link.send(1, b"pong" * 100) == 0
        assert _wait(lambda: sinks[0].nbytes == 400)

    def test_forced_device_loop_still_works(self):
        link, socks, sinks = self._make_link(
            slot_words=1024, host_loopback=False
        )
        assert link._step is not None  # the jitted on-device swap
        payload = b"device-loop" * 50
        assert link.send(0, payload) == 0
        assert _wait(lambda: sinks[1].nbytes == len(payload), timeout=30)
        assert b"".join(sinks[1].chunks) == payload

    def test_fast_and_device_paths_deliver_identical_streams(self):
        payload = bytes((i * 7 + 3) % 256 for i in range(50000))
        outs = []
        for forced in (None, False):
            link, socks, sinks = self._make_link(
                slot_words=256, window=2, host_loopback=forced
            )
            assert link.send(0, payload) == 0
            assert _wait(lambda: sinks[1].nbytes == len(payload), timeout=60)
            outs.append(b"".join(sinks[1].chunks))
        assert outs[0] == outs[1] == payload

    def test_loopback_throughput_sane(self):
        # the fast path must move bytes at memcpy-class rates — a
        # regression to per-step device round trips would fail this easily
        link, socks, sinks = self._make_link(
            slot_words=256 * 1024, window=8
        )
        chunk = b"t" * (1 << 20)
        total = 64 << 20
        t0 = time.perf_counter()
        for _ in range(total // len(chunk)):
            assert link.send(0, chunk, timeout=30) == 0
        assert _wait(lambda: sinks[1].nbytes == total, timeout=60)
        gbps = total / (time.perf_counter() - t0) / 1e9
        assert gbps > 0.2, f"loopback link moved only {gbps:.3f} GB/s"


class TestWireAckWindow:
    """ack_mode='wire': the credit window gates on the cumulative-delivered
    count carried in received slot headers (word 3) — the only signal a
    multi-controller host has (the RDMA piggybacked imm-data acks +
    accumulated-ack/SendImm catch-up, rdma_endpoint.h:117-123,176-195)."""

    def _make_link(self, **kw):
        import jax

        from incubator_brpc_tpu.transport.device_link import (
            DeviceLink,
            DeviceSocket,
        )

        devs = jax.devices()
        pair = devs[:2] if len(devs) >= 2 else [devs[0], devs[0]]
        link = DeviceLink(pair, ack_mode="wire", **kw)
        sinks = (_CountingSink(), _CountingSink())
        DeviceSocket(link, side=0, messenger=sinks[0])
        DeviceSocket(link, side=1, messenger=sinks[1])
        return link, sinks

    def test_stream_drains_under_wire_acks(self):
        link, sinks = self._make_link(slot_words=256, window=4)
        payload = bytes((i * 13 + 5) % 256 for i in range(100_000))
        assert link.send(0, payload, timeout=60) == 0
        assert _wait(lambda: sinks[1].nbytes == len(payload), timeout=60)
        assert b"".join(sinks[1].chunks) == payload
        # the window held: seq never ran more than window + 1 catch-up
        # step ahead of the acks the wire carried
        assert link._seq - link._peer_ack <= link.window + 1

    def test_window_one_still_makes_progress(self):
        # the degenerate window: every data step needs an ack catch-up
        # step — throughput halves, progress must NOT stop
        link, sinks = self._make_link(slot_words=128, window=1)
        payload = b"w1" * 3000
        assert link.send(0, payload, timeout=60) == 0
        assert _wait(lambda: sinks[1].nbytes == len(payload), timeout=60)
        assert b"".join(sinks[1].chunks) == payload

    def test_bidirectional_wire_acks(self):
        link, sinks = self._make_link(slot_words=256, window=2)
        a = bytes(range(256)) * 40
        b = bytes(reversed(range(256))) * 40
        assert link.send(0, a, timeout=60) == 0
        assert link.send(1, b, timeout=60) == 0
        assert _wait(lambda: sinks[1].nbytes == len(a), timeout=60)
        assert _wait(lambda: sinks[0].nbytes == len(b), timeout=60)
        assert b"".join(sinks[1].chunks) == a
        assert b"".join(sinks[0].chunks) == b

    def test_rpc_over_wire_ack_link(self, echo_server):
        from incubator_brpc_tpu.rpc import Controller

        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{echo_server.port}",
            options=ChannelOptions(
                transport="tpu",
                timeout_ms=60000,
                link_ack_mode="wire",
                link_slot_words=256,
                link_window=2,
            ),
        )
        big = bytes(range(256)) * 64
        cntl = ch.call_method(
            "EchoService", "Echo", big, cntl=Controller(timeout_ms=60000)
        )
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == big
        assert ch._device_sock.link.ack_mode == "wire"


class TestNPartyFabric:
    """The SocketMap-analog link manager: N peers, one link per peer device,
    partitioned RPC over the device plane (VERDICT r3 item 3)."""

    def _start_partition_servers(self, n=4):
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        servers = []
        for i in range(n):
            # each partition's server binds its own mesh device (1..n);
            # the client side of every link is device 0 — a star fabric
            s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
            s.add_service(
                "part", {"get": (lambda cntl, req, _i=i: f"p{_i}:".encode() + req)}
            )
            assert s.start(0)
            servers.append(s)
        return servers

    def test_partition_channel_over_device_links(self):
        import jax

        from incubator_brpc_tpu.rpc.combo import PartitionChannel

        if len(jax.devices()) < 5:
            pytest.skip("needs a 5+ device mesh")
        servers = self._start_partition_servers(4)
        try:
            url = "list://" + ",".join(
                f"127.0.0.1:{s.port} {i}/4" for i, s in enumerate(servers)
            )
            pc = PartitionChannel()
            assert pc.init(
                url,
                partition_count=4,
                options=ChannelOptions(transport="tpu", timeout_ms=60000),
            )
            from incubator_brpc_tpu.rpc import Controller

            cntl = pc.call_method(
                "part", "get", b"X", cntl=Controller(timeout_ms=60000)
            )
            assert cntl.ok(), cntl.error_text
            # default merger concatenates in channel (partition) order
            assert cntl.response_payload == b"p0:Xp1:Xp2:Xp3:X"
            # every sub-channel rides a device link, each to a DIFFERENT
            # server device, all sharing the client device — a 5-party star
            links = [sub[0]._device_sock.link for sub in pc._subs]
            assert all(link._mesh is not None for link in links)
            client_devs = {str(link.devices[0]) for link in links}
            server_devs = [str(link.devices[1]) for link in links]
            assert len(client_devs) == 1
            assert len(set(server_devs)) == 4
            assert client_devs.isdisjoint(server_devs)
            pc.stop()
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_link_map_dedupes_links_across_channels(self):
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        srv = Server(ServerOptions(device_index=1))
        srv.add_service("EchoService", {"Echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch1 = _tpu_channel(srv)
            ch2 = _tpu_channel(srv)
            assert ch1.call_method("EchoService", "Echo", b"a").ok()
            assert ch2.call_method("EchoService", "Echo", b"b").ok()
            # one handshake, one link: both channels share the map entry
            assert ch1._device_sock is ch2._device_sock
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_lb_target_with_tpu_transport(self):
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        s1 = Server(ServerOptions(device_index=1))
        s2 = Server(ServerOptions(device_index=2))
        for i, s in enumerate((s1, s2)):
            s.add_service("svc", {"who": (lambda cntl, req, _i=i: f"s{_i}".encode())})
            assert s.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                "rr",
                options=ChannelOptions(transport="tpu", timeout_ms=60000),
            )
            seen = set()
            for _ in range(6):
                cntl = ch.call_method("svc", "who", b"")
                assert cntl.ok(), cntl.error_text
                seen.add(cntl.response_payload)
            assert seen == {b"s0", b"s1"}  # rr rotated across both peers
        finally:
            s1.stop()
            s2.stop()


class TestCollectiveLowering:
    """ParallelChannel/PartitionChannel fused to ONE shard_map dispatch
    when every sub-channel rides a device link to a distinct mesh device
    and the method is a registered device kernel (VERDICT r3 item 2;
    SURVEY §2.5 all-gather lowering; BASELINE configs #3/#4)."""

    @staticmethod
    def _kernel(data, n):
        # a real transform (not echo) so a wrong shard order / stale cache
        # shows up in the bytes: add the byte's index, wrap mod 256
        import jax.numpy as jnp

        idx = jnp.arange(data.shape[0], dtype=jnp.uint8)
        return data + idx, n

    def _servers(self, n=4):
        from incubator_brpc_tpu.rpc import Server, ServerOptions, device_method

        servers = []
        for i in range(n):
            s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
            s.add_service("dsvc", {"xform": device_method(self._kernel, width=512)})
            assert s.start(0)
            servers.append(s)
        return servers

    def _make_pc(self, servers, fuse, mapper=None):
        from incubator_brpc_tpu.rpc.combo import ParallelChannel

        pc = ParallelChannel(fuse_device_calls=fuse)
        for s in servers:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(transport="tpu", timeout_ms=60000),
            )
            pc.add_channel(ch, call_mapper=mapper)
        return pc

    def test_fused_and_host_fanout_produce_identical_merges(self):
        import jax

        if len(jax.devices()) < 5:
            pytest.skip("needs a 5+ device mesh")

        class PerIndexMapper:
            def map(self, i, nchan, service, method, request):
                from incubator_brpc_tpu.rpc.combo import SubCall

                return SubCall(request=bytes([i * 10]) * (i + 3))

        servers = self._servers(4)
        try:
            mapper = PerIndexMapper()
            fused_pc = self._make_pc(servers, fuse=True, mapper=mapper)
            host_pc = self._make_pc(servers, fuse=False, mapper=mapper)
            from incubator_brpc_tpu.rpc import Controller

            f = fused_pc.call_method(
                "dsvc", "xform", b"ignored", cntl=Controller(timeout_ms=60000)
            )
            h = host_pc.call_method(
                "dsvc", "xform", b"ignored", cntl=Controller(timeout_ms=60000)
            )
            assert f.ok(), f.error_text
            assert h.ok(), h.error_text
            assert getattr(f, "collective_fused", False) is True
            assert getattr(h, "collective_fused", False) is False
            assert f.response_payload == h.response_payload
            assert len(f.response_payload) == 3 + 4 + 5 + 6
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_fused_falls_back_for_plain_methods(self):
        import jax

        if len(jax.devices()) < 3:
            pytest.skip("needs a 3+ device mesh")
        from incubator_brpc_tpu.rpc import Server, ServerOptions

        servers = []
        for i in range(2):
            s = Server(ServerOptions(device_index=i + 1))
            s.add_service("plain", {"echo": lambda cntl, req: req})
            assert s.start(0)
            servers.append(s)
        try:
            pc = self._make_pc(servers, fuse=True)
            from incubator_brpc_tpu.rpc import Controller

            cntl = pc.call_method(
                "plain", "echo", b"hp", cntl=Controller(timeout_ms=60000)
            )
            assert cntl.ok(), cntl.error_text
            assert getattr(cntl, "collective_fused", False) is False
            assert cntl.response_payload == b"hphp"  # host fan-out concat
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)


class TestFabricFailurePaths:
    def test_fused_falls_back_when_one_link_is_dead(self):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs a 4+ device mesh")
        from incubator_brpc_tpu.rpc import (
            Controller,
            Server,
            ServerOptions,
            device_method,
        )
        from incubator_brpc_tpu.rpc.combo import ParallelChannel

        def k(data, n):
            return data, n

        servers = []
        for i in range(3):
            s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
            s.add_service("fsvc", {"m": device_method(k, width=64)})
            assert s.start(0)
            servers.append(s)
        try:
            pc = ParallelChannel(fail_limit=1)  # any sub failing fails the call
            for s in servers:
                ch = Channel()
                assert ch.init(
                    f"127.0.0.1:{s.port}",
                    options=ChannelOptions(transport="tpu", timeout_ms=60000),
                )
                pc.add_channel(ch)
            c = pc.call_method("fsvc", "m", b"ok", cntl=Controller(timeout_ms=60000))
            assert c.ok() and getattr(c, "collective_fused", False)
            # kill one member: the fused preconditions must fail CLEANLY
            # and the host fan-out arbitrate (no hang, no partial fuse).
            # Server stop closes its link half GRACEFULLY (F_CLOSE rides
            # the link); wait for the client side to observe it
            dead_ds = pc._subs[1][0]._device_sock
            servers[1].stop()
            servers[1].join(timeout=5)
            assert _wait(lambda: dead_ds.state != 0, timeout=10)
            c2 = pc.call_method("fsvc", "m", b"after", cntl=Controller(timeout_ms=5000))
            assert getattr(c2, "collective_fused", False) is False
            # fail_limit=1 with a dead member: the call reports failure
            assert c2.failed()
        finally:
            for s in servers:
                s.stop()

    def test_link_map_isolates_credentials(self, echo_server):
        from incubator_brpc_tpu.transport.device_link import device_link_map

        class FakeAuth:
            def generate_credential(self) -> str:
                return "cred"  # credentials are str by contract

            def verify_credential(self, cred, sock) -> bool:
                return True

        from incubator_brpc_tpu.utils.endpoint import EndPoint

        target = EndPoint(ip="127.0.0.1", port=echo_server.port)
        plain = device_link_map.get_or_create(target, timeout_ms=30000)
        authed = device_link_map.get_or_create(
            target, timeout_ms=30000, auth=FakeAuth()
        )
        # different credentials must NEVER share a link (socket_map.h:35
        # keys by auth identity for the same reason)
        assert plain is not authed
        assert plain.link is not authed.link


class TestStepFailureInjection:
    def test_dispatch_failure_mid_traffic_fails_link_cleanly(self, echo_server):
        # inject a step that blows up on the Nth dispatch: the link must
        # fail (not wedge), in-flight callers must get errors, and the
        # next call must re-handshake onto a FRESH link
        from incubator_brpc_tpu.rpc import Controller

        ch = _tpu_channel(echo_server)
        assert ch.call_method(
            "EchoService", "Echo", b"warm", cntl=Controller(timeout_ms=30000)
        ).ok()
        link = ch._device_sock.link
        orig_step = link._step
        calls = {"n": 0}

        def failing_step(slots):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise RuntimeError("injected device fault")
            return orig_step(slots)

        link._step = failing_step
        # this call's request or response step hits the injected fault
        c = ch.call_method(
            "EchoService", "Echo", b"boom", cntl=Controller(timeout_ms=10000)
        )
        # either the failure landed mid-call (error) or after (link dead)
        assert c.failed() or link._closed
        assert _wait(lambda: link._closed, timeout=10)
        # recovery: the map re-handshakes a fresh link and traffic resumes
        c2 = ch.call_method(
            "EchoService", "Echo", b"again", cntl=Controller(timeout_ms=30000)
        )
        assert c2.ok(), c2.error_text
        assert ch._device_sock.link is not link


class TestZeroCopyDelivery:
    def test_received_blocks_reference_step_output_memory(self, echo_server):
        # The receive path must wrap the link step's output buffer as an
        # external IOBuf block (HBM-backed IOBuf: rdma block_pool.h:20-66 /
        # iobuf.cpp:258-306) — no host-side payload copy before the parse
        # boundary. Asserted by address identity: the fed block's view must
        # point INTO the delivered row's own buffer.
        import numpy as np

        from incubator_brpc_tpu.iobuf import IOBuf
        from incubator_brpc_tpu.transport import device_link as dl

        ch = _tpu_channel(echo_server, link_slot_words=4096)
        assert ch.call_method("EchoService", "Echo", b"warm").ok()

        ext_addrs = []  # addresses handed to append_external (zero-copy wraps)
        row_spans = []  # [start, end) of delivered rows' buffers

        orig_ext = IOBuf.append_external

        def ext_spy(iobuf_self, obj, release_cb=None):
            a = np.frombuffer(memoryview(obj), dtype=np.uint8)
            ext_addrs.append((a.ctypes.data, a.nbytes))
            return orig_ext(iobuf_self, obj, release_cb)

        orig_rows = dl.DeviceLink._rows_to_host

        def rows_spy(link_self, arrays):
            rows = orig_rows(link_self, arrays)
            for row in rows:
                if row is not None:
                    b = row.view(np.uint8)
                    row_spans.append((b.ctypes.data, b.ctypes.data + b.nbytes))
            return rows

        IOBuf.append_external = ext_spy
        dl.DeviceLink._rows_to_host = rows_spy
        try:
            big = b"q" * 12000  # > 4096: external-block delivery path
            cntl = ch.call_method("EchoService", "Echo", big)
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == big
        finally:
            IOBuf.append_external = orig_ext
            dl.DeviceLink._rows_to_host = orig_rows
        # at least one received chunk was wrapped IN PLACE inside a
        # delivered row's own buffer — no host copy before the parse
        aliased = [
            (a, n)
            for a, n in ext_addrs
            for lo, hi in row_spans
            if lo <= a and a + n <= hi
        ]
        assert aliased, f"no external block aliased a delivered row: {ext_addrs[:3]} vs {row_spans[:3]}"

    def test_iobuf_write_queues_block_views(self, echo_server):
        # DeviceSocket.write(IOBuf) must not flatten to bytes: the link
        # gathers from the IOBuf's own block views
        from incubator_brpc_tpu.iobuf import IOBuf

        ch = _tpu_channel(echo_server)
        assert ch.call_method("EchoService", "Echo", b"warm").ok()
        link = ch._device_sock.link
        buf = IOBuf()
        payload = b"Z" * 9000
        buf.append_external(payload)
        # inject directly: the queue entries must be views, with the IOBuf
        # itself as the keepalive
        rc = link.send(0, buf)
        assert rc == 0
        # drained by the driver shortly; the send accounting was by view
        import time as _t

        deadline = _t.monotonic() + 5
        while link._out_nbytes[0] and _t.monotonic() < deadline:
            _t.sleep(0.01)
        assert link._out_nbytes[0] == 0


class TestDynamicPartitionFused:
    def test_dynamic_scheme_fuses_too(self):
        """DynamicPartitionChannel picks a scheme, whose ParallelChannel
        applies the same collective lowering when its partitions are
        device-method servers."""
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs a 4+ device mesh")
        from incubator_brpc_tpu.rpc import (
            Controller,
            Server,
            ServerOptions,
            device_method,
        )
        from incubator_brpc_tpu.rpc.combo import DynamicPartitionChannel

        def bump(data, n):
            import jax.numpy as jnp

            return data + jnp.uint8(2), n

        servers = []
        for i in range(3):
            s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
            s.add_service("dd", {"k": device_method(bump, width=128)})
            assert s.start(0)
            servers.append(s)
        try:
            url = "list://" + ",".join(
                f"127.0.0.1:{s.port} {i}/3" for i, s in enumerate(servers)
            )
            from incubator_brpc_tpu.rpc import ChannelOptions as CO

            dpc = DynamicPartitionChannel()
            assert dpc.init(
                url, options=CO(transport="tpu", timeout_ms=60000)
            )
            deadline = time.monotonic() + 10
            while not dpc._schemes and time.monotonic() < deadline:
                time.sleep(0.05)
            c = dpc.call_method(
                "dd", "k", b"\x07", cntl=Controller(timeout_ms=60000)
            )
            assert c.ok(), c.error_text
            assert c.response_payload == b"\x09" * 3
            assert getattr(c, "collective_fused", False) is True
            dpc.stop()
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)
