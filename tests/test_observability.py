"""Pod-wide metrics exposition (/brpc_metrics) + device-plane
instrumentation tests (reference builtin/prometheus_metrics_service.cpp;
format per the Prometheus text exposition format v0.0.4).

Covers: exposition-format golden rendering (counter/gauge/summary,
escaping, quantile labels), scrape-under-load against a live server,
device-link/collective bvars appearing and advancing after traffic,
collective rpcz spans parented into the proposing RPC's trace, and the
satellite fixes riding this PR (async-handler session reap, lazy
ParsedFrame.payload, opt-in collective registration, rpc_view --metrics).
"""

import json
import os
import re
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from incubator_brpc_tpu.builtin.prometheus import (  # noqa: E402
    CONTENT_TYPE,
    escape_label_value,
    render_metrics,
    sanitize_metric_name,
)
from incubator_brpc_tpu.bvar import (  # noqa: E402
    Adder,
    IntRecorder,
    LatencyRecorder,
    Maxer,
    PassiveStatus,
    PerSecond,
)
from incubator_brpc_tpu.protocol import http as http_mod  # noqa: E402
from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
)
from incubator_brpc_tpu.utils.flags import (  # noqa: E402
    flag_registry,
    set_flag,
)
from incubator_brpc_tpu.utils.status import ErrorCode  # noqa: E402

# -- exposition-format validator ----------------------------------------------

_COMMENT_RE = re.compile(
    r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .+)?$"
)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"([^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"([^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$"
)


def validate_exposition(text: str) -> None:
    """Every line must be a TYPE/HELP comment or a well-formed sample."""
    if not text:
        return  # an empty exposition (nothing matched the prefix) is valid
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        assert _COMMENT_RE.match(line) or _SAMPLE_RE.match(line), (
            f"invalid exposition line: {line!r}"
        )


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def _sample_value(text: str, name: str):
    """Value of the (unlabelled) sample ``name`` in an exposition body."""
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    return None


@pytest.fixture
def hidden():
    """Collects bvars created by a test and hides them afterwards so the
    global registry stays clean for other tests."""
    created = []
    yield created.append
    for var in created:
        var.hide()


# -- golden rendering ---------------------------------------------------------


class TestRendering:
    def test_adder_renders_as_counter(self, hidden):
        a = Adder(name="obsx_requests_total")
        hidden(a)
        a << 7
        text = render_metrics(prefix="obsx_")
        assert "# TYPE obsx_requests_total counter" in text
        assert "obsx_requests_total 7" in text
        validate_exposition(text)

    def test_passive_status_and_recorders_render_as_gauges(self, hidden):
        ps = PassiveStatus(lambda: 2.5, name="obsx_gauge")
        rec = IntRecorder(name="obsx_avg")
        mx = Maxer(name="obsx_max")
        for v in (ps, rec, mx):
            hidden(v)
        rec << 10
        rec << 20
        mx << 42
        text = render_metrics(prefix="obsx_")
        assert "# TYPE obsx_gauge gauge" in text
        assert "obsx_gauge 2.5" in text
        assert "obsx_avg 15.0" in text
        assert "obsx_max 42" in text
        validate_exposition(text)

    def test_window_renders_as_gauge(self, hidden):
        base = Adder()
        rate = PerSecond(base, name="obsx_rate")
        hidden(rate)
        text = render_metrics(prefix="obsx_")
        assert "# TYPE obsx_rate gauge" in text
        validate_exposition(text)

    def test_latency_recorder_renders_as_summary(self, hidden):
        lr = LatencyRecorder(name="obsx_latency")
        hidden(lr)
        for v in (100, 200, 300, 400):
            lr << v
        text = render_metrics(prefix="obsx_")
        assert "# TYPE obsx_latency summary" in text
        for q in ("0.5", "0.9", "0.99", "0.999"):
            assert f'obsx_latency{{quantile="{q}"}}' in text
        assert "obsx_latency_sum 1000" in text
        assert "obsx_latency_count 4" in text
        assert "obsx_latency_max_latency 400.0" in text
        assert "# TYPE obsx_latency_qps gauge" in text
        validate_exposition(text)

    def test_non_numeric_values_are_skipped(self, hidden):
        s = PassiveStatus(lambda: "not-a-number", name="obsx_stringy")
        hidden(s)
        text = render_metrics(prefix="obsx_")
        assert "obsx_stringy" not in text
        validate_exposition(text)

    def test_numeric_flags_mirrored_as_gauges(self):
        text = render_metrics(prefix="flag_max_body_size")
        assert "# TYPE flag_max_body_size gauge" in text
        assert _sample_value(text, "flag_max_body_size") == float(
            flag_registry.get("max_body_size")
        )
        validate_exposition(text)

    def test_escape_label_value(self):
        assert escape_label_value('a"b') == 'a\\"b'
        assert escape_label_value("a\\b") == "a\\\\b"
        assert escape_label_value("a\nb") == "a\\nb"
        assert escape_label_value('q"\\' + "\n") == 'q\\"\\\\\\n'

    def test_sanitize_metric_name(self):
        assert sanitize_metric_name("ok_name") == "ok_name"
        assert sanitize_metric_name("9starts_with_digit") == (
            "_9starts_with_digit"
        )
        assert sanitize_metric_name("dots.and-dashes") == "dots_and_dashes"

    def test_prefix_filters(self, hidden):
        a = Adder(name="obsx_inside")
        hidden(a)
        text = render_metrics(prefix="obsx_inside")
        assert "obsx_inside" in text
        assert "\nprocess_" not in text and "flag_max_body_size" not in text


# -- live server scrape -------------------------------------------------------


@pytest.fixture
def portal_server():
    server = Server()
    server.add_service("obsdemo", {"echo": lambda cntl, req: req})
    assert server.start(0)
    yield server
    server.stop()
    server.join(timeout=5)


@pytest.fixture
def echo_server_factory():
    """Builds servers with per-test-unique service names: method bvar
    names dedup globally (expose() keeps the FIRST registrant), so a test
    asserting on its own method summary must not reuse a service name a
    previous test's dead server still holds in the registry."""
    servers = []

    def make(service: str):
        server = Server()
        server.add_service(service, {"echo": lambda cntl, req: req})
        assert server.start(0)
        servers.append(server)
        return server

    yield make
    for server in servers:
        server.stop()
        server.join(timeout=5)


def _fetch(server, path):
    return http_mod.http_call("127.0.0.1", server.port, path)


class TestPortalScrape:
    def test_scrape_is_valid_and_typed(self, portal_server):
        status, headers, body = _fetch(portal_server, "/brpc_metrics")
        assert status == 200
        assert headers.get("content-type", "").startswith("text/plain")
        text = body.decode()
        validate_exposition(text)
        assert "# TYPE" in text

    def test_index_links_brpc_metrics(self, portal_server):
        status, _, body = _fetch(portal_server, "/")
        assert status == 200 and b"/brpc_metrics" in body

    def test_method_summary_advances_with_traffic(self, echo_server_factory):
        server = echo_server_factory("obstraffic")
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        for i in range(5):
            assert ch.call_method("obstraffic", "echo", b"x%d" % i).ok()
        _, _, body = _fetch(server, "/brpc_metrics")
        text = body.decode()
        name = "method_obstraffic_echo_latency"
        assert f"# TYPE {name} summary" in text
        assert _sample_value(text, f"{name}_count") >= 5
        assert f'{name}{{quantile="0.99"}}' in text

    def test_prefix_query(self, portal_server):
        _, _, body = _fetch(
            portal_server, "/brpc_metrics?prefix=method_obsdemo"
        )
        text = body.decode()
        validate_exposition(text)
        for line in text.splitlines():
            if not line.startswith("#"):
                assert line.startswith("method_obsdemo")

    def test_scrape_under_load(self, portal_server):
        """Scrapes stay valid while traffic hammers the same server."""
        stop = threading.Event()
        errs = []

        def pound():
            ch = Channel()
            assert ch.init(f"127.0.0.1:{portal_server.port}")
            i = 0
            while not stop.is_set():
                c = ch.call_method("obsdemo", "echo", b"load-%d" % i)
                if c.failed():
                    errs.append(c.error_text)
                i += 1

        threads = [threading.Thread(target=pound) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(5):
                status, _, body = _fetch(portal_server, "/brpc_metrics")
                assert status == 200
                validate_exposition(body.decode())
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errs, errs[:3]


# -- device-plane metrics -----------------------------------------------------


class TestDeviceLinkMetrics:
    def test_link_bvars_appear_and_advance(self, portal_server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(transport="tpu", timeout_ms=30000),
        )
        body = b"device-plane payload " * 64
        for _ in range(3):
            cntl = ch.call_method("obsdemo", "echo", body)
            assert cntl.ok(), cntl.error_text
        link = ch._device_sock.link
        # direct bvar reads: latency recorders and byte counters advanced
        assert link._m_rtt.count() > 0
        assert link._m_flush.count() > 0
        assert link._m_pump.count() > 0
        assert link._m_out_bytes.get_value() >= len(body) * 3
        assert link._m_in_bytes.get_value() >= len(body) * 3
        # and the same names are scrapeable from the live portal
        _, _, raw = _fetch(portal_server, "/brpc_metrics")
        text = raw.decode()
        validate_exposition(text)
        pfx = f"device_link_{link.link_id}"
        assert f"# TYPE {pfx}_step_rtt_us summary" in text
        assert _sample_value(text, f"{pfx}_step_rtt_us_count") > 0
        assert f"# TYPE {pfx}_out_bytes_second gauge" in text
        assert f"# TYPE {pfx}_in_bytes_second gauge" in text
        assert _sample_value(text, "device_link_bytes") > 0

    def test_link_metrics_retire_on_clean_close(self, portal_server):
        """An orderly ECLOSE dance (no fail()) must also drop the link's
        registry names — churning links cannot accumulate entries."""
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(transport="tpu", timeout_ms=30000),
        )
        assert ch.call_method("obsdemo", "echo", b"x").ok()
        link = ch._device_sock.link
        pfx = f"device_link_{link.link_id}"
        assert f"{pfx}_step_rtt_us" in render_metrics(prefix=pfx)
        # one side starts the orderly close; the F_CLOSE dance takes the
        # peer side down too, and the second ECLOSE retires the names
        ch._device_sock.set_failed(ErrorCode.ECLOSE, "clean close")
        assert _wait(lambda: render_metrics(prefix=pfx) == "")

    def test_link_metrics_retire_on_failure(self, portal_server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(transport="tpu", timeout_ms=30000),
        )
        assert ch.call_method("obsdemo", "echo", b"x").ok()
        link = ch._device_sock.link
        pfx = f"device_link_{link.link_id}"
        assert f"{pfx}_step_rtt_us" in render_metrics(prefix=pfx)
        link.fail("test-induced failure")
        assert render_metrics(prefix=pfx) == ""
        from incubator_brpc_tpu.transport.device_link import link_errors

        assert link_errors.get_value() > 0


# -- collective sessions ------------------------------------------------------


class TestCollectiveObservability:
    def test_collective_registration_is_opt_in(self):
        server = Server()  # no jax.distributed in-process: default OFF
        assert server.start(0)
        try:
            assert not server.has_method("_tpu_transport.collective")
            assert server.has_method("_tpu_transport.handshake")
        finally:
            server.stop()
            server.join(timeout=5)

    def test_collective_opt_in_gets_concurrency_limit(self):
        server = Server(
            ServerOptions(
                enable_collective_service=True, collective_max_concurrency=2
            )
        )
        assert server.start(0)
        try:
            assert server.has_method("_tpu_transport.collective")
            assert (
                server.method_max_concurrency("_tpu_transport.collective")
                == 2
            )
        finally:
            server.stop()
            server.join(timeout=5)

    def test_session_span_parented_to_proposing_rpc(self, monkeypatch):
        from incubator_brpc_tpu.builtin.rpcz import span_store
        from incubator_brpc_tpu.parallel import mc_collective

        monkeypatch.setattr(
            mc_collective,
            "run_collective_session",
            lambda parties, idx, steps, width, seed: (
                np.zeros(width, np.float32),
                0.001,
            ),
        )
        server = Server(ServerOptions(enable_collective_service=True))
        assert server.start(0)
        assert set_flag("enable_rpcz", True)
        span_store.clear()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{server.port}")
            payload = json.dumps(
                {
                    "parties": [0, 1],
                    "index": 1,
                    "steps": 3,
                    "width": 4,
                    "seed": 7,
                }
            ).encode()
            cntl = ch.call_method("_tpu_transport", "collective", payload)
            assert cntl.ok(), cntl.error_text
            assert cntl.trace_id
            spans = [
                s
                for s in span_store.recent(limit=500)
                if s.span_type == "collective"
            ]
            assert spans, "no collective span sampled"
            span = spans[-1]
            # parented into the proposing RPC's trace
            assert span.trace_id == cntl.trace_id
            assert span.parent_span_id == cntl.span_id
            notes = " ".join(text for _, text in span.annotations)
            assert "steps=3" in notes and "width=4" in notes
            assert "parties=[0, 1]" in notes
            # and visible on the /rpcz page under the client's trace id
            _, _, body = _fetch(server, f"/rpcz?trace_id={cntl.trace_id:x}")
            assert b"collective" in body
            # session bvars advanced (the stub bypasses
            # run_collective_session, so count the handler-side counters
            # via /brpc_metrics presence instead)
            _, _, raw = _fetch(server, "/brpc_metrics")
            assert "# TYPE mc_collective_sessions counter" in raw.decode()
        finally:
            set_flag("enable_rpcz", False)
            span_store.clear()
            server.stop()
            server.join(timeout=5)

    def test_session_bvars_count_real_sessions(self):
        """run_collective_session itself feeds the session counters —
        single-party degenerate session, no cross-process fabric needed."""
        import jax

        from incubator_brpc_tpu.parallel.mc_collective import (
            collective_sessions,
            collective_steps,
            run_collective_session,
        )

        before = collective_sessions.get_value()
        steps_before = collective_steps.get_value()
        own, elapsed = run_collective_session(
            [jax.devices()[0].id], 0, steps=2, width=8, seed=3
        )
        assert own.shape == (8,)
        assert collective_sessions.get_value() == before + 1
        assert collective_steps.get_value() == steps_before + 2


# -- satellite: async binary-handler session reap -----------------------------


class _CountingFactory:
    def __init__(self):
        self.created = []
        self.destroyed = []

    def create(self):
        obj = object()
        self.created.append(obj)
        return obj

    def destroy(self, obj):
        self.destroyed.append(obj)


class TestAsyncResponseReap:
    def test_async_handler_without_response_is_reaped(self):
        factory = _CountingFactory()
        server = Server(
            ServerOptions(session_local_data_factory=factory)
        )
        held = []

        def never_responds(cntl, req):
            cntl.session_local_data()
            cntl.set_async()
            held.append(cntl)
            return None

        server.add_service(
            "leak", {"never": never_responds}, max_concurrency=1
        )
        assert server.start(0)
        old = flag_registry.get("async_response_timeout_s")
        assert set_flag("async_response_timeout_s", 0.3)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{server.port}",
                options=ChannelOptions(timeout_ms=10000),
            )
            cntl = ch.call_method("leak", "never", b"x")
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.ERPCTIMEDOUT
            assert "async handler" in cntl.error_text
            st = server.method_status("leak", "never")
            assert _wait(lambda: st.processing == 0)
            # the session-handler refcount drained: the pooled object can
            # be given back when the connection dies (the leak ADVICE r5
            # describes left it pinned forever)
            sock = held[0]._sock
            assert _wait(
                lambda: sock.context.get("_session_nhandlers", 0) == 0
            )
            # admission slot released: with max_concurrency=1 a second
            # call is admitted (it would be ELIMIT if the slot leaked)
            cntl2 = ch.call_method("leak", "never", b"y")
            assert cntl2.error_code == ErrorCode.ERPCTIMEDOUT
            # connection death pools the session object back
            sock.set_failed(ErrorCode.ECLOSE, "test closes")
            assert _wait(
                lambda: "_session_local_data" not in sock.context
            )
        finally:
            flag_registry.set_unchecked("async_response_timeout_s", old)
            server.stop()
            server.join(timeout=5)

    def test_send_response_then_return_finishes_once(self):
        server = Server()

        def double_finisher(cntl, req):
            cntl.send_response(b"first")
            return b"second"  # must be ignored: the finish is once-only

        server.add_service("once", {"both": double_finisher})
        assert server.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{server.port}")
            cntl = ch.call_method("once", "both", b"x")
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"first"
            assert _wait(lambda: server._nprocessing == 0)
            assert server._nprocessing == 0  # not driven negative
        finally:
            server.stop()
            server.join(timeout=5)


# -- satellite: lazy ParsedFrame.payload --------------------------------------


class TestLazyStreamPayload:
    def test_stream_frame_payload_materializes_lazily(self):
        import incubator_brpc_tpu.rpc.stream  # noqa: F401 — binds process_stream
        from incubator_brpc_tpu import native
        from incubator_brpc_tpu.iobuf import IOBuf
        from incubator_brpc_tpu.protocol.tbus_std import (
            FLAG_STREAM,
            Meta,
            pack_frame,
            parse_frame_iobuf,
        )

        if not native.NATIVE_AVAILABLE:
            pytest.skip("zero-copy stream cut needs the native IOBuf")
        payload = b"stream-bytes-" * 37
        raw = pack_frame(Meta(stream_id=9), payload, 0x77, flags=FLAG_STREAM)
        buf = IOBuf()
        buf.append(raw)
        frame, consumed = parse_frame_iobuf(buf)
        assert consumed == len(raw)
        assert frame.is_stream
        assert frame.payload_iobuf is not None
        assert frame._payload == b""  # the cut itself stayed zero-copy
        assert frame.payload == payload  # lazy materialization on access
        assert frame.payload == payload  # cached, stable

    def test_payload_setter_still_works(self):
        from incubator_brpc_tpu.protocol.tbus_std import Meta, ParsedFrame

        frame = ParsedFrame(meta=Meta(), payload=b"abc")
        assert frame.payload == b"abc"
        frame.payload = b"xyz"
        assert frame.payload == b"xyz"


# -- satellite: rpc_view --metrics --------------------------------------------


class TestRpcViewMetrics:
    TEXT1 = (
        "# TYPE c counter\nc 5\n"
        "# TYPE g gauge\ng 2.5\n"
        "# TYPE s summary\n"
        's{quantile="0.5"} 100.0\ns_sum 300\ns_count 3\n'
    )
    TEXT2 = (
        "# TYPE c counter\nc 15\n"
        "# TYPE g gauge\ng 2.5\n"
        "# TYPE s summary\n"
        's{quantile="0.5"} 150.0\ns_sum 900\ns_count 6\n'
    )

    def test_parse_exposition(self):
        from tools.rpc_view import parse_exposition

        values, types = parse_exposition(self.TEXT1)
        assert values["c"] == 5.0
        assert values['s{quantile="0.5"}'] == 100.0
        assert types == {"c": "counter", "g": "gauge", "s": "summary"}

    def test_delta_lines(self):
        from tools.rpc_view import metrics_delta_lines, parse_exposition

        v1, t = parse_exposition(self.TEXT1)
        v2, _ = parse_exposition(self.TEXT2)
        lines = metrics_delta_lines(v1, v2, t, seconds=2.0)
        joined = "\n".join(lines)
        assert "c 5 -> 15  (+10, 5.0/s)" in joined
        assert "s_count 3 -> 6" in joined  # summary counters rate too
        assert 's{quantile="0.5"} 150' in joined  # traffic: quantiles shown
        assert "\ng " not in joined and not joined.startswith("g ")  # unchanged

    def test_metrics_mode_against_live_server(
        self, echo_server_factory, capsys
    ):
        from tools.rpc_view import metrics_mode

        server = echo_server_factory("obsview1")
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        for i in range(3):
            assert ch.call_method("obsview1", "echo", b"m%d" % i).ok()
        rc = metrics_mode(
            f"127.0.0.1:{server.port}", 0, prefix="method_obsview1"
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "method_obsview1_echo_latency_count" in out

    def test_metrics_mode_delta_against_live_server(
        self, echo_server_factory, capsys
    ):
        import tools.rpc_view as rv

        server = echo_server_factory("obsview2")
        ch = Channel()
        assert ch.init(f"127.0.0.1:{server.port}")
        assert ch.call_method("obsview2", "echo", b"warm").ok()

        # traffic flows WHILE metrics_mode sits between its two scrapes,
        # so the second scrape sees a real delta
        stop = threading.Event()

        def drive():
            i = 0
            while not stop.is_set():
                ch.call_method("obsview2", "echo", b"d%d" % i)
                i += 1

        t = threading.Thread(target=drive)
        t.start()
        try:
            rc = rv.metrics_mode(
                f"127.0.0.1:{server.port}", 0.3, prefix="method_obsview2"
            )
        finally:
            stop.set()
            t.join()
        out = capsys.readouterr().out
        assert rc == 0
        assert "method_obsview2_echo_latency_count" in out
        assert "/s)" in out  # rate column rendered

    def test_content_type_constant(self):
        assert CONTENT_TYPE.startswith("text/plain")


# -- native telemetry ring (PR 4) ---------------------------------------------
#
# The C++ dispatch plane records every natively-answered request into a
# lock-free completion ring (src/tbnet); transport/native_plane.py drains
# it into per-method latency summaries, sampled rpcz server spans, and
# adaptive-limiter feedback. These tests drive PURE-native PRPC floods
# (cb_frames stays 0 — no interpreter on the request path) and assert the
# observability plane still sees everything.

from incubator_brpc_tpu.transport import native_plane as np_mod  # noqa: E402

# (flag snapshot/restore comes from the shared ``tuned_flags`` fixture
# in conftest.py)


@pytest.mark.skipif(
    not np_mod.NET_AVAILABLE, reason="native runtime unavailable"
)
class TestNativeTelemetry:
    def _native_server(self, service: str, **opts):
        from incubator_brpc_tpu.rpc import native_echo

        srv = Server(ServerOptions(native_plane=True, **opts))
        srv.add_service(service, {"echo": native_echo})
        assert srv.start(0)
        assert srv._native_plane is not None, "native plane did not engage"
        return srv

    def test_per_method_summary_advances_pure_native(self, tuned_flags):
        # flood over the baidu_std C++ fast path, then SCRAPE: the
        # /brpc_metrics render must force-drain the ring (scrape hook) and
        # show the per-method summary — without one Python-routed request
        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_sample_every", 0)
        srv = self._native_server("telsvc1")
        try:
            ch = np_mod.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                ch.pump("telsvc1", "echo", b"y" * 64, 1000, inflight=32)
            finally:
                ch.close()
            _, _, body = _fetch(srv, "/brpc_metrics?prefix=native_method_")
            text = body.decode()
            name = "native_method_telsvc1_echo_latency_us"
            assert f"# TYPE {name} summary" in text
            assert _sample_value(text, f"{name}_count") == 1000
            assert f'{name}{{quantile="0.99"}}' in text
            stats = srv._native_plane.stats()
            assert stats["native_reqs"] >= 1000
            assert stats["cb_frames"] == 0, "flood was not pure-native"
        finally:
            srv.stop()

    def test_sampled_spans_land_at_configured_rate(self, tuned_flags):
        from incubator_brpc_tpu.builtin.rpcz import span_store

        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_sample_every", 8)
        tuned_flags("enable_rpcz", True)
        # the shared rpcz token bucket ALSO bounds native spans/second;
        # raise it so this test observes the exact 1/N election alone
        tuned_flags("rpcz_samples_per_second", 10_000_000)
        srv = self._native_server("telsvc2")
        try:
            ch = np_mod.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                ch.pump("telsvc2", "echo", b"z" * 32, 800, inflight=32)
            finally:
                ch.close()
            srv._native_plane.drain_telemetry()
            spans = [
                sp
                for sp in span_store.recent(limit=20000)
                if sp.service == "telsvc2"
            ]
            # counter-based 1/N sampling is exact: ticks 0,8,16,...
            assert len(spans) == 800 // 8
            sp = spans[0]
            assert sp.span_type == "server" and sp.method == "echo"
            assert sp.trace_id != 0 and sp.span_id != 0
            assert sp.request_size == 32 and sp.response_size == 32
            assert sp.latency_us >= 0
            assert srv._native_plane.stats()["cb_frames"] == 0
        finally:
            srv.stop()

    def test_adaptive_limit_moves_without_python_route(self, tuned_flags):
        # the PR 3 blind spot: a 100%-native server used to hold its last
        # pushed limit because the adaptive signal came only from
        # Python-routed completions. The telemetry drain closes it: the
        # limiter must move off its seed from native completions alone,
        # and the new limit must land back in the C++ admission table.
        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_sample_every", 0)
        tuned_flags("auto_cl_initial_max_concurrency", 40)
        tuned_flags("auto_cl_sampling_interval_us", 20)
        tuned_flags("auto_cl_min_sample_count", 20)
        tuned_flags("auto_cl_max_sample_count", 100)
        tuned_flags("auto_cl_sample_window_size_ms", 50)
        srv = self._native_server("telsvc3", max_concurrency="auto")
        plane = srv._native_plane
        try:
            assert "telsvc3.echo" in plane.native_method_names()
            seed = 40
            assert srv.max_concurrency == seed
            assert plane.native_max_concurrency("telsvc3.echo") == seed
            ch = np_mod.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                for _ in range(4):
                    ch.pump("telsvc3", "echo", b"q" * 16, 5000, inflight=16)
                    plane.drain_telemetry()
            finally:
                ch.close()
            assert srv.max_concurrency != seed, (
                "adaptive limit never moved off its seed despite a "
                "pure-native flood"
            )
            # the moved limit is pushed back into the C++ admission table
            assert (
                plane.native_max_concurrency("telsvc3.echo")
                == srv.max_concurrency
            )
            assert plane.stats()["cb_frames"] == 0, "flood was not pure-native"
        finally:
            srv.stop()

    def test_ring_overflow_drops_instead_of_stalling(self, tuned_flags):
        tuned_flags("native_telemetry", True)
        tuned_flags("native_telemetry_sample_every", 0)
        tuned_flags("native_telemetry_ring_size", 64)
        tuned_flags("native_telemetry_drain_ms", 60000)  # bg pump idles
        srv = self._native_server("telsvc4")
        plane = srv._native_plane
        try:
            ch = np_mod.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                # 2000 completions into a 64-slot ring with nobody
                # draining: the hot path must keep answering (drop, not
                # block) and count what it sheds
                ch.pump("telsvc4", "echo", b"w" * 8, 2000, inflight=32)
                dropped = plane.telemetry_dropped()
                assert dropped > 0
                drained = plane.drain_telemetry()
                assert 0 < drained <= 64
                # the server is still alive and answering
                rc, err, _, body = ch.call("telsvc4", "echo", b"alive")
                assert rc >= 0 and err == 0 and body.to_bytes() == b"alive"
            finally:
                ch.close()
            assert plane.telemetry_dropped() + plane._tel_drained >= 2000
        finally:
            srv.stop()

    def test_telemetry_disabled_records_nothing(self, tuned_flags):
        tuned_flags("native_telemetry", False)
        srv = self._native_server("telsvc5")
        plane = srv._native_plane
        try:
            ch = np_mod.NativeClientChannel(
                "127.0.0.1", srv.port, protocol="baidu_std"
            )
            try:
                ch.pump("telsvc5", "echo", b"n" * 8, 200, inflight=16)
            finally:
                ch.close()
            assert plane.drain_telemetry() == 0
            assert plane.telemetry_dropped() == 0
            assert plane._tel_recorders == {}
        finally:
            srv.stop()


# -- satellites: SpanStore reload/round-trip + /rpcz query upgrades -----------


class TestSpanStoreSatellites:
    def test_rpcz_max_spans_reload_applies(self, tuned_flags):
        # deque(maxlen=...) froze the flag value read at construction;
        # submit() must re-check it so a runtime retune takes effect
        from incubator_brpc_tpu.builtin.rpcz import Span, SpanStore

        tuned_flags("rpcz_max_spans", 10)
        store = SpanStore()
        for i in range(10):
            store.submit(Span(trace_id=i + 1, span_id=i + 1))
        assert len(store) == 10
        tuned_flags("rpcz_max_spans", 4)
        store.submit(Span(trace_id=100, span_id=100))
        assert len(store) == 4  # shrank live, newest kept
        assert store.recent(limit=10)[-1].trace_id == 100
        tuned_flags("rpcz_max_spans", 6)
        for i in range(6):
            store.submit(Span(trace_id=200 + i, span_id=200 + i))
        assert len(store) == 6  # grew live

    def test_json_mode_rejects_cleanly_when_rpcz_off(
        self, portal_server, tuned_flags
    ):
        # a machine consumer must get JSON and a non-2xx, never a 200
        # text blob it cannot parse
        tuned_flags("enable_rpcz", False)
        status, headers, body = _fetch(portal_server, "/rpcz?json=1")
        assert status == 503
        assert "json" in headers.get("content-type", "")
        assert "rpcz is off" in json.loads(body.decode())["error"]

    def test_load_spans_round_trips_persisted_spans(
        self, tuned_flags, tmp_path
    ):
        from incubator_brpc_tpu.builtin.rpcz import (
            Span,
            SpanStore,
            load_spans,
        )

        tuned_flags("rpcz_database_dir", str(tmp_path))
        store = SpanStore()
        span = Span(
            trace_id=0xFEED,
            span_id=0xBEEF,
            parent_span_id=0x1,
            span_type="server",
            service="persist",
            method="echo",
            remote_side="127.0.0.1:9",
            log_id=7,
            error_code=3,
            start_real_us=123456789,
            latency_us=42.5,
            request_size=10,
            response_size=20,
        )
        span.annotations.append((1.25, "queued"))
        span.annotations.append((2.5, "done"))
        store.submit(span)
        store.close_db()
        loaded = load_spans(str(tmp_path / "rpcz.jsonl"))
        assert len(loaded) == 1
        # dataclass equality covers every field — including annotations
        # normalized back to the (offset, text) TUPLES live spans hold
        # (the JSON round trip turned them into lists before this PR)
        assert loaded[0] == span
        assert isinstance(loaded[0].annotations[0], tuple)

    def test_load_spans_skips_torn_lines(self, tmp_path):
        from incubator_brpc_tpu.builtin.rpcz import load_spans

        p = tmp_path / "rpcz.jsonl"
        p.write_text(
            '{"trace_id": 1, "span_id": 2, "type": "server"}\n'
            '{"trace_id": 3, "span_id":'  # torn tail (crash mid-write)
        )
        loaded = load_spans(str(p))
        assert len(loaded) == 1 and loaded[0].trace_id == 1
        assert load_spans(str(tmp_path / "missing.jsonl")) == []


class TestRpczQueries:
    @pytest.fixture
    def trace_server(self, portal_server, tuned_flags):
        from incubator_brpc_tpu.builtin.rpcz import Span, span_store

        tuned_flags("enable_rpcz", True)
        span_store.clear()
        mk = Span
        span_store.submit(mk(
            trace_id=0xABC, span_id=1, parent_span_id=0, span_type="server",
            service="q", method="root", latency_us=900, start_real_us=100,
        ))
        span_store.submit(mk(
            trace_id=0xABC, span_id=2, parent_span_id=1, span_type="client",
            service="q", method="child1", latency_us=300, start_real_us=200,
        ))
        span_store.submit(mk(
            trace_id=0xABC, span_id=3, parent_span_id=1, span_type="client",
            service="q", method="child2", latency_us=100, error_code=7,
            start_real_us=300,
        ))
        span_store.submit(mk(
            trace_id=0xABC, span_id=4, parent_span_id=2, span_type="server",
            service="q", method="grandchild", latency_us=50,
            start_real_us=400,
        ))
        yield portal_server
        span_store.clear()

    def test_trace_id_renders_parent_child_tree(self, trace_server):
        _, _, body = _fetch(trace_server, "/rpcz?trace_id=abc")
        lines = body.decode().splitlines()
        assert len(lines) == 4
        assert lines[0].startswith("trace=abc span=1")  # root, no indent
        assert lines[1].startswith("  trace=abc span=2")
        assert lines[2].startswith("    trace=abc span=4")  # under child1
        assert lines[3].startswith("  trace=abc span=3")

    def test_min_latency_filter_is_latency_ordered(self, trace_server):
        _, _, body = _fetch(trace_server, "/rpcz?min_latency_us=200")
        lines = body.decode().splitlines()
        assert len(lines) == 2
        assert "span=1" in lines[0] and "span=2" in lines[1]  # worst first

    def test_error_only_filter(self, trace_server):
        _, _, body = _fetch(trace_server, "/rpcz?error_only=1")
        lines = [ln for ln in body.decode().splitlines() if ln]
        assert len(lines) == 1 and "error=7" in lines[0]

    def test_json_mode_serves_span_dicts(self, trace_server):
        status, headers, body = _fetch(trace_server, "/rpcz?json=1")
        assert status == 200 and "json" in headers.get("content-type", "")
        rows = json.loads(body.decode())
        assert len(rows) == 4
        by_span = {r["span_id"]: r for r in rows}
        assert by_span[3]["error_code"] == 7
        assert by_span[1]["type"] == "server"
        assert by_span[1]["latency_us"] == 900

    def test_bad_query_values_rejected(self, trace_server):
        status, _, _ = _fetch(trace_server, "/rpcz?min_latency_us=abc")
        assert status == 400
        status, _, _ = _fetch(trace_server, "/rpcz?trace_id=zzz")
        assert status == 400


class TestSpanRetention:
    """rpcz_keep_span_seconds: age pruning against the HOST clock, with
    non-wall-time (synthetic/replayed) spans exempt — one skewed
    producer must never purge the process-global store."""

    def _span(self, start_us, span_id):
        from incubator_brpc_tpu.builtin.rpcz import Span

        return Span(
            trace_id=0xF0, span_id=span_id, parent_span_id=0,
            span_type="server", service="r", method="m",
            latency_us=10, start_real_us=start_us,
        )

    def test_wall_clock_spans_age_out_synthetic_spans_survive(
        self, tuned_flags
    ):
        import time as _time

        from incubator_brpc_tpu.builtin.rpcz import SpanStore

        tuned_flags("rpcz_keep_span_seconds", 60)
        store = SpanStore()
        now_us = _time.time() * 1e6
        store.submit(self._span(100, 1))  # synthetic clock: exempt
        store.submit(self._span(now_us - 120e6, 2))  # 2 min old: stale
        store.submit(self._span(now_us - 1e6, 3))  # fresh
        store.submit(self._span(now_us, 4))  # triggers the prune
        ids = [s.span_id for s in store.recent()]
        assert 2 not in ids, ids  # aged out past the 60 s horizon
        assert {1, 3, 4} <= set(ids), ids  # exempt + fresh survive

    def test_skewed_future_span_cannot_purge_the_store(self, tuned_flags):
        import time as _time

        from incubator_brpc_tpu.builtin.rpcz import SpanStore

        tuned_flags("rpcz_keep_span_seconds", 60)
        store = SpanStore()
        now_us = _time.time() * 1e6
        store.submit(self._span(now_us, 1))
        # a producer 10 hours in the future: must not evict span 1
        store.submit(self._span(now_us + 36000e6, 2))
        ids = {s.span_id for s in store.recent()}
        assert ids == {1, 2}, ids
