"""Load-balancer + naming integration tests (the VERDICT round-1 matrix:
multi-server loopback with add/remove mid-traffic, LA punishing an
injected-slow server — reference test/brpc_load_balancer_unittest.cpp and
the File:// naming shape of brpc_channel_unittest.cpp:149-260)."""

import collections
import time

import pytest

from incubator_brpc_tpu.lb import (
    ConsistentHashLB,
    LocalityAwareLB,
    RoundRobinLB,
    WeightedRoundRobinLB,
)
from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.status import ErrorCode


def ep(port):
    return EndPoint(ip="127.0.0.1", port=port)


class TestLbUnits:
    def test_rr_cycles_evenly(self):
        lb = RoundRobinLB()
        for p in (1, 2, 3):
            lb.add_server(ep(p))
        picks = [lb.select().port for _ in range(9)]
        assert collections.Counter(picks) == {1: 3, 2: 3, 3: 3}

    def test_wrr_respects_weights(self):
        lb = WeightedRoundRobinLB()
        lb.add_server(ep(1), weight=3)
        lb.add_server(ep(2), weight=1)
        picks = collections.Counter(lb.select().port for _ in range(80))
        assert picks[1] > picks[2] * 2

    def test_consistent_hash_stability(self):
        lb = ConsistentHashLB()
        for p in (1, 2, 3, 4):
            lb.add_server(ep(p))
        owner = {code: lb.select(request_code=code).port for code in range(200)}
        # same code -> same server, deterministically
        for code in range(200):
            assert lb.select(request_code=code).port == owner[code]
        # removing one server remaps ONLY its keys (ketama property)
        lb.remove_server(ep(3))
        moved = sum(
            1
            for code in range(200)
            if lb.select(request_code=code).port != owner[code]
        )
        lost = sum(1 for code in range(200) if owner[code] == 3)
        assert moved == lost

    def test_la_prefers_fast_server(self):
        # seeded RNG: the distribution assertion is deterministic — no
        # dependence on the process-global random stream or host load
        import random as _random

        lb = LocalityAwareLB(rng=_random.Random(42))
        fast, slow = ep(1), ep(2)
        lb.add_server(fast)
        lb.add_server(slow)
        for _ in range(50):
            chosen = lb.select()
            lb.feedback(chosen, 100.0 if chosen == fast else 50_000.0, 0)
        picks = collections.Counter()
        for _ in range(200):
            chosen = lb.select()
            picks[chosen.port] += 1
            # settle the in-flight charge with the server's typical latency
            # so the counter measures steady-state preference, not the
            # in-flight penalty accumulating over an un-drained burst
            lb.feedback(chosen, 100.0 if chosen == fast else 50_000.0, 0)
        assert picks[1] > picks[2] * 5

    def test_la_punishes_errors(self):
        lb = LocalityAwareLB()
        good, bad = ep(1), ep(2)
        lb.add_server(good)
        lb.add_server(bad)
        for _ in range(50):
            chosen = lb.select()
            lb.feedback(chosen, 200.0, 0 if chosen == good else 1014)
        assert lb.expected_latency_us(bad) > lb.expected_latency_us(good) * 3


def named_server(name: bytes, delay: float = 0.0):
    s = Server()

    def echo(cntl, req):
        if delay:
            time.sleep(delay)
        return name

    s.add_service("svc", {"echo": echo})
    assert s.start(0)
    return s


class TestNamingMidTraffic:
    def test_add_remove_servers_mid_traffic(self, tmp_path):
        """The File:// naming shape: servers join and leave a live channel
        by editing the file (brpc_channel_unittest.cpp:162)."""
        s1 = named_server(b"one")
        s2 = named_server(b"two")
        f = tmp_path / "servers"
        f.write_text(f"127.0.0.1:{s1.port}\n")
        try:
            ch = Channel()
            assert ch.init(f"file://{f}", "rr")
            for _ in range(4):
                assert ch.call_method("svc", "echo", b"").response_payload == b"one"
            # add s2 and push the refresh (tests drive it directly instead
            # of waiting out the poll interval)
            f.write_text(f"127.0.0.1:{s1.port}\n127.0.0.1:{s2.port}\n")
            ch._lb.ns_thread._refresh()
            seen = {
                ch.call_method("svc", "echo", b"").response_payload
                for _ in range(10)
            }
            assert seen == {b"one", b"two"}
            # remove s1: traffic must drain to s2 only, no failures
            f.write_text(f"127.0.0.1:{s2.port}\n")
            ch._lb.ns_thread._refresh()
            for _ in range(6):
                cntl = ch.call_method("svc", "echo", b"")
                assert cntl.ok(), cntl.error_text
                assert cntl.response_payload == b"two"
        finally:
            s1.stop()
            s2.stop()

    def test_la_avoids_injected_slow_server_e2e(self):
        """The full stack: list:// naming + la LB; a server with injected
        latency ends up with a small share of live traffic."""
        fast1 = named_server(b"f1")
        fast2 = named_server(b"f2")
        slow = named_server(b"slow", delay=0.08)
        try:
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{fast1.port},127.0.0.1:{fast2.port},"
                f"127.0.0.1:{slow.port}",
                "la",
                options=ChannelOptions(timeout_ms=5000),
            )
            counts = collections.Counter()
            for _ in range(60):
                cntl = ch.call_method("svc", "echo", b"")
                assert cntl.ok(), cntl.error_text
                counts[cntl.response_payload] += 1
            # the slow server must get markedly less than a fair third
            assert counts[b"slow"] < 60 / 3 / 2, counts
            assert counts[b"f1"] > 0 and counts[b"f2"] > 0
        finally:
            fast1.stop()
            fast2.stop()
            slow.stop()

    def test_all_servers_removed_fails_cleanly(self, tmp_path):
        s1 = named_server(b"solo")
        f = tmp_path / "servers"
        f.write_text(f"127.0.0.1:{s1.port}\n")
        try:
            ch = Channel()
            assert ch.init(f"file://{f}", "rr")
            assert ch.call_method("svc", "echo", b"").ok()
            f.write_text("\n")
            ch._lb.ns_thread._refresh()
            cntl = ch.call_method("svc", "echo", b"")
            assert cntl.failed()  # no server: fails, doesn't hang
        finally:
            s1.stop()


class TestAllExcludedAndReconnect:
    def test_all_excluded_fails_selection(self):
        # rr/random must FAIL the pick when every server is excluded
        # (reference ExcludedServers), never silently return an excluded one
        from incubator_brpc_tpu.lb import RandomLB, RoundRobinLB
        from incubator_brpc_tpu.utils.endpoint import EndPoint

        for lb in (RoundRobinLB(), RandomLB()):
            eps = [EndPoint("127.0.0.1", 7001), EndPoint("127.0.0.1", 7002)]
            for ep in eps:
                lb.add_server(ep)
            assert lb.select(excluded=set(eps)) is None
            assert lb.select(excluded={eps[0]}) == eps[1]

    def test_all_excluded_rpc_fails_with_ehostdown(self):
        # one server, max_retry=1: first attempt fails (dead socket), the
        # retry excludes it -> selection fails -> EHOSTDOWN surfaces
        import tempfile

        from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller, Server

        srv = Server()
        srv.add_service("t", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        with tempfile.NamedTemporaryFile("w", suffix=".lst", delete=False) as f:
            f.write(f"127.0.0.1:{srv.port}\n")
            path = f.name
        ch = Channel()
        assert ch.init(f"file://{path}", "rr",
                       options=ChannelOptions(timeout_ms=3000, max_retry=2))
        assert ch.call_method("t", "echo", b"warm").ok()
        # kill the server hard: the next call's attempts all fail, every
        # candidate ends up excluded, and the terminal code is EHOSTDOWN
        # (connect refused path) or EFAILEDSOCKET (write raced the close) —
        # never a silent re-pick that hangs
        srv.stop()
        srv.join(timeout=5)
        cntl = ch.call_method("t", "echo", b"x", cntl=Controller(timeout_ms=3000, max_retry=2))
        assert cntl.failed()
        # ERPCTIMEDOUT appears when a loaded host stretches the dial
        # attempts past the deadline; what must NEVER happen is a silent
        # success against an excluded dead server
        assert cntl.error_code in (
            ErrorCode.EHOSTDOWN, ErrorCode.EFAILEDSOCKET, ErrorCode.EEOF,
            ErrorCode.ERPCTIMEDOUT,
        )

    def test_fast_reconnect_without_health_check_wait(self):
        # kill the server, restart it on the SAME port, call immediately:
        # connect_if_not must revive the socket inline — no health wait.
        # The probe interval is raised to 30s for the duration, so a
        # success within the 8s call budget PROVES the inline path (a
        # loaded host can stall >3s, which used to flake a wall-clock
        # threshold; no probe can fire inside 30s).
        from incubator_brpc_tpu.rpc import Channel, Controller, Server
        from incubator_brpc_tpu.utils.flags import set_flag

        assert set_flag("health_check_interval", 30)
        srv = Server()
        srv.add_service("t", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        port = srv.port
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{port}")
            assert ch.call_method("t", "echo", b"warm").ok()
            srv.stop()
            srv.join(timeout=5)
            # burn one call so the client notices the socket died
            ch.call_method(
                "t", "echo", b"probe",
                cntl=Controller(timeout_ms=300, max_retry=0),
            )
            srv2 = Server()
            srv2.add_service("t", {"echo": lambda cntl, req: req})
            assert srv2.start(port)
            try:
                cntl = ch.call_method(
                    "t", "echo", b"back",
                    cntl=Controller(timeout_ms=8000, max_retry=1),
                )
                assert cntl.ok(), (
                    f"inline reconnect did not happen: {cntl.error_text}"
                )
            finally:
                srv2.stop()
                srv2.join(timeout=5)
        finally:
            set_flag("health_check_interval", 3)
