"""End-to-end RPC tests — the loopback client↔server matrix the reference
runs in test/brpc_channel_unittest.cpp:149-260 and brpc_server_unittest.cpp
(in-process servers on 127.0.0.1, real naming/LB/retry/backup paths)."""

import threading
import time

import pytest

from incubator_brpc_tpu.builtin.rpcz import span_store
from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller, Server
from incubator_brpc_tpu.utils.flags import get_flag, set_flag_unchecked
from incubator_brpc_tpu.utils.status import ErrorCode


def make_echo_server(max_concurrency=0, method_max_concurrency=0, delay_s=0.0):
    from incubator_brpc_tpu.rpc.server import ServerOptions

    srv = Server(
        ServerOptions(
            max_concurrency=max_concurrency,
            method_max_concurrency=method_max_concurrency,
        )
    )

    def echo(cntl, req):
        if delay_s:
            time.sleep(delay_s)
        cntl.response_attachment = cntl.request_attachment
        return req

    def fail(cntl, req):
        cntl.set_failed(ErrorCode.EINTERNAL, "deliberate")
        return b""

    def boom(cntl, req):
        raise RuntimeError("kaboom")

    srv.add_service("Echo", {"echo": echo, "fail": fail, "boom": boom})
    assert srv.start(0)
    return srv


@pytest.fixture
def echo_server():
    srv = make_echo_server()
    yield srv
    srv.stop()
    srv.join(timeout=5)


def connect(port, **opts) -> Channel:
    ch = Channel()
    assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(**opts))
    return ch


class TestEcho:
    def test_sync_echo(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Echo", "echo", b"payload-123")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"payload-123"
        assert cntl.latency_us > 0

    def test_large_payload(self, echo_server):
        ch = connect(echo_server.port)
        blob = bytes(range(256)) * 4096  # 1 MiB
        cntl = ch.call("Echo", "echo", blob)
        assert cntl.ok()
        assert cntl.response_payload == blob

    def test_attachment_roundtrip(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Echo", "echo", b"body", attachment=b"side-channel")
        assert cntl.ok()
        assert cntl.response_payload == b"body"
        assert cntl.response_attachment == b"side-channel"

    def test_async_done(self, echo_server):
        ch = connect(echo_server.port)
        done_evt = threading.Event()
        result = {}

        def done(cntl):
            result["payload"] = cntl.response_payload
            result["ok"] = cntl.ok()
            done_evt.set()

        ch.call("Echo", "echo", b"async", done=done)
        assert done_evt.wait(5)
        assert result["ok"] and result["payload"] == b"async"

    def test_many_sequential(self, echo_server):
        ch = connect(echo_server.port)
        for i in range(50):
            cntl = ch.call("Echo", "echo", f"msg-{i}".encode())
            assert cntl.ok() and cntl.response_payload == f"msg-{i}".encode()

    def test_concurrent_callers(self, echo_server):
        ch = connect(echo_server.port)
        errors = []

        def worker(n):
            for i in range(10):
                cntl = ch.call("Echo", "echo", f"{n}-{i}".encode())
                if not cntl.ok() or cntl.response_payload != f"{n}-{i}".encode():
                    errors.append((n, i, cntl.error_code))

        threads = [threading.Thread(target=worker, args=(n,)) for n in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_compress_roundtrip(self, echo_server):
        ch = connect(echo_server.port)
        for codec in ("gzip", "zlib", "zlib1"):
            cntl = Controller()
            cntl.compress_type = codec
            cntl = ch.call("Echo", "echo", b"Z" * 50000, cntl=cntl)
            assert cntl.ok(), (codec, cntl.error_text)
            assert cntl.response_payload == b"Z" * 50000


class TestErrors:
    def test_unknown_codec_fails_fast(self, echo_server):
        ch = connect(echo_server.port)
        cntl = Controller()
        cntl.compress_type = "lz4"  # not registered
        t0 = time.monotonic()
        cntl = ch.call("Echo", "echo", b"x", cntl=cntl)
        assert cntl.error_code == ErrorCode.EREQUEST
        assert time.monotonic() - t0 < 0.4  # failed fast, not via timeout

    def test_enoservice(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Nothing", "echo", b"")
        assert cntl.error_code == ErrorCode.ENOSERVICE

    def test_enomethod(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Echo", "nonexistent", b"")
        assert cntl.error_code == ErrorCode.ENOMETHOD

    def test_handler_set_failed(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Echo", "fail", b"")
        assert cntl.error_code == ErrorCode.EINTERNAL
        assert "deliberate" in cntl.error_text

    def test_handler_raises(self, echo_server):
        ch = connect(echo_server.port)
        cntl = ch.call("Echo", "boom", b"")
        assert cntl.error_code == ErrorCode.EINTERNAL
        assert "kaboom" in cntl.error_text

    def test_connection_refused(self):
        ch = connect(1, max_retry=0)  # port 1: nothing listens
        cntl = ch.call("Echo", "echo", b"")
        assert cntl.failed()
        assert cntl.error_code == ErrorCode.EFAILEDSOCKET

    def test_timeout(self):
        srv = make_echo_server(delay_s=1.0)
        try:
            ch = connect(srv.port, timeout_ms=100)
            t0 = time.monotonic()
            cntl = ch.call("Echo", "echo", b"slow")
            dt = time.monotonic() - t0
            assert cntl.error_code == ErrorCode.ERPCTIMEDOUT
            assert dt < 0.9  # returned at the deadline, not the handler
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_elogoff_when_stopping(self, echo_server):
        ch = connect(echo_server.port)
        assert ch.call("Echo", "echo", b"warm").ok()
        echo_server._stopping = True  # stop intake without closing conns
        try:
            cntl = ch.call("Echo", "echo", b"x")
            assert cntl.error_code == ErrorCode.ELOGOFF
        finally:
            echo_server._stopping = False


class TestAdmission:
    def test_method_elimit(self):
        srv = make_echo_server(method_max_concurrency=1, delay_s=0.5)
        try:
            ch = connect(srv.port, timeout_ms=5000)
            codes = []
            lock = threading.Lock()

            def call():
                cntl = ch.call("Echo", "echo", b"x")
                with lock:
                    codes.append(cntl.error_code)

            threads = [threading.Thread(target=call) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert ErrorCode.ELIMIT in codes  # someone was turned away
            assert 0 in codes  # someone got through
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_server_level_limit(self):
        srv = make_echo_server(max_concurrency=1, delay_s=0.5)
        try:
            ch = connect(srv.port, timeout_ms=5000)
            codes = []
            lock = threading.Lock()

            def call():
                cntl = ch.call("Echo", "echo", b"x")
                with lock:
                    codes.append(cntl.error_code)

            threads = [threading.Thread(target=call) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert ErrorCode.ELIMIT in codes
            assert 0 in codes
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_method_status_bvars_live(self, echo_server):
        """The cross-cutting 'bvar fed by the RPC path' property (SURVEY §1
        L0): per-method latency recorder sees real calls."""
        ch = connect(echo_server.port)
        for _ in range(5):
            assert ch.call("Echo", "echo", b"x").ok()
        # windowed bvars sample at 1 Hz — poll until the sampler catches up
        st = echo_server.method_status("Echo", "echo")
        deadline = time.monotonic() + 5
        while st.latency.count() < 5 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert st.latency.count() >= 5
        assert st.latency.latency() > 0


class TestAsyncHandler:
    def test_deferred_response(self):
        srv = Server()

        def deferred(cntl, req):
            cntl.set_async()

            def later():
                time.sleep(0.05)
                cntl.send_response(b"deferred:" + req)

            threading.Thread(target=later).start()
            return None

        srv.add_service("Late", {"reply": deferred})
        assert srv.start(0)
        try:
            ch = connect(srv.port)
            cntl = ch.call("Late", "reply", b"x")
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"deferred:x"
        finally:
            srv.stop()
            srv.join(timeout=5)


class TestRetryAndBackup:
    def test_retry_exhaustion_counts(self):
        ch = connect(1, max_retry=2)
        cntl = ch.call("Echo", "echo", b"")
        assert cntl.failed()
        assert cntl.retried_count == 2

    def test_lb_retry_failover(self):
        """First pick lands on a dead server; retry must fail over to the
        live one (ExcludedServers, controller.cpp:578-615)."""
        srv = make_echo_server()
        try:
            dead_port = 1
            ch = Channel()
            assert ch.init(
                f"list://127.0.0.1:{dead_port},127.0.0.1:{srv.port}",
                lb_name="rr",
                options=ChannelOptions(max_retry=3, timeout_ms=2000),
            )
            oks = 0
            for _ in range(6):
                cntl = ch.call("Echo", "echo", b"failover")
                if cntl.ok():
                    oks += 1
            assert oks == 6  # every call lands despite the dead server
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_backup_request_wins(self):
        """Slow primary, fast backup: the duplicate fired at backup_request_ms
        completes the RPC first (controller.cpp:565-598)."""
        slow = make_echo_server(delay_s=1.0)
        fast = make_echo_server()
        try:
            ch = Channel()
            # rr from a fresh channel: first pick is deterministic enough —
            # run several calls; every one must finish well before the slow
            # handler's 1 s because the backup fires at 100 ms.
            assert ch.init(
                f"list://127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
                lb_name="rr",
                options=ChannelOptions(
                    timeout_ms=5000, backup_request_ms=100, max_retry=1
                ),
            )
            for _ in range(4):
                t0 = time.monotonic()
                cntl = ch.call("Echo", "echo", b"backup")
                dt = time.monotonic() - t0
                assert cntl.ok(), cntl.error_text
                assert dt < 0.9, f"took {dt:.3f}s — backup did not win"
        finally:
            slow.stop()
            fast.stop()
            slow.join(timeout=5)
            fast.join(timeout=5)


class TestRpcz:
    def test_spans_collected(self, echo_server):
        span_store.clear()
        old = get_flag("enable_rpcz")
        set_flag_unchecked("enable_rpcz", True)
        try:
            ch = connect(echo_server.port)
            cntl = ch.call("Echo", "echo", b"traced")
            assert cntl.ok()
            deadline = time.monotonic() + 2
            while len(span_store) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            spans = span_store.recent()
            kinds = {s.span_type for s in spans}
            assert kinds == {"client", "server"}
            traces = {s.trace_id for s in spans}
            assert len(traces) == 1  # one trace id across both sides
            client = next(s for s in spans if s.span_type == "client")
            assert client.latency_us > 0
            assert client.method == "echo"
        finally:
            set_flag_unchecked("enable_rpcz", old)
            span_store.clear()


class TestSyncDeadlineWithoutTimer:
    def test_silent_server_times_out(self):
        # Sync calls carry NO TimerThread entry (the caller's poll loop owns
        # the deadline): a server that accepts the request and never
        # responds must still produce ERPCTIMEDOUT on time.
        from incubator_brpc_tpu.rpc import Channel, Controller, Server

        srv = Server()

        def black_hole(cntl, req):
            cntl.set_async()  # handler keeps the response forever
            return None

        srv.add_service("t", {"hole": black_hole})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            t0 = time.monotonic()
            cntl = ch.call_method("t", "hole", b"x", cntl=Controller(timeout_ms=300))
            dt = time.monotonic() - t0
            assert cntl.error_code == ErrorCode.ERPCTIMEDOUT
            assert 0.2 < dt < 2.0, f"deadline not enforced: {dt:.2f}s"
        finally:
            srv.stop()
            srv.join(timeout=5)


class TestIdleReaper:
    def test_idle_connection_reaped_and_client_recovers(self):
        """ServerOptions.idle_timeout_s (the reference's idle-connection
        reaper): a connection with no wire activity is closed; the next
        call redials inline and succeeds."""
        import time

        from incubator_brpc_tpu.rpc import Server, ServerOptions

        srv = Server(ServerOptions(idle_timeout_s=0.4))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            assert ch.call_method("svc", "echo", b"one").ok()
            assert len(srv._acceptor.connections()) == 1
            deadline = time.monotonic() + 10  # generous: 1-core CI host
            while time.monotonic() < deadline:
                if not srv._acceptor.connections():
                    break
                time.sleep(0.05)
            assert not srv._acceptor.connections(), "idle conn not reaped"
            # the client's socket was closed by the server; the next call
            # reconnects (connect_if_not) and succeeds. Under load the
            # client may not have seen the FIN yet — the first write can
            # land on the dying socket; retries absorb that race.
            c = ch.call_method(
                "svc", "echo", b"two", cntl=Controller(max_retry=3)
            )
            assert c.ok(), c.error_text
            assert c.response_payload == b"two"
        finally:
            srv.stop()

    def test_active_connection_not_reaped(self):
        import time

        from incubator_brpc_tpu.rpc import Server, ServerOptions

        srv = Server(ServerOptions(idle_timeout_s=0.6))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            end = time.monotonic() + 1.8  # 3x the timeout, kept busy
            while time.monotonic() < end:
                assert ch.call_method("svc", "echo", b"k").ok()
                time.sleep(0.2)
            assert len(srv._acceptor.connections()) == 1
        finally:
            srv.stop()


class TestStartCancel:
    """Controller.start_cancel / brpc::StartCancel(CallId)
    (controller.cpp:699): cancel an in-flight RPC from any thread."""

    def _slow_server(self, delay=2.0):
        import time as _t

        from incubator_brpc_tpu.rpc import Server

        srv = Server()

        def slow(cntl, req):
            _t.sleep(delay)
            return req

        srv.add_service("svc", {"slow": slow, "fast": lambda c, r: r})
        assert srv.start(0)
        return srv

    def test_sync_call_canceled_from_another_thread(self):
        import threading
        import time as _t

        from incubator_brpc_tpu.rpc import Channel, Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        srv = self._slow_server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            cntl = Controller(timeout_ms=30000, max_retry=0)
            t = threading.Thread(
                target=lambda: (_t.sleep(0.15), cntl.start_cancel())
            )
            t.start()
            t0 = _t.monotonic()
            out = ch.call_method("svc", "slow", b"x", cntl=cntl)
            dt = _t.monotonic() - t0
            t.join()
            assert out.failed()
            assert out.error_code == ErrorCode.ECANCELED
            assert dt < 2.0, f"cancel did not interrupt the join ({dt:.2f}s)"
            # the channel still works; a late response to the dead id
            # drops. The slow handler is still running on that
            # connection's reader fiber for ~2s, so give the follow-up
            # call time to queue behind it.
            ok = ch.call_method(
                "svc", "fast", b"still-alive",
                cntl=Controller(timeout_ms=15000, max_retry=0),
            )
            assert ok.ok() and ok.response_payload == b"still-alive"
        finally:
            srv.stop()

    def test_async_done_runs_with_ecanceled(self):
        import threading

        from incubator_brpc_tpu.rpc import Channel, Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        srv = self._slow_server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            done = threading.Event()
            seen = []

            def on_done(c):
                seen.append(c.error_code)
                done.set()

            cntl = Controller(timeout_ms=30000, max_retry=0)
            ch.call_method("svc", "slow", b"x", cntl=cntl, done=on_done)
            cntl.start_cancel()
            assert done.wait(5)
            assert seen == [ErrorCode.ECANCELED]
        finally:
            srv.stop()

    def test_cancel_after_completion_is_noop(self):
        from incubator_brpc_tpu.rpc import Channel

        srv = self._slow_server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            cntl = ch.call_method("svc", "fast", b"y")
            assert cntl.ok()
            cntl.start_cancel()  # settled: dead id, silently dropped
            assert cntl.ok() and cntl.response_payload == b"y"
        finally:
            srv.stop()

    def test_server_side_cancel_refused(self):
        # a proxy's handler must not be able to cancel an unrelated
        # outgoing call via the peer's wire id
        import threading

        from incubator_brpc_tpu.rpc import Channel, Server

        saw = []
        srv = Server()

        def handler(cntl, req):
            cntl.start_cancel()  # must be a guarded no-op
            saw.append(cntl.call_id)
            return req

        srv.add_service("svc", {"m": handler})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            out = ch.call_method("svc", "m", b"p")
            assert out.ok() and out.response_payload == b"p"
            assert saw  # handler ran and the guard did not raise
        finally:
            srv.stop()


class TestRetryPolicy:
    """ChannelOptions.retry_policy (reference RetryPolicy::DoRetry,
    retry_policy.h:26): the caller decides which errors retry."""

    def test_custom_policy_retries_a_server_error(self):
        from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server
        from incubator_brpc_tpu.utils.status import ErrorCode

        calls = []
        srv = Server()

        def flaky(cntl, req):
            calls.append(1)
            if len(calls) < 3:
                cntl.set_failed(ErrorCode.EINTERNAL, "transient")
                return b""
            return req

        srv.add_service("svc", {"m": flaky})
        assert srv.start(0)
        try:
            # default policy: EINTERNAL is NOT retriable -> fails
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            out = ch.call_method("svc", "m", b"a")
            assert out.failed() and out.error_code == ErrorCode.EINTERNAL
            # custom policy: retry EINTERNAL within the budget -> succeeds
            calls.clear()
            ch2 = Channel()
            assert ch2.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(
                    max_retry=3,
                    retry_policy=lambda c: c.error_code
                    == ErrorCode.EINTERNAL,
                ),
            )
            out = ch2.call_method("svc", "m", b"b")
            assert out.ok(), out.error_text
            assert len(calls) == 3
        finally:
            srv.stop()

    def test_policy_can_refuse_default_retriables(self):
        from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller

        # no server listening: connect fails (normally retriable); a
        # never-retry policy must fail on the FIRST attempt
        ch = Channel()
        assert ch.init(
            "127.0.0.1:1",  # reserved port: refuses immediately
            options=ChannelOptions(
                max_retry=3, retry_policy=lambda c: False, timeout_ms=5000
            ),
        )
        out = ch.call_method("svc", "m", b"x", cntl=Controller(timeout_ms=5000))
        assert out.failed()
        assert out.retried_count == 0


class TestSessionAndThreadLocalData:
    """ServerOptions{session_local_data_factory, thread_local_data_factory}
    (reference server.h:55-239 + simple_data_pool): per-connection data is
    pooled and REUSED across connections; per-thread data is created once
    per worker and destroyed with the server."""

    class _Factory:
        def __init__(self):
            self.created = 0
            self.destroyed = []
            self.lock = threading.Lock()

        def create(self):
            with self.lock:
                self.created += 1
                return {"id": self.created, "uses": 0}

        def destroy(self, obj):
            with self.lock:
                self.destroyed.append(obj["id"])

    def _server(self, session_factory=None, thread_factory=None, reserved=0):
        from incubator_brpc_tpu.rpc.server import ServerOptions

        srv = Server(
            ServerOptions(
                session_local_data_factory=session_factory,
                reserved_session_local_data=reserved,
                thread_local_data_factory=thread_factory,
            )
        )

        def use(cntl, req):
            from incubator_brpc_tpu.rpc import thread_local_data

            sd = cntl.session_local_data()
            td = thread_local_data()
            parts = []
            if sd is not None:
                sd["uses"] += 1
                parts.append(b"s%d:%d" % (sd["id"], sd["uses"]))
            if td is not None:
                td["uses"] += 1
                parts.append(b"t%d" % td["id"])
            return b" ".join(parts) or b"none"

        srv.add_service("d", {"use": use})
        assert srv.start(0)
        return srv

    def test_session_data_sticks_to_connection_and_pools_across(self):
        f = self._Factory()
        srv = self._server(session_factory=f)
        try:
            # one long-lived connection: SAME object every request
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            for i in range(1, 4):
                c = ch.call_method("d", "use", b"")
                assert c.ok(), c.error_text
                assert c.response_payload == b"s1:%d" % i
            assert f.created == 1
            # a second, SHORT connection cycle: dies after the call, its
            # data returns to the pool
            ch2 = Channel()
            assert ch2.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(connection_type="short"),
            )
            c = ch2.call_method("d", "use", b"")
            assert c.ok()
            assert c.response_payload.startswith(b"s2:")  # fresh object
            deadline = time.monotonic() + 25  # 1-core CI: generous
            pool = srv._session_pool
            while pool.free_count == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.free_count >= 1, "short conn's data never pooled"
            # the NEXT short connection reuses it (no create #3)
            ch3 = Channel()
            assert ch3.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(connection_type="short"),
            )
            c = ch3.call_method("d", "use", b"")
            assert c.ok()
            assert c.response_payload.startswith(b"s2:"), c.response_payload
            assert f.created == 2, "pooled object was not reused"
        finally:
            srv.stop()
            srv.join(timeout=10)
        # stop destroys everything the factory made
        assert sorted(f.destroyed) == [1, 2]

    def test_thread_local_data_per_worker_and_destroyed_on_stop(self):
        f = self._Factory()
        srv = self._server(thread_factory=f)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            seen = set()
            for _ in range(8):
                c = ch.call_method("d", "use", b"")
                assert c.ok(), c.error_text
                seen.add(c.response_payload)
            # one object per worker THREAD, not per request: far fewer
            # distinct ids than requests, each created exactly once
            assert f.created == len({p.split(b":")[0] for p in seen})
        finally:
            srv.stop()
            srv.join(timeout=10)
        assert sorted(f.destroyed) == list(range(1, f.created + 1))

    def test_reserved_prebuilds_and_gateway_sees_the_same_data(self):
        f = self._Factory()
        srv = self._server(session_factory=f, thread_factory=None, reserved=2)
        try:
            assert f.created == 2  # reserved_session_local_data
            # the http→rpc gateway runs the same accessor path when the
            # connection is known; reserved objects serve without a create
            from incubator_brpc_tpu.transport.sock import CONNECTED

            class _StubSock:
                context = {}
                on_failed = []
                remote = None
                state = CONNECTED

            status, _, body = srv.invoke_for_http("d", "use", b"", sock=_StubSock())
            assert status == 200
            assert body.startswith(b"s")
            assert f.created == 2  # served from the reserve
            # and sockless gateway calls have no session — None, not a leak
            status, _, body = srv.invoke_for_http("d", "use", b"")
            assert body == b"none"
        finally:
            srv.stop()
            srv.join(timeout=10)

    def test_without_factories_accessors_return_none(self):
        srv = self._server()
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            c = ch.call_method("d", "use", b"")
            assert c.ok()
            assert c.response_payload == b"none"
        finally:
            srv.stop()
            srv.join(timeout=10)


class TestInflightFailFast:
    def test_inflight_call_fails_when_connection_dies(self):
        """An RPC already on the wire fails the moment its connection dies
        (the reference fails every id parked on a Socket at SetFailed) —
        not at the call deadline."""
        from incubator_brpc_tpu.rpc import Server

        srv = make_echo_server(delay_s=8.0)  # handler outlives the server
        ch = connect(srv.port, timeout_ms=30000, max_retry=0)
        done = threading.Event()
        out = {}

        def on_done(cntl):
            out["code"] = cntl.error_code
            out["elapsed"] = time.monotonic() - t0
            done.set()

        t0 = time.monotonic()
        ch.call("Echo", "echo", b"doomed", done=on_done)
        time.sleep(0.4)  # request is in flight, handler sleeping
        srv.stop()  # kills every connection under the client
        assert done.wait(10), "call did not fail after connection death"
        assert out["code"] == ErrorCode.EFAILEDSOCKET, out
        assert out["elapsed"] < 6.0, (
            f"failed at {out['elapsed']:.1f}s — deadline, not socket death"
        )
        srv.join(timeout=15)


class TestRuntimeConcurrencyReset:
    """Reference Server::ResetMaxConcurrency + MaxConcurrencyOf setter
    (server.h:483-490): limits are retunable while serving."""

    def test_server_level_reset_takes_effect_live(self):
        srv = make_echo_server(max_concurrency=0, delay_s=0.4)
        try:
            ch = connect(srv.port, timeout_ms=10000)
            assert ch.call("Echo", "echo", b"warm").ok()
            assert srv.reset_max_concurrency(1) == 0
            codes = []
            lock = threading.Lock()

            def call():
                c = ch.call("Echo", "echo", b"x")
                with lock:
                    codes.append(c.error_code)

            threads = [threading.Thread(target=call) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert ErrorCode.ELIMIT in codes  # new limit enforced live
            assert 0 in codes
            # and back to unlimited
            srv.reset_max_concurrency(0)
            threads = [threading.Thread(target=call) for _ in range(3)]
            codes.clear()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert codes.count(0) == 3, codes
        finally:
            srv.stop()
            srv.join(timeout=10)

    def test_method_level_setter(self):
        srv = make_echo_server()
        try:
            assert srv.method_max_concurrency("Echo.echo") == 0
            assert srv.set_method_max_concurrency("Echo.echo", 7)
            assert srv.method_max_concurrency("Echo.echo") == 7
            assert not srv.set_method_max_concurrency("Echo.nope", 3)
            assert srv.method_max_concurrency("Echo.nope") is None
        finally:
            srv.stop()
            srv.join(timeout=10)


def test_native_plane_method_limit_retunes_live():
    """set_method_max_concurrency reaches natively-dispatched methods
    (their limit is read per request in C++)."""
    from incubator_brpc_tpu.rpc import (
        Channel,
        ChannelOptions,
        Server,
        ServerOptions,
        native_echo,
    )
    from incubator_brpc_tpu.transport import native_plane as np_mod

    if not np_mod.NET_AVAILABLE:
        import pytest

        pytest.skip("native plane unavailable")
    srv = Server(ServerOptions(native_plane=True, usercode_inline=True))
    srv.add_service("n", {"echo": native_echo})
    assert srv.start(0)
    try:
        if srv._native_plane is None:
            import pytest

            pytest.skip("native plane not active")
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}", options=ChannelOptions(native_plane=True)
        )
        assert ch.call_method("n", "echo", b"x").ok()
        # the limit lives in C and is read per request: the setter must
        # reach it, and traffic keeps flowing under the retuned value
        assert srv._native_plane.native_max_concurrency("n.echo") == 0
        assert srv.set_method_max_concurrency("n.echo", 5)
        assert srv._native_plane.native_max_concurrency("n.echo") == 5
        assert srv.method_max_concurrency("n.echo") == 5
        nch = np_mod.NativeClientChannel("127.0.0.1", srv.port)
        try:
            nch.pump("n", "echo", b"y", 2000, inflight=2)
        finally:
            nch.close()
        assert srv.set_method_max_concurrency("n.echo", 0)
        assert srv._native_plane.native_max_concurrency("n.echo") == 0
    finally:
        srv.stop()
        srv.join(timeout=10)


def test_concurrent_callers_on_a_stale_mapped_socket():
    """Ephemeral-port reuse resurrects a FAILED socket from the global
    client map; concurrent callers must converge on ONE inline reconnect
    (racers wait for the dialer's verdict) instead of burning their whole
    retry budget inside the dial window."""
    srv_a = make_echo_server()
    port = srv_a.port
    warm = connect(port)
    assert warm.call("Echo", "echo", b"warm").ok()
    srv_a.stop()
    srv_a.join(timeout=5)
    # a NEW server on the SAME port: the map still holds the dead socket
    srv_b = Server()

    def slow_echo(cntl, req):
        time.sleep(0.2)
        return req

    srv_b.add_service("Echo", {"echo": slow_echo})
    if not srv_b.start(port):
        import pytest

        pytest.skip("port could not be rebound")
    try:
        ch = connect(port, timeout_ms=5000)
        codes = []
        lock = threading.Lock()

        def call():
            c = ch.call("Echo", "echo", b"x", cntl=Controller(timeout_ms=5000))
            with lock:
                codes.append(c.error_code)

        threads = [threading.Thread(target=call) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert codes.count(0) == 3, codes
    finally:
        srv_b.stop()
        srv_b.join(timeout=10)
