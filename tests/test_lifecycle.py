"""Regression tests for the lifecycle violations fabricverify convicted
at introduction (fixed, not allowlisted — the PR 6 policy), plus the
SimpleDataPool destroy_all-vs-concurrent-borrow/give_back races the
data_pool.py "give_back won the pop" comment describes but no test
exercised.

The lint half of these guarantees lives in tests/test_static_analysis.py
(the tree stays clean); these tests pin the *behavior* so a future
refactor can't reintroduce the leak while keeping the lint happy by
accident.
"""

from __future__ import annotations

import threading
import time

from incubator_brpc_tpu.lb import LoadBalancerWithNaming
from incubator_brpc_tpu.naming import NamingServiceThread
from incubator_brpc_tpu.rpc.data_pool import SimpleDataPool
from incubator_brpc_tpu.runtime.timer_thread import global_timer_thread
from incubator_brpc_tpu.utils.endpoint import EndPoint


class _CountingFactory:
    """create/destroy bookkeeping with double-destroy detection."""

    def __init__(self, create_gate: threading.Event = None):
        self.lock = threading.Lock()
        self.created = 0
        self.destroyed = 0
        self.double_destroys = 0
        self.live = set()
        self._gate = create_gate

    def create(self):
        if self._gate is not None:
            self._gate.wait(5.0)
        with self.lock:
            self.created += 1
            obj = object()
            self.live.add(id(obj))
            return obj

    def destroy(self, obj):
        with self.lock:
            if id(obj) in self.live:
                self.live.discard(id(obj))
                self.destroyed += 1
            else:
                self.double_destroys += 1


class TestLBRevivalTimerLifecycle:
    """lb/__init__.py:_isolate armed a revival timer per isolation and
    stop() never unscheduled it: a stopped LB stayed pinned by (and was
    fired into by) its timers for up to the isolation window."""

    def _isolated_lb(self):
        lb = LoadBalancerWithNaming(url="list://", circuit_breaker=True)
        ep = EndPoint("10.9.9.9", 1234)
        lb.lb.add_server(ep)
        lb._isolate(ep)
        return lb, ep

    def test_isolate_tracks_its_timer(self):
        lb, ep = self._isolated_lb()
        try:
            assert ep in lb._revive_timers
            assert ep in lb._isolated
        finally:
            lb.stop()

    def test_stop_unschedules_revival_timers(self):
        lb, ep = self._isolated_lb()
        tid = lb._revive_timers[ep]
        lb.stop()
        assert lb._revive_timers == {}
        assert lb._isolated == {}
        # the timer entry is gone from the shared TimerThread: a second
        # unschedule finds nothing to prevent
        assert global_timer_thread().unschedule(tid) is False

    def test_straggler_timer_cannot_revive_dead_lb(self):
        lb, ep = self._isolated_lb()
        lb.stop()
        # a timer that was already in flight at stop: the stopped guard
        # makes it a no-op instead of resurrecting breaker state
        lb._maybe_revive(ep)
        assert lb._isolated == {} and lb._revive_timers == {}

    def test_revival_consumes_the_timer_entry(self):
        lb, ep = self._isolated_lb()
        try:
            with lb._cb_lock:
                lb._isolated[ep] = time.monotonic() - 1.0  # force due
            lb._maybe_revive(ep)
            assert ep not in lb._revive_timers
            assert ep not in lb._isolated
        finally:
            lb.stop()

    def test_isolate_racing_stop_is_a_noop(self):
        # a trip verdict landing after stop() must not re-arm a timer,
        # re-populate _isolated, or re-register a breaker row under the
        # dead owner tag (the registry row would outlive the process)
        from incubator_brpc_tpu.rpc.circuit_breaker import breaker_registry

        lb, ep = self._isolated_lb()
        lb.stop()
        lb._isolate(ep)
        lb._feed_breaker(ep, 100.0, 1)
        assert lb._revive_timers == {} and lb._isolated == {}
        assert not any(
            tag == lb._cb_tag for (tag, _ep), _cb in breaker_registry.snapshot()
        )

    def test_reisolation_unschedules_the_superseded_timer(self):
        lb, ep = self._isolated_lb()
        try:
            first = lb._revive_timers[ep]
            lb._isolate(ep)  # extended deadline arms a fresh timer
            second = lb._revive_timers[ep]
            assert second != first
            # the superseded timer is gone from the shared thread, not
            # just doomed to no-op at fire
            assert global_timer_thread().unschedule(first) is False
        finally:
            lb.stop()

    def test_naming_churn_drops_the_timer_with_the_breaker(self):
        lb, ep = self._isolated_lb()
        try:
            tid = lb._revive_timers[ep]
            lb._drop_breaker(ep)
            assert ep not in lb._revive_timers
            assert global_timer_thread().unschedule(tid) is False
        finally:
            lb.stop()


class TestNamingObserverLifecycle:
    """NamingServiceThread had no remove_observer at all: every LB (and
    partition channel) on a shared watcher stayed an observer forever."""

    def test_remove_observer(self):
        ns = NamingServiceThread("list://127.0.0.1:7001")
        obs_events = []

        class Obs:
            def add_server(self, ep):
                obs_events.append(("add", ep))

            def remove_server(self, ep):
                obs_events.append(("remove", ep))

        o = Obs()
        ns.add_observer(o)
        assert o in ns._observers
        ns.remove_observer(o)
        assert o not in ns._observers
        ns.remove_observer(o)  # idempotent

    def test_lb_stop_detaches_from_shared_watcher(self):
        ns = NamingServiceThread("list://127.0.0.1:7002")
        assert ns.start()
        try:
            lb = LoadBalancerWithNaming(ns_thread=ns, circuit_breaker=False)
            assert lb.start()
            assert lb in ns._observers
            lb.stop()
            assert lb not in ns._observers
        finally:
            ns.stop()


class TestServerIdleReapTimerLifecycle:
    """rpc/server.py discarded the idle-reap timer id: a stopped server
    stayed pinned by the parked scan for up to idle_timeout_s/2."""

    def test_stop_cancels_the_parked_reap(self):
        from incubator_brpc_tpu.rpc.server import Server, ServerOptions

        srv = Server(ServerOptions(idle_timeout_s=30.0))
        assert srv.start(0)
        tid = srv._idle_reap_timer_id
        assert tid is not None
        srv.stop()
        srv.join(timeout=5)
        assert srv._idle_reap_timer_id is None
        assert global_timer_thread().unschedule(tid) is False

    def test_reap_mid_flight_at_stop_does_not_rearm(self):
        from incubator_brpc_tpu.rpc.server import Server, ServerOptions

        srv = Server(ServerOptions(idle_timeout_s=30.0))
        assert srv.start(0)
        srv.stop()
        # a scan that was already spawned when stop() landed re-arms via
        # _schedule_idle_reap; the _stopping guard must refuse it
        srv._schedule_idle_reap()
        assert srv._idle_reap_timer_id is None
        srv.join(timeout=5)


class TestDataPoolDestroyRaces:
    """rpc/data_pool.py:85 — 'give_back won the pop below before
    destroy_all snapshotted' describes an interleaving nothing exercised:
    a borrow() that raced destroy_all() re-registers its object in the
    FRESH outstanding dict, destroy_all never sees it, and the late
    give_back must destroy it (exactly once)."""

    def test_borrow_racing_destroy_all_destroys_exactly_once(self):
        gate = threading.Event()
        fac = _CountingFactory(create_gate=gate)
        pool = SimpleDataPool(fac)
        got = []

        def borrower():
            got.append(pool.borrow())  # blocks in create() on the gate

        t = threading.Thread(target=borrower)
        t.start()
        # wait until the borrower is inside _create (ncreated bumped
        # under the pool lock before the factory call)
        deadline = time.monotonic() + 5
        while pool.ncreated == 0 and time.monotonic() < deadline:
            time.sleep(0.001)
        pool.destroy_all()          # snapshots BEFORE the borrow lands
        gate.set()
        t.join(5)
        assert len(got) == 1
        assert fac.destroyed == 0   # destroy_all never saw the object
        pool.give_back(got[0])      # the owned=True path destroys it
        assert fac.destroyed == 1 and fac.double_destroys == 0
        pool.give_back(got[0])      # second give_back: no double destroy
        assert fac.destroyed == 1 and fac.double_destroys == 0

    def test_give_back_losing_the_race_is_a_noop(self):
        fac = _CountingFactory()
        pool = SimpleDataPool(fac)
        obj = pool.borrow()
        pool.destroy_all()          # sees the outstanding borrow, destroys it
        assert fac.destroyed == 1
        pool.give_back(obj)         # lost the race: must NOT double-destroy
        assert fac.destroyed == 1 and fac.double_destroys == 0

    def test_dead_pool_borrow_then_give_back_balances(self):
        fac = _CountingFactory()
        pool = SimpleDataPool(fac)
        pool.destroy_all()
        obj = pool.borrow()         # pools keep serving after death…
        pool.give_back(obj)         # …but nothing may leak or re-pool
        assert pool.free_count == 0
        assert fac.destroyed == fac.created == 1
        assert fac.double_destroys == 0

    def test_concurrent_borrow_give_back_vs_destroy_all_storm(self):
        fac = _CountingFactory()
        pool = SimpleDataPool(fac, reserved=4)
        stop = threading.Event()
        errors = []

        def churn():
            try:
                while not stop.is_set():
                    obj = pool.borrow()
                    time.sleep(0)   # force interleaving
                    pool.give_back(obj)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=churn) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        pool.destroy_all()          # mid-storm teardown
        time.sleep(0.02)
        stop.set()
        for t in threads:
            t.join(5)
        assert not errors
        # drain: anything a churn thread still held follows the dead-pool
        # give_back path; after that every created object died exactly once
        with fac.lock:
            leaked = set(fac.live)
        # objects still outstanding at this instant were destroyed by
        # their own give_back already (threads joined) — so none remain
        assert not leaked, f"{len(leaked)} pooled objects never destroyed"
        assert fac.double_destroys == 0
        assert fac.destroyed == fac.created
