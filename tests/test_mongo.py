"""mongo server-side protocol (protocol/mongo.py — reference
policy/mongo_protocol.cpp + mongo_service_adaptor.h).

Wire fixtures are hand-built from the public mongo wire spec (the head
layout in the reference's mongo_head.h) so the codec pins to the wire.
"""

from __future__ import annotations

import socket as pysock
import struct

import pytest

from incubator_brpc_tpu.protocol import mongo
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.rpc import Server, ServerOptions


class TestBson:
    def test_roundtrip_all_kinds(self):
        doc = {
            "d": 1.5,
            "s": "text",
            "sub": {"k": 1},
            "arr": [1, "two", None],
            "bin": b"\x00\xff",
            "oid": mongo.ObjectId(b"0123456789ab"),
            "t": True,
            "n": None,
            "i32": 42,
            "i64": 1 << 40,
        }
        data = mongo.bson_encode(doc)
        back, used = mongo.bson_decode(data)
        assert used == len(data)
        assert back == doc

    def test_known_fixture_bytes(self):
        # {"hello": "world"} per the BSON spec's own worked example:
        # \x16\x00\x00\x00 \x02 hello\x00 \x06\x00\x00\x00 world\x00 \x00
        fixture = (
            b"\x16\x00\x00\x00\x02hello\x00\x06\x00\x00\x00world\x00\x00"
        )
        assert mongo.bson_encode({"hello": "world"}) == fixture
        doc, used = mongo.bson_decode(fixture)
        assert doc == {"hello": "world"} and used == len(fixture)

    def test_truncated_rejected(self):
        data = mongo.bson_encode({"a": 1, "b": "xx"})
        for cut in (3, 6, len(data) - 1):
            with pytest.raises(ParseError):
                mongo.bson_decode(data[:cut] + b"\x00" * 0)

    def test_unknown_element_type_rejected(self):
        bad = b"\x0b\x00\x00\x00\x7fx\x00\x00\x00\x00\x00"
        with pytest.raises(ParseError):
            mongo.bson_decode(bad)

    def test_depth_bomb_rejected(self):
        doc = {"k": 1}
        for _ in range(200):
            doc = {"d": doc}
        data = mongo.bson_encode(doc)
        with pytest.raises(ParseError):
            mongo.bson_decode(data)

    def test_malformed_raises_parse_error_not_struct_error(self):
        # a double element whose 8 value bytes overrun the declared length
        bad = struct.pack("<i", 8) + b"\x01d\x00" + b"\x00"
        with pytest.raises(ParseError):
            mongo.bson_decode(bad)
        # an "array" whose keys are not numeric indices
        arr_body = mongo.bson_encode({"notanum": 1})
        elem = b"\x04a\x00" + arr_body
        framed = struct.pack("<i", 4 + len(elem) + 1) + elem + b"\x00"
        with pytest.raises(ParseError):
            mongo.bson_decode(framed)


def build_query(collection: str, query: dict, request_id: int = 7,
                skip: int = 0, limit: int = 0) -> bytes:
    body = (
        struct.pack("<i", 0)
        + collection.encode() + b"\x00"
        + struct.pack("<ii", skip, limit)
        + mongo.bson_encode(query)
    )
    return mongo.HEAD.pack(16 + len(body), request_id, 0, mongo.OP_QUERY) + body


def parse_reply(data: bytes):
    length, rid, rto, op = mongo.HEAD.unpack_from(data)
    assert op == mongo.OP_REPLY and length == len(data)
    flags, cursor, start, count = struct.unpack_from("<iqii", data, 16)
    docs, off = [], 36
    for _ in range(count):
        doc, used = mongo.bson_decode(data, off)
        docs.append(doc)
        off += used
    return rto, flags, docs


class _Adaptor(mongo.MongoServiceAdaptor):
    def __init__(self):
        self.inserts = []

    def create_socket_context(self):
        return {"queries": 0}

    def handle_query(self, ctx, q: mongo.QueryMessage):
        ctx["queries"] += 1
        if q.collection == "db.fail":
            raise ParseError("synthetic failure")
        return [
            {"collection": q.collection, "n": ctx["queries"], **q.query},
        ]

    def handle_insert(self, ctx, body: bytes):
        self.inserts.append(body)


@pytest.fixture
def mongo_server():
    adaptor = _Adaptor()
    srv = Server(
        ServerOptions(usercode_inline=True, mongo_service_adaptor=adaptor)
    )
    assert srv.start(0)
    yield srv, adaptor
    srv.stop()


def _recv_reply(conn) -> bytes:
    data = b""
    while len(data) < 4 or len(data) < struct.unpack_from("<i", data)[0]:
        chunk = conn.recv(65536)
        if not chunk:
            break
        data += chunk
    return data


class TestQueryPath:
    def test_query_reply_and_per_conn_context(self, mongo_server):
        srv, _ = mongo_server
        conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
        conn.sendall(build_query("db.items", {"x": 1}, request_id=11))
        rto, flags, docs = parse_reply(_recv_reply(conn))
        assert rto == 11 and flags == 0
        assert docs == [{"collection": "db.items", "n": 1, "x": 1}]
        # same connection: the context counter advances (stateful protocol)
        conn.sendall(build_query("db.items", {}, request_id=12))
        _, _, docs2 = parse_reply(_recv_reply(conn))
        assert docs2[0]["n"] == 2
        conn.close()
        # a NEW connection gets a fresh context
        conn2 = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
        conn2.sendall(build_query("db.items", {}, request_id=13))
        _, _, docs3 = parse_reply(_recv_reply(conn2))
        assert docs3[0]["n"] == 1
        conn2.close()

    def test_adaptor_error_serializes_err_reply(self, mongo_server):
        srv, _ = mongo_server
        conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
        conn.sendall(build_query("db.fail", {}, request_id=21))
        rto, flags, docs = parse_reply(_recv_reply(conn))
        assert rto == 21
        assert flags & 2  # QueryFailure
        assert "$err" in docs[0]
        conn.close()

    def test_insert_is_fire_and_forget(self, mongo_server):
        srv, adaptor = mongo_server
        body = struct.pack("<i", 0) + b"db.c\x00" + mongo.bson_encode({"v": 1})
        frame = mongo.HEAD.pack(16 + len(body), 31, 0, mongo.OP_INSERT) + body
        conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
        conn.sendall(frame)
        # follow with a query to prove the connection survived the no-reply op
        conn.sendall(build_query("db.c", {}, request_id=32))
        rto, _, _ = parse_reply(_recv_reply(conn))
        assert rto == 32
        assert len(adaptor.inserts) == 1
        conn.close()

    def test_get_more_reports_cursor_not_found(self, mongo_server):
        srv, _ = mongo_server
        body = struct.pack("<i", 0) + b"db.c\x00" + struct.pack("<iq", 0, 99)
        frame = mongo.HEAD.pack(16 + len(body), 41, 0, mongo.OP_GET_MORE) + body
        conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
        conn.sendall(frame)
        rto, flags, docs = parse_reply(_recv_reply(conn))
        assert rto == 41 and flags & 1 and docs == []
        conn.close()


class TestGating:
    def test_disabled_without_adaptor(self):
        """A server with no adaptor must not speak mongo (the reference
        returns TRY_OTHERS, and the scan then rejects the bytes)."""
        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=5)
            conn.sendall(build_query("db.x", {}))
            conn.settimeout(3)
            assert conn.recv(1024) == b""  # connection failed, no reply
            conn.close()
        finally:
            srv.stop()

    def test_multiplexed_with_tbus_on_one_port(self, mongo_server):
        from incubator_brpc_tpu.rpc import Channel

        srv, _ = mongo_server
        srv._methods  # server also serves tbus_std on the same port
        ch = Channel()
        assert ch.init(f"127.0.0.1:{srv.port}")
        c = ch.call_method("nosuch", "m", b"")
        # tbus_std reached the server's request path (error ≠ transport kill)
        assert c.failed() and c.error_code in (1001, 1002)
