"""Device transport tests — the transport=tpu slot (reference
brpc_rdma_unittest.cpp shape: endpoint rings, credit window, completion
delivery; runs on the virtual CPU mesh devices here)."""

import threading

import numpy as np
import pytest

from incubator_brpc_tpu.transport.device import DeviceEndpoint, _bucket_words
from incubator_brpc_tpu.utils.status import ErrorCode


class TestBuckets:
    def test_power_of_two_buckets(self):
        assert _bucket_words(1) == 64
        assert _bucket_words(64) == 64
        assert _bucket_words(65) == 128
        assert _bucket_words(1000) == 1024
        with pytest.raises(ValueError):
            _bucket_words(1 << 26)


@pytest.fixture(scope="module")
def endpoint():
    return DeviceEndpoint(window_size=4)


class TestDeviceCalls:
    def test_word_echo_roundtrip(self, endpoint):
        words = np.arange(100, dtype=np.uint32)
        pending = endpoint.call_words(words, correlation_id=7)
        assert pending.wait(timeout=30)
        assert pending.error_code == 0
        np.testing.assert_array_equal(pending.response_words, words)

    def test_byte_echo_roundtrip(self, endpoint):
        payload = b"device-transport-payload!"  # not word-aligned
        code, out = endpoint.call_bytes(payload, timeout=30)
        assert code == 0
        assert out == payload

    def test_unknown_method_is_enomethod(self, endpoint):
        code, _ = endpoint.call_bytes(b"xxxx", method_id=999, timeout=30)
        assert code == 1002  # ENOMETHOD from the device dispatch table

    def test_pipelined_calls_within_window(self, endpoint):
        pendings = [
            endpoint.call_words(
                np.full(32, i, dtype=np.uint32), correlation_id=i + 1
            )
            for i in range(4)
        ]
        for i, p in enumerate(pendings):
            assert p.wait(timeout=30)
            assert p.error_code == 0
            assert p.response_words[0] == i

    def test_credit_window_bounds_inflight(self):
        ep = DeviceEndpoint(window_size=2)
        n = 8
        results = []
        lock = threading.Lock()

        def caller(i):
            code, out = ep.call_bytes(b"abcd" * 8, timeout=30)
            with lock:
                results.append(code)

        ts = [threading.Thread(target=caller, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert results == [0] * n  # window stalls, nothing fails
        assert ep.inflight == 0  # every credit returned

    def test_server_handler_integration(self, endpoint):
        """Full host-RPC → device step → response path: the reference's
        'flip transport=tpu and rerun the same example pair' (SURVEY §7
        step 5)."""
        from incubator_brpc_tpu.rpc import Channel, Server

        server = Server()
        server.add_service("tensor", {"echo": endpoint.server_handler()})
        assert server.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{server.port}")
            cntl = ch.call_method(
                "tensor", "echo", b"rpc-over-hbm", cntl=None
            )
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"rpc-over-hbm"
        finally:
            server.stop()
            server.join(timeout=5)


class TestBatchedDispatch:
    """Micro-batched DeviceEndpoint: concurrent calls stack into one
    vmapped dispatch; per-row method ids and correlation ids must route
    independently inside the batch."""

    def test_mixed_methods_in_one_batch(self):
        import threading

        import jax.numpy as jnp
        import numpy as np

        from incubator_brpc_tpu.models.tensor_echo import TensorEchoService
        from incubator_brpc_tpu.transport.device import DeviceEndpoint

        svc = TensorEchoService()
        svc.add_method(3, lambda p: p * jnp.uint32(2))
        svc.add_method(5, lambda p: p + jnp.uint32(10))
        ep = DeviceEndpoint(service=svc, window_size=32, max_batch=16)
        ep.warm(64)
        results = {}

        def worker(i):
            mid = (0, 3, 5)[i % 3]
            words = np.full(16, i + 1, dtype=np.uint32)
            pending = ep.call_words(
                words, method_id=mid, correlation_id=i + 1, timeout=60
            )
            assert pending.wait(60)
            results[i] = (mid, pending.error_code, pending.response_words)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(18)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 18
        for i, (mid, code, words) in results.items():
            assert code == 0, (i, code)
            base = i + 1
            want = {0: base, 3: base * 2, 5: base + 10}[mid]
            assert (words == want).all(), (i, mid, words[:4])

    def test_unknown_method_in_batch_errors_only_its_row(self):
        import threading

        from incubator_brpc_tpu.transport.device import DeviceEndpoint

        ep = DeviceEndpoint(window_size=16, max_batch=8)
        ep.warm(32)
        results = {}

        def worker(i):
            mid = 999 if i == 3 else 0
            code, out = ep.call_bytes(
                b"row%02d" % i, method_id=mid, correlation_id=i + 1, timeout=60
            )
            results[i] = (code, out)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (code, out) in results.items():
            if i == 3:
                assert code == 1002, (i, code)  # ENOMETHOD, only this row
            else:
                assert code == 0 and out == b"row%02d" % i, (i, code)
