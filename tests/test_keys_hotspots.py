"""Fiber-local storage + hotspots profiler tests (reference
test/bthread_key_unittest.cpp, hotspots_service coverage in
brpc_builtin_service_unittest.cpp)."""

import threading
import time

from incubator_brpc_tpu.builtin import hotspots
from incubator_brpc_tpu.runtime import spawn
from incubator_brpc_tpu.runtime.keys import (
    fiber_getspecific,
    fiber_key_create,
    fiber_key_delete,
    fiber_setspecific,
)


class TestFiberKeys:
    def test_set_get_on_plain_thread(self):
        k = fiber_key_create()
        assert fiber_getspecific(k) is None
        assert fiber_setspecific(k, "value")
        assert fiber_getspecific(k) == "value"

    def test_isolation_between_fibers(self):
        k = fiber_key_create()
        out = {}

        def fib(name):
            assert fiber_getspecific(k) is None  # fresh per fiber
            fiber_setspecific(k, name)
            time.sleep(0.01)
            out[name] = fiber_getspecific(k)

        fibers = [spawn(fib, f"f{i}") for i in range(4)]
        for f in fibers:
            assert f.join(timeout=5)
        assert out == {f"f{i}": f"f{i}" for i in range(4)}

    def test_isolation_between_threads(self):
        k = fiber_key_create()
        out = {}

        def th(name):
            fiber_setspecific(k, name)
            time.sleep(0.01)
            out[name] = fiber_getspecific(k)

        ts = [threading.Thread(target=th, args=(f"t{i}",)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert out == {f"t{i}": f"t{i}" for i in range(3)}

    def test_destructor_runs_on_fiber_exit(self):
        destroyed = []
        k = fiber_key_create(destructor=destroyed.append)

        def fib():
            fiber_setspecific(k, "resource")

        assert spawn(fib).join(timeout=5)
        assert destroyed == ["resource"]

    def test_deleted_key_reads_none_and_skips_destructor(self):
        destroyed = []
        k = fiber_key_create(destructor=destroyed.append)

        def fib():
            fiber_setspecific(k, "gone")
            assert fiber_key_delete(k)
            assert fiber_getspecific(k) is None

        assert spawn(fib).join(timeout=5)
        assert destroyed == []

    def test_key_version_prevents_stale_reads(self):
        k1 = fiber_key_create()
        fiber_setspecific(k1, "old")
        assert fiber_key_delete(k1)
        k2 = fiber_key_create()  # may reuse the index
        if k2[0] == k1[0]:
            assert fiber_getspecific(k2) is None  # versioned: no bleed
        assert fiber_getspecific(k1) is None


class TestHotspots:
    def test_cpu_sampler_catches_a_busy_thread(self):
        stop = threading.Event()

        def burn():
            while not stop.is_set():
                sum(i * i for i in range(1000))

        t = threading.Thread(target=burn, name="burner")
        t.start()
        try:
            result = hotspots.sample_cpu(seconds=0.3, hz=200)
        finally:
            stop.set()
            t.join()
        assert result["samples"] > 10
        text = hotspots.render_cpu_text(result)
        assert "burn" in text

    def test_single_run_at_a_time(self):
        import pytest

        t = threading.Thread(
            target=lambda: hotspots.sample_cpu(seconds=0.3)
        )
        t.start()
        time.sleep(0.05)
        with pytest.raises(RuntimeError):
            hotspots.sample_cpu(seconds=0.1)
        t.join()

    def test_portal_pages(self):
        from incubator_brpc_tpu.protocol.http import http_call
        from incubator_brpc_tpu.rpc import Server

        s = Server()
        s.add_service("h", {"m": lambda c, r: r})
        assert s.start(0)
        try:
            status, _, body = http_call(
                "127.0.0.1", s.port, "/hotspots?seconds=0.2", timeout=10
            )
            assert status == 200
            assert b"samples:" in body
            status, _, body = http_call("127.0.0.1", s.port, "/hotspots/contention")
            assert status == 200
            assert b"contended acquires" in body
        finally:
            s.stop()


class TestRpczPersistence:
    """rpcz_database_dir (reference span.cpp:41 LevelDB persistence):
    finished spans append durably as JSON lines."""

    def test_spans_persist_and_survive_ring_eviction(self, tmp_path):
        import json

        from incubator_brpc_tpu.builtin.rpcz import span_store
        from incubator_brpc_tpu.rpc import Channel, Server
        from incubator_brpc_tpu.utils.flags import set_flag

        assert set_flag("enable_rpcz", True)
        assert set_flag("rpcz_database_dir", str(tmp_path))
        try:
            srv = Server()
            srv.add_service("persist", {"echo": lambda cntl, req: req})
            assert srv.start(0)
            try:
                ch = Channel()
                assert ch.init(f"127.0.0.1:{srv.port}")
                for _ in range(3):
                    assert ch.call_method("persist", "echo", b"traced").ok()
            finally:
                srv.stop()
            db = tmp_path / "rpcz.jsonl"
            assert db.exists()
            rows = [json.loads(ln) for ln in db.read_text().splitlines()]
            mine = [r for r in rows if r["service"] == "persist"]
            assert len(mine) >= 3  # client + server spans for 3 calls
            assert any(r["type"] == "server" for r in mine)
            assert all(r["latency_us"] >= 0 for r in mine)
        finally:
            set_flag("enable_rpcz", False)
            set_flag("rpcz_database_dir", "")
            span_store.close_db()
