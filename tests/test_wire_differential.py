"""Differential wire-decoder fuzz: the C++ RpcMeta scanner vs the
Python decoder on identical bytes (ISSUE 12 satellite).

PR 11's snappy fuzz proved the codec twins agree byte-for-byte on
random input; this module extends that oracle one layer up, to the RPC
meta parsers — the exact code fabricscan's wire-bounds pass guards.
``tb_scan_prpc_meta`` exports the scanner the server cut path and the
client pump run, and every test here feeds the same blob to it and to
``protocol/baidu_std.py``'s ``RpcMeta.decode`` and diffs the verdicts:

- **native accept ⇒ Python accept**, and every decoded field agrees
  (cid, attachment, compress, timeout, error_code, service, method,
  response-ness) modulo the documented width clamps;
- **Python reject ⇒ native reject** (a meta the pure-Python plane
  refuses must never ride the native fast path);
- native-only rejects are allowed ONLY for the documented
  native-stricter caps (compress beyond u32, attachment/timeout beyond
  2^31) — anything else is drift between the twins.

Runs inside tier-1 and inside ``make san``'s ASAN subset (random bytes
through a hand-rolled C++ parser is exactly what ASAN exists to watch).

The bottom class is the regression test for the wire-bounds violation
fabricscan found at introduction: ``tb_channel_pump``'s tbus read path
trusted a hostile server's claimed ``body_len`` with no frame cap, so a
~4 GiB claim grew the client's read buffer without bound while it
"waited for the full frame".  The cap now answers -EPROTO immediately;
docs/ANALYSIS.md documents the find.
"""

from __future__ import annotations

import ctypes
import random
import socket
import struct
import threading

import pytest

from incubator_brpc_tpu.protocol import baidu_std
from incubator_brpc_tpu.protocol.baidu_std import RpcMeta
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.transport import native_plane

pytestmark = pytest.mark.skipif(
    not native_plane.NET_AVAILABLE, reason="native runtime unavailable"
)

_M64 = (1 << 64) - 1
_CAP = 4096  # name caps handed to the native scanner


def _native_scan(meta: bytes):
    """Run the C++ scanner; None on reject, else a comparable dict."""
    from incubator_brpc_tpu.native import LIB

    cid = ctypes.c_uint64()
    att = ctypes.c_long()
    tmo = ctypes.c_long()
    comp = ctypes.c_uint32()
    ec = ctypes.c_uint32()
    svc = ctypes.create_string_buffer(_CAP)
    mth = ctypes.create_string_buffer(_CAP)
    sl = ctypes.c_size_t()
    ml = ctypes.c_size_t()
    log_id = ctypes.c_uint64()
    trace_id = ctypes.c_uint64()
    span_id = ctypes.c_uint64()
    parent_span_id = ctypes.c_uint64()
    sampled = ctypes.c_uint32()
    rc = LIB.tb_scan_prpc_meta(
        meta, len(meta), ctypes.byref(cid), ctypes.byref(att),
        ctypes.byref(tmo), ctypes.byref(comp), ctypes.byref(ec),
        svc, _CAP, ctypes.byref(sl), mth, _CAP, ctypes.byref(ml),
        ctypes.byref(log_id), ctypes.byref(trace_id), ctypes.byref(span_id),
        ctypes.byref(parent_span_id), ctypes.byref(sampled),
    )
    if rc == -1:
        return None
    assert rc >= 0, f"name cap too small for fuzz meta ({rc})"
    return {
        "cid": cid.value,
        "attachment": att.value,
        "timeout_ms": tmo.value,
        "compress": comp.value,
        "error_code": ec.value,
        "svc": svc.raw[: sl.value],
        "mth": mth.raw[: ml.value],
        "log_id": log_id.value,
        "trace_id": trace_id.value,
        "span_id": span_id.value,
        "parent_span_id": parent_span_id.value,
        "sampled": sampled.value,
        "to_python": bool(rc & 1),
        "is_response": bool(rc & 2),
    }


def _python_scan(meta: bytes):
    try:
        return RpcMeta.decode(meta)
    except ParseError:
        return None


def _native_stricter_cap(rm: RpcMeta) -> bool:
    """The documented clamps where the C++ scanner rejects metas the
    permissive Python decoder still represents: values beyond the widths
    the native plane can carry (u32 compress, 2^31 attachment/timeout).
    Compared mod 2^64 because the C++ varint reader wraps there."""
    return (
        (rm.compress_type & _M64) > 0xFFFFFFFF
        or (rm.attachment_size & _M64) > (1 << 31)
        or (rm.timeout_ms & _M64) > (1 << 31)
    )


def _assert_agree(meta: bytes):
    nat = _native_scan(meta)
    py = _python_scan(meta)
    label = meta.hex()
    if nat is None:
        # native reject: Python rejected too, or the meta trips a
        # documented native-stricter width clamp — nothing else
        assert py is None or _native_stricter_cap(py), (
            f"native rejected a meta Python accepts with in-range "
            f"fields: {label}"
        )
        return
    # native accept ⇒ Python accept, fields agree (mod the wraps)
    assert py is not None, f"Python rejected a native-accepted meta: {label}"
    assert nat["cid"] == py.correlation_id & _M64, label
    assert nat["attachment"] == py.attachment_size & _M64, label
    assert nat["compress"] == py.compress_type & _M64, label
    assert nat["timeout_ms"] == py.timeout_ms & _M64, label
    assert nat["error_code"] == py.error_code & 0xFFFFFFFF, label
    assert nat["is_response"] == py.is_response, label
    # trace context decodes field-exactly on both planes (the Python
    # decoder masks to u64 exactly like the C++ scanner's arithmetic)
    assert nat["log_id"] == py.log_id & _M64, label
    assert nat["trace_id"] == py.trace_id & _M64, label
    assert nat["span_id"] == py.span_id & _M64, label
    assert nat["parent_span_id"] == py.parent_span_id & _M64, label
    assert nat["sampled"] == py.sampled, label
    assert nat["svc"].decode("utf-8", errors="replace") == py.service_name, (
        label
    )
    assert nat["mth"].decode("utf-8", errors="replace") == py.method_name, (
        label
    )


class TestMetaScannerDifferential:
    def test_structured_request_metas_agree_exactly(self):
        rng = random.Random(0x12A)
        for _ in range(200):
            rm = RpcMeta(
                service_name="".join(
                    rng.choice("abcXYZ_09.") for _ in range(rng.randrange(1, 24))
                ),
                method_name="".join(
                    rng.choice("abcXYZ_09") for _ in range(rng.randrange(1, 24))
                ),
                compress_type=rng.choice([0, 1, 2, 3, 17, 0xFFFFFFFF]),
                correlation_id=rng.getrandbits(rng.choice([8, 32, 63, 64])),
                attachment_size=rng.choice([0, 1, 4096, 1 << 31]),
                timeout_ms=rng.choice([0, 1, 250, 1 << 31]),
            )
            blob = rm.encode()
            nat = _native_scan(blob)
            assert nat is not None, blob.hex()
            assert not nat["is_response"], blob.hex()
            _assert_agree(blob)

    def test_structured_response_metas_agree_exactly(self):
        rng = random.Random(0x12B)
        for _ in range(200):
            rm = RpcMeta(
                is_response=True,
                error_code=rng.choice([0, 1, 1007, (1 << 31) - 1]),
                error_text=rng.choice(["", "boom", "x" * 200]),
                correlation_id=rng.getrandbits(64),
                compress_type=rng.choice([0, 1, 2, 3]),
            )
            blob = rm.encode()
            nat = _native_scan(blob)
            assert nat is not None, blob.hex()
            assert nat["is_response"], blob.hex()
            _assert_agree(blob)

    def test_mutated_valid_metas_agree(self):
        # byte flips, truncations, insertions, splices of real metas —
        # the classic decoder-differential recipe
        rng = random.Random(0x12C)
        bases = [
            RpcMeta(
                service_name="EchoService",
                method_name="Echo",
                correlation_id=0x1122334455667788,
                attachment_size=64,
                compress_type=1,
                timeout_ms=1500,
            ).encode(),
            RpcMeta(
                is_response=True,
                error_code=1004,
                error_text="deadline",
                correlation_id=99,
            ).encode(),
            RpcMeta(
                service_name="s",
                method_name="m",
                authentication_data=b"tok\x00en",
            ).encode(),
        ]
        for _ in range(600):
            blob = bytearray(rng.choice(bases))
            for _ in range(rng.randrange(1, 4)):
                op = rng.randrange(4)
                if op == 0 and blob:  # flip
                    i = rng.randrange(len(blob))
                    blob[i] ^= 1 << rng.randrange(8)
                elif op == 1 and blob:  # truncate
                    del blob[rng.randrange(len(blob)):]
                elif op == 2:  # insert
                    blob.insert(
                        rng.randrange(len(blob) + 1), rng.getrandbits(8)
                    )
                else:  # splice a random run
                    junk = bytes(
                        rng.getrandbits(8) for _ in range(rng.randrange(1, 9))
                    )
                    at = rng.randrange(len(blob) + 1)
                    blob[at:at] = junk
            _assert_agree(bytes(blob))

    def test_random_streams_agree(self):
        rng = random.Random(0x12D)
        for _ in range(800):
            blob = bytes(
                rng.getrandbits(8) for _ in range(rng.randrange(0, 96))
            )
            _assert_agree(blob)

    def test_adversarial_shapes_agree(self):
        tag = baidu_std._tag
        varint = baidu_std._varint
        cases = [
            b"",  # empty meta: both accept, all defaults
            tag(1, 2) + varint((1 << 64) - 1),  # wrap-length submessage
            tag(1, 2) + varint(1 << 32) + b"x",  # length beyond buffer
            tag(4, 0) + b"\xff" * 10,  # overlong varint (11 bytes w/ key)
            tag(4, 0) + b"\xff" * 9 + b"\x01",  # 10-byte cid, bit 63
            tag(4, 0) + b"\x80" * 9 + b"\x7f",  # cid with bits beyond 64
            tag(3, 0) + varint(1 << 33),  # compress beyond u32 (native cap)
            tag(5, 0) + varint((1 << 31) + 1),  # attachment beyond clamp
            tag(1, 2)
            + varint(len(varint((1 << 31) + 1)) + 1)
            + tag(8, 0)
            + varint((1 << 31) + 1),  # timeout beyond clamp, in the sub
            tag(6, 1) + b"\x01" * 8,  # fixed64: skipped by both
            tag(6, 1) + b"\x01" * 7,  # truncated fixed64
            tag(6, 5) + b"\x01" * 4,  # fixed32
            tag(6, 5) + b"\x01",  # truncated fixed32
            tag(6, 3),  # group-start: rejected by both
            tag(6, 4),  # group-end
            b"\x0f",  # wire type 7
            tag(1, 2) + varint(2) + tag(1, 2) + varint(5),  # nested overrun
            tag(2, 2) + varint(2) + tag(1, 0) + b"\x80",  # sub truncated varint
            tag(7, 2) + varint(3) + b"a\x00b",  # auth with NUL
            tag(1, 2) + b"\x00",  # empty request sub
            tag(2, 2) + b"\x00" + tag(1, 2) + b"\x00",  # response + request
        ]
        for blob in cases:
            _assert_agree(blob)

    def test_traced_metas_agree_exactly(self):
        # ISSUE 15: structured metas with Dapper trace fields — the
        # trace decode branches are new fast-path territory, so the
        # differential pins them field-exact
        rng = random.Random(0x15A)
        for _ in range(300):
            rm = RpcMeta(
                service_name="TraceSvc",
                method_name="Echo",
                log_id=rng.choice([0, 1, rng.getrandbits(63)]),
                trace_id=rng.choice([0, 1, rng.getrandbits(63),
                                     rng.getrandbits(64)]),
                span_id=rng.choice([0, rng.getrandbits(64)]),
                parent_span_id=rng.choice([0, rng.getrandbits(63)]),
                sampled=rng.choice([0, 1]),
                correlation_id=rng.getrandbits(32),
            )
            blob = rm.encode()
            nat = _native_scan(blob)
            assert nat is not None, blob.hex()
            assert not nat["to_python"], (
                f"a traced meta fell off the fast path: {blob.hex()}"
            )
            _assert_agree(blob)

    def test_traced_meta_fuzz_huge_zero_duplicate_varints(self):
        # the satellite's adversarial trio — huge (overlong/10-byte)
        # trace varints, zero-valued fields, and DUPLICATED fields
        # (proto2 last-wins on both planes) — through the differential
        tag = baidu_std._tag
        varint = baidu_std._varint

        def sub(fields: bytes) -> bytes:
            return tag(1, 2) + varint(len(fields)) + fields

        cases = [
            # huge: 10-byte varints with bits at/beyond 64 (both planes
            # reduce mod 2^64)
            sub(tag(4, 0) + b"\xff" * 9 + b"\x01"),
            sub(tag(5, 0) + b"\x80" * 9 + b"\x7f"),
            sub(tag(3, 0) + b"\xff" * 9 + b"\x7f"),
            # overlong-but-small: non-minimal zero (wire-legal)
            sub(tag(4, 0) + b"\x80\x80\x80\x00"),
            # zero-valued trace fields: present but 0 — must NOT route
            # to Python (the pre-ISSUE-15 scanner only fast-pathed the
            # zero case; now both are native)
            sub(tag(4, 0) + varint(0) + tag(5, 0) + varint(0)),
            sub(tag(9, 0) + varint(0)),
            # duplicates: last wins on both planes
            sub(tag(4, 0) + varint(111) + tag(4, 0) + varint(222)),
            sub(tag(5, 0) + varint(1) + tag(5, 0) + varint(0)),
            sub(tag(9, 0) + varint(1) + tag(9, 0) + varint(0)),
            # sampled with a huge value: both planes normalize to 1
            sub(tag(9, 0) + b"\xff" * 9 + b"\x01"),
            # trace fields with the WRONG wire type (fixed64/fixed32):
            # ignored by Python, to_python'd by the scanner — values 0
            sub(tag(4, 1) + b"\x01" * 8),
            sub(tag(5, 5) + b"\x01" * 4),
            # truncated trace varint: reject on both planes
            sub(tag(4, 0) + b"\x80"),
        ]
        for blob in cases:
            _assert_agree(blob)
        # the duplicate case decodes last-wins, pinned explicitly
        nat = _native_scan(cases[6])
        assert nat is not None and nat["trace_id"] == 222
        # and randomized trace-field soup
        rng = random.Random(0x15B)
        for _ in range(400):
            fields = b""
            for _ in range(rng.randrange(1, 6)):
                f = rng.choice([3, 4, 5, 6, 9])
                v = rng.choice([
                    0, 1, rng.getrandbits(7), rng.getrandbits(63),
                    rng.getrandbits(64),
                ])
                fields += tag(f, 0) + varint(v)
            _assert_agree(sub(fields))

    def test_native_stricter_rejects_are_exactly_the_caps(self):
        # the three documented clamps DO reject natively while Python
        # accepts — pinned so a future widening shows up here
        tag = baidu_std._tag
        varint = baidu_std._varint
        for blob in (
            tag(3, 0) + varint(1 << 33),
            tag(5, 0) + varint((1 << 31) + 1),
            tag(1, 2)
            + varint(len(varint((1 << 31) + 1)) + 1)
            + tag(8, 0)
            + varint((1 << 31) + 1),
        ):
            assert _native_scan(blob) is None, blob.hex()
            py = _python_scan(blob)
            assert py is not None and _native_stricter_cap(py), blob.hex()


class TestPumpHostileFrameCap:
    """Regression for the wire-bounds violation fabricscan found at
    introduction (ISSUE 12): the pump's tbus read path must reject a
    hostile claimed body_len instead of growing rbuf toward ~4 GiB."""

    def test_pump_rejects_oversized_body_claim(self):
        from incubator_brpc_tpu.protocol import tbus_std

        hostile_header = struct.pack(
            "<8I",
            tbus_std.MAGIC,
            600 << 20,  # claimed body: beyond the 512 MiB client cap
            tbus_std.FLAG_RESPONSE,
            1, 0,  # cid lo/hi
            0, 0, 0,  # meta_len / crc / error
        )
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]

        def serve():
            conn, _ = lst.accept()
            try:
                conn.recv(4096)  # whatever the pump sent first
                conn.sendall(hostile_header)
                # keep the conn open: without the cap the client would
                # sit in "wait for the full frame" until timeout
                conn.recv(4096)
            except OSError:
                pass
            finally:
                conn.close()

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        nch = native_plane.NativeClientChannel("127.0.0.1", port)
        try:
            with pytest.raises(OSError) as ei:
                nch.pump("svc", "echo", b"x", 4, inflight=2, timeout_ms=8000)
            import errno

            # -EPROTO promptly — NOT -ETIMEDOUT after buffering the claim
            assert ei.value.errno == errno.EPROTO
        finally:
            nch.close()
            lst.close()
            th.join(timeout=5)
