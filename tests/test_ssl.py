"""TLS transport (transport/sock.py MemoryBIO/SSLObject pump — reference
details/ssl_helper.cpp, SSLHandshake socket.cpp:1880, SocketMapKey ssl
slot socket_map.h:35): encrypted echo end-to-end, large payloads across
many TLS records, streaming over TLS, plaintext/TLS socket partition,
reconnect re-handshake, and handshake failure paths.
"""

from __future__ import annotations

import pathlib
import ssl
import threading

import pytest

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions

DATA = pathlib.Path(__file__).parent / "data"
CERT = str(DATA / "test_cert.pem")
KEY = str(DATA / "test_key.pem")


def server_ctx() -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(CERT, KEY)
    return ctx


def client_ctx(verify: bool = True) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if verify:
        ctx.load_verify_locations(CERT)
        ctx.check_hostname = False  # cert is CN=localhost; targets use the IP
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


@pytest.fixture
def tls_server():
    srv = Server(ServerOptions(usercode_inline=True, ssl_context=server_ctx()))
    srv.add_service("svc", {"echo": lambda cntl, req: req})
    assert srv.start(0)
    yield srv
    srv.stop()


def _tls_channel(port, **opts) -> Channel:
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{port}",
        options=ChannelOptions(ssl_context=client_ctx(), **opts),
    )
    return ch


class TestTlsRpc:
    def test_echo_over_tls(self, tls_server):
        ch = _tls_channel(tls_server.port)
        c = ch.call_method("svc", "echo", b"secret-ping")
        assert c.ok(), c.error_text
        assert c.response_payload == b"secret-ping"

    def test_large_payload_many_records(self, tls_server):
        # >> the 16 KiB TLS record limit: exercises record reassembly on
        # both sides of the BIO pump
        payload = bytes(range(256)) * 4096  # 1 MiB
        ch = _tls_channel(tls_server.port, timeout_ms=20000)
        c = ch.call_method("svc", "echo", payload)
        assert c.ok(), c.error_text
        assert c.response_payload == payload

    def test_concurrent_tls_writers(self, tls_server):
        # encrypt-and-enqueue must be atomic or records interleave corruptly
        ch = _tls_channel(tls_server.port, timeout_ms=20000)
        errs = []

        def hammer(tid):
            for i in range(10):
                payload = bytes([tid]) * (1000 + i * 977)
                c = ch.call_method("svc", "echo", payload)
                if not (c.ok() and c.response_payload == payload):
                    errs.append((tid, i, c.error_text))

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_wire_is_actually_encrypted(self, tls_server):
        # a recording TCP proxy between client and server: the plaintext
        # marker must never appear in either direction's wire bytes
        import socket as pysock

        marker = b"PLAINTEXT-MARKER-0123456789"
        seen = bytearray()
        seen_lock = threading.Lock()
        lsock = pysock.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        proxy_port = lsock.getsockname()[1]
        stop = threading.Event()

        def pump(src, dst):
            try:
                while not stop.is_set():
                    data = src.recv(65536)
                    if not data:
                        break
                    with seen_lock:
                        seen.extend(data)
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for c in (src, dst):
                    try:
                        c.close()
                    except OSError:
                        pass

        def proxy():
            while not stop.is_set():
                try:
                    cli, _ = lsock.accept()
                except OSError:
                    return
                upstream = pysock.create_connection(
                    ("127.0.0.1", tls_server.port)
                )
                threading.Thread(
                    target=pump, args=(cli, upstream), daemon=True
                ).start()
                threading.Thread(
                    target=pump, args=(upstream, cli), daemon=True
                ).start()

        threading.Thread(target=proxy, daemon=True).start()
        try:
            ch = _tls_channel(proxy_port, timeout_ms=10000)
            c = ch.call_method("svc", "echo", marker)
            assert c.ok(), c.error_text
            assert c.response_payload == marker
            with seen_lock:
                wire = bytes(seen)
            assert len(wire) > 0
            assert marker not in wire
        finally:
            stop.set()
            lsock.close()

    def test_plaintext_client_cannot_talk_to_tls_server(self, tls_server):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{tls_server.port}",
            options=ChannelOptions(timeout_ms=2000, max_retry=0),
        )
        c = ch.call_method("svc", "echo", b"x")
        assert not c.ok()

    def test_tls_client_against_plaintext_server_fails_cleanly(self):
        srv = Server(ServerOptions(usercode_inline=True))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(
                    ssl_context=client_ctx(), timeout_ms=2000, max_retry=0
                ),
            )
            c = ch.call_method("svc", "echo", b"x")
            assert not c.ok()
        finally:
            srv.stop()

    def test_untrusted_cert_rejected(self, tls_server):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED  # but no CA loaded
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{tls_server.port}",
            options=ChannelOptions(
                ssl_context=ctx, timeout_ms=2000, max_retry=0
            ),
        )
        c = ch.call_method("svc", "echo", b"x")
        assert not c.ok()

    def test_tls_and_plain_partition_in_socket_map(self, tls_server):
        from incubator_brpc_tpu.rpc.channel import _client_socket_map

        ch = _tls_channel(tls_server.port)
        assert ch.call_method("svc", "echo", b"a").ok()
        keys = [
            k for k in _client_socket_map._map
            if k.startswith(f"127.0.0.1:{tls_server.port}|")
        ]
        assert any("|ssl-" in k for k in keys), keys


class TestTlsStream:
    def test_stream_over_tls(self):
        from incubator_brpc_tpu.rpc import (
            StreamHandler,
            StreamOptions,
            stream_accept,
            stream_create,
        )

        total = 4 << 20
        got = [0]
        done = threading.Event()

        class Sink(StreamHandler):
            def on_received_messages(self, s, msgs):
                got[0] += sum(len(m) for m in msgs)
                if got[0] >= total:
                    done.set()

        def open_stream(cntl, req):
            stream_accept(cntl, StreamOptions(handler=Sink()))
            return b""

        srv = Server(
            ServerOptions(usercode_inline=True, ssl_context=server_ctx())
        )
        srv.add_service("str", {"open": open_stream})
        assert srv.start(0)
        try:
            ch = _tls_channel(srv.port, timeout_ms=20000)
            s = stream_create(StreamOptions())
            c = ch.call_method("str", "open", b"", request_stream=s)
            assert c.ok(), c.error_text
            assert s.wait_connected(5)
            chunk = b"s" * (256 * 1024)
            sent = 0
            while sent < total:
                assert s.write(chunk, timeout=30) == 0
                sent += len(chunk)
            assert done.wait(30), f"got {got[0]} of {total}"
            s.close()
        finally:
            srv.stop()


class TestTlsCombo:
    def test_partition_channel_over_tls(self):
        from incubator_brpc_tpu.rpc import PartitionChannel

        servers = []
        try:
            eps = []
            for part in range(2):
                srv = Server(
                    ServerOptions(
                        usercode_inline=True, ssl_context=server_ctx()
                    )
                )
                srv.add_service(
                    "svc",
                    {"echo": (lambda p: lambda cntl, req: req + b"|p%d" % p)(part)},
                )
                assert srv.start(0)
                servers.append(srv)
                eps.append(f"127.0.0.1:{srv.port} %d/2" % part)
            pch = PartitionChannel()
            assert pch.init(
                "list://" + ",".join(eps),
                partition_count=2,
                lb_name="rr",
                options=ChannelOptions(
                    ssl_context=client_ctx(), timeout_ms=10000
                ),
            )
            c = pch.call_method("svc", "echo", b"x")
            assert c.ok(), c.error_text
        finally:
            for srv in servers:
                srv.stop()


class TestTlsReconnect:
    def test_reconnect_rehandshakes(self):
        srv = Server(ServerOptions(usercode_inline=True, ssl_context=server_ctx()))
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        port = srv.port
        ch = _tls_channel(port, timeout_ms=5000)
        assert ch.call_method("svc", "echo", b"one").ok()
        srv.stop()
        # restart on the same port; the dropped TLS socket must re-dial AND
        # re-handshake a fresh session (connect_if_not -> _ssl_rewrap)
        srv2 = Server(ServerOptions(usercode_inline=True, ssl_context=server_ctx()))
        srv2.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv2.start(port)
        try:
            deadline = 10
            import time

            end = time.monotonic() + deadline
            ok = False
            while time.monotonic() < end:
                c = ch.call_method("svc", "echo", b"two")
                if c.ok():
                    ok = True
                    break
                time.sleep(0.2)
            assert ok, f"reconnect never succeeded: {c.error_text}"
            assert c.response_payload == b"two"
        finally:
            srv2.stop()
