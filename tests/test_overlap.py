"""The compute/communication overlap scheduler (ISSUE 13, T3):
chunked collective steps + double-buffered sessions in
parallel/mc_dispatch.py, the fabricnet microbatch overlap schedule, and
the rpcz proof-of-overlap plane.

Gates encoded here (the acceptance criteria):

- every overlap schedule is BYTE-identical to the serialized one (and to
  the integer session model);
- ``chunks=1, double_buffer=False`` degenerates to the exact pre-overlap
  code path (observable: the chunk bvar never moves);
- a party death mid-step with half a step's chunks acked aborts cleanly
  and ``propose_with_recovery`` heals with the resume point at a STEP
  boundary — never a torn chunk;
- the per-step watchdog stamps per-chunk progress and an abort reason
  names step+chunk;
- an overlapped session's rpcz trace shows chunk collective spans
  time-overlapping the NEXT step's compute span — asserted numerically,
  not eyeballed.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    device_method,
)
from incubator_brpc_tpu.transport.mc_worker import (
    SESSION_WIDTH,
    _scale_psum_kernel,
    session_expected,
)


@pytest.fixture(scope="module")
def shard_map_capable():
    import jax

    from incubator_brpc_tpu.parallel.compat import resolve_shard_map

    try:
        resolve_shard_map()
    except ImportError:
        pytest.skip("no shard_map in this jax build")
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4+ device mesh")
    return True


@pytest.fixture
def registered_chunkable(shard_map_capable):
    """("dsvc", "scale") registered CHUNK-SAFE in this process's registry
    (psum + elementwise scale treats every width slice alike and passes
    n through — the chunk-safety contract)."""
    from incubator_brpc_tpu.rpc.device_method import (
        DeviceMethod,
        lookup_device_method,
        register_device_method,
    )

    dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH, chunkable=True)
    prev = lookup_device_method("dsvc", "scale")
    register_device_method("dsvc", "scale", dm)
    yield dm
    if prev is not None:
        register_device_method("dsvc", "scale", prev)


def _servers(n, chunkable=True, start_index=1, inline=True):
    servers = []
    for i in range(n):
        s = Server(
            ServerOptions(
                device_index=start_index + i,
                usercode_inline=inline,
                enable_collective_service=True,
                collective_max_concurrency=0,
            )
        )
        s.add_service(
            "dsvc",
            {"scale": device_method(
                _scale_psum_kernel, width=SESSION_WIDTH, chunkable=chunkable
            )},
        )
        assert s.start(0)
        servers.append(s)
    return servers


def _channels(servers):
    chans = []
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        chans.append(ch)
    return chans


def _stop(servers):
    for s in servers:
        s.stop()
        s.join(timeout=5)


class TestChunkedSessions:
    """Chunked + double-buffered schedules vs the integer session model
    and the degenerate path."""

    @pytest.mark.parametrize(
        "chunks,double_buffer",
        [(1, False), (4, False), (4, True), (1, True), (8, True)],
    )
    def test_every_schedule_matches_integer_model(
        self, registered_chunkable, chunks, double_buffer
    ):
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch

        servers = _servers(2)
        try:
            chans = _channels(servers)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            operands = [bytes(range(40)), bytes(range(100, 180))]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=3, proposer_index=None, timeout_ms=60000,
                chunks=chunks, double_buffer=double_buffer,
            )
            assert out["final_steps"] == 3
            assert out["results"] == session_expected(operands, 3)
        finally:
            _stop(servers)

    def test_degenerate_path_is_the_pre_overlap_code(
        self, registered_chunkable
    ):
        """chunks=1 + double_buffer=False must run the exact unchunked
        chain: the chunk bvar (counted once per chunked session) stays
        untouched, while any chunked schedule moves it."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import (
            dispatch_chunks,
            propose_dispatch,
        )

        servers = _servers(2)
        try:
            chans = _channels(servers)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            operands = [b"\x05" * 16, b"\x09" * 24]
            before = dispatch_chunks.get_value()
            propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=None, timeout_ms=60000,
            )
            assert dispatch_chunks.get_value() == before, (
                "the default schedule dispatched chunk sub-collectives"
            )
            propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=None, timeout_ms=60000,
                chunks=2,
            )
            # 2 parties x 2 steps x 2 chunks
            assert dispatch_chunks.get_value() == before + 8
        finally:
            _stop(servers)

    def test_overlap_ratio_gauge_reads(self, registered_chunkable):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            overlap_ratio_gauge,
        )

        assert 0.0 <= overlap_ratio_gauge.get_value() <= 1.0

    def test_proposer_rejects_unchunkable_kernel(self, shard_map_capable):
        """A method registered without chunkable=True cannot run chunked
        — the proposer validates against its own registry before any
        fan-out (a silently mis-chunked kernel would diverge, not
        fail)."""
        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )

        register_device_method(
            "dsvc", "plain_scale",
            DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH),
        )
        # channels are never dialed: the validation rejects first
        with pytest.raises(ValueError, match="chunk"):
            propose_dispatch(
                [None], [0, 1], "dsvc", "plain_scale", [b"a", b"b"],
                steps=1, proposer_index=0, chunks=2,
            )

    def test_proposer_rejects_bad_chunk_geometry(
        self, registered_chunkable
    ):
        from incubator_brpc_tpu.parallel.mc_dispatch import (
            MAX_CHUNKS,
            propose_dispatch,
        )

        # channels are never dialed: the validation rejects first
        with pytest.raises(ValueError, match="divide"):
            propose_dispatch(
                [None], [0, 1], "dsvc", "scale", [b"a", b"b"],
                steps=1, proposer_index=0, chunks=3,  # 3 ∤ SESSION_WIDTH
            )
        with pytest.raises(ValueError, match="chunks"):
            propose_dispatch(
                [None], [0, 1], "dsvc", "scale", [b"a", b"b"],
                steps=1, proposer_index=0, chunks=MAX_CHUNKS + 1,
            )

    def test_party_without_chunkable_registration_rejects(
        self, shard_map_capable
    ):
        """Chunk-safety is validated by EVERY party against its LOCAL
        registry, like the fingerprint: a server whose registration
        lacks the declaration cleanly rejects the run proposal before
        any lockstep entry (the fingerprint matches — chunkability is a
        capability, not part of the kernel's identity)."""
        import base64
        import json

        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import dispatch_rejects
        from incubator_brpc_tpu.rpc import Controller
        from incubator_brpc_tpu.rpc.device_method import DeviceMethod
        from incubator_brpc_tpu.utils.status import ErrorCode

        servers = _servers(1, chunkable=False)
        try:
            (ch,) = _channels(servers)
            parties = [jax.devices()[1].id, jax.devices()[2].id]
            fp = DeviceMethod(
                _scale_psum_kernel, width=SESSION_WIDTH
            ).fingerprint()
            before = dispatch_rejects.get_value()
            run = {
                "parties": parties,
                "index": 0,
                "steps": 1,
                "width": SESSION_WIDTH,
                "service": "dsvc",
                "method": "scale",
                "fingerprint": fp,
                "operands": [
                    base64.b64encode(b"\x01" * 8).decode(),
                    base64.b64encode(b"\x02" * 8).decode(),
                ],
                "chunks": 2,
            }
            cntl = Controller(timeout_ms=30000)
            ch.call_method(
                "_tpu_transport", "collective_dispatch",
                json.dumps(run).encode(), cntl=cntl,
            )
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.EREQUEST
            assert "chunkable" in cntl.error_text
            assert dispatch_rejects.get_value() == before + 1
        finally:
            _stop(servers)


class TestOverlapRpczProof:
    """The acceptance criterion: chunk collective spans of an overlapped
    session TIME-OVERLAP the next step's compute span — asserted on the
    sampled spans, with the serialized schedule as the control."""

    @pytest.fixture
    def rpcz_on(self, tuned_flags):
        tuned_flags("enable_rpcz", True)
        tuned_flags("rpcz_samples_per_second", 1_000_000)
        from incubator_brpc_tpu.builtin.rpcz import span_store

        yield span_store

    def _run_session(self, double_buffer, steps=4, pace_s=0.0):
        import jax

        from incubator_brpc_tpu.parallel import mc_dispatch

        servers = _servers(2)
        try:
            chans = _channels(servers)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            if pace_s:
                mc_dispatch.set_step_hook(
                    lambda s, i, c: time.sleep(pace_s)
                )
            out = mc_dispatch.propose_dispatch(
                chans, party_ids, "dsvc", "scale",
                [bytes(range(40)), bytes(range(100, 180))],
                steps=steps, proposer_index=None, timeout_ms=60000,
                chunks=4, double_buffer=double_buffer,
            )
            assert out["results"] == session_expected(
                [bytes(range(40)), bytes(range(100, 180))], steps
            )
        finally:
            mc_dispatch.set_step_hook(None)
            _stop(servers)

    @staticmethod
    def _session_spans(store):
        return [
            sp for sp in store.recent(limit=10000)
            if any(
                t.startswith(("chunk=", "compute step="))
                for _off, t in sp.annotations
            )
        ]

    def test_double_buffered_chunks_overlap_next_compute(
        self, registered_chunkable, rpcz_on
    ):
        from incubator_brpc_tpu.builtin.rpcz import (
            _CHUNK_ANN_RE,
            _COMPUTE_ANN_RE,
            overlap_report,
        )

        rpcz_on.clear()
        self._run_session(double_buffer=True)
        spans = self._session_spans(rpcz_on)
        assert spans, "no overlap-session spans sampled"

        # the numeric assertion: at least one chunk span of step k whose
        # [start, end] interval intersects the SAME party chain's step
        # k+1 compute span (chunk spans parent to their step's compute
        # span; step spans share a per-party session parent — cross-
        # party skew must not count as overlap)
        by_id = {sp.span_id: sp for sp in spans}
        computes = {}
        for sp in spans:
            for _off, t in sp.annotations:
                m = _COMPUTE_ANN_RE.match(t)
                if m:
                    computes[(sp.parent_span_id, int(m.group(1)))] = (
                        sp.start_real_us,
                        sp.start_real_us + sp.latency_us,
                    )
        overlapped = 0
        for sp in spans:
            for _off, t in sp.annotations:
                m = _CHUNK_ANN_RE.match(t)
                if not m:
                    continue
                step = int(m.group(3))
                parent = by_id.get(sp.parent_span_id)
                party = (
                    parent.parent_span_id if parent is not None else 0
                )
                cs, ce = (
                    sp.start_real_us, sp.start_real_us + sp.latency_us
                )
                nxt = computes.get((party, step + 1))
                if nxt and min(ce, nxt[1]) - max(cs, nxt[0]) > 0:
                    overlapped += 1
        assert overlapped > 0, (
            "no chunk collective span time-overlaps the next step's "
            "compute span — the schedule serialized"
        )
        # and the operator view agrees
        report = overlap_report(spans)
        assert report and report[-1].endswith("OVERLAPPED")

    def test_serialized_schedule_reads_serialized(
        self, registered_chunkable, rpcz_on
    ):
        """The control: with the per-step ack barrier, every chunk span
        closes before the next compute span begins — the report calls
        the regression out."""
        from incubator_brpc_tpu.builtin.rpcz import overlap_report

        rpcz_on.clear()
        self._run_session(double_buffer=False)
        spans = self._session_spans(rpcz_on)
        assert spans
        report = overlap_report(spans)
        assert report and report[-1].endswith("SERIALIZED")

    def test_overlap_report_unit(self):
        """Deterministic synthetic spans: one overlapped, one serialized
        — the report lines and verdict are exact."""
        from incubator_brpc_tpu.builtin.rpcz import Span, overlap_report

        def mk(start, lat, ann):
            sp = Span(start_real_us=start, latency_us=lat)
            sp.annotations.append((0.0, ann))
            return sp

        base = [
            mk(1000, 100, "compute step=0/2 chunks=2 schedule=double_buffer"),
            mk(1200, 100, "compute step=1/2 chunks=2 schedule=double_buffer"),
        ]
        overlapped = base + [
            # chunk of step 0 closing inside step 1's window
            mk(1050, 200, "chunk=0/2 step=0"),
        ]
        report = overlap_report(overlapped)
        assert any("overlapped" in line for line in report)
        assert report[-1].endswith("OVERLAPPED")
        serialized = base + [
            mk(1050, 100, "chunk=0/2 step=0"),  # closes at 1150 < 1200
        ]
        report = overlap_report(serialized)
        assert any("serialized" in line for line in report)
        assert report[-1].endswith("SERIALIZED")
        assert overlap_report([mk(0, 1, "plain annotation")]) == []

    def test_rpc_view_trace_tree_appends_overlap_report(
        self, registered_chunkable, rpcz_on
    ):
        """The operator pipe end to end: scrape a live server's /rpcz
        trace and the trace-tree rendering carries the verdict line."""
        from incubator_brpc_tpu.builtin.rpcz import overlap_report
        from tools.rpc_view import scrape_rpcz

        rpcz_on.clear()
        self._run_session(double_buffer=True)
        spans = self._session_spans(rpcz_on)
        trace_ids = {sp.trace_id for sp in spans}
        assert trace_ids
        srv = Server(ServerOptions())
        assert srv.start(0)
        try:
            tid = trace_ids.pop()
            scraped = scrape_rpcz(
                f"127.0.0.1:{srv.port}", trace_id=f"{tid:x}"
            )
            assert scraped, "live /rpcz scrape returned no spans"
            report = overlap_report(scraped)
            assert report, "scraped trace carries no chunk annotations"
        finally:
            srv.stop()
            srv.join(timeout=5)


class TestChunkedWatchdog:
    """The satellite fix: a chunked step is C progress stamps, and an
    abort reason names step+chunk — a stalled last chunk is attributed
    to ITS step, not misread as the next one hanging."""

    def test_watchdog_abort_names_step_and_chunk(
        self, registered_chunkable
    ):
        import jax

        from incubator_brpc_tpu.parallel import mc_dispatch

        servers = _servers(3)
        try:
            chans = _channels(servers)
            party_ids = [d.id for d in jax.devices()[1:4]]
            operands = [bytes([i + 1]) * 8 for i in range(3)]
            before = mc_dispatch.dispatch_aborts.get_value()

            STALL_S = 2.5

            def hook(step, idx, chunk):
                if idx == 1 and step == 2 and chunk == 1:
                    time.sleep(STALL_S)  # wedged inside step 2 chunk 1

            mc_dispatch.set_step_hook(hook)
            t0 = time.monotonic()
            with pytest.raises(mc_dispatch.SessionAborted) as exc:
                # the deadline must sit well under STALL_S (the watchdog,
                # not the session deadline, is what fires) but above a
                # loaded host's first-dispatch window — compile time
                # charges against step 0's budget, and a too-tight value
                # aborts at "step 0" before the seeded stall is reached
                mc_dispatch.propose_dispatch(
                    chans, party_ids, "dsvc", "scale", operands,
                    steps=30, proposer_index=None, timeout_ms=60000,
                    session_deadline_ms=30000, step_deadline_ms=600,
                    chunks=2, double_buffer=True,
                )
            elapsed = time.monotonic() - t0
            mc_dispatch.set_step_hook(None)
            # the watchdog (not the 30 s session deadline) fired, and
            # the blame names the torn step AND chunk
            assert elapsed < STALL_S + 4.0
            msg = str(exc.value)
            assert "step deadline" in msg
            assert "step 2 chunk 1/2" in msg, msg
            assert mc_dispatch.dispatch_aborts.get_value() > before
        finally:
            mc_dispatch.set_step_hook(None)
            _stop(servers)


class TestOverlapChaosDrill:
    """Party death mid-step with half the chunks acked: the session
    aborts cleanly and propose_with_recovery heals with the resume point
    at a STEP boundary — never a torn chunk."""

    DEADLINE_MS = 6000
    STEPS = 60

    def test_death_mid_chunked_step_heals_at_step_boundary(
        self, registered_chunkable, tuned_flags
    ):
        import jax

        from incubator_brpc_tpu.parallel import mc_dispatch

        if len(jax.devices()) < 5:
            pytest.skip("needs a 5+ device mesh (3 parties + spare)")
        # worker-pool servers (not inline): the resume barrier's census
        # RPCs must be servable while the party chains hold their
        # handler threads
        servers = _servers(4, inline=False)  # 3 parties + 1 spare
        channels = []
        try:
            for s in servers:
                ch = Channel()
                assert ch.init(
                    f"list://127.0.0.1:{s.port}", lb_name="rr",
                    options=ChannelOptions(max_retry=1, timeout_ms=10000),
                )
                channels.append(ch)
            party_ids = [d.id for d in jax.devices()[1:4]]
            spare_dev = jax.devices()[4].id
            operands = [bytes([i + 1]) * 8 for i in range(3)]

            # pace every CHUNK, and trigger the kill on PROGRESS (step
            # 12, mid-step at chunk 2 — half the step's chunks already
            # dispatched: a torn step), not wall time: jit compilation
            # of the chunked programs would otherwise eat a fixed timer
            # budget before any checkpoint exists
            kill_now = threading.Event()

            def hook(step, idx, chunk):
                if step >= 12 and chunk >= 2:
                    kill_now.set()
                time.sleep(0.008)

            def killer_body():
                if kill_now.wait(timeout=30):
                    servers[0].stop()
                    servers[0].join(timeout=3)

            mc_dispatch.set_step_hook(hook)
            killer = threading.Thread(target=killer_body, daemon=True)
            killer.start()
            try:
                out = mc_dispatch.propose_with_recovery(
                    channels[:3], party_ids, "dsvc", "scale", operands,
                    steps=self.STEPS, proposer_index=None,
                    timeout_ms=60000,
                    session_deadline_ms=self.DEADLINE_MS,
                    spares=[(channels[3], spare_dev)],
                    checkpoint_every=2,
                    chunks=4, double_buffer=True,
                )
            finally:
                kill_now.set()
                mc_dispatch.set_step_hook(None)
                killer.join(timeout=5)

            assert out["dead_party_ids"] == [party_ids[0]]
            assert out["replaced_party_ids"] == [spare_dev]
            # the resume point is a WHOLE checkpointed step — chunks
            # re-concat before entering the ring, so a torn chunk can
            # never be elected
            assert out["resumed_from"] is not None
            assert out["resumed_from"] > 0
            assert out["resumed_from"] % 2 == 0
            assert out["final_steps"] == self.STEPS
            want = session_expected(operands, self.STEPS)
            for i, (got, exp) in enumerate(zip(out["results"], want)):
                assert got == exp, f"slot {i} diverged after resume"
        finally:
            for ch in channels:
                if ch._lb is not None:
                    ch._lb.stop()
            _stop(servers)
