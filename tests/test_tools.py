"""rpc_dump capture + rpc_replay/rpc_press/rpc_view tool tests (reference
src/brpc/rpc_dump.{h,cpp}, tools/rpc_replay, tools/rpc_press)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from incubator_brpc_tpu.rpc import Channel, Server  # noqa: E402
from incubator_brpc_tpu.rpc.dump import RpcDumper, load_dump_file  # noqa: E402
from incubator_brpc_tpu.utils.flags import flag_registry, set_flag  # noqa: E402


@pytest.fixture
def echo_server():
    server = Server()
    seen = []

    def echo(cntl, request):
        seen.append(request)
        return request

    server.add_service("dump", {"echo": echo})
    assert server.start(0)
    yield server, seen
    server.stop()
    server.join(timeout=5)


class TestRpcDump:
    def test_server_samples_when_enabled(self, echo_server, tmp_path):
        from incubator_brpc_tpu.rpc.dump import reset_global_dumper

        server, _ = echo_server
        old_dir = flag_registry.get("rpc_dump_dir")
        flag_registry.set_unchecked("rpc_dump_dir", str(tmp_path))
        assert set_flag("rpc_dump", True)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{server.port}")
            for i in range(5):
                assert ch.call_method("dump", "echo", b"req-%d" % i).ok()
        finally:
            set_flag("rpc_dump", False)
            flag_registry.set_unchecked("rpc_dump_dir", old_dir)
            reset_global_dumper()  # drop the handle into tmp_path
        files = [f for f in os.listdir(tmp_path) if f.startswith("requests.")]
        assert files
        samples = []
        for f in files:
            samples.extend(load_dump_file(str(tmp_path / f)))
        payloads = {p for _, p, _ in samples}
        assert {b"req-%d" % i for i in range(5)} <= payloads
        meta = samples[0][0]
        assert (meta.service, meta.method) == ("dump", "echo")

    def test_sampling_budget_caps_rate(self, tmp_path):
        d = RpcDumper(directory=str(tmp_path))
        flag_registry.set_unchecked("rpc_dump_max_requests_per_second", 3)
        try:
            from incubator_brpc_tpu.protocol.tbus_std import Meta

            taken = [d.sample(Meta(service="s", method="m"), b"x") for _ in range(10)]
            assert taken.count(True) == 3
        finally:
            flag_registry.set_unchecked("rpc_dump_max_requests_per_second", 100)
        d.close()

    def test_file_rotation(self, tmp_path):
        from incubator_brpc_tpu.protocol.tbus_std import Meta

        flag_registry.set_unchecked("rpc_dump_max_requests_in_one_file", 2)
        try:
            d = RpcDumper(directory=str(tmp_path))
            for i in range(5):
                assert d.sample(Meta(service="s", method="m"), b"%d" % i)
            d.close()
        finally:
            flag_registry.set_unchecked("rpc_dump_max_requests_in_one_file", 1000)
        files = sorted(os.listdir(tmp_path))
        assert len(files) == 3  # 2 + 2 + 1


class TestReplay:
    def test_replay_reissues_samples(self, echo_server, tmp_path):
        from incubator_brpc_tpu.protocol.tbus_std import Meta

        server, seen = echo_server
        d = RpcDumper(directory=str(tmp_path))
        for i in range(4):
            assert d.sample(Meta(service="dump", method="echo"), b"replay-%d" % i)
        d.close()

        from tools.rpc_replay import load_requests, run_replay

        requests = load_requests(str(tmp_path))
        assert len(requests) == 4
        # generous timeout: a loaded host can stall >1s and flake the
        # default; the assertion is about correctness, not latency
        stats = run_replay(
            requests, f"127.0.0.1:{server.port}", threads=2, times=2,
            timeout_ms=15000,
        )
        assert stats == {"ok": 8, "fail": 0, "total": 8}
        assert sorted(seen) == sorted([b"replay-%d" % i for i in range(4)] * 2)


class TestPress:
    def test_press_drives_load(self, echo_server):
        server, _ = echo_server
        from tools.rpc_press import run_press

        stats = run_press(
            f"127.0.0.1:{server.port}",
            "dump",
            "echo",
            b"press",
            threads=2,
            duration=0.5,
        )
        assert stats["fail"] == 0
        assert stats["ok"] > 10
        assert stats["latency_us_p99"] >= stats["latency_us_p50"] > 0

    def test_press_reactor_mode_reports_distribution(self):
        # --reactors N --conns-per-reactor M: the sharded-accept load
        # run against a multi-reactor native server, per-reactor conn
        # distribution scraped from the target's /vars
        from incubator_brpc_tpu.rpc import (
            Server,
            ServerOptions,
            native_echo,
        )
        from incubator_brpc_tpu.transport import native_plane as np_mod
        from tools.rpc_press import run_reactor_press

        if not np_mod.NET_AVAILABLE:
            import pytest as _pytest

            _pytest.skip("native runtime unavailable")
        srv = Server(
            ServerOptions(
                native_plane=True, usercode_inline=True, num_reactors=4
            )
        )
        srv.add_service("demo", {"echo": native_echo})
        assert srv.start(0)
        try:
            stats = run_reactor_press(
                f"127.0.0.1:{srv.port}", "demo", "echo", b"press",
                reactors=4, conns_per_reactor=1, duration=0.5,
                timeout_ms=15000,
            )
            assert stats["fail"] == 0
            assert stats["ok"] > 10
            assert stats["cid_misroutes"] == 0
            # round-robin accept sharding: 4 conns spread one per reactor
            assert stats["reactor_conns"] == {0: 1, 1: 1, 2: 1, 3: 1}
            assert len(stats["client_shards"]) == 4
        finally:
            srv.stop()

    def test_press_over_device_links(self, echo_server):
        # --transport tpu: the rdma_performance client's use_rdma flag —
        # the same load loop over the device plane
        server, _ = echo_server
        from tools.rpc_press import run_press

        stats = run_press(
            f"127.0.0.1:{server.port}",
            "dump",
            "echo",
            b"press-tpu",
            threads=2,
            duration=0.5,
            timeout_ms=60000,
            transport="tpu",
        )
        assert stats["fail"] == 0
        assert stats["ok"] > 5


class TestView:
    def test_view_prints_samples(self, tmp_path, capsys):
        from incubator_brpc_tpu.protocol.tbus_std import Meta

        d = RpcDumper(directory=str(tmp_path))
        assert d.sample(Meta(service="v", method="m"), b"hello-view")
        d.close()
        from tools.rpc_view import main as view_main

        path = os.path.join(str(tmp_path), sorted(os.listdir(tmp_path))[0])
        assert view_main([path]) == 0
        out = capsys.readouterr().out
        assert "v.m" in out and "hello-view" in out and "1/1 samples" in out

    def test_view_filters_and_json(self, tmp_path, capsys):
        from incubator_brpc_tpu.protocol.tbus_std import Meta

        d = RpcDumper(directory=str(tmp_path))
        assert d.sample(Meta(service="a", method="m1"), b"one")
        assert d.sample(Meta(service="b", method="m2"), b"two")
        d.close()
        from tools.rpc_view import main as view_main

        path = os.path.join(str(tmp_path), sorted(os.listdir(tmp_path))[0])
        assert view_main(["--service", "b", "--json", path]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        import json as _json

        rows = [_json.loads(line) for line in out]
        assert len(rows) == 1 and rows[0]["service"] == "b"

    def test_view_proxies_target_portal(self, echo_server):
        # the reference rpc_view shape: a front server relaying every path
        # to the target's builtin portal (rpc_view.cpp)
        from incubator_brpc_tpu.protocol.http import http_call
        from tools.rpc_view import make_proxy_server, serve_proxy

        target_server, _ = echo_server
        assert make_proxy_server("not-a-target") is None
        assert serve_proxy(0, "not-a-target") == 2
        front = make_proxy_server(f"127.0.0.1:{target_server.port}")
        assert front is not None and front.start(0)
        try:
            status, _, body = http_call("127.0.0.1", front.port, "/health")
            assert status == 200
            assert b"OK" in body and b"rpc_view of" in body  # tagged relay
            status, _, body = http_call("127.0.0.1", front.port, "/vars")
            assert status == 200 and b"socket_in_bytes" in body
            status, _, body = http_call("127.0.0.1", front.port, "/")
            assert status == 200 and b"rpc_view of" in body  # html tag
        finally:
            front.stop()


class TestViewRpcz:
    """rpc_view --rpcz: the scrape-side twin of --metrics for the
    tracing plane (fetches /rpcz?json=1, prints spans or one trace
    tree)."""

    @pytest.fixture
    def traced_server(self, echo_server, tuned_flags):
        from incubator_brpc_tpu.builtin.rpcz import Span, span_store

        server, _ = echo_server
        tuned_flags("enable_rpcz", True)
        span_store.clear()
        span_store.submit(Span(
            trace_id=0xBEE, span_id=1, parent_span_id=0, span_type="server",
            service="tool", method="root", latency_us=500, start_real_us=10,
        ))
        span_store.submit(Span(
            trace_id=0xBEE, span_id=2, parent_span_id=1, span_type="client",
            service="tool", method="leaf", latency_us=100, error_code=9,
            start_real_us=20,
        ))
        yield server
        span_store.clear()

    def test_rpcz_mode_prints_recent_spans(self, traced_server, capsys):
        from tools.rpc_view import main as view_main

        rc = view_main(
            ["--rpcz", "--target", f"127.0.0.1:{traced_server.port}"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "2 spans" in out
        assert "tool.root" in out and "tool.leaf" in out

    def test_rpcz_mode_assembles_trace_tree(self, traced_server, capsys):
        from tools.rpc_view import main as view_main

        rc = view_main([
            "--rpcz", "--target", f"127.0.0.1:{traced_server.port}",
            "--trace-id", "bee",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        lines = [ln for ln in out.splitlines() if "trace=" in ln]
        assert lines[0].startswith("trace=bee span=1")
        assert lines[1].startswith("  trace=bee span=2")  # child indented

    def test_rpcz_mode_filters(self, traced_server, capsys):
        from tools.rpc_view import main as view_main

        rc = view_main([
            "--rpcz", "--target", f"127.0.0.1:{traced_server.port}",
            "--error-only",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1 spans" in out and "error=9" in out

    def test_rpcz_mode_bad_target(self, capsys):
        from tools.rpc_view import main as view_main

        assert view_main(["--rpcz", "--target", "not-a-target"]) == 2
        # unreachable port: a clean error, not a traceback
        assert view_main(["--rpcz", "--target", "127.0.0.1:1"]) == 1


class TestParallelHttp:
    def test_fetches_portal_urls_concurrently(self, echo_server):
        from tools.parallel_http import fetch_all

        server, _ = echo_server
        port = server.port
        urls = [
            f"http://127.0.0.1:{port}/health",
            f"http://127.0.0.1:{port}/version",
            f"http://127.0.0.1:{port}/vars.json",
            f"http://127.0.0.1:{port}/does-not-exist",
        ]
        results = fetch_all(urls, threads=4, timeout_ms=5000)
        by_url = {r[0]: r for r in results}
        assert by_url[urls[0]][1] == 200
        assert by_url[urls[1]][1] == 200
        assert by_url[urls[2]][1] == 200 and by_url[urls[2]][2] > 2
        # a 404 is a completed fetch with an error status, not a crash
        assert by_url[urls[3]][1] in (None, 404)
